"""Grouped (ragged) matmul: per-expert row blocks through the MXU.

The TPU-native analogue of the reference's expert-parallel dispatch ops
(paddle/fluid/operators/collective/global_scatter_op.cc builds per-expert
contiguous row buffers from counts; the expert FFN then matmuls each block).
Here the blocks stay in ONE [m, k] array sorted by expert, and a grouped
kernel walks the per-expert row ranges back-to-back on the systolic array —
no capacity padding, no one-hot dispatch tensors (megablox-style).

Backends:
- TPU: the Pallas megablox `gmm` kernel shipped with JAX (tiled grouped
  matmul with a custom VJP — the backward runs gmm for dx and the transposed
  tgmm for dw). Tiling tuned on v5e at the bench MoE shape
  (m=32768, k=1536, n=2048): (512, 512, 1024) -> 81 TF/s; larger k-tiles
  OOM the 16MB VMEM at these widths.
- CPU (tests / virtual meshes): `jax.lax.ragged_dot`, which XLA:CPU expands
  natively and which carries full JVP/transpose rules.

Measured context (v5e, bf16, equal groups at the bench shape): a plain
batched `jnp.einsum("ech,ehi->eci")` over capacity-padded [e, cap, h]
buffers reaches 128 TF/s vs gmm's 81 TF/s, so the capacity path remains the
default MoE FFN; gmm wins only when padding waste exceeds ~1.6x (dropless
recipes with heavy imbalance). Both are exposed — see
nn/layer/moe.py `FLAGS_moe_dispatch`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# v5e-tuned default (see module docstring); callers may override.
DEFAULT_TILING = (512, 512, 1024)


def grouped_matmul(lhs, rhs, group_sizes, *, tiling=None):
    """lhs[m, k] @ rhs[g, k, n] per contiguous row group -> [m, n].

    Rows of `lhs` must be grouped by expert: rows
    [sum(group_sizes[:i]), sum(group_sizes[:i+1])) multiply rhs[i].
    sum(group_sizes) must equal m. Accumulates fp32, returns lhs.dtype.
    Differentiable on both backends.
    """
    group_sizes = group_sizes.astype(jnp.int32)
    m, k = lhs.shape
    n = rhs.shape[-1]
    # the Pallas kernel tiles in (8, 128) registers: every matmul dim must
    # be tileable (fwd AND the bwd tgmm, which transposes the roles of
    # m/k/n) — small/odd layers take the XLA ragged_dot expansion instead
    aligned = m % 8 == 0 and k % 128 == 0 and n % 128 == 0
    if jax.default_backend() == "tpu" and aligned:
        from jax.experimental.pallas.ops.tpu import megablox as mb

        tm, tk, tn = tiling or DEFAULT_TILING
        tm, tk, tn = min(tm, m), min(tk, k), min(tn, n)
        out = mb.gmm(lhs, rhs, group_sizes,
                     preferred_element_type=jnp.float32, tiling=(tm, tk, tn))
    else:
        out = jax.lax.ragged_dot(lhs, rhs, group_sizes,
                                 preferred_element_type=jnp.float32)
    return out.astype(lhs.dtype)
