"""Pallas TPU kernels: the fused-op library (operators/fused/ role)."""
