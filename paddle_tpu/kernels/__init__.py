"""Pallas TPU kernels: the fused-op library (operators/fused/ role).

- ``flash_attention``: Pallas flash attention fwd/bwd (online softmax).
- ``grouped_matmul``: megablox-style ragged per-expert matmul.
- ``pallas``: the fused-op layer (RMSNorm/RoPE fusions, fused MoE
  dispatch, paged attention) — each op a Pallas kernel + composed-XLA
  twin pair behind the ``registry`` dispatch seam
  (``FLAGS_fused_kernels``; see docs/performance.md "Fused kernels").
"""
from . import registry  # noqa: F401
