"""Fused RMSNorm and RMSNorm+residual — Pallas kernels (fwd + VJP).

The FlashAttention lesson applied to the norm: the composed-XLA form
reads the activation once for the mean-square reduction and again for the
normalize (plus a third pass when a residual add precedes it), so a
[b, s, h] hidden state round-trips HBM up to 3x per norm. The fused
kernel streams each row block once: residual add, f32 mean-square,
rsqrt, scale — one read, one write, with the per-row ``rstd`` saved for
a single-pass backward (no recompute of the reduction).

Two entry points:

- ``rms_norm(x, w, eps)``: plain norm, y = x * rsqrt(mean(x^2)+eps) * w.
- ``rms_norm_residual(x, res, w, eps) -> (y, s)``: the decoder-layer
  pattern ``s = x + res; y = norm(s)`` fused; ``s`` is returned as the
  new residual stream (both outputs carry cotangents in the VJP).

Both carry custom VJPs whose backward is also one kernel (dx [+dres] and
a cross-row dw accumulated in VMEM scratch over the sequential grid).
The composed-XLA twin implements the identical math + VJP structure in
jnp — the CPU production path and the TPU A/B reference. Parity is
pinned by tests/test_pallas_kernels.py (fwd and grads, odd widths).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import register_kernel, resolve
from ._common import interpret_default as _interpret
from ._common import pick_rows as _pick_rows

__all__ = ["rms_norm", "rms_norm_residual"]


# -- forward ------------------------------------------------------------------
# The plain and +residual variants have DIFFERENT operand lists (not just
# different math): the plain kernel must not stream a dead residual input
# or write a redundant s output — on a memory-bound op those extra
# [n, h] DMAs would cost what the fusion saves. The saved "s" for the
# plain backward IS the primal input.

def _fwd_kernel(x_ref, r_ref, w_ref, y_ref, s_ref, rstd_ref, *, eps):
    s = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    ms = jnp.mean(s * s, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y_ref[...] = (s * rstd * w_ref[...].astype(jnp.float32)).astype(
        y_ref.dtype)
    s_ref[...] = s.astype(s_ref.dtype)
    rstd_ref[...] = rstd


def _fwd_kernel_plain(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y_ref[...] = (x * rstd * w_ref[...].astype(jnp.float32)).astype(
        y_ref.dtype)
    rstd_ref[...] = rstd


def _fwd_pallas(x2, r2, w, eps, residual, interpret):
    n, h = x2.shape
    bn = _pick_rows(n)
    grid = (n // bn,)
    w2 = w.reshape(1, h)
    row = pl.BlockSpec((bn, h), lambda i: (i, 0))
    wspec = pl.BlockSpec((1, h), lambda i: (0, 0))
    rstd_spec = pl.BlockSpec((bn, 1), lambda i: (i, 0))
    if residual:
        y, s, rstd = pl.pallas_call(
            functools.partial(_fwd_kernel, eps=eps),
            grid=grid,
            in_specs=[row, row, wspec],
            out_specs=[row, row, rstd_spec],
            out_shape=[
                jax.ShapeDtypeStruct((n, h), x2.dtype),
                jax.ShapeDtypeStruct((n, h), x2.dtype),
                jax.ShapeDtypeStruct((n, 1), jnp.float32),
            ],
            interpret=interpret,
        )(x2, r2, w2)
        return y, s, rstd
    y, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel_plain, eps=eps),
        grid=grid,
        in_specs=[row, wspec],
        out_specs=[row, rstd_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w2)
    return y, x2, rstd


def _fwd_composed(x2, r2, w, eps, residual):
    if residual:
        s = x2.astype(jnp.float32) + r2.astype(jnp.float32)
    else:
        s = x2.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(s * s, axis=-1, keepdims=True) + eps)
    y = (s * rstd * w.astype(jnp.float32)).astype(x2.dtype)
    return y, (s.astype(x2.dtype) if residual else x2), rstd


# -- backward -----------------------------------------------------------------

def _bwd_body(s, w, rstd, dy, dr):
    g = dy * w
    # y = s * rstd * w with rstd = (mean(s^2)+eps)^-1/2:
    # ds = rstd * (g - s * rstd^2 * mean(g*s))
    ds = rstd * (g - s * (rstd * rstd) *
                 jnp.mean(g * s, axis=-1, keepdims=True))
    if dr is not None:
        # s is ALSO the new-residual output — its cotangent adds straight
        # through (dx == dres: the add fans the same gradient both ways)
        ds = ds + dr
    return ds, jnp.sum(dy * s * rstd, axis=0, keepdims=True)


def _bwd_kernel(s_ref, w_ref, rstd_ref, dy_ref, dr_ref, dx_ref, dw_ref,
                dw_acc):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_acc[...] = jnp.zeros_like(dw_acc)

    ds, dw_part = _bwd_body(
        s_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        rstd_ref[...], dy_ref[...].astype(jnp.float32),
        dr_ref[...].astype(jnp.float32))
    dx_ref[...] = ds.astype(dx_ref.dtype)
    dw_acc[...] += dw_part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dw_ref[...] = dw_acc[...]


def _bwd_kernel_plain(s_ref, w_ref, rstd_ref, dy_ref, dx_ref, dw_ref,
                      dw_acc):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_acc[...] = jnp.zeros_like(dw_acc)

    ds, dw_part = _bwd_body(
        s_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        rstd_ref[...], dy_ref[...].astype(jnp.float32), None)
    dx_ref[...] = ds.astype(dx_ref.dtype)
    dw_acc[...] += dw_part

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dw_ref[...] = dw_acc[...]


def _bwd_pallas(s, w, rstd, dy, dr, residual, interpret):
    n, h = s.shape
    bn = _pick_rows(n)
    grid = (n // bn,)
    w2 = w.reshape(1, h)
    row = pl.BlockSpec((bn, h), lambda i: (i, 0))
    wspec = pl.BlockSpec((1, h), lambda i: (0, 0))
    rstd_spec = pl.BlockSpec((bn, 1), lambda i: (i, 0))
    out_specs = [row, wspec]
    out_shape = [jax.ShapeDtypeStruct((n, h), s.dtype),
                 jax.ShapeDtypeStruct((1, h), jnp.float32)]
    scratch = [pltpu.VMEM((1, h), jnp.float32)]
    if residual:
        dx, dw = pl.pallas_call(
            _bwd_kernel, grid=grid,
            in_specs=[row, wspec, rstd_spec, row, row],
            out_specs=out_specs, out_shape=out_shape,
            scratch_shapes=scratch, interpret=interpret,
        )(s, w2, rstd, dy, dr)
    else:
        dx, dw = pl.pallas_call(
            _bwd_kernel_plain, grid=grid,
            in_specs=[row, wspec, rstd_spec, row],
            out_specs=out_specs, out_shape=out_shape,
            scratch_shapes=scratch, interpret=interpret,
        )(s, w2, rstd, dy)
    return dx, dw.reshape(h)


def _bwd_composed(s, w, rstd, dy, dr, residual):
    ds, dw = _bwd_body(s.astype(jnp.float32),
                       w.astype(jnp.float32), rstd,
                       dy.astype(jnp.float32),
                       dr.astype(jnp.float32) if residual else None)
    return ds.astype(s.dtype), dw.reshape(-1)


# -- differentiable wrappers ([n, h] layout) ----------------------------------

def _run_fwd(x2, r2, w, eps, impl, residual):
    if impl in ("pallas", "interpret"):
        return _fwd_pallas(x2, r2, w, eps, residual,
                           interpret=(impl == "interpret") or _interpret())
    return _fwd_composed(x2, r2, w, eps, residual)


def _run_bwd(s, w, rstd, dy, dr, impl, residual):
    if impl in ("pallas", "interpret"):
        return _bwd_pallas(s, w, rstd, dy, dr, residual,
                           interpret=(impl == "interpret") or _interpret())
    return _bwd_composed(s, w, rstd, dy, dr, residual)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms2(x2, w, eps, impl):
    return _run_fwd(x2, x2, w, eps, impl, residual=False)[0]


def _rms2_fwd(x2, w, eps, impl):
    y, s, rstd = _run_fwd(x2, x2, w, eps, impl, residual=False)
    return y, (s, w, rstd)


def _rms2_bwd(eps, impl, res, dy):
    s, w, rstd = res
    dx, dw = _run_bwd(s, w, rstd, dy, dy, impl, residual=False)
    return dx, dw.astype(w.dtype)


_rms2.defvjp(_rms2_fwd, _rms2_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rms2_res(x2, r2, w, eps, impl):
    y, s, _ = _run_fwd(x2, r2, w, eps, impl, residual=True)
    return y, s


def _rms2_res_fwd(x2, r2, w, eps, impl):
    y, s, rstd = _run_fwd(x2, r2, w, eps, impl, residual=True)
    return (y, s), (s, w, rstd)


def _rms2_res_bwd(eps, impl, res, cts):
    s, w, rstd = res
    dy, dr = cts
    ds, dw = _run_bwd(s, w, rstd, dy, dr, impl, residual=True)
    return ds, ds, dw.astype(w.dtype)


_rms2_res.defvjp(_rms2_res_fwd, _rms2_res_bwd)


# -- public API ([..., h] layout) ---------------------------------------------

def rms_norm(x, w, eps: float = 1e-6, impl: str = None):
    """Fused RMSNorm over the last axis. ``impl``: None (registry pick),
    'pallas', 'interpret' (Pallas through the interpreter — parity
    tests), or 'composed' (the jnp twin)."""
    if impl is None:
        impl = resolve("rms_norm")[0]
    h = x.shape[-1]
    y = _rms2(x.reshape(-1, h), w, float(eps), impl)
    return y.reshape(x.shape)


def rms_norm_residual(x, res, w, eps: float = 1e-6, impl: str = None):
    """Fused ``s = x + res; y = rmsnorm(s) * w`` -> ``(y, s)`` — the
    pre-norm decoder pattern with the residual add folded into the same
    HBM pass. Returns the normed branch input and the new residual."""
    if impl is None:
        impl = resolve("rms_norm")[0]
    h = x.shape[-1]
    y, s = _rms2_res(x.reshape(-1, h), res.reshape(-1, h), w, float(eps),
                     impl)
    return y.reshape(x.shape), s.reshape(x.shape)


register_kernel(
    "rms_norm",
    pallas=functools.partial(rms_norm, impl="pallas"),
    composed=functools.partial(rms_norm, impl="composed"),
    doc="RMSNorm (+residual) fused: one HBM pass fwd, one-kernel VJP")
