"""Paged attention — decode/window attention against a page table.

PR 11's window step gathers every slot's K/V pages into a dense
[S, L, h, d] context (``kc[tables]``) and then attends — the gather
round-trips the whole addressable context through HBM even though the
attention itself touches each page once. This kernel closes that follow-
up: the grid walks (slot, page), the page table rides SMEM via scalar
prefetch, and each step DMAs ONE page of K/V and folds it into a
per-slot online softmax (flash-style f32 accumulators in VMEM scratch) —
the dense gathered context never exists.

Layouts (matching ``serving.paged_kv`` + ``_build_window_step``):

- ``q``:        [S, W, nh, hd] — W window tokens per slot
- ``k/v``:      [P, PL, kvh, hd] — the page-pool arenas (kvh <= nh, GQA)
- ``tables``:   [S, B] int32 page ids (0 = scratch page)
- ``pos``:      [S, W] int32 global positions; key position j is visible
                to window token (s, w) iff j <= pos[s, w]

Serving never differentiates through the decode step, but the op still
carries a VJP (backward = ``jax.vjp`` of the composed twin) so the
parity suite can pin gradients and nothing breaks if a scoring path
ever backprops through it. The composed twin IS the PR-11 gather-then-
attend math — on CPU the registry resolves to it, so the paged-decode
step is by construction no slower than the gather path there; the TPU
A/B rides the bench ``fused_kernels`` recipe.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import register_kernel, resolve
from ._common import interpret_default as _interpret

__all__ = ["paged_attention"]

_NEG = -1e30


def _paged_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, W, nh, kvh, hd, PL, scale):
    b = pl.program_id(1)
    rep = nh // kvh

    @pl.when(b == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    qpos = pos_ref[...][0]                                   # [W] int32
    kpos = b * PL + jax.lax.broadcasted_iota(jnp.int32, (1, PL), 1)[0]
    # rows are (w, r) pairs flattened per kv-head group
    qpos_r = jnp.broadcast_to(qpos[:, None], (W, rep)).reshape(W * rep)
    visible = kpos[None, :] <= qpos_r[:, None]               # [W*rep, PL]

    for g in range(kvh):
        lo, hi = g * W * rep, (g + 1) * W * rep
        q = q_ref[0][:, g * rep:(g + 1) * rep, :].reshape(W * rep, hd)
        k = k_ref[0][:, g, :]                                # [PL, hd]
        v = v_ref[0][:, g, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = jnp.where(visible, s, _NEG)
        m_prev = m_ref[lo:hi, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(s > _NEG * 0.5, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l_ref[lo:hi, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[lo:hi, :] = acc_ref[lo:hi, :] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[lo:hi, :] = jnp.broadcast_to(m_new, (hi - lo, m_ref.shape[1]))
        l_ref[lo:hi, :] = jnp.broadcast_to(l_new, (hi - lo, l_ref.shape[1]))

    @pl.when(b == pl.num_programs(1) - 1)
    def _():
        for g in range(kvh):
            lo, hi = g * W * rep, (g + 1) * W * rep
            l = jnp.maximum(l_ref[lo:hi, :1], 1e-30)
            ctx = (acc_ref[lo:hi, :] / l).reshape(W, rep, hd)
            o_ref[0, :, g * rep:(g + 1) * rep, :] = ctx.astype(o_ref.dtype)


def _paged_pallas(q, k_arena, v_arena, tables, pos, scale, interpret):
    S, W, nh, hd = q.shape
    P, PL, kvh, _ = k_arena.shape
    B = tables.shape[1]
    out = pl.pallas_call(
        functools.partial(_paged_kernel, W=W, nh=nh, kvh=kvh, hd=hd, PL=PL,
                          scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(S, B),
            in_specs=[
                pl.BlockSpec((1, W), lambda s, b, t: (s, 0)),
                pl.BlockSpec((1, W, nh, hd), lambda s, b, t: (s, 0, 0, 0)),
                pl.BlockSpec((1, PL, kvh, hd),
                             lambda s, b, t: (t[s, b], 0, 0, 0)),
                pl.BlockSpec((1, PL, kvh, hd),
                             lambda s, b, t: (t[s, b], 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, W, nh, hd),
                                   lambda s, b, t: (s, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((W * nh, hd), jnp.float32),
                pltpu.VMEM((W * nh, 128), jnp.float32),
                pltpu.VMEM((W * nh, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, W, nh, hd), q.dtype),
        interpret=interpret,
    )(tables, pos, q, k_arena, v_arena)
    return out


def _paged_composed(q, k_arena, v_arena, tables, pos, scale):
    """The PR-11 gather-then-attend math, verbatim (the CPU production
    path and the TPU A/B reference)."""
    S, W, nh, hd = q.shape
    _P, PL, kvh, _ = k_arena.shape
    B = tables.shape[1]
    L = B * PL
    kk = k_arena[tables].reshape(S, L, kvh, hd)
    vv = v_arena[tables].reshape(S, L, kvh, hd)
    if kvh != nh:
        rep = nh // kvh
        kk = jnp.repeat(kk, rep, axis=2)
        vv = jnp.repeat(vv, rep, axis=2)
    j = jnp.arange(L)
    mask = j[None, None, :] <= pos[:, :, None]               # [S, W, L]
    logits = jnp.einsum("swhd,sLhd->swhL", q, kk)
    logits = logits.astype(jnp.float32) * scale
    logits = jnp.where(mask[:, :, None, :], logits, _NEG)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("swhL,sLhd->swhd", probs, vv)


def _run(q, k_arena, v_arena, tables, pos, scale, impl):
    if impl in ("pallas", "interpret"):
        return _paged_pallas(q, k_arena, v_arena, tables, pos, scale,
                             interpret=(impl == "interpret") or _interpret())
    return _paged_composed(q, k_arena, v_arena, tables, pos, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _paged(q, k_arena, v_arena, tables, pos, scale, impl):
    return _run(q, k_arena, v_arena, tables, pos, scale, impl)


def _paged_fwd(q, k_arena, v_arena, tables, pos, scale, impl):
    out = _run(q, k_arena, v_arena, tables, pos, scale, impl)
    return out, (q, k_arena, v_arena, tables, pos)


def _paged_bwd(scale, impl, res, do):
    # serving never backprops through decode; the VJP exists for the
    # parity suite and recomputes through the composed twin
    q, k_arena, v_arena, tables, pos = res
    _, vjp = jax.vjp(
        lambda qq, kk, vv: _paged_composed(qq, kk, vv, tables, pos, scale),
        q, k_arena, v_arena)
    dq, dk, dv = vjp(do)
    return dq, dk, dv, None, None


_paged.defvjp(_paged_fwd, _paged_bwd)


def paged_attention(q, k_arena, v_arena, tables, pos, scale=None,
                    impl: str = None):
    """Window attention straight against the page table. ``q`` [S, W,
    nh, hd]; arenas [P, PL, kvh, hd]; ``tables`` [S, B]; ``pos`` [S, W]
    (key j visible iff j <= pos). Returns [S, W, nh, hd] in q.dtype."""
    nh, kvh = q.shape[2], k_arena.shape[2]
    if nh % kvh:
        raise ValueError(f"num_heads {nh} not a multiple of kv heads {kvh}")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl is None:
        impl = resolve("paged_attention")[0]
    return _paged(q, k_arena, v_arena, tables.astype(jnp.int32),
                  pos.astype(jnp.int32), float(scale), impl)


register_kernel(
    "paged_attention",
    pallas=functools.partial(paged_attention, impl="pallas"),
    composed=functools.partial(paged_attention, impl="composed"),
    doc="decode window attention against the PagedKVPool page table: "
        "per-page online softmax, no dense gathered context")
