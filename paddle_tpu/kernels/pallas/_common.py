"""Shared helpers for the fused-op kernel modules."""
from __future__ import annotations

import jax

__all__ = ["interpret_default", "pick_rows"]


def interpret_default() -> bool:
    """Run the Pallas kernel through the interpreter? (CPU backend —
    tests and virtual meshes; real TPUs compile.)"""
    try:
        return jax.default_backend() == "cpu"
    except Exception:  # pragma: no cover
        return True


def pick_rows(n: int, pref: int = 256) -> int:
    """Largest row-block <= pref dividing n (kernels that reduce over
    the full row width block whole rows only)."""
    b = min(pref, n)
    while n % b:
        b -= 1
    return max(b, 1)
