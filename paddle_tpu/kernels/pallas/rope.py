"""Fused rotate-half RoPE — Pallas kernel (fwd + VJP).

The composed form materializes cos/sin tables, splits the activation,
and concatenates — several elementwise HLOs over the full [b, s, h, d]
q/k tensors. The fused kernel streams each sequence block once and
computes the angles in-register from the block's global positions (no
cos/sin tables in HBM at all).

The VJP needs no residuals: a rotation is orthogonal, so the backward is
the same kernel with the angle negated (``inverse=True``) applied to the
cotangent — RoPE becomes memory-traffic-free to differentiate.

``pos_offset`` shifts the global positions (decode-cache append and the
context-parallel rank offset ride this, matching ``models/llama.py``'s
``rope_apply`` contract). Parity vs the composed twin (and the legacy
``_rope`` primitive) is pinned by tests/test_pallas_kernels.py.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..registry import register_kernel, resolve
from ._common import interpret_default as _interpret
from ._common import pick_rows

__all__ = ["rope_apply"]


def _pick_seq_block(s: int, pref: int = 512) -> int:
    return pick_rows(s, pref)


def _angles(bs: int, d: int, theta: float, base_pos):
    """cos/sin [bs, 1, d//2] for positions base_pos + [0..bs) — computed
    in-register (f32) from iotas; no table input."""
    half = d // 2
    pos = base_pos + jax.lax.broadcasted_iota(jnp.float32, (bs, 1, half), 0)
    # inv_freq_i = theta^(-2i/d) == exp(-(2i/d) * ln(theta))
    idx = jax.lax.broadcasted_iota(jnp.float32, (bs, 1, half), 2)
    inv = jnp.exp(idx * (-2.0 / d) * math.log(theta))
    freqs = pos * inv
    return jnp.cos(freqs), jnp.sin(freqs)


def _rope_kernel(x_ref, o_ref, *, theta, pos_offset, block_s, d, inverse):
    s_start = pl.program_id(1) * block_s
    cos, sin = _angles(block_s, d, theta, jnp.float32(pos_offset) + s_start)
    if inverse:
        sin = -sin
    xf = x_ref[0].astype(jnp.float32)          # [block_s, h, d]
    half = d // 2
    x1, x2 = xf[..., :half], xf[..., half:]
    o_ref[0] = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
        axis=-1).astype(o_ref.dtype)


def _rope_pallas(x, theta, pos_offset, inverse, interpret):
    b, s, h, d = x.shape
    bs = _pick_seq_block(s)
    return pl.pallas_call(
        functools.partial(_rope_kernel, theta=theta, pos_offset=pos_offset,
                          block_s=bs, d=d, inverse=inverse),
        grid=(b, s // bs),
        in_specs=[pl.BlockSpec((1, bs, h, d), lambda i, j: (i, j, 0, 0))],
        out_specs=pl.BlockSpec((1, bs, h, d), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)


def _rope_composed(x, theta, pos_offset, inverse):
    b, s, h, d = x.shape
    pos = jnp.arange(pos_offset, pos_offset + s, dtype=jnp.float32)
    inv = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    freqs = jnp.outer(pos, inv)
    cos = jnp.cos(freqs)[None, :, None, :]
    sin = jnp.sin(freqs)[None, :, None, :]
    if inverse:
        sin = -sin
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def _run(x, theta, pos_offset, impl, inverse):
    if impl in ("pallas", "interpret"):
        return _rope_pallas(x, theta, pos_offset, inverse,
                            interpret=(impl == "interpret") or _interpret())
    return _rope_composed(x, theta, pos_offset, inverse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _rope4(x, theta, pos_offset, impl):
    return _run(x, theta, pos_offset, impl, inverse=False)


def _rope4_fwd(x, theta, pos_offset, impl):
    return _run(x, theta, pos_offset, impl, inverse=False), None


def _rope4_bwd(theta, pos_offset, impl, _res, dy):
    return (_run(dy, theta, pos_offset, impl, inverse=True),)


_rope4.defvjp(_rope4_fwd, _rope4_bwd)


def rope_apply(x, theta: float = 10000.0, pos_offset: int = 0,
               impl: str = None):
    """Fused rotate-half RoPE on [b, s, h, d]; d must be even. ``impl``:
    None (registry pick), 'pallas', 'interpret', or 'composed'."""
    if x.shape[-1] % 2:
        raise ValueError(f"RoPE head_dim must be even, got {x.shape[-1]}")
    if impl is None:
        impl = resolve("rope")[0]
    return _rope4(x, float(theta), int(pos_offset), impl)


register_kernel(
    "rope",
    pallas=functools.partial(rope_apply, impl="pallas"),
    composed=functools.partial(rope_apply, impl="composed"),
    doc="rotate-half RoPE: in-register angles, residual-free inverse VJP")
