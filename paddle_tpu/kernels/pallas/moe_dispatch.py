"""Fused MoE routing/dispatch — Pallas kernels feeding ``grouped_matmul``.

The r04 probe pinned the MoE bottleneck on routing/dispatch, not the
expert matmuls (``dispatch_share`` 0.148): the composed paths spend their
time in XLA gather/scatter soup around the FFN. This module is the
dropless fused answer (``FLAGS_moe_dispatch='fused'``):

- **routing kernel** — ONE sequential-grid Pallas kernel does the whole
  router: gate logits (x @ wg on the MXU), f32 softmax, iterative top-k
  select, gate renormalization, AND the "sort by expert" — per-expert
  running counters live in VMEM scratch across the grid, so every
  (token, choice) leaves the kernel with its position in its expert's
  contiguous row block (token-major order, exactly the stable-argsort
  order of the ``gmm`` path — no argsort executed). Per-expert counts
  and the aux-loss sufficient statistics (prob sums, top-1 counts) fall
  out of the same pass.
- **dispatch/combine kernels** — row movement into/out of the grouped
  layout runs as scalar-prefetch Pallas gathers: the destination map is
  prefetched into SMEM and each grid step DMAs exactly one source row
  block, so the wide-row movement never lowers to an XLA scatter (TPU
  serializes those). Custom VJPs keep the backward gather-only too —
  dispatch's backward IS a combine, combine's backward IS a dispatch
  (plus a rowwise dot for the gate grads).

The expert FFN itself stays on ``kernels.grouped_matmul`` (megablox on
TPU, ``ragged_dot`` on CPU). Differentiability through the ROUTER is
preserved by a recompute VJP: the backward re-traces softmax → top-k
pick → renorm → aux in plain XLA from the saved ``gate_i`` (one [n, e]
matmul — noise next to the FFN backward), matching ``_route``'s
gradients exactly.

Constraints: single-device experts (like ``gmm``; ragged groups cannot
cross a static-shape all_to_all) and ``num_experts <= 128`` (the expert
axis rides the lane dimension). ``nn/layer/moe.py`` falls back to the
index path outside them.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import register_kernel, resolve
from ._common import interpret_default as _interpret
from ._common import pick_rows as _pick_rows

__all__ = ["fused_moe_mlp", "fused_route", "MAX_EXPERTS"]

MAX_EXPERTS = 128  # the expert axis rides the lane dim of one block


# ---------------------------------------------------------------------------
# routing: top-k select + position-in-expert in one kernel
# ---------------------------------------------------------------------------

def _routing_kernel(x_ref, wg_ref, gv_ref, gi_ref, pos_ref, cnt_ref,
                    me_ref, ce_ref, carry, me_acc, ce_acc, *, top_k, e):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        carry[...] = jnp.zeros_like(carry)
        me_acc[...] = jnp.zeros_like(me_acc)
        ce_acc[...] = jnp.zeros_like(ce_acc)

    x = x_ref[...].astype(jnp.float32)                     # [bn, h]
    wg = wg_ref[...].astype(jnp.float32)                   # [h, e]
    logits = jax.lax.dot_general(x, wg, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    mx = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - mx)
    p = p / jnp.sum(p, axis=-1, keepdims=True)             # [bn, e]
    bn = p.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (bn, e), 1)
    masked = p
    gvs, gis = [], []
    for _c in range(top_k):                                # iterative top-k
        idx = jnp.argmax(masked, axis=-1).astype(jnp.int32)
        gvs.append(jnp.max(masked, axis=-1))
        gis.append(idx)
        masked = jnp.where(lane == idx[:, None], -1.0, masked)
    gv = jnp.stack(gvs, axis=1)                            # [bn, k]
    gi = jnp.stack(gis, axis=1)
    gv = gv / jnp.maximum(jnp.sum(gv, axis=-1, keepdims=True), 1e-9)

    # position-in-expert, token-major (row r = t*k + c): running per-expert
    # counters persist in scratch across the sequential grid — this IS the
    # stable sort-by-expert, without executing a sort
    flat_e = gi.reshape(bn * top_k)
    lane_f = jax.lax.broadcasted_iota(jnp.int32, (bn * top_k, e), 1)
    oh = lane_f == flat_e[:, None]
    ohi = oh.astype(jnp.int32)
    pos_local = jnp.cumsum(ohi, axis=0) - 1                # [bn*k, e]
    base = carry[...]                                      # [1, e]
    pos_flat = jnp.sum(jnp.where(oh, pos_local + base, 0), axis=-1)
    pos_ref[...] = pos_flat.reshape(bn, top_k).astype(jnp.int32)
    carry[...] = base + jnp.sum(ohi, axis=0, keepdims=True)
    me_acc[...] += jnp.sum(p, axis=0, keepdims=True)
    top1 = (lane == gi[:, 0][:, None]).astype(jnp.float32)
    ce_acc[...] += jnp.sum(top1, axis=0, keepdims=True)
    gv_ref[...] = gv
    gi_ref[...] = gi

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        cnt_ref[...] = carry[...]
        me_ref[...] = me_acc[...]
        ce_ref[...] = ce_acc[...]


def _routing_pallas(xt, wg, top_k, interpret):
    n, h = xt.shape
    e = wg.shape[1]
    bn = _pick_rows(n)
    grid = (n // bn,)
    gv, gi, pos, cnt, me, ce = pl.pallas_call(
        functools.partial(_routing_kernel, top_k=top_k, e=e),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((h, e), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, top_k), lambda i: (i, 0)),
            pl.BlockSpec((bn, top_k), lambda i: (i, 0)),
            pl.BlockSpec((bn, top_k), lambda i: (i, 0)),
            pl.BlockSpec((1, e), lambda i: (0, 0)),
            pl.BlockSpec((1, e), lambda i: (0, 0)),
            pl.BlockSpec((1, e), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, top_k), jnp.float32),
            jax.ShapeDtypeStruct((n, top_k), jnp.int32),
            jax.ShapeDtypeStruct((n, top_k), jnp.int32),
            jax.ShapeDtypeStruct((1, e), jnp.int32),
            jax.ShapeDtypeStruct((1, e), jnp.float32),
            jax.ShapeDtypeStruct((1, e), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, e), jnp.int32),
            pltpu.VMEM((1, e), jnp.float32),
            pltpu.VMEM((1, e), jnp.float32),
        ],
        interpret=interpret,
    )(xt, wg)
    return gv, gi, pos, cnt.reshape(e), me.reshape(e), ce.reshape(e)


def _routing_composed(xt, wg, top_k):
    """The jnp twin: identical math, token-major cumsum positions."""
    n, _ = xt.shape
    e = wg.shape[1]
    logits = jnp.matmul(xt.astype(jnp.float32), wg.astype(jnp.float32))
    p = jax.nn.softmax(logits, axis=-1)
    gv, gi = jax.lax.top_k(p, top_k)
    gv = gv / jnp.maximum(jnp.sum(gv, axis=-1, keepdims=True), 1e-9)
    flat_e = gi.reshape(n * top_k)                         # token-major
    oh = flat_e[:, None] == jnp.arange(e, dtype=flat_e.dtype)[None, :]
    ohi = oh.astype(jnp.int32)
    pos = jnp.sum(jnp.where(oh, jnp.cumsum(ohi, axis=0) - 1, 0),
                  axis=-1).reshape(n, top_k)
    cnt = jnp.sum(ohi, axis=0)
    me = jnp.sum(p, axis=0)
    ce = jnp.sum(jax.nn.one_hot(gi[:, 0], e, dtype=jnp.float32), axis=0)
    return gv, gi.astype(jnp.int32), pos.astype(jnp.int32), cnt, me, ce


def _route_diff(xt, wg, gate_i, top_k, e):
    """The differentiable router chain, recomputed from the saved top-k
    pick: softmax -> gather the chosen probs -> renorm, plus the
    Switch/GShard aux. Gradients match ``nn.layer.moe._route`` (the
    top-1 frequency term is piecewise-constant there too)."""
    p = jax.nn.softmax(
        jnp.matmul(xt.astype(jnp.float32), wg.astype(jnp.float32)), axis=-1)
    v = jnp.take_along_axis(p, gate_i, axis=1)
    gate = v / jnp.maximum(jnp.sum(v, axis=-1, keepdims=True), 1e-9)
    me = jnp.mean(p, axis=0)
    ce = jnp.mean(jax.nn.one_hot(gate_i[:, 0], e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)
    return gate, aux


def _route_impl(xt, wg, top_k, impl):
    gv, gi, pos, cnt, me, ce = (
        _routing_pallas(xt, wg, top_k,
                        interpret=(impl == "interpret") or _interpret())
        if impl in ("pallas", "interpret")
        else _routing_composed(xt, wg, top_k))
    n = xt.shape[0]
    e = wg.shape[1]
    aux = e * jnp.sum((me / n) * (ce / n))
    # index outputs leave the custom-vjp boundary as FLOATS: an integer
    # output of a custom_vjp gets a float0 tangent, and the scanned
    # decoder stack's linearization materializes those into downstream
    # int arithmetic (cumsum/sub) — float outputs carry ordinary zero
    # tangents instead. Exact for values < 2^24 (kn rows); callers cast
    # back to int32 (a nondiff convert with a symbolic-zero tangent).
    return (gv, gi.astype(jnp.float32), pos.astype(jnp.float32),
            cnt.astype(jnp.float32), aux)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_route(xt, wg, top_k, impl):
    """(gate_v, gate_i, pos_in_expert, counts, aux): the full router in
    one kernel pass; the index outputs ride as f32 (see ``_route_impl``).
    Differentiable in (xt, wg) through gate_v and aux."""
    return _route_impl(xt, wg, top_k, impl)


def _fused_route_fwd(xt, wg, top_k, impl):
    out = _route_impl(xt, wg, top_k, impl)
    return out, (xt, wg, out[1].astype(jnp.int32))


def _fused_route_bwd(top_k, impl, res, cts):
    xt, wg, gate_i = res
    d_gv, _d_gi, _d_pos, _d_cnt, d_aux = cts
    e = wg.shape[1]
    _, vjp = jax.vjp(
        lambda x, w: _route_diff(x, w, gate_i, top_k, e), xt, wg)
    dx, dw = vjp((d_gv.astype(jnp.float32), d_aux.astype(jnp.float32)))
    return dx.astype(xt.dtype), dw.astype(wg.dtype)


fused_route.defvjp(_fused_route_fwd, _fused_route_bwd)


# ---------------------------------------------------------------------------
# row movement: scalar-prefetch gather / weighted combine
# ---------------------------------------------------------------------------

def _gather_kernel(idx_ref, src_ref, out_ref):
    del idx_ref  # consumed by the index maps
    out_ref[...] = src_ref[...]


def _gather_rows(src, idx, impl):
    """out[i] = src[idx[i]] — the grouped-layout gather. One row block
    per grid step, destination-ordered; the index vector rides SMEM via
    scalar prefetch so the DMA engine walks it ahead of compute."""
    if impl == "composed":
        return jnp.take(src, idx, axis=0)
    n = idx.shape[0]
    h = src.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[pl.BlockSpec((1, h), lambda i, idx_ref: (idx_ref[i], 0))],
        out_specs=pl.BlockSpec((1, h), lambda i, idx_ref: (i, 0)),
    )
    return pl.pallas_call(
        _gather_kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, h), src.dtype),
        interpret=(impl == "interpret") or _interpret(),
    )(idx, src)


def _make_combine_kernel(top_k):
    def kernel(dest_ref, g_ref, *refs):
        del dest_ref
        y_refs, out_ref = refs[:top_k], refs[top_k]
        acc = jnp.zeros(out_ref.shape, jnp.float32)
        for c in range(top_k):
            acc += g_ref[...][0, c] * y_refs[c][...].astype(jnp.float32)
        out_ref[...] = acc.astype(out_ref.dtype)
    return kernel


def _combine_rows(y, gates, dest2, impl, out_dtype=None):
    """out[t] = sum_c gates[t, c] * y[dest2[t, c]] — the scatter-back,
    expressed as k gathers + an f32 weighted add per token row."""
    n, k = dest2.shape
    out_dtype = out_dtype or y.dtype
    if impl == "composed":
        rows = jnp.take(y, dest2.reshape(n * k), axis=0).reshape(n, k, -1)
        return jnp.sum(rows.astype(jnp.float32) *
                       gates[..., None].astype(jnp.float32),
                       axis=1).astype(out_dtype)
    h = y.shape[1]
    in_specs = [pl.BlockSpec((1, k), lambda i, d: (i, 0))]
    for c in range(k):
        in_specs.append(pl.BlockSpec(
            (1, h), functools.partial(
                lambda i, d, _c: (d[i, _c], 0), _c=c)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h), lambda i, d: (i, 0)),
    )
    return pl.pallas_call(
        _make_combine_kernel(k), grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, h), out_dtype),
        interpret=(impl == "interpret") or _interpret(),
    )(dest2, gates, *([y] * k))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_dispatch(xt, src_tok, dest2, impl):
    """Grouped-layout gather with a GATHER-ONLY backward: the cotangent
    of ``xs[i] = xt[src_tok[i]]`` is a unit-gate combine through the same
    destination map — no [kn, h] scatter ever lowers."""
    return _gather_rows(xt, src_tok, impl)


def _fused_dispatch_fwd(xt, src_tok, dest2, impl):
    return _gather_rows(xt, src_tok, impl), (dest2,)


def _fused_dispatch_bwd(impl, res, g):
    (dest2,) = res
    ones = jnp.ones(dest2.shape, jnp.float32)
    # the gather preserves dtype, so the cotangent's dtype IS xt's
    d_xt = _combine_rows(g, ones, dest2, impl, out_dtype=g.dtype)
    return d_xt, None, None


_fused_dispatch.defvjp(_fused_dispatch_fwd, _fused_dispatch_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_combine(ys, gates, dest2, g2f, impl):
    """Weighted scatter-back with a gather-only backward (``g2f`` maps
    each grouped row back to its flat (token, choice) row)."""
    return _combine_rows(ys, gates, dest2, impl)


def _fused_combine_fwd(ys, gates, dest2, g2f, impl):
    return _combine_rows(ys, gates, dest2, impl), (ys, gates, dest2, g2f)


def _fused_combine_bwd(impl, res, d_out):
    ys, gates, dest2, g2f = res
    n, k = dest2.shape
    kn = n * k
    src_tok = g2f // k
    gate_sorted = jnp.take(gates.reshape(kn), g2f)
    d_ys = (_gather_rows(d_out, src_tok, impl).astype(jnp.float32) *
            gate_sorted[:, None]).astype(ys.dtype)
    y_rows = _gather_rows(ys, dest2.reshape(kn), impl).reshape(n, k, -1)
    d_gates = jnp.sum(d_out[:, None, :].astype(jnp.float32) *
                      y_rows.astype(jnp.float32), axis=-1
                      ).astype(gates.dtype)
    return d_ys, d_gates, None, None


_fused_combine.defvjp(_fused_combine_fwd, _fused_combine_bwd)


# ---------------------------------------------------------------------------
# the fused dropless MoE MLP
# ---------------------------------------------------------------------------

def fused_moe_mlp(x, wg, w_gate, w_up, w_down, *, top_k, impl=None):
    """Dropless routed expert FFN, fused dispatch: [b, s, h] ->
    ([b, s, h], aux). Row order matches ``_moe_mlp_gmm``'s stable sort
    exactly (token-major positions), so parity with the composed paths
    is tolerance-tight. Executed FLOPs == activated FLOPs — no capacity
    padding, no drops; ``capacity_factor`` does not apply."""
    from ..grouped_matmul import grouped_matmul

    if impl is None:
        impl = resolve("moe_dispatch")[0]
    b, s, h = x.shape
    n = b * s
    e = wg.shape[1]
    if e > MAX_EXPERTS:
        raise ValueError(
            f"fused MoE dispatch supports <= {MAX_EXPERTS} experts "
            f"(lane-dim constraint), got {e}; use FLAGS_moe_dispatch="
            f"'index'")
    kn = top_k * n

    xt = x.reshape(n, h)
    gate_v, gate_i_f, pos_f, counts_f, aux = fused_route(xt, wg, top_k,
                                                         impl)
    # back to ints OUTSIDE the custom-vjp boundary (nondiff converts)
    gate_i = gate_i_f.astype(jnp.int32)
    pos = pos_f.astype(jnp.int32)
    counts = counts_f.astype(jnp.int32)

    # dest[r] = grouped row of flat (token, choice) r: expert block offset
    # + position-in-expert (both from the routing kernel — no argsort)
    offsets = jnp.cumsum(counts) - counts                  # exclusive [e]
    dest2 = (jnp.take(offsets, gate_i) + pos).astype(jnp.int32)  # [n, k]
    dest = dest2.reshape(kn)
    rng = jnp.arange(kn, dtype=jnp.int32)
    # the ONE int32 scatter: grouped row -> flat row (and token = r // k)
    g2f = jnp.zeros((kn,), jnp.int32).at[dest].set(rng)
    src_tok = g2f // top_k

    xs = _fused_dispatch(xt, src_tok, dest2, impl)         # [kn, h] grouped
    g_proj = grouped_matmul(xs, w_gate, counts)
    u_proj = grouped_matmul(xs, w_up, counts)
    act = jax.nn.silu(g_proj) * u_proj
    ys = grouped_matmul(act, w_down, counts)               # [kn, h]

    out = _fused_combine(ys, gate_v, dest2, g2f, impl)
    return out.reshape(b, s, h).astype(x.dtype), aux


register_kernel(
    "moe_dispatch",
    pallas=functools.partial(fused_moe_mlp, impl="pallas"),
    composed=functools.partial(fused_moe_mlp, impl="composed"),
    doc="dropless MoE routing+dispatch: one routing kernel (top-k + "
        "sort-by-expert counters), scalar-prefetch gathers, gather-only "
        "VJPs, grouped_matmul FFN")
