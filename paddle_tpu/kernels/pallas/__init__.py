"""Pallas fused-op library (the operators/fused/ role, TPU-native).

Each module ships one fused op as a matched pair — the Pallas TPU kernel
and its composed-XLA twin (identical math + custom-VJP structure) — and
registers both through ``kernels.registry``:

- ``rmsnorm``: RMSNorm and RMSNorm+residual, fwd + VJP in single kernels
  (the FlashAttention lesson applied to norms: the f32 normalize never
  round-trips the activation through HBM twice);
- ``rope``: rotate-half rotary embedding, fwd + VJP (the VJP is the
  inverse rotation — no residuals beyond the input positions);
- ``moe_dispatch``: dropless MoE routing/dispatch — top-k select +
  position-in-expert (the "sort by expert") in ONE sequential-grid
  kernel, row movement through scalar-prefetch gather/combine kernels
  with gather-only VJPs, feeding ``kernels.grouped_matmul``;
- ``paged_attention``: decode/window attention straight against the
  ``serving.paged_kv`` page table (per-page online softmax) instead of
  gather-then-attend.

Import order matters only in that importing this package populates the
registry; call sites go through ``kernels.registry.resolve``.
"""
from . import moe_dispatch, paged_attention, rmsnorm, rope  # noqa: F401

__all__ = ["rmsnorm", "rope", "moe_dispatch", "paged_attention"]
