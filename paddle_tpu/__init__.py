"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's API surface.

Brand-new design over JAX/XLA/Pallas (see SURVEY.md for the reference map):
eager Tensors dispatch per-op to jitted XLA executables, autograd is a
define-by-run tape whose backward runs cached jitted vjps, and distributed
training is GSPMD over a `jax.sharding.Mesh` instead of NCCL process groups.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .framework import (  # noqa: F401
    bool_ as bool,
    uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128,
    get_default_dtype, set_default_dtype,
    CPUPlace, CUDAPlace, TPUPlace,
    get_device, set_device, seed, get_rng_state, set_rng_state,
    is_compiled_with_tpu, set_flags, get_flags,
)
from .core import Tensor, no_grad, enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .framework.dtype import iinfo, finfo  # noqa: F401
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation  # noqa: F401

# paddle-compat: `paddle.Tensor` + creation entry point
from .ops.creation import to_tensor  # noqa: F401


def is_grad_enabled_():  # pragma: no cover - compat shim
    return is_grad_enabled()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """Functional gradient (paddle.grad equivalent, reference: partial_grad_engine.cc).

    Eager implementation: run backward on a copy of the graph and collect
    .grad of the requested inputs without touching their existing .grad.
    """
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = [t.grad for t in ins]
    for t in ins:
        t.grad = None
    gouts = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs] * len(outs)
    for o, g in zip(outs, gouts):
        o.backward(g, retain_graph=True)
    results = []
    for t, s in zip(ins, saved):
        if t.grad is None and not allow_unused:
            raise RuntimeError(f"grad: input {t.name} unused in graph")
        results.append(t.grad)
        t.grad = s
    return results


def disable_static(place=None):
    from .static import compat

    compat.disable_static()
    return None


def enable_static():
    """Enter static-graph compat mode: ops record into the default Program
    (replayed by static.Executor.run) while the build runs eagerly on
    placeholder values. See static/compat.py."""
    from .static import compat

    compat.enable_static()


def in_dynamic_mode():
    from .static import compat

    return not compat.in_static_mode()


in_dygraph_mode = in_dynamic_mode

# Subpackages (each guarded so the core imports even mid-build).
def _try_import(names):
    import importlib

    for n in names:
        try:
            globals()[n] = importlib.import_module(f".{n}", __name__)
        except ImportError:
            pass


_try_import(["nn", "optimizer", "io", "amp", "jit", "metric", "vision",
              "distributed", "regularizer", "autograd", "profiler", "text",
              "distribution", "static", "incubate", "device", "hapi",
              "inference", "utils", "fft", "signal", "sparse", "onnx",
              "version", "sysconfig", "quantization", "analysis",
              "observability"])
try:
    from .hapi import Model, summary, flops  # noqa: F401,E402
    from .hapi import hub  # noqa: F401,E402
    from .hapi import callbacks  # noqa: F401,E402
except ImportError:
    pass
from .nn.layer.layers import ParamAttr  # noqa: E402,F401

try:
    from .framework.io import save, load  # noqa: F401,E402
except ImportError:
    pass

# -- reference top-level long tail -------------------------------------------
from .framework.place import CUDAPinnedPlace, NPUPlace  # noqa: F401,E402
from .framework import dtype as dtype  # noqa: F401,E402  (paddle.dtype module-alias)
from .distributed.parallel import DataParallel  # noqa: F401,E402
from . import compat  # noqa: F401,E402
from . import reader  # noqa: F401,E402
from . import dataset  # noqa: F401,E402
from . import cost_model  # noqa: F401,E402
from .ops.creation import create_parameter  # noqa: F401,E402


def shape(x):
    """Tensor of x's shape (reference layers.shape returns an int32 tensor)."""
    import numpy as _np

    return to_tensor(_np.asarray(x.shape, "int32"))


def rank(x):
    """0-d int32 tensor holding x's ndim (reference layers.rank)."""
    import numpy as _np

    return to_tensor(_np.asarray(len(x.shape), "int32"))


def broadcast_shape(x_shape, y_shape):
    import numpy as _np

    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def is_complex(x):
    return "complex" in str(x.dtype)


def is_floating_point(x):
    return "float" in str(x.dtype) and "complex" not in str(x.dtype)


def is_integer(x):
    d = str(x.dtype)
    return "int" in d and "bool" not in d


def tolist(x):
    return x.tolist()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """numpy-backed print options (reference tensor print formatting)."""
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op: the reference installs C++ fatal-signal dumpers; XLA does not."""
    return None


def get_cuda_rng_state():
    """API-compat: no CUDA generator exists on TPU builds (empty state)."""
    return []


def set_cuda_rng_state(state):
    return None


def check_shape(shape):
    """Validate a shape argument the way reference layers.utils.check_shape
    does (positive/-1 dims only)."""
    for d in shape:
        d = int(d)
        if d < -1 or d == 0:
            raise ValueError(f"invalid dim {d} in shape {list(shape)}")
    return True


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference paddle.batch): groups an iterable
    sample reader into lists of batch_size samples."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def _module_inplace(name):
    def fn(x, *args, **kwargs):
        return getattr(x, name)(*args, **kwargs)

    fn.__name__ = name
    fn.__doc__ = f"Module-level alias of Tensor.{name} (inplace)."
    return fn


reshape_ = _module_inplace("reshape_")
squeeze_ = _module_inplace("squeeze_")
unsqueeze_ = _module_inplace("unsqueeze_")
tanh_ = _module_inplace("tanh_")
scatter_ = _module_inplace("scatter_")
