"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's API surface.

Brand-new design over JAX/XLA/Pallas (see SURVEY.md for the reference map):
eager Tensors dispatch per-op to jitted XLA executables, autograd is a
define-by-run tape whose backward runs cached jitted vjps, and distributed
training is GSPMD over a `jax.sharding.Mesh` instead of NCCL process groups.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .framework import (  # noqa: F401
    bool_ as bool,
    uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128,
    get_default_dtype, set_default_dtype,
    CPUPlace, CUDAPlace, TPUPlace,
    get_device, set_device, seed, get_rng_state, set_rng_state,
    is_compiled_with_tpu, set_flags, get_flags,
)
from .core import Tensor, no_grad, enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .framework.dtype import iinfo, finfo  # noqa: F401
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation  # noqa: F401

# paddle-compat: `paddle.Tensor` + creation entry point
from .ops.creation import to_tensor  # noqa: F401


def is_grad_enabled_():  # pragma: no cover - compat shim
    return is_grad_enabled()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """Functional gradient (paddle.grad equivalent, reference: partial_grad_engine.cc).

    Eager implementation: run backward on a copy of the graph and collect
    .grad of the requested inputs without touching their existing .grad.
    """
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = [t.grad for t in ins]
    for t in ins:
        t.grad = None
    gouts = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs] * len(outs)
    for o, g in zip(outs, gouts):
        o.backward(g, retain_graph=True)
    results = []
    for t, s in zip(ins, saved):
        if t.grad is None and not allow_unused:
            raise RuntimeError(f"grad: input {t.name} unused in graph")
        results.append(t.grad)
        t.grad = s
    return results


def disable_static(place=None):  # dygraph is the only mode; compat no-op
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is dygraph-first; use paddle_tpu.jit.to_static for graph capture"
    )


def in_dynamic_mode():
    return True


in_dygraph_mode = in_dynamic_mode

# Subpackages (each guarded so the core imports even mid-build).
def _try_import(names):
    import importlib

    for n in names:
        try:
            globals()[n] = importlib.import_module(f".{n}", __name__)
        except ImportError:
            pass


_try_import(["nn", "optimizer", "io", "amp", "jit", "metric", "vision",
              "distributed", "regularizer", "autograd", "profiler", "text",
              "distribution", "static", "incubate", "device", "hapi",
              "inference", "utils", "fft", "signal", "sparse", "onnx",
              "version", "sysconfig", "quantization"])
try:
    from .hapi import Model, summary, flops  # noqa: F401,E402
    from .hapi import hub  # noqa: F401,E402
    from .hapi import callbacks  # noqa: F401,E402
except ImportError:
    pass
from .nn.layer.layers import ParamAttr  # noqa: E402,F401

try:
    from .framework.io import save, load  # noqa: F401,E402
except ImportError:
    pass
