"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py +
paddle/fluid/operators/viterbi_decode_op.h).

TPU-native: the whole DP is one ``lax.scan`` over time inside a single
primitive — scores/history stay on-device, backtrace is a second scan.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.dispatch import primitive
from ..nn.layer.layers import Layer


@primitive("viterbi_decode", nondiff=True)
def _viterbi(potentials, transition, lengths, include_bos_eos_tag=True):
    """potentials [B,T,N], transition [N,N], lengths [B] -> (scores[B], path[B,T])."""
    B, T, N = potentials.shape
    emis = jnp.swapaxes(potentials, 0, 1)  # [T,B,N]
    if include_bos_eos_tag:
        # reference semantics: tag N-2 is BOS, N-1 is EOS
        alpha0 = emis[0] + transition[N - 2][None, :]
    else:
        alpha0 = emis[0]

    steps = jnp.arange(1, T)

    def step(alpha, inp):
        e_t, t_idx = inp  # e_t [B,N]
        # score[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + transition[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)  # [B,N]
        best_score = jnp.max(scores, axis=1) + e_t
        valid = (t_idx < lengths)[:, None]  # rows past length keep state
        new_alpha = jnp.where(valid, best_score, alpha)
        return new_alpha, best_prev

    alpha_T, history = lax.scan(step, alpha0, (emis[1:], steps))  # history [T-1,B,N]
    if include_bos_eos_tag:
        last = alpha_T + transition[:, N - 1][None, :]
    else:
        last = alpha_T
    scores = jnp.max(last, axis=-1)
    last_tag = jnp.argmax(last, axis=-1)  # [B]

    # backtrace: walk history from the back; entries at t >= length are no-ops
    def back(tag, inp):
        hist_t, t_idx = inp  # [B,N], scalar
        prev = jnp.take_along_axis(hist_t, tag[:, None], axis=-1)[:, 0]
        valid = t_idx < (lengths - 1)
        new_tag = jnp.where(valid, prev, tag)
        return new_tag, new_tag

    tags_rev_init = last_tag
    _, prev_tags = lax.scan(back, tags_rev_init, (history[::-1], steps[::-1] - 1))
    # path = [prev_tags reversed..., last_tag] trimmed per row by length
    path = jnp.concatenate([prev_tags[::-1], last_tag[None, :]], axis=0)  # [T,B]
    path = jnp.swapaxes(path, 0, 1).astype(jnp.int64)  # [B,T]
    return scores, path


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Returns (scores, paths). paths is [B, T] with entries beyond each row's
    length repeating the row's last valid tag (callers trim by length,
    matching the reference's LoD-trimmed output)."""
    return _viterbi(potentials, transition_params, lengths,
                    include_bos_eos_tag=bool(include_bos_eos_tag))


class ViterbiDecoder(Layer):
    """Layer wrapper (reference text/viterbi_decode.py ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
