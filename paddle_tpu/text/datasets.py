"""Text datasets (reference: python/paddle/text/datasets/).

Zero-egress environment: each dataset parses the reference's on-disk archive
format from a local ``data_file`` and raises a clear error when absent
(download=True cannot fetch). Formats match the reference loaders:
UCIHousing (whitespace floats), Imdb (aclImdb tar), Imikolov (ptb tar),
Movielens (ml-1m zip), Conll05st (tarred column files), WMT14/16 (parallel
corpus tars).
"""
from __future__ import annotations

import io
import os
import re
import tarfile
import zipfile

import numpy as np

from ..io import Dataset


def _require(data_file, name, hint):
    if data_file is None or not os.path.exists(data_file):
        raise RuntimeError(
            f"{name}: automatic download is unavailable in this environment; "
            f"pass data_file pointing at a local copy ({hint})")
    return data_file


class UCIHousing(Dataset):
    """Boston housing regression set (reference text/datasets/uci_housing.py:78).

    data_file: whitespace-separated rows of 14 floats (13 features + price).
    """

    FEATURE_NUM = 14

    def __init__(self, data_file=None, mode="train", download=False):
        assert mode in ("train", "test")
        _require(data_file, "UCIHousing", "housing.data, 14 columns per row")
        self.mode = mode
        self._load_data(data_file)

    def _load_data(self, path, ratio=0.8):
        data = np.fromfile(path, sep=" ", dtype=np.float32)
        data = data.reshape(data.shape[0] // self.FEATURE_NUM, self.FEATURE_NUM)
        maximums = data.max(axis=0)
        minimums = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(self.FEATURE_NUM - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return np.asarray(row[:-1], "float32"), np.asarray(row[-1:], "float32")

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference text/datasets/imdb.py): aclImdb tarball with
    {mode}/pos/*.txt and {mode}/neg/*.txt members; builds a frequency-ranked
    word index and returns (int64 ids, int64 label)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=False):
        assert mode in ("train", "test")
        _require(data_file, "Imdb", "aclImdb_v1.tar.gz")
        self.mode = mode
        self.docs, self.labels = [], []
        self._load(data_file, cutoff)

    def _tokenize(self, text):
        return re.sub(r"[^a-z\s]", "", text.lower()).split()

    def _load(self, data_file, cutoff):
        """One pass over the archive: frequency counts over all four splits
        (dict matches the reference's train+test vocabulary) while keeping the
        requested split's token lists; ids assigned afterwards."""
        freq = {}
        kept = []  # (tokens, label) for self.mode
        any_split = re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        mine = re.compile(f"aclImdb/{self.mode}/((pos)|(neg))/.*\\.txt$")
        with tarfile.open(data_file) as tf:
            for member in tf:
                if not any_split.match(member.name):
                    continue
                tokens = self._tokenize(
                    tf.extractfile(member).read().decode("latin-1"))
                for w in tokens:
                    freq[w] = freq.get(w, 0) + 1
                if mine.match(member.name):
                    kept.append((tokens, 0 if "/pos/" in member.name else 1))
        freq = {w: c for w, c in freq.items() if c >= cutoff}
        words = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        self.word_idx = {w: i for i, (w, _) in enumerate(words)}
        unk = self.word_idx["<unk>"] = len(words)
        for tokens, label in kept:
            self.docs.append(np.asarray(
                [self.word_idx.get(w, unk) for w in tokens], "int64"))
            self.labels.append(np.int64(label))

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model set (reference text/datasets/imikolov.py): tarball
    with simple-examples/data/ptb.{train,valid}.txt; data_type 'NGRAM' yields
    fixed n-grams, 'SEQ' yields (input, target) shifted sequences."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=False):
        assert data_type.upper() in ("NGRAM", "SEQ")
        assert mode in ("train", "test")
        _require(data_file, "Imikolov", "simple-examples.tgz (PTB)")
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.mode = mode
        self.min_word_freq = min_word_freq
        self.word_idx = self._build_dict(data_file)
        self.data = self._load_anno(data_file)

    def _member(self, tf, split):
        name = f"./simple-examples/data/ptb.{split}.txt"
        for cand in (name, name[2:]):
            try:
                return tf.extractfile(cand).read().decode("utf-8")
            except KeyError:
                continue
        raise RuntimeError(f"Imikolov: member {name} missing from archive")

    def _build_dict(self, data_file):
        freq = {}
        with tarfile.open(data_file) as tf:
            for line in self._member(tf, "train").splitlines():
                for w in line.strip().split():
                    freq[w] = freq.get(w, 0) + 1
        freq = {w: c for w, c in freq.items() if c >= self.min_word_freq}
        freq.pop("<unk>", None)
        words = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        word_idx = {w: i for i, (w, _) in enumerate(words)}
        word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self, data_file):
        split = "train" if self.mode == "train" else "valid"
        unk = self.word_idx["<unk>"]
        out = []
        with tarfile.open(data_file) as tf:
            for line in self._member(tf, split).splitlines():
                if self.data_type == "NGRAM":
                    assert self.window_size > -1, "NGRAM needs window_size > 0"
                    words = ["<s>"] + line.strip().split() + ["<e>"]
                    ids = [self.word_idx.get(w, unk) for w in words]
                    for i in range(self.window_size, len(ids)):
                        out.append(np.asarray(ids[i - self.window_size:i + 1], "int64"))
                else:
                    words = ["<s>"] + line.strip().split() + ["<e>"]
                    ids = [self.word_idx.get(w, unk) for w in words]
                    out.append((np.asarray(ids[:-1], "int64"),
                                np.asarray(ids[1:], "int64")))
        return out

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-1M ratings (reference text/datasets/movielens.py): ml-1m.zip
    with users.dat / movies.dat / ratings.dat ('::'-separated). Yields
    (user_id, gender, age, job, movie_id, title_ids, categories_onehot, rating).
    """

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        assert mode in ("train", "test")
        _require(data_file, "Movielens", "ml-1m.zip")
        self.mode = mode
        self.test_ratio = test_ratio
        self.rand_seed = rand_seed
        self._load_meta(data_file)

    def _read(self, zf, name):
        for cand in (f"ml-1m/{name}", name):
            try:
                return zf.read(cand).decode("latin-1")
            except KeyError:
                continue
        raise RuntimeError(f"Movielens: {name} missing from archive")

    def _load_meta(self, data_file):
        with zipfile.ZipFile(data_file) as zf:
            users, movies, ratings = (self._read(zf, n) for n in
                                      ("users.dat", "movies.dat", "ratings.dat"))
        self.user_info = {}
        for line in users.splitlines():
            if not line.strip():
                continue
            uid, gender, age, job, _zip = line.split("::")
            self.user_info[int(uid)] = (
                int(uid), 0 if gender == "M" else 1,
                self.AGES.index(int(age)) if int(age) in self.AGES else 0,
                int(job))
        # title word + category vocabularies
        titles, cats = set(), set()
        movie_rows = []
        for line in movies.splitlines():
            if not line.strip():
                continue
            mid, title, genres = line.split("::")
            title = re.sub(r"\(\d{4}\)$", "", title).strip()
            words = title.lower().split()
            gs = genres.strip().split("|")
            titles.update(words)
            cats.update(gs)
            movie_rows.append((int(mid), words, gs))
        self.title_idx = {w: i for i, w in enumerate(sorted(titles))}
        self.cat_idx = {c: i for i, c in enumerate(sorted(cats))}
        self.movie_info = {}
        for mid, words, gs in movie_rows:
            tids = np.asarray([self.title_idx[w] for w in words], "int64")
            onehot = np.zeros(len(self.cat_idx), "float32")
            for g in gs:
                onehot[self.cat_idx[g]] = 1.0
            self.movie_info[mid] = (mid, tids, onehot)
        rng = np.random.RandomState(self.rand_seed)
        self.samples = []
        for line in ratings.splitlines():
            if not line.strip():
                continue
            uid, mid, rating, _ts = line.split("::")
            uid, mid = int(uid), int(mid)
            if uid not in self.user_info or mid not in self.movie_info:
                continue
            is_test = rng.rand() < self.test_ratio
            if (self.mode == "test") == is_test:
                self.samples.append((uid, mid, float(rating)))

    def __getitem__(self, idx):
        uid, mid, rating = self.samples[idx]
        u = self.user_info[uid]
        m = self.movie_info[mid]
        return (np.int64(u[0]), np.int64(u[1]), np.int64(u[2]), np.int64(u[3]),
                np.int64(m[0]), m[1], m[2], np.float32(rating))

    def __len__(self):
        return len(self.samples)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference text/datasets/conll05.py): expects a tarball
    with conll05st-release/test.wsj word/prop column files plus word/verb/target
    dicts. Yields (word_ids, ctx_n2/n1/0/p1/p2, verb_id, mark, label_ids)."""

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, download=False):
        _require(data_file, "Conll05st", "conll05st-tests.tar.gz")
        _require(word_dict_file, "Conll05st", "wordDict.txt")
        _require(verb_dict_file, "Conll05st", "verbDict.txt")
        _require(target_dict_file, "Conll05st", "targetDict.txt")
        self.word_dict = self._load_dict(word_dict_file)
        self.verb_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_label_dict(target_dict_file)
        self.samples = self._load_anno(data_file)

    @staticmethod
    def _load_dict(path):
        d = {}
        with open(path) as f:
            for i, line in enumerate(f):
                d[line.strip()] = i
        return d

    @staticmethod
    def _load_label_dict(path):
        """File order sets ids; each B-X reserves the next id for its I-X
        (reference conll05.py load_label_dict)."""
        d = {}
        index = 0
        with open(path) as f:
            for line in f:
                label = line.strip()
                if not label:
                    continue
                if label.startswith("B-"):
                    d[label] = index
                    d[f"I-{label[2:]}"] = index + 1
                    index += 2
                else:
                    d[label] = index
                    index += 1
        return d

    def _load_anno(self, data_file):
        import gzip as _gzip

        sentences = []
        with tarfile.open(data_file) as tf:
            words_member = props_member = None
            for m in tf.getmembers():
                if m.name.endswith("words.gz"):
                    words_member = m
                elif m.name.endswith("props.gz"):
                    props_member = m
            if words_member is None or props_member is None:
                raise RuntimeError("Conll05st: words.gz/props.gz missing")
            words_txt = _gzip.decompress(tf.extractfile(words_member).read()).decode()
            props_txt = _gzip.decompress(tf.extractfile(props_member).read()).decode()
        sent, props = [], []
        samples = []
        prop_lines = iter(props_txt.splitlines())
        for wline in words_txt.splitlines():
            pline = next(prop_lines, "")
            if wline.strip():
                sent.append(wline.strip())
                props.append(pline.strip().split())
            else:
                if sent and props and props[0]:
                    samples.extend(self._make_samples(sent, props))
                sent, props = [], []
        if sent and props and props[0]:
            samples.extend(self._make_samples(sent, props))
        return samples

    def _make_samples(self, sent, props):
        unk = self.word_dict.get("<unk>", 0)
        n = len(sent)
        word_ids = np.asarray([self.word_dict.get(w.lower(), unk) for w in sent],
                              "int64")
        samples = []
        n_props = len(props[0]) - 1 if props and props[0] else 0
        for col in range(1, n_props + 1):
            verb, verb_pos = None, -1
            labels = []
            for i, row in enumerate(props):
                tag = row[col] if col < len(row) else "*"
                labels.append(tag)
                if "(V*" in tag:
                    verb, verb_pos = props[i][0], i
            if verb is None or verb == "-":
                continue
            ctx = [max(0, min(n - 1, verb_pos + d)) for d in (-2, -1, 0, 1, 2)]
            ctx_ids = [word_ids[c] for c in ctx]
            mark = np.zeros(n, "int64")
            mark[verb_pos] = 1
            label_ids = np.asarray(
                [self.label_dict.get(self._iobes(l), 0) for l in labels], "int64")
            samples.append((word_ids,
                            *(np.full(n, c, "int64") for c in ctx_ids),
                            np.full(n, self.verb_dict.get(verb, 0), "int64"),
                            mark, label_ids))
        return samples

    @staticmethod
    def _iobes(tag):
        if tag == "*":
            return "O"
        m = re.match(r"\((\S+?)\*", tag)
        return f"B-{m.group(1)}" if m else "O"

    def get_dict(self):
        return self.word_dict, self.verb_dict, self.label_dict

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMTBase(Dataset):
    START = "<s>"
    END = "<e>"
    UNK = "<unk>"

    def _build_ids(self, pairs, src_dict, trg_dict):
        unk_s = src_dict[self.UNK]
        unk_t = trg_dict[self.UNK]
        data = []
        for src, trg in pairs:
            s = [src_dict.get(w, unk_s) for w in src.split()]
            t = ([trg_dict[self.START]]
                 + [trg_dict.get(w, unk_t) for w in trg.split()]
                 + [trg_dict[self.END]])
            if not s:
                continue
            data.append((np.asarray(s, "int64"),
                         np.asarray(t[:-1], "int64"),
                         np.asarray(t[1:], "int64")))
        return data

    def _freq_dict(self, texts, dict_size):
        freq = {}
        for text in texts:
            for w in text.split():
                freq[w] = freq.get(w, 0) + 1
        words = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        vocab = [self.START, self.END, self.UNK] + [w for w, _ in words]
        vocab = vocab[:dict_size] if dict_size > 0 else vocab
        return {w: i for i, w in enumerate(vocab)}

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(_WMTBase):
    """WMT'14 en→fr (reference text/datasets/wmt14.py): tarball with
    {mode}/*.src (en) and matching *.trg (fr) parallel line files."""

    def __init__(self, data_file=None, mode="train", dict_size=-1, download=False):
        assert mode in ("train", "test", "gen")
        _require(data_file, "WMT14", "wmt14 tarball with train/ test/ gen/ pairs")
        self.mode = mode
        pairs = self._read_pairs(data_file, mode)
        # vocabulary always comes from the training corpus so train/test ids
        # agree (reference wmt14.py builds one dict from train)
        try:
            dict_pairs = pairs if mode == "train" else \
                self._read_pairs(data_file, "train")
        except RuntimeError:
            dict_pairs = pairs
        self.src_dict = self._freq_dict([p[0] for p in dict_pairs], dict_size)
        self.trg_dict = self._freq_dict([p[1] for p in dict_pairs], dict_size)
        self.data = self._build_ids(pairs, self.src_dict, self.trg_dict)

    def _read_pairs(self, data_file, mode):
        srcs, trgs = {}, {}
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if f"/{mode}/" not in f"/{m.name}" and not m.name.startswith(mode):
                    continue
                if base.endswith(".src"):
                    srcs[base[:-4]] = tf.extractfile(m).read().decode("utf-8")
                elif base.endswith(".trg"):
                    trgs[base[:-4]] = tf.extractfile(m).read().decode("utf-8")
        pairs = []
        for k in sorted(srcs):
            if k in trgs:
                for s, t in zip(srcs[k].splitlines(), trgs[k].splitlines()):
                    if s.strip() and t.strip():
                        pairs.append((s.strip().lower(), t.strip().lower()))
        if not pairs:
            raise RuntimeError(f"WMT14: no {self.mode} .src/.trg pairs in archive")
        return pairs

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(_WMTBase):
    """WMT'16 en↔de (reference text/datasets/wmt16.py): tarball with
    wmt16/{train,val,test} tab-separated 'src\\ttrg' lines."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=False):
        assert mode in ("train", "val", "test")
        _require(data_file, "WMT16", "wmt16.tar.gz with wmt16/{train,val,test}")
        self.mode = mode
        self.lang = lang
        pairs = self._read_pairs(data_file, mode)
        # one vocabulary, built from the training split (reference wmt16.py)
        try:
            dict_pairs = pairs if mode == "train" else \
                self._read_pairs(data_file, "train")
        except RuntimeError:
            dict_pairs = pairs
        self.src_dict = self._freq_dict([p[0] for p in dict_pairs], src_dict_size)
        self.trg_dict = self._freq_dict([p[1] for p in dict_pairs], trg_dict_size)
        self.data = self._build_ids(pairs, self.src_dict, self.trg_dict)

    def _read_pairs(self, data_file, mode):
        text = None
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if os.path.basename(m.name) == mode:
                    text = tf.extractfile(m).read().decode("utf-8")
                    break
        if text is None:
            raise RuntimeError(f"WMT16: member '{mode}' missing from archive")
        pairs = []
        for line in text.splitlines():
            parts = line.split("\t")
            if len(parts) != 2:
                continue
            src, trg = (parts if self.lang == "en" else parts[::-1])
            if src.strip() and trg.strip():
                pairs.append((src.strip().lower(), trg.strip().lower()))
        return pairs

    def get_dict(self, lang="en", reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d
