"""Define-by-run autograd engine.

Mirrors the reference's eager autograd (GradNodeBase/Edge graph +
queue-with-in-degree backward walk, paddle/fluid/eager/grad_node_info.h:77 and
paddle/fluid/eager/backward.cc:79) — but each node's grad computation is a
cached jitted ``jax.vjp`` of the recorded pure op (see core/dispatch.py), so
backward math runs as compiled XLA, not hand-written kernels.
"""
from __future__ import annotations

import contextlib
from collections import deque
from typing import Dict, List, Optional

import jax.numpy as jnp

_GRAD_ENABLED = [True]


@contextlib.contextmanager
def no_grad():
    """paddle.no_grad equivalent: suspend tape recording."""
    _GRAD_ENABLED.append(False)
    try:
        yield
    finally:
        _GRAD_ENABLED.pop()


@contextlib.contextmanager
def enable_grad():
    _GRAD_ENABLED.append(True)
    try:
        yield
    finally:
        _GRAD_ENABLED.pop()


def is_grad_enabled() -> bool:
    return _GRAD_ENABLED[-1]


def set_grad_enabled(mode: bool):
    _GRAD_ENABLED[-1] = bool(mode)


class GradNode:
    """One recorded op on the tape.

    ``inputs`` holds the input Tensors (edges, like egr::Edge); ``primals`` the
    raw arrays saved for the vjp (TensorWrapper analogue); output metadata is
    kept to synthesize zero cotangents for unused outputs.
    """

    __slots__ = (
        "prim", "attrs", "primals", "inputs",
        "out_avals", "n_outputs", "multi_output", "__weakref__",
    )

    def __init__(self, prim, attrs, primals, inputs, outs, multi_output):
        self.prim = prim
        self.attrs = attrs
        self.primals = primals
        self.inputs = inputs  # list[Tensor]; aligned with primals positions that are tensors
        self.multi_output = multi_output
        self.out_avals = [(o.shape, o.dtype) for o in outs]
        self.n_outputs = len(outs)

    def run(self, out_cts: List[Optional[object]]):
        cts = []
        for ct, (shape, dtype) in zip(out_cts, self.out_avals):
            if ct is None:
                ct = jnp.zeros(shape, dtype)
            elif ct.dtype != dtype:
                # AMP boundaries: downstream may produce cotangents in a
                # different float dtype than this op's output
                ct = ct.astype(dtype)
            cts.append(ct)
        ct_struct = tuple(cts) if self.multi_output else cts[0]
        bwd = self.prim.bwd(self.attrs)
        return bwd(self.primals, ct_struct)


def backward(root, grad=None, retain_graph: bool = False):
    """Reverse-walk the tape from ``root``, accumulating into leaf ``.grad``.

    Mirrors egr::RunBackward (paddle/fluid/eager/backward.cc:155-261): compute
    in-degrees over reachable nodes, process a ready-queue, route each produced
    cotangent either into a leaf Tensor's .grad or into the producer node's
    pending output-cotangent slots.
    """
    from .tensor import Tensor

    node = root._grad_node
    if node is None:
        if root.stop_gradient:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True and no grad graph"
            )
        # A leaf: d(root)/d(root) accumulates directly.
        g = jnp.ones(root.shape, root.dtype) if grad is None else _raw(grad)
        _accumulate_leaf(root, g)
        return

    grad_arr = jnp.ones(root.shape, root.dtype) if grad is None else _raw(grad)

    # 1) discover reachable nodes + in-degrees (number of consumer edges).
    #    An edge exists for each non-stopped input tensor that has a producer node.
    indeg: Dict[int, int] = {}
    nodes: Dict[int, GradNode] = {}
    stack = [node]
    seen = {id(node)}
    nodes[id(node)] = node
    indeg[id(node)] = 0
    while stack:
        n = stack.pop()
        for t in n.inputs:
            if t is None:
                continue
            up = t._grad_node
            if up is None or t.stop_gradient:
                continue
            indeg[id(up)] = indeg.get(id(up), 0) + 1
            if id(up) not in seen:
                seen.add(id(up))
                nodes[id(up)] = up
                stack.append(up)

    # 2) ready-queue walk.
    pending_cts: Dict[int, List[Optional[object]]] = {
        nid: [None] * n.n_outputs for nid, n in nodes.items()
    }
    pending_cts[id(node)][root._out_index] = grad_arr

    queue = deque([node])
    while queue:
        n = queue.popleft()
        in_cts = n.run(pending_cts[id(n)])
        for t, g in zip(n.inputs, in_cts):
            if t is None:
                continue
            up = t._grad_node
            if up is None or t.stop_gradient:
                # leaf or stopped: accumulate if a usable cotangent was produced
                if up is None and not t.stop_gradient and g is not None and not _is_float0(g):
                    _accumulate_leaf(t, g)
                continue
            # edge into an upstream node: always retire the edge, even if the
            # cotangent is unusable, so the producer still gets scheduled.
            if g is not None and not _is_float0(g):
                slot = pending_cts[id(up)]
                slot[t._out_index] = g if slot[t._out_index] is None else slot[t._out_index] + g
            indeg[id(up)] -= 1
            if indeg[id(up)] == 0:
                queue.append(up)

    if not retain_graph:
        # free the graph like the reference does after backward
        for n in nodes.values():
            n.primals = None
            n.inputs = ()
        root._grad_node = None


def _accumulate_leaf(t, g):
    from .tensor import Tensor

    # in-place proxies route their gradient to the live (mutated) tensor
    target = getattr(t, "_grad_target", None)
    if target is not None:
        t = target
    if g.dtype != t.dtype:
        g = g.astype(t.dtype)
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad.data + g, stop_gradient=True)


def _is_float0(g):
    import jax

    dt = getattr(g, "dtype", None)
    return dt is not None and dt == jax.dtypes.float0


def _raw(x):
    from .tensor import Tensor

    return x.data if isinstance(x, Tensor) else x
