from .tensor import Tensor  # noqa: F401
from .autograd import no_grad, enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .dispatch import primitive, get_primitive, registry  # noqa: F401
