"""The eager Tensor: a paddle-compatible facade over ``jax.Array``.

Role of phi::DenseTensor + imperative::VarBase combined
(paddle/phi/core/dense_tensor.h:38, paddle/fluid/imperative/layer.h:66): holds
the device buffer (here an async jax.Array — dispatch is naturally non-blocking
like the reference's stream-async kernels), autograd metadata (stop_gradient,
.grad, producer GradNode edge) and the user-facing method surface.

Tensors are registered as a jax pytree node so whole programs over Tensors can
be captured by ``jax.jit`` (the @to_static path).
"""
from __future__ import annotations

import itertools
import weakref
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from .autograd import GradNode, backward as _backward_engine, is_grad_enabled

_name_counter = itertools.count()


class Tensor:
    __slots__ = (
        "data", "stop_gradient", "grad", "name", "persistable",
        "_grad_node", "_out_index", "_grad_target", "_edges", "_edges_cap",
        "trainable", "__weakref__",
    )

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data.data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        self.data = data
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self.name = name or f"tensor_{next(_name_counter)}"
        self.persistable = False
        self.trainable = not stop_gradient
        self._grad_node: Optional[GradNode] = None
        self._out_index: int = 0
        self._grad_target: Optional["Tensor"] = None
        self._edges = None  # list[(weakref(GradNode), slot)] consumers of this tensor

    # -- metadata -----------------------------------------------------------
    @property
    def shape(self):
        return list(self.data.shape)

    @property
    def dtype(self):
        return np.dtype(self.data.dtype)

    @property
    def ndim(self):
        return self.data.ndim

    def dim(self):
        return self.data.ndim

    def rank(self):
        return self.data.ndim

    @property
    def size(self):
        return int(np.prod(self.data.shape)) if self.data.shape else 1

    def numel(self):
        return self.size

    @property
    def place(self):
        from ..framework import place as place_mod

        devs = self.data.devices() if hasattr(self.data, "devices") else set()
        dev = next(iter(devs)) if devs else jax.devices()[0]
        plat = dev.platform.lower()
        if plat == "cpu":
            return place_mod.CPUPlace(dev.id)
        if plat in ("gpu", "cuda", "rocm"):
            return place_mod.CUDAPlace(dev.id)
        return place_mod.TPUPlace(dev.id)

    @property
    def is_leaf(self):
        return self._grad_node is None

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        return np.asarray(self.data)

    def item(self):
        return self.data.item()

    def tolist(self):
        return np.asarray(self.data).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self.data)
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.data.item())

    def __int__(self):
        return int(self.data.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of a multi-element Tensor is ambiguous")
        try:
            return bool(self.data.item())
        except Exception as e:
            if "Tracer" in type(e).__name__ or "Concretization" in str(type(e)):
                raise TypeError(
                    "data-dependent Python control flow on a traced Tensor: "
                    "this branch cannot be captured. Use "
                    "paddle.static.nn.cond / while_loop, or keep the if/while "
                    "simple (plain-name assignments or two-arm returns) so "
                    "paddle.jit.to_static auto-converts it "
                    "(reference: dygraph_to_static/program_translator.py)."
                ) from e
            raise

    def __len__(self):
        if not self.data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self.data.shape[0]

    def __iter__(self):
        # without this, Python falls back to __getitem__(0,1,2,...) waiting
        # for an IndexError that jnp's clamping indexing never raises — an
        # eager `for row in tensor` would spin (and compile) forever
        if not self.data.shape:
            raise TypeError("iteration over a 0-d tensor")
        return (self[i] for i in range(self.data.shape[0]))

    def __hash__(self):
        return id(self)

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        _backward_engine(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor(self.data, stop_gradient=True, name=self.name + ".detach")

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops import math as _m

        return _m.assign(self)

    # -- in-place plumbing ---------------------------------------------------
    def _rebind(self, other: "Tensor"):
        """Adopt another tensor's value + autograd identity (in-place op support).

        Every live GradNode edge that references *this* tensor (the in-place
        op's own node AND any earlier consumer) must be repointed at the
        pre-mutation version, otherwise backward either deadlocks on a
        self-referential edge or chains earlier consumers through the in-place
        node and multiplies their cotangent by it (mirrors eager TensorWrapper
        snapshotting, paddle/fluid/eager/tensor_wrapper.h).
        """
        if self._edges:
            proxy = None
            for ref, slot in self._edges:
                node = ref()
                if node is None or not node.inputs:
                    continue
                if slot < len(node.inputs) and node.inputs[slot] is self:
                    if proxy is None:
                        proxy = Tensor.__new__(Tensor)
                        proxy.data = self.data  # pre-mutation buffer
                        proxy.stop_gradient = self.stop_gradient
                        proxy.grad = None
                        proxy.name = self.name + ".prev"
                        proxy.persistable = False
                        proxy.trainable = self.trainable
                        proxy._grad_node = self._grad_node
                        proxy._out_index = self._out_index
                        proxy._edges = None
                        # leaves keep accumulating into the live tensor's .grad
                        proxy._grad_target = self if self._grad_node is None else None
                    node.inputs[slot] = proxy
        self._edges = other._edges
        self.data = other.data
        self._grad_node = other._grad_node
        self._out_index = other._out_index
        if other._grad_node is not None:
            self.stop_gradient = other.stop_gradient
        # else (e.g. in-place under no_grad): keep our own flag so a mutated
        # parameter stays trainable afterwards
        return self

    def set_value(self, value):
        arr = value.data if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(arr.shape) != tuple(self.data.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self.data.shape}")
        self.data = arr.astype(self.data.dtype)
        return self

    def __deepcopy__(self, memo):
        import copy

        cls = type(self)
        new = cls.__new__(cls)
        memo[id(self)] = new
        for holder in cls.__mro__:
            for s in getattr(holder, "__slots__", ()):
                if s == "__weakref__":
                    continue
                try:
                    v = getattr(self, s)
                except AttributeError:
                    continue
                if isinstance(v, jax.Array) or s in ("_grad_node", "_edges"):
                    object.__setattr__(new, s, v if s not in ("_grad_node", "_edges") else None)
                else:
                    object.__setattr__(new, s, copy.deepcopy(v, memo))
        # fresh identity: copies must not collide in name-keyed stores
        # (optimizer state_dict keys are f"{param.name}_{slot}")
        new.name = f"{self.name}.copy_{next(_name_counter)}"
        return new

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}{grad_info},\n"
            f"       {np.asarray(self.data)!r})"
        )

    # The op method surface (__add__, sum, reshape, matmul, ...) is attached by
    # paddle_tpu/ops/_bind.py once the op corpus is defined.


_NAN_INF_FAM = None  # lazily-bound observability family


def _count_nan_inf(op_name, dtype) -> None:
    """Record the trip in the ``nan_inf_events`` counter family (op, dtype)
    so monitors can alert on non-finite outputs without crashing the run."""
    global _NAN_INF_FAM
    try:
        if _NAN_INF_FAM is None:
            from ..observability import family

            _NAN_INF_FAM = family("nan_inf_events", ("op", "dtype"))
        _NAN_INF_FAM.inc((op_name, str(dtype)))
    except Exception:  # telemetry must never mask the trip itself
        pass


class NanStepSkipped(ArithmeticError):
    """A per-op nan/inf trip under ``FLAGS_check_nan_inf_action='skip'``:
    step-aware loops (``hapi.Model.fit``) catch this, drop the poisoned
    step (grads cleared, no optimizer update) and continue — the
    skip-and-continue contract of the fault-tolerant runtime. Outside such
    a loop it propagates like the 'raise' action."""


def _check_nan_inf(op_name, outs):
    """FLAGS_check_nan_inf per-op guard (nan_inf_utils_detail.* equivalent).

    Every trip lands a ``nan_inf_events`` row; FLAGS_check_nan_inf_action
    picks raise (default, reference behavior) vs log-and-continue vs skip
    (raise ``NanStepSkipped`` for the train loop to eat)."""
    from ..framework import flags as _flags

    for i, o in enumerate(outs):
        if not hasattr(o, "dtype") or not jnp.issubdtype(o.dtype, jnp.inexact):
            continue
        bad = int(jnp.sum(~jnp.isfinite(o)))
        if bad:
            _count_nan_inf(op_name, o.dtype)
            msg = (
                f"check_nan_inf: op '{op_name}' output {i} contains {bad} "
                f"nan/inf values (shape={tuple(o.shape)}, dtype={o.dtype})")
            action = _flags.flag("check_nan_inf_action")
            if action == "log":
                import warnings

                warnings.warn(msg, RuntimeWarning, stacklevel=3)
                continue
            if action == "skip":
                raise NanStepSkipped(msg)
            raise RuntimeError(msg)


_HOT = None  # lazily-bound (amp_state, maybe_cast_inputs, flags, profiler, time)
_static_recorder = [None]  # lazily-bound static.compat module (False = absent)


def dispatch(prim, args, attrs):
    """Run one op: unwrap -> jitted forward -> (maybe) record GradNode.

    This is the Tracer::TraceOp equivalent (paddle/fluid/imperative/tracer.cc:172):
    forward dispatch + conditional tape recording in one place.
    """
    arrays = []
    inputs = []
    any_grad = False
    for a in args:
        if isinstance(a, Tensor):
            arrays.append(a.data)
            inputs.append(a)
            if not a.stop_gradient:
                any_grad = True
        else:
            arrays.append(a if isinstance(a, jax.Array) else jnp.asarray(a))
            inputs.append(None)

    # AMP O1/O2 auto-cast hook (reference: tracer.cc:209-226 AMP pass)
    global _HOT
    if _HOT is None:  # one-time late bind (amp/flags/profiler import this module)
        from ..amp import amp_state, maybe_cast_inputs
        from ..framework import flags
        from .. import profiler
        import time

        _HOT = (amp_state, maybe_cast_inputs, flags, profiler, time)
    amp_state, maybe_cast_inputs, _flags, _profiler, _time = _HOT

    arrays_precast = arrays
    if amp_state()["enabled"]:
        arrays = maybe_cast_inputs(prim.name, arrays)

    _prof = _profiler.is_recording()
    _t0 = None
    if _prof:
        _t0 = _time.perf_counter() * 1e6

    out = prim.fwd(attrs)(*arrays)
    multi = isinstance(out, (tuple, list))
    outs_raw = tuple(out) if multi else (out,)

    if _flags.flag("benchmark") or _flags.flag("check_nan_inf"):
        for o in outs_raw:
            if hasattr(o, "block_until_ready"):
                o.block_until_ready()
        if _flags.flag("check_nan_inf"):
            _check_nan_inf(prim.name, outs_raw)
    if _prof:
        _profiler.record_op_span(prim.name, _t0)

    # static-mode shim: record the SSA node into the default Program
    # (reference: static append_op; see static/compat.py)
    if _static_recorder[0] is None:
        try:
            from ..static import compat as _compat

            _static_recorder[0] = _compat
        except ImportError:  # mid-build partial package
            _static_recorder[0] = False
    _compat = _static_recorder[0]
    if _compat and _compat.in_static_mode():
        # record against the PRE-amp-cast arrays: a cast copy has a fresh id,
        # which would sever feed placeholders from the replayed graph (the
        # replay then runs un-cast, i.e. at full precision — fine)
        _compat.record_dispatch(prim, attrs, arrays_precast, inputs,
                                outs_raw, multi)

    record = any_grad and is_grad_enabled() and not prim.nondiff
    out_tensors = [Tensor(o, stop_gradient=not record) for o in outs_raw]
    if record:
        node = GradNode(prim, attrs, tuple(arrays), inputs, outs_raw, multi)
        ref = weakref.ref(node)
        for slot, t in enumerate(inputs):
            if t is None:
                continue
            # consumer-edge backrefs so in-place mutation (_rebind) can repoint
            # every recorded edge at the pre-mutation version
            if t._edges is None:
                t._edges = []
                t._edges_cap = 32
            elif len(t._edges) >= t._edges_cap:
                live = []
                for r, s in t._edges:
                    n = r()
                    if n is not None and n.inputs:
                        live.append((r, s))
                t._edges = live
                # double the threshold when pruning freed little, so a tensor
                # consumed n times in one forward costs O(n), not O(n^2)
                t._edges_cap = max(32, 2 * len(live) + 16)
            t._edges.append((ref, slot))
        for i, t in enumerate(out_tensors):
            t._grad_node = node
            t._out_index = i
    return tuple(out_tensors) if multi else out_tensors[0]


# -- pytree registration: lets jax.jit/tree_map see through Tensors -----------

def _tensor_flatten(t: Tensor):
    return (t.data,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    return Tensor(children[0], stop_gradient=aux[0])


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
