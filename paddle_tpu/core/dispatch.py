"""Op dispatch: the PHI-kernel-registry equivalent, TPU-first.

In the reference every eager op goes through Tracer::TraceOp -> KernelFactory
(paddle/fluid/imperative/tracer.cc:172, paddle/phi/core/kernel_factory.h:222):
a registry keyed by (op, backend, layout, dtype) picking a hand-written kernel.

On TPU the kernel library is XLA, so the idiomatic equivalent is: each op is a
pure jax function; "kernel selection + caching" is a per-(op, attrs) ``jax.jit``
cache (XLA then caches per shape/dtype underneath, playing the role of the
reference's KernelKey). Backward does not use per-op hand-written grad kernels:
a cached jitted ``jax.vjp`` of the same pure function is the grad "kernel"
(recompute-based, which XLA DCEs when the primal isn't needed) — the analogue of
the reference's generated GradNode kernels (paddle/fluid/eager/auto_code_generator).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..framework import dtype as dtype_mod

# ---------------------------------------------------------------------------
# caches
# ---------------------------------------------------------------------------

_FWD_CACHE: Dict[Tuple, Callable] = {}
_BWD_CACHE: Dict[Tuple, Callable] = {}

_REGISTRY: Dict[str, "Primitive"] = {}

# Trace-cache audit extension point (paddle_tpu.analysis.retrace). When
# installed, fwd/bwd route their jitted callables through the hook so the
# auditor can attribute recompiles to cache-key drift. A single `is None`
# check when auditing is off — the default hot path is untouched.
_AUDIT_HOOK: Optional[Callable] = None


def install_audit_hook(hook: Optional[Callable]) -> None:
    """hook(op_name, stage, cache_key, jitted_fn) -> callable, or None to
    uninstall. Installed by analysis.retrace.enable()."""
    global _AUDIT_HOOK
    _AUDIT_HOOK = hook


def _op_jit(fn: Callable, op_name: str, stage: str, key: Tuple) -> Callable:
    """Jit one eager op kernel, routed through the persistent executable
    cache when it is enabled (ROADMAP PR-3 follow-up: the per-op dispatch
    caches warm-start across processes — the bench per-op table shows
    repeated sub-ms compiles every fresh process repays). The cache key is
    prim + attrs (via ``key``) + the abstract call signature CachedJit
    derives per call; with the cache disabled CachedJit is a one-flag-check
    passthrough to ``jax.jit``. Lazy import: paddle_tpu.jit sits above the
    core layer and is always imported by the time an op runs."""
    try:
        from ..jit.persistent_cache import cached_jit

        return cached_jit(fn, label=f"op:{op_name}:{stage}",
                          extra_meta=("op", op_name, stage, repr(key)))
    except ImportError:  # mid-build partial package: plain jit
        return jax.jit(fn)


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, np.dtype):
        return ("dtype", v.name)
    if isinstance(v, np.ndarray):
        return ("nda", v.tobytes(), v.shape, v.dtype.name)
    return v


def _attrs_key(attrs: dict) -> Tuple:
    return tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))


class Primitive:
    """A named pure-jax op: forward jit cache + vjp-backed backward jit cache.

    ``fn(*arrays, **attrs)`` must be pure jax. ``nondiff=True`` marks ops whose
    outputs never carry gradients (int outputs, comparisons, rng-int, ...).
    A custom vjp rule may be registered with ``defvjp`` for ops where the
    recompute-vjp fallback is wrong or wasteful; rule signature:
    ``rule(ct, out, primals, **attrs) -> tuple_of_input_cotangents_or_None``.
    """

    def __init__(self, name: str, fn: Callable, nondiff: bool = False):
        self.name = name
        self.fn = fn
        self.nondiff = nondiff
        self.vjp_rule: Optional[Callable] = None
        _REGISTRY[name] = self

    def defvjp(self, rule: Callable) -> Callable:
        self.vjp_rule = rule
        return rule

    # -- forward ------------------------------------------------------------
    def fwd(self, attrs: dict) -> Callable:
        key = (self.name, _attrs_key(attrs))
        f = _FWD_CACHE.get(key)
        if f is None:
            f = _op_jit(functools.partial(self.fn, **attrs),
                        self.name, "fwd", key)
            _FWD_CACHE[key] = f
        if _AUDIT_HOOK is not None:
            return _AUDIT_HOOK(self.name, "fwd", key, f)
        return f

    # -- backward -----------------------------------------------------------
    def bwd(self, attrs: dict) -> Callable:
        """jitted (primals, cotangents) -> input cotangents, via jax.vjp."""
        key = (self.name, _attrs_key(attrs))
        b = _BWD_CACHE.get(key)
        if b is None:
            if self.vjp_rule is not None:
                rule = self.vjp_rule

                def b(primals, ct, _rule=rule, _attrs=attrs):
                    out = self.fn(*primals, **_attrs)
                    return _rule(ct, out, primals, **_attrs)

            else:
                pfn = functools.partial(self.fn, **attrs)

                def b(primals, ct, _pfn=pfn):
                    _out, vjp = jax.vjp(_pfn, *primals)
                    return vjp(ct)

            b = _op_jit(b, self.name, "bwd", key)
            _BWD_CACHE[key] = b
        if _AUDIT_HOOK is not None:
            return _AUDIT_HOOK(self.name, "bwd", key, b)
        return b

    def __call__(self, *args, **attrs):
        from .tensor import dispatch  # local import: Tensor layer sits above dispatch

        return dispatch(self, args, attrs)


def primitive(name: str, nondiff: bool = False):
    """Decorator registering a pure jax function as a framework op."""

    def deco(fn: Callable) -> Primitive:
        return Primitive(name, fn, nondiff=nondiff)

    return deco


def get_primitive(name: str) -> Primitive:
    return _REGISTRY[name]


def registry() -> Dict[str, Primitive]:
    return _REGISTRY
