"""Shared scaffolding for the serving engines: lifecycle (start/close/
context manager), the bounded admission queue, and retrace-label
observability. ``ServingEngine`` and ``GenerationEngine`` differ in what
their worker loop DOES (micro-batch vs continuous decode), not in how it
lives — that part exists exactly once, here.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Optional

from .metrics import MetricsRegistry

__all__ = ["EngineBase", "QueueFull", "DeadlineExceeded", "EngineClosed",
           "BadRequest", "ReplicaFault", "RequestCancelled"]


def _tracer():
    """The process-wide request tracer (observability.trace): every
    admitted request gets a propagated trace ID, spans recorded from the
    engines' own timestamps."""
    from ..observability.trace.request_trace import tracer

    return tracer()


def _oom_guard(site, label=None, **ids):
    """Memory-truth OOM bracket (observability.memory): injected-fault
    site + RESOURCE_EXHAUSTED forensics around device execution."""
    from ..observability.memory import oom_guard

    return oom_guard(site, label=label, **ids)


class QueueFull(RuntimeError):
    """Admission control: the bounded request queue is at capacity."""


class EngineClosed(RuntimeError):
    """The engine is shut down; no further submissions."""


class BadRequest(ValueError):
    """Payload rejected by validation (shape/dtype/rank/length)."""


class DeadlineExceeded(TimeoutError):
    """The request expired before execution and was shed."""


class ReplicaFault(EngineClosed):
    """The replica itself failed (process crash, lost RPC connection,
    hung heartbeat) — the REPLICA-fault shape the router fences on, as
    opposed to request-scoped errors (``BadRequest``/``DeadlineExceeded``)
    that must leave a healthy replica in the candidate set."""


class RequestCancelled(RuntimeError):
    """The request was cancelled before completion (hedge first-wins,
    client cancel RPC)."""


class EngineBase:
    """Queue + condition + worker-thread lifecycle. Subclasses implement
    ``_worker`` (the loop) and may override ``_on_start`` (e.g. AOT
    warmup). Requests must carry a ``.future`` attribute."""

    _close_timeout = 30.0

    def __init__(self, name: str, qps_window_s: float = 30.0):
        self.name = name
        self.metrics = MetricsRegistry(qps_window_s=qps_window_s)
        self.metrics.gauge("queue_depth", self.queue_depth)
        # framework-wide telemetry: this engine's rows ride along in
        # observability.snapshot() under registries["serving:<name>"]
        # (weak-valued — a collected engine's rows disappear with it)
        from ..observability import register_registry

        register_registry(f"serving:{name}", self.metrics)
        self._queue: deque = deque()
        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        # a witnessed Lock works as Condition's lock: wait()'s release/
        # re-acquire pass through acquire/release, keeping the per-thread
        # held stack truthful across parks
        self._cond = threading.Condition(
            _named_lock(f"serving.Engine[{name}]._cond"))
        self._start_lock = _named_lock(f"serving.Engine[{name}]._start_lock")
        self._closed = False
        self._fenced = False
        self._thread: Optional[threading.Thread] = None
        self._flight_rec = None  # lazily-resolved process flight recorder
        # monotonically increasing weight generation this engine serves.
        # 0 = the weights the engine was constructed with; bumped by
        # swap_weights() (the post-training weight-push fast path).
        self.weight_version = 0

    def _flight(self):
        """The process flight recorder (created on first use) so executed
        batches/decode steps land in its ring automatically — None when
        the observability stack is unavailable."""
        rec = self._flight_rec
        if rec is None:
            try:
                from ..observability.trace.flight import flight_recorder

                rec = flight_recorder()
            except Exception:
                rec = False
            self._flight_rec = rec
        return rec or None

    # -- hooks ----------------------------------------------------------------
    def _on_start(self) -> None:
        pass

    def _worker(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        with self._start_lock:  # concurrent submits race the auto-start
            if self._thread is not None:
                return self
            self._on_start()
            self._thread = threading.Thread(target=self._worker,
                                            name=f"pt-serving-{self.name}",
                                            daemon=True)
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the worker. ``drain=True`` serves what is already queued;
        ``drain=False`` fails queued requests with ``EngineClosed``."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._queue:
                    r = self._queue.popleft()
                    if not r.future.done():
                        r.future.set_exception(EngineClosed("engine closed"))
                    _tracer().finish(getattr(r, "trace", None), ok=False,
                                     error="EngineClosed")
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=self._close_timeout
                              if timeout is None else timeout)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    def swap_weights(self, state, version: Optional[int] = None,
                     timeout: Optional[float] = None) -> int:
        """Replace the served weights IN PLACE between batches — the
        weight-push fast path (seconds, not a respawn). In-flight
        requests finish bit-identically on the version they started on:
        the swap applies only at a step boundary with zero active work.
        Returns the new ``weight_version``. Subclasses that can swap
        implement it; the base refuses (callers fall back to
        ``rolling_restart``)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support in-place weight swap")

    def fence(self) -> None:
        """Stop admitting NEW work while queued + in-flight requests run
        to completion — the rolling-restart drain half: fence-new-work,
        finish in-flight, then restart."""
        with self._cond:
            self._fenced = True

    def unfence(self) -> None:
        with self._cond:
            self._fenced = False

    def health(self) -> bool:
        """Liveness probe (router re-admission): the engine accepts work
        and its worker loop (if started) is still running."""
        if self._closed or self._fenced:
            return False
        t = self._thread
        return t is None or t.is_alive()

    def cancel(self, future) -> bool:
        """Dequeue the request owning ``future`` before it executes (its
        future fails with ``RequestCancelled``). Returns False when the
        request already left the queue — an executing request runs to
        completion and the caller discards the result."""
        req = None
        with self._cond:
            for r in self._queue:
                if r.future is future:
                    self._queue.remove(r)
                    req = r
                    break
        if req is None:
            return False
        if not req.future.done():
            req.future.set_exception(RequestCancelled("request cancelled"))
        _tracer().finish(getattr(req, "trace", None), ok=False,
                         error="RequestCancelled")
        self.metrics.inc("cancelled_total")
        return True

    # -- admission ------------------------------------------------------------
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def _enqueue(self, req, max_queue: int) -> None:
        """Bounded-queue admission (raises ``EngineClosed``/``QueueFull``);
        auto-starts the worker on first use."""
        with self._cond:
            if self._closed:
                raise EngineClosed("engine closed")
            if self._fenced:
                raise EngineClosed("engine fenced (draining)")
            if len(self._queue) >= max_queue:
                self.metrics.inc("rejected_total")
                raise QueueFull(f"queue at capacity ({max_queue})")
            self._queue.append(req)
            self._cond.notify()
        if self._thread is None:
            self.start()

    # -- observability --------------------------------------------------------
    def retrace_events(self) -> Optional[int]:
        """Recompiles recorded under this engine's ``serving:<name>:``
        labels (None when the retrace auditor is not enabled)."""
        try:
            from ..analysis import retrace
        except Exception:  # pragma: no cover - analysis always present
            return None
        if not retrace.is_enabled() and not retrace.get_auditor().events:
            return None
        prefix = f"serving:{self.name}:"
        return sum(1 for e in retrace.get_auditor().events
                   if str(e.label).startswith(prefix))

    def _stats_base(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()
        snap["name"] = self.name
        snap["weight_version"] = self.weight_version
        rt = self.retrace_events()
        if rt is not None:
            snap["retrace_events"] = rt
        return snap
