"""The batching inference engine (AnalysisPredictor -> TPU-native serving).

Reference role: the reference deploys ``AnalysisPredictor`` behind
Paddle Serving / FleetExecutor's ``dist_model.cc`` multi-rank driver; a
request is one predictor run. On TPU that shape is wrong: per-request
execution wastes the MXU and every odd input shape is a fresh XLA compile.
This engine inverts it — requests enter a thread-safe bounded queue, a
micro-batcher coalesces them into padded batches along pre-declared shape
buckets (``BucketSpec``), and one worker loop executes AOT-warmed compiled
programs, so steady-state traffic rides warm executables only.

Robustness contract:
- bounded queue with backpressure (``QueueFull`` raised at submit);
- per-request deadline: requests that expire while queued are shed with
  ``DeadlineExceeded`` before wasting device time;
- per-request error isolation: a malformed payload fails ITS OWN future at
  submit; an execution fault fails only the requests of that batch.

Observability: a ``MetricsRegistry`` snapshot (QPS, p50/p95/p99 latency,
batch occupancy, queue depth, compile-cache hits/misses) via ``stats()``,
plus ``profiler.RecordEvent`` spans around every executed batch.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import (BadRequest, DeadlineExceeded, EngineBase, EngineClosed,
                   QueueFull, _oom_guard, _tracer)
from .buckets import BucketSpec

__all__ = ["ServingConfig", "ServingEngine", "QueueFull", "DeadlineExceeded",
           "EngineClosed", "BadRequest"]

# Raw (pre-padding) variable-dim request sizes for the online tuner's
# bucket derivation; edges mirror generation.PROMPT_TOKEN_BUCKETS so
# quantile-cover resolution matches across engine kinds.
REQUEST_TOKEN_BUCKETS = (1, 2, 4, 8, 12, 16, 24, 32, 48, 64, 96, 128,
                         192, 256, 384, 512, 768, 1024, 1536, 2048, 4096)


@dataclass
class ServingConfig:
    """Engine knobs (reference: AnalysisConfig's predictor switches)."""

    max_queue: int = 256            # admission bound (backpressure beyond)
    max_batch_wait_ms: float = 2.0  # micro-batcher coalescing window
    default_deadline_ms: Optional[float] = None   # None = no deadline
    donate_inputs: bool = True      # donate padded input buffers to XLA
    warmup_on_start: bool = True    # AOT-compile every bucket before serving
    qps_window_s: float = 30.0      # sliding window for the QPS gauge


class _Request:
    __slots__ = ("arrays", "key", "future", "t_submit", "deadline", "trace")

    def __init__(self, arrays, key, future, t_submit, deadline):
        self.arrays = arrays
        self.key = key
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline
        self.trace = None  # request-scoped trace id (observability.trace)


_ENGINE_NO = itertools.count(1)


def _injector():
    from ..distributed.resilience.faults import injector

    return injector()


def _np_dtype(dt: str) -> np.dtype:
    try:
        return np.dtype(dt)
    except TypeError:  # bfloat16 lives in ml_dtypes (a jax dependency)
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, dt))


def _spec_tuple(spec) -> Tuple[Tuple, str]:
    """Normalize an input spec to (per-sample shape with None dims, dtype)."""
    if hasattr(spec, "shape") and hasattr(spec, "dtype"):  # InputSpec/array
        shape = tuple(None if (d is None or (isinstance(d, int) and d < 0))
                      else int(d) for d in spec.shape)
        return shape, str(np.dtype(str(spec.dtype))
                          if str(spec.dtype) != "bfloat16" else "bfloat16")
    shape, dtype = spec
    shape = tuple(None if (d is None or (isinstance(d, int) and d < 0))
                  else int(d) for d in shape)
    return shape, str(np.dtype(dtype)) if dtype != "bfloat16" else "bfloat16"


class ServingEngine(EngineBase):
    """Coalescing batch server over a Predictor, an ``nn.Layer``, or a
    plain array function.

    ::

        eng = ServingEngine(predictor, buckets=BucketSpec((1, 2, 4, 8)))
        eng.start()
        fut = eng.submit([sample])        # per-sample arrays, NO batch dim
        outs = fut.result()               # per-sample outputs, batch dim off
        eng.stats()                       # QPS / latency / occupancy / ...
        eng.close()

    ``target``:
    - ``inference.Predictor``: executes the loaded jax.export artifact
      (input specs read from the ``.pdmeta``; save with a ``None`` batch dim
      so one executable serves every bucket);
    - ``nn.Layer``: per-bucket ``jax.jit`` of the forward with the padded
      input buffers donated (the engine owns them);
    - callable ``fn(*arrays) -> array(s)``: same per-bucket jit.

    For Layer/callable targets pass ``input_specs``: per-sample shapes
    (``None`` marks the variable/seq dim) + dtypes, e.g.
    ``[((None,), "int64")]`` or ``static.InputSpec`` objects or example
    arrays.
    """

    def __init__(self, target, buckets: BucketSpec,
                 input_specs: Optional[Sequence] = None,
                 config: Optional[ServingConfig] = None,
                 name: Optional[str] = None):
        self.buckets = buckets
        self.config = config or ServingConfig()
        super().__init__(name or f"engine#{next(_ENGINE_NO)}",
                         qps_window_s=self.config.qps_window_s)

        self._specs = self._resolve_specs(target, input_specs)
        for shape, _dt in self._specs:
            for ax, d in enumerate(shape):
                if d is None and ax != buckets.seq_axis:
                    raise ValueError(
                        f"variable dim at per-sample axis {ax} but "
                        f"BucketSpec.seq_axis={buckets.seq_axis}; only the "
                        "declared seq axis may vary")
        self._runner_factory = self._make_runner_factory(target)
        self._compiled: Dict[Tuple, Callable] = {}
        self._warmed = False
        # request-size truth for the online tuner (variable-dim engines
        # only): raw pre-padding seq sizes, fleet-mergeable fixed edges
        try:
            from ..observability import histogram

            self._hist_req_tokens = histogram("request_tokens",
                                              REQUEST_TOKEN_BUCKETS)
        except Exception:
            self._hist_req_tokens = None
        # memory truth: this engine's executable footprint (padded input
        # working set per warmed bucket) rides in the `memory` provider
        try:
            from ..observability.memory import register_component

            register_component(f"serving:{self.name}:executables",
                               type(self)._executable_footprint_bytes,
                               owner=self)
        except Exception:
            pass

    def _executable_footprint_bytes(self) -> int:
        """Padded input-buffer bytes across warmed buckets — the working
        set the engine's executables hold (weights are the model's own)."""
        total = 0
        for (bucket_b, key) in list(self._compiled):
            for dt, shape in key:
                n = bucket_b
                for d in shape:
                    n *= int(d)
                total += n * _np_dtype(dt).itemsize
        return total

    # -- target plumbing ------------------------------------------------------
    @staticmethod
    def _resolve_specs(target, input_specs):
        if input_specs is None:
            get = getattr(target, "get_input_specs", None)
            if get is None:
                raise ValueError(
                    "input_specs required for Layer/callable targets "
                    "(per-sample shapes + dtypes; None marks the seq dim)")
            # Predictor specs carry the batch dim at axis 0: strip it
            specs = []
            for s in get():
                shape, dt = _spec_tuple(s)
                if not shape:
                    raise ValueError("saved input spec has no batch dim")
                specs.append((shape[1:], dt))
            if not specs:
                raise ValueError(
                    "the predictor's .pdmeta carries no input_specs "
                    "(artifact saved by an older jit.save?) — re-save the "
                    "model or pass input_specs explicitly")
            return specs
        return [_spec_tuple(s) for s in input_specs]

    def _make_runner_factory(self, target):
        """Return build(bucket_b, key) -> runner(list_of_np) -> list_of_np."""
        import jax

        from .. import jit as jit_mod

        donate = self.config.donate_inputs and jax.default_backend() != "cpu"

        build_native = getattr(target, "build_serving_runner", None)
        if build_native is not None:
            # engine-native target (e.g. sparse.EmbeddingLookupTarget):
            # the TARGET builds the per-bucket runner — host-side work
            # (dedup/routing) around its own warmed fixed-shape
            # executables, which a plain jitted-callable target cannot
            # express. The engine still owns buckets/padding/coalescing,
            # and the runner is audit-wrapped under the engine label so
            # the zero-retrace contract stays checkable.
            def build(bucket_b, key):
                label = self._label(bucket_b, key)
                return jit_mod._maybe_audit(
                    label, build_native(bucket_b, key, label=label))
            return build

        pred_layer = getattr(target, "_layer", None)
        if pred_layer is not None and hasattr(target, "run"):  # Predictor
            def build(bucket_b, key):
                label = self._label(bucket_b, key)

                def runner(np_inputs):
                    outs = pred_layer(*[jax.numpy.asarray(a)
                                        for a in np_inputs])
                    outs = outs if isinstance(outs, (list, tuple)) else [outs]
                    return [np.asarray(t.data) for t in outs]

                return jit_mod._maybe_audit(label, runner)
            return build

        from ..core import autograd
        from ..core.tensor import Tensor
        from ..nn.layer.layers import Layer

        if isinstance(target, Layer):
            target.eval()  # serve inference semantics (dropout off)
            named, buffers = jit_mod._collect_params(target)
            tensors = [p for _, p in named] + [b for _, b in buffers]

            def build(bucket_b, key):
                def raw(param_arrays, input_arrays):
                    with jit_mod._Binder(tensors) as b:
                        b.bind(list(param_arrays))
                        with autograd.no_grad():
                            out = target(*[Tensor(a) for a in input_arrays])
                    return jax.tree_util.tree_map(
                        lambda t: t.data if isinstance(t, Tensor) else t, out,
                        is_leaf=lambda t: isinstance(t, Tensor))

                label = self._label(bucket_b, key)
                jitted = jit_mod._maybe_audit(
                    label,
                    jit_mod.persistent_cache.cached_jit(
                        raw, donate_argnums=(1,) if donate else (),
                        label=label))

                def runner(np_inputs):
                    out = jitted([t.data for t in tensors],
                                 tuple(jax.numpy.asarray(a)
                                       for a in np_inputs))
                    return [np.asarray(x)
                            for x in jax.tree_util.tree_leaves(out)]

                return runner
            return build

        if callable(target):
            def build(bucket_b, key):
                def raw(input_arrays):
                    return target(*input_arrays)

                label = self._label(bucket_b, key)
                jitted = jit_mod._maybe_audit(
                    label,
                    jit_mod.persistent_cache.cached_jit(
                        raw, donate_argnums=(0,) if donate else (),
                        label=label))

                def runner(np_inputs):
                    out = jitted(tuple(jax.numpy.asarray(a)
                                       for a in np_inputs))
                    return [np.asarray(x)
                            for x in jax.tree_util.tree_leaves(out)]

                return runner
            return build

        raise TypeError(f"cannot serve target of type {type(target)!r}")

    def _label(self, bucket_b, key):
        shapes = "/".join("x".join(map(str, (bucket_b,) + shape))
                          for _dt, shape in key)
        return f"serving:{self.name}:{shapes}"

    # -- lifecycle ------------------------------------------------------------
    def _on_start(self):
        """Warm every declared bucket before the worker serves traffic."""
        if self.config.warmup_on_start:
            self.warmup()

    def warmup(self):
        """AOT-compile one executable per (batch bucket, seq bucket) combo
        so steady state never compiles. With ``analysis.retrace`` enabled
        the warmup compiles are the per-label baselines; any later retrace
        under a ``serving:<name>:`` label is a genuine shape leak."""
        shapes = [shape for shape, _dt in self._specs]
        for bb, concrete in self.buckets.warm_shapes(shapes):
            key = tuple((dt, shp) for (_s, dt), shp
                        in zip(self._specs, concrete))
            if (bb, key) in self._compiled:
                continue
            runner = self._runner_factory(bb, key)
            dummies = [np.full((bb,) + shp, self.buckets.pad_value,
                               dtype=_np_dtype(dt))
                       for (dt, shp) in key]
            runner(dummies)
            self._compiled[(bb, key)] = runner
            self.metrics.inc("warmup_compiles")
        self._warmed = True
        return self

    def respec(self, buckets: BucketSpec) -> "ServingEngine":
        """Swap the bucket spec LIVE with the zero-retrace invariant
        intact: every runner the new spec can route to is AOT-warmed
        BEFORE the swap, outside the engine lock (compiles are seconds —
        serving never stalls behind them), then the spec reference flips
        under the lock at a batch boundary.

        In-flight requests were padded under the OLD spec, so the warm
        set also covers (new batch bucket x already-seen key) — a
        request validated pre-swap executes post-swap without a fresh
        compile.  Old runners stay cached: an executable is only memory,
        a retrace is an SLO hole.  This is the single-process actuator;
        multi-process fleets re-shape through ``ServingFleet.
        apply_serving_shape`` (respawn + warm behind the rolling-restart
        fence) instead."""
        shapes = [shape for shape, _dt in self._specs]
        fresh: Dict[Tuple, Callable] = {}

        def warm(bb, key):
            if (bb, key) in self._compiled or (bb, key) in fresh:
                return
            runner = self._runner_factory(bb, key)
            dummies = [np.full((bb,) + shp, buckets.pad_value,
                               dtype=_np_dtype(dt))
                       for (dt, shp) in key]
            runner(dummies)
            fresh[(bb, key)] = runner
            self.metrics.inc("respec_compiles")

        for bb, concrete in buckets.warm_shapes(shapes):
            warm(bb, tuple((dt, shp) for (_s, dt), shp
                           in zip(self._specs, concrete)))
        for _bb, key in list(self._compiled):
            for bb in buckets.batch_sizes:
                warm(bb, key)
        with self._cond:
            self._compiled.update(fresh)
            self.buckets = buckets
        self.metrics.inc("respecs")
        return self

    # -- submission -----------------------------------------------------------
    def submit(self, inputs: Sequence, deadline_ms: Optional[float] = None,
               trace_parent: Optional[str] = None) -> "Future":
        """Enqueue one request (per-sample arrays, no batch dim); returns a
        future resolving to the per-sample outputs (batch dim stripped).

        A malformed payload fails the returned future (never the batch);
        a full queue raises ``QueueFull`` synchronously — backpressure the
        caller must see."""
        self.metrics.inc("requests_total")
        fut: Future = Future()
        t_submit = time.monotonic()
        try:
            arrays, key = self._validate(inputs)
        except BadRequest as e:
            self.metrics.inc("errors_total")
            self.metrics.inc("bad_requests")
            fut.set_exception(e)
            return fut
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = None if deadline_ms is None \
            else t_submit + deadline_ms / 1000.0
        req = _Request(arrays, key, fut, t_submit, deadline)
        # request-scoped trace: one ID from admission to completion; the
        # admission span is the validation/enqueue work just done
        tr = _tracer()
        req.trace = tr.start(self.name, kind="serve",
                             parent=trace_parent,
                             deadline_ms=deadline_ms)
        tr.span(req.trace, "admission", t_submit, time.monotonic())
        try:
            self._enqueue(req, self.config.max_queue)
        except Exception as e:  # QueueFull/EngineClosed backpressure
            tr.finish(req.trace, ok=False, error=type(e).__name__)
            raise
        return fut

    def _validate(self, inputs) -> Tuple[List[np.ndarray], Tuple]:
        if not isinstance(inputs, (list, tuple)) or \
                len(inputs) != len(self._specs):
            raise BadRequest(
                f"expected {len(self._specs)} input arrays, got "
                f"{len(inputs) if isinstance(inputs, (list, tuple)) else type(inputs)!r}")
        arrays, key = [], []
        for i, (a, (shape, dt)) in enumerate(zip(inputs, self._specs)):
            a = np.asarray(a)
            if str(a.dtype) != dt:
                raise BadRequest(
                    f"input {i}: dtype {a.dtype} != expected {dt}")
            if a.ndim != len(shape):
                raise BadRequest(
                    f"input {i}: rank {a.ndim} != expected {len(shape)} "
                    "(submit per-sample arrays without the batch dim)")
            for ax, d in enumerate(shape):
                if d is not None and a.shape[ax] != d:
                    raise BadRequest(
                        f"input {i}: dim {ax} is {a.shape[ax]}, expected {d}")
            if any(d is None for d in shape):  # only declared-variable dims
                if self._hist_req_tokens is not None and \
                        self.buckets.seq_axis < a.ndim:
                    # raw size BEFORE padding (and before any reject):
                    # the tuner derives buckets from what ARRIVES
                    self._hist_req_tokens.observe(
                        a.shape[self.buckets.seq_axis])
                try:                           # ride the seq buckets
                    a = self.buckets.pad_sample_seq(a)
                except ValueError as e:
                    raise BadRequest(str(e))
            arrays.append(np.ascontiguousarray(a))
            key.append((dt, a.shape))
        return arrays, tuple(key)

    # -- worker ---------------------------------------------------------------
    def _fail(self, req: _Request, exc: Exception):
        if not req.future.done():
            req.future.set_exception(exc)
        _tracer().finish(req.trace, ok=False, error=type(exc).__name__)

    def _shed_expired_locked(self, now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        keep = deque()
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                self.metrics.inc("shed_total")
                self._fail(r, DeadlineExceeded(
                    "deadline expired while queued"))
            else:
                keep.append(r)
        self._queue = keep

    def _collect_matching_locked(self, batch, key, limit):
        keep = deque()
        now = time.monotonic()
        for r in self._queue:
            if len(batch) < limit and r.key == key:
                if r.deadline is not None and now > r.deadline:
                    self.metrics.inc("shed_total")
                    self._fail(r, DeadlineExceeded(
                        "deadline expired while queued"))
                else:
                    batch.append(r)
            else:
                keep.append(r)
        self._queue = keep

    def _next_batch(self):
        cfg = self.config
        with self._cond:
            while True:
                self._shed_expired_locked()
                if self._queue:
                    break
                if self._closed:
                    return None
                # untimed: submit/close notify, and an empty queue has no
                # deadlines to shed — no idle polling
                self._cond.wait()
            seed = self._queue.popleft()
            batch = [seed]
            key = seed.key
            limit = self.buckets.max_batch
            t_open = time.monotonic()  # coalesce window opens (trace spans)
            t_close = t_open + cfg.max_batch_wait_ms / 1000.0
            while len(batch) < limit:
                self._collect_matching_locked(batch, key, limit)
                if len(batch) >= limit:
                    break
                rem = t_close - time.monotonic()
                if rem <= 0 or (self._closed and not self._queue):
                    break
                self._cond.wait(rem)
            return batch, key, t_open

    def _worker(self):
        while True:
            item = self._next_batch()
            if item is None:
                return
            batch, key, t_open = item
            try:
                self._execute(batch, key, t_open)
            except Exception as e:  # never kill the loop: fail the batch
                for r in batch:
                    self._fail(r, e)
                self.metrics.inc("errors_total", len(batch))
                self.metrics.inc("batch_failures")

    def _execute(self, batch: List[_Request], key: Tuple,
                 t_open: Optional[float] = None):
        from .. import profiler

        # last deadline check: a request may have expired while the batch
        # coalesced — shed it now rather than spend device time on it
        now = time.monotonic()
        live = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                self.metrics.inc("shed_total")
                self._fail(r, DeadlineExceeded(
                    "deadline expired before execution"))
            else:
                live.append(r)
        batch = live
        if not batch:
            return
        bucket_b = self.buckets.batch_bucket(len(batch))
        cache_key = (bucket_b, key)
        runner = self._compiled.get(cache_key)
        if runner is None:
            self.metrics.inc("compile_cache_misses")
            runner = self._runner_factory(bucket_b, key)
            self._compiled[cache_key] = runner
        else:
            self.metrics.inc("compile_cache_hits")
        n = len(batch)
        inputs = [self.buckets.stack_batch([r.arrays[i] for r in batch],
                                           bucket_b)
                  for i in range(len(self._specs))]
        t_exec = time.monotonic()
        tr = _tracer()
        for r in batch:
            self.metrics.observe_queue_wait((t_exec - r.t_submit) * 1e3)
            # queue = waiting for a coalesce window to pick this request
            # up; batch_coalesce = riding the open window until execution
            t_mid = min(max(r.t_submit, t_open if t_open is not None
                            else t_exec), t_exec)
            tr.span(r.trace, "queue", r.t_submit, t_mid)
            tr.span(r.trace, "batch_coalesce", t_mid, t_exec,
                    bucket=bucket_b)
        # chaos site: a scripted batch fault at an exact executed-batch
        # index (PT_FAULTS="batch_fault@batch=3") — exercises the
        # isolation contract (only THIS batch's futures fail, the queue
        # keeps draining) without real hardware faults
        self._batch_no = getattr(self, "_batch_no", -1) + 1
        _injector().check("batch_fault", engine=self.name,
                          batch=self._batch_no)
        # a runner fault propagates to _worker's batch-failure handler;
        # RESOURCE_EXHAUSTED additionally leaves a memory-forensics bundle
        # (PT_FAULTS="oom@site=serving" drills the path)
        with profiler.RecordEvent(
                f"serving::batch[{self.name} b{bucket_b} n{n}]",
                "Serving"):
            with _oom_guard("serving", label=self._label(bucket_b, key),
                            engine=self.name, batch=self._batch_no):
                outs = runner(inputs)
        t_done = time.monotonic()
        fr = self._flight()
        if fr is not None:  # serving batches land in the flight ring
            fr.record_serving_step(self.name, "batch",
                                   (t_done - t_exec) * 1e3, n)
        for i, r in enumerate(batch):
            if not r.future.done():
                r.future.set_result([o[i] for o in outs])
            self.metrics.observe_latency((t_done - r.t_submit) * 1e3)
            tr.span(r.trace, "execute", t_exec, t_done, bucket=bucket_b,
                    batch=n)
            tr.finish(r.trace, ok=True,
                      latency_ms=round((t_done - r.t_submit) * 1e3, 3))
        self.metrics.inc("responses_total", n)
        self.metrics.inc("batches_total")
        self.metrics.observe_occupancy(n / bucket_b)
        self.metrics.mark_done(n)

    # -- observability --------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """One snapshot: QPS, latency percentiles, occupancy, counters,
        queue depth, warmed executables, steady-state retrace count, and —
        when the persistent executable cache is on — this engine's on-disk
        hit/miss rows (warm starts skip the bucket compiles entirely)."""
        snap = self._stats_base()
        snap["buckets"] = repr(self.buckets)
        snap["warmed_executables"] = len(self._compiled)
        from ..jit import persistent_cache as pcache

        if pcache.is_enabled():
            prefix = f"serving:{self.name}:"
            rows = {k: v for k, v in pcache.stats()["by_label"].items()
                    if k.startswith(prefix)}
            snap["persistent_cache"] = {
                "hits": sum(r.get("hits", 0) for r in rows.values()),
                "misses": sum(r.get("misses", 0) for r in rows.values()),
                "dir": pcache.cache_dir(),
            }
        return snap
