"""Shape buckets: the contract that keeps XLA's compile cache finite.

On TPU an unseen input shape is a fresh XLA compilation (seconds), so a
serving engine must never let raw request shapes reach the executor. The
``BucketSpec`` declares the closed set of (batch, seq) shapes the engine is
allowed to execute; every request is padded UP to the smallest bucket that
fits, and the engine AOT-warms exactly one executable per bucket. Steady
state is then provably retrace-free (asserted via ``analysis.retrace``).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BucketSpec"]


class BucketSpec:
    """Pre-declared padding targets along batch and (optionally) sequence.

    - ``batch_sizes``: allowed batch dims, e.g. ``(1, 2, 4, 8)``; a batch of
      3 requests executes in the 4-bucket with one padded row.
    - ``seq_lens``: allowed lengths for variable (``None``) per-sample dims,
      e.g. ``(64, 128, 256)``; ``None`` means no variable dims are served.
    - ``seq_axis``: which PER-SAMPLE axis is the sequence axis (default 0,
      i.e. axis 1 of the batched tensor).
    - ``pad_value``: fill for padded rows/positions (0 is safe for token ids
      and for causal-attention tails — padded positions are masked off or
      causally unreachable from real ones).
    - ``observed_floor``: smallest request size this spec claims to serve
      (the online tuner passes the smallest OBSERVED size).  Any seq
      bucket below it is dead weight — it can never be selected, it only
      spends a warmed executable — so construction rejects it outright
      instead of silently padding around it.

    Both axes are validated, not repaired: entries must be positive
    integers and free of duplicates (order-insensitive input is fine and
    is canonicalized ascending; a duplicate is a spec author's error the
    engine must surface, not fold away).  Derived specs from
    ``paddle_tpu.tuning`` construct through this same path, so a bad
    derivation fails HERE, before any executable is warmed.
    """

    def __init__(self, batch_sizes: Sequence[int] = (1, 2, 4, 8),
                 seq_lens: Optional[Sequence[int]] = None,
                 seq_axis: int = 0, pad_value=0,
                 observed_floor: Optional[int] = None):
        self.batch_sizes: Tuple[int, ...] = self._validated(
            "batch_sizes", batch_sizes)
        self.seq_lens: Optional[Tuple[int, ...]] = (
            self._validated("seq_lens", seq_lens, floor=observed_floor)
            if seq_lens else None)
        self.seq_axis = int(seq_axis)
        self.pad_value = pad_value
        self.observed_floor = (int(observed_floor)
                               if observed_floor is not None else None)

    @staticmethod
    def _validated(name: str, sizes: Sequence[int],
                   floor: Optional[int] = None) -> Tuple[int, ...]:
        """One validation path for every bucket axis (hand-declared and
        tuner-derived): positive ints, no duplicates, monotonic ascending
        canonical form, nothing below the observed floor."""
        if not sizes:
            raise ValueError(f"BucketSpec: {name} must be non-empty")
        vals = [int(s) for s in sizes]
        if any(int(s) != s for s in sizes) or min(vals) < 1:
            raise ValueError(
                f"BucketSpec: {name} must be positive integers, got "
                f"{tuple(sizes)}")
        out = tuple(sorted(vals))
        if len(out) != len(set(out)):
            dups = sorted({v for v in vals if vals.count(v) > 1})
            raise ValueError(
                f"BucketSpec: duplicate {name} entries {dups} — each "
                f"bucket is one warmed executable, declare it once")
        if floor is not None and out[0] < int(floor):
            below = tuple(v for v in out if v < int(floor))
            raise ValueError(
                f"BucketSpec: {name} buckets {below} are below the "
                f"smallest observed size {int(floor)} — they can never "
                f"be selected and only waste warmed executables")
        return out

    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    def batch_bucket(self, n: int) -> Optional[int]:
        """Smallest declared batch size >= n (None: n exceeds every bucket)."""
        for b in self.batch_sizes:
            if b >= n:
                return b
        return None

    def seq_bucket(self, length: int) -> Optional[int]:
        """Smallest declared seq length >= length (None: no fit)."""
        if self.seq_lens is None:
            return None
        for s in self.seq_lens:
            if s >= length:
                return s
        return None

    # -- padding --------------------------------------------------------------
    def pad_sample_seq(self, arr: np.ndarray) -> np.ndarray:
        """Pad one per-sample array's seq axis up to its bucket (no-op when
        no seq buckets are declared or the axis is already bucket-sized)."""
        if self.seq_lens is None:
            return arr
        axis = self.seq_axis
        if axis >= arr.ndim:
            return arr
        target = self.seq_bucket(arr.shape[axis])
        if target is None:
            raise ValueError(
                f"sequence length {arr.shape[axis]} exceeds the largest "
                f"declared seq bucket {self.seq_lens[-1]}")
        if target == arr.shape[axis]:
            return arr
        pad = [(0, 0)] * arr.ndim
        pad[axis] = (0, target - arr.shape[axis])
        return np.pad(arr, pad, constant_values=self.pad_value)

    def stack_batch(self, samples: List[np.ndarray], bucket_b: int) -> np.ndarray:
        """Stack same-shaped samples and pad the batch dim up to bucket_b."""
        out = np.full((bucket_b,) + samples[0].shape, self.pad_value,
                      dtype=samples[0].dtype)
        for i, s in enumerate(samples):
            out[i] = s
        return out

    def warm_shapes(self, sample_shapes: List[Tuple[int, ...]]):
        """Every (batch_bucket, per-sample shapes) combination to AOT-warm.

        ``sample_shapes`` may contain ``None`` dims (variable); each distinct
        seq bucket instantiates them (all variable dims of one request share
        a bucket — the LM convention where ids/masks ride the same length).
        Yields (batch_bucket, tuple_of_concrete_sample_shapes).
        """
        has_var = any(d is None for shape in sample_shapes for d in shape)
        seq_choices = self.seq_lens if (has_var and self.seq_lens) else (None,)
        if has_var and not self.seq_lens:
            raise ValueError(
                "inputs have variable dims but BucketSpec declares no "
                "seq_lens")
        for bb in self.batch_sizes:
            for sl in seq_choices:
                concrete = tuple(
                    tuple(sl if d is None else d for d in shape)
                    for shape in sample_shapes)
                yield bb, concrete

    def __repr__(self):
        return (f"BucketSpec(batch_sizes={self.batch_sizes}, "
                f"seq_lens={self.seq_lens}, seq_axis={self.seq_axis})")
