"""KV-page shipping: pack, quantize, chunk, and cache paged-KV pages.

This module generalizes the PR-17 weight-transfer path
(``post_training/weights.py`` — chunked, SHA-256-verified, resumable)
into a page shipper for disaggregated prefill/decode serving:

- ``pack_kv_pages`` / ``unpack_kv_pages`` serialize per-layer K/V page
  stacks into one contiguous blob with a JSON-able manifest.  Pages can
  be shipped fp32-exact (bit-identical install) or int8-quantized with
  per-page scales (~4x fewer transit bytes; dequantized on install).
- ``chunk_blob`` / ``assemble_chunks`` split the blob into base64
  chunks with per-chunk SHA-256 plus a whole-blob digest, matching the
  weight-transfer wire discipline so a torn or corrupted transfer is
  detected and retried per-chunk instead of restarting.
- ``FleetKVCache`` is the supervisor-side warm tier: packed (usually
  int8) payloads for recently-prefilled prompts, admitted by a
  frequency-gated ghost counter (the PR-14 ``HotRowCache`` pattern) and
  evicted LRU under a byte budget.
- ``KVMigrationStats`` aggregates the counters the ``kv_migration``
  observability provider exposes.
"""

from __future__ import annotations

import base64
import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.lockdep import lock as _named_lock

__all__ = [
    "quantize_page",
    "dequantize_page",
    "pack_kv_pages",
    "unpack_kv_pages",
    "chunk_blob",
    "assemble_chunks",
    "payload_digest",
    "prompt_cache_key",
    "FleetKVCache",
    "KVMigrationStats",
]


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def payload_digest(blob: bytes) -> str:
    """SHA-256 hex digest of a packed page blob."""
    return _sha(blob)


# ---------------------------------------------------------------------------
# Per-page int8 quantization
# ---------------------------------------------------------------------------


def quantize_page(arr: np.ndarray) -> Tuple[np.ndarray, float]:
    """Symmetric int8 quantization of one KV page.

    Returns ``(q, scale)`` with ``q = round(arr / scale)`` clipped to
    [-127, 127].  ``scale`` is strictly positive even for an all-zero
    page so dequantization never divides by zero.
    """
    a = np.asarray(arr, dtype=np.float32)
    amax = float(np.max(np.abs(a))) if a.size else 0.0
    scale = max(amax / 127.0, 1e-12)
    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_page(q: np.ndarray, scale: float, dtype: Any = np.float32) -> np.ndarray:
    """Inverse of :func:`quantize_page` (lossy; error ≤ scale/2 per element)."""
    return (np.asarray(q, dtype=np.float32) * float(scale)).astype(dtype)


# ---------------------------------------------------------------------------
# Pack / unpack
# ---------------------------------------------------------------------------


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bf16 et al.

        return np.dtype(getattr(ml_dtypes, name))


def pack_kv_pages(
    k_pages: Sequence[np.ndarray],
    v_pages: Sequence[np.ndarray],
    quantize: bool = False,
) -> Tuple[bytes, List[Dict[str, Any]], Dict[str, Any]]:
    """Serialize per-layer K/V page stacks into ``(blob, manifest, meta)``.

    ``k_pages[i]`` / ``v_pages[i]`` are ``[n_pages, page_len, heads, dim]``
    arrays for layer ``i``.  With ``quantize=True`` each page is stored
    int8 with a per-page fp32 scale in the manifest; otherwise pages are
    stored in their native dtype, byte-exact.  ``meta`` reports both the
    wire byte count and the fp32-equivalent byte count so callers can
    measure the transit savings.
    """
    if len(k_pages) != len(v_pages):
        raise ValueError(f"layer mismatch: {len(k_pages)} K vs {len(v_pages)} V")
    manifest: List[Dict[str, Any]] = []
    parts: List[bytes] = []
    offset = 0
    fp32_bytes = 0
    npages = None
    for li in range(len(k_pages)):
        for tag, arr in (("k", k_pages[li]), ("v", v_pages[li])):
            a = np.ascontiguousarray(arr)
            if a.ndim != 4:
                raise ValueError(f"{tag}{li}: expected [n, page_len, heads, dim], got {a.shape}")
            if npages is None:
                npages = int(a.shape[0])
            elif int(a.shape[0]) != npages:
                raise ValueError(f"{tag}{li}: page count {a.shape[0]} != {npages}")
            fp32_bytes += int(a.size) * 4
            scales: Optional[List[float]] = None
            if quantize:
                qs = []
                scales = []
                for p in range(a.shape[0]):
                    q, s = quantize_page(a[p])
                    qs.append(q)
                    scales.append(s)
                a = np.stack(qs, axis=0) if qs else np.zeros(a.shape, dtype=np.int8)
            raw = a.tobytes()
            manifest.append(
                {
                    "name": f"{tag}{li}",
                    "dtype": str(np.asarray(arr).dtype),
                    "qdtype": str(a.dtype),
                    "shape": [int(x) for x in np.asarray(arr).shape],
                    "scales": scales,
                    "offset": offset,
                    "size": len(raw),
                }
            )
            parts.append(raw)
            offset += len(raw)
    blob = b"".join(parts)
    meta = {
        "npages": int(npages or 0),
        "layers": len(k_pages),
        "quantized": bool(quantize),
        "wire_bytes": len(blob),
        "fp32_bytes": fp32_bytes,
        "digest": _sha(blob),
    }
    return blob, manifest, meta


def unpack_kv_pages(
    blob: bytes, manifest: Sequence[Dict[str, Any]]
) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    """Inverse of :func:`pack_kv_pages` → ``(k_pages, v_pages)`` per layer.

    Quantized entries are dequantized back to their original dtype using
    the per-page scales recorded in the manifest.
    """
    k_out: Dict[int, np.ndarray] = {}
    v_out: Dict[int, np.ndarray] = {}
    for ent in manifest:
        seg = blob[ent["offset"] : ent["offset"] + ent["size"]]
        shape = tuple(int(x) for x in ent["shape"])
        arr = np.frombuffer(seg, dtype=_np_dtype(ent["qdtype"])).reshape(shape)
        if ent.get("scales") is not None:
            pages = [
                dequantize_page(arr[p], ent["scales"][p], _np_dtype(ent["dtype"]))
                for p in range(shape[0])
            ]
            arr = (
                np.stack(pages, axis=0)
                if pages
                else np.zeros(shape, dtype=_np_dtype(ent["dtype"]))
            )
        else:
            arr = arr.copy()
        name = ent["name"]
        li = int(name[1:])
        (k_out if name[0] == "k" else v_out)[li] = arr
    layers = sorted(k_out)
    if layers != sorted(v_out):
        raise ValueError("manifest missing K or V entries for some layers")
    return [k_out[i] for i in layers], [v_out[i] for i in layers]


# ---------------------------------------------------------------------------
# Chunking (the weight-transfer wire discipline)
# ---------------------------------------------------------------------------


def chunk_blob(blob: bytes, chunk_bytes: int = 1 << 20) -> List[Dict[str, Any]]:
    """Split ``blob`` into base64 chunks with per-chunk SHA-256."""
    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be positive")
    raws = [blob[i : i + chunk_bytes] for i in range(0, len(blob), chunk_bytes)] or [b""]
    return [
        {"idx": i, "data": base64.b64encode(raw).decode("ascii"), "sha": _sha(raw)}
        for i, raw in enumerate(raws)
    ]


def assemble_chunks(chunks: Sequence[Dict[str, Any]], digest: Optional[str] = None) -> bytes:
    """Reassemble chunks, verifying per-chunk SHA and the blob digest."""
    parts: List[bytes] = []
    for i, ch in enumerate(sorted(chunks, key=lambda c: c["idx"])):
        if int(ch["idx"]) != i:
            raise ValueError(f"chunk sequence broken at {i} (got idx {ch['idx']})")
        raw = base64.b64decode(ch["data"])
        if _sha(raw) != ch["sha"]:
            raise ValueError(f"chunk {i} SHA mismatch")
        parts.append(raw)
    blob = b"".join(parts)
    if digest is not None and _sha(blob) != digest:
        raise ValueError("assembled blob digest mismatch")
    return blob


# ---------------------------------------------------------------------------
# Fleet-wide warm tier
# ---------------------------------------------------------------------------


def prompt_cache_key(prompt_ids: Sequence[int], page_len: int) -> Optional[str]:
    """Stable key for the full-page prefix of a prompt (None if < 1 page)."""
    n = (len(prompt_ids) // page_len) * page_len
    if n <= 0:
        return None
    h = hashlib.sha256()
    h.update(str(page_len).encode("ascii"))
    for t in prompt_ids[:n]:
        h.update(int(t).to_bytes(8, "big", signed=True))
    return h.hexdigest()


class FleetKVCache:
    """Host-RAM warm tier for packed KV payloads, shared across the fleet.

    The supervisor stores the packed (typically int8) payload of each
    prefill it has seen; a repeat prompt is served from host RAM instead
    of re-prefilling or re-exporting.  Admission is frequency-gated with
    a ghost counter (an entry must be *seen* ``admit_threshold`` times
    before its bytes are kept), and residency is LRU under
    ``capacity_bytes``.
    """

    def __init__(
        self,
        capacity_bytes: int = 256 << 20,
        admit_threshold: int = 2,
        ghost_cap: int = 4096,
    ):
        self.capacity_bytes = int(capacity_bytes)
        self.admit_threshold = int(admit_threshold)
        self.ghost_cap = int(ghost_cap)
        self._entries: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._bytes = 0
        self._ghost: Dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.admits = 0
        self.rejects = 0
        self.evictions = 0
        self._lock = _named_lock("serving.kv_transfer.FleetKVCache._lock")

    def note_access(self, key: str) -> None:
        with self._lock:
            self._ghost[key] = self._ghost.get(key, 0) + 1
            if len(self._ghost) > self.ghost_cap:
                self._ghost = {k: v // 2 for k, v in self._ghost.items() if v // 2 > 0}

    def admittable(self, key: str) -> bool:
        with self._lock:
            return self._ghost.get(key, 0) >= self.admit_threshold

    def get(self, key: Optional[str]) -> Optional[Dict[str, Any]]:
        if key is None:
            return None
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ent

    def put(self, key: Optional[str], payload: Dict[str, Any]) -> bool:
        """Admit ``payload`` (a dict with a ``data`` bytes field) if warranted."""
        if key is None:
            return False
        self.note_access(key)
        nbytes = len(payload.get("data", b""))
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            if nbytes > self.capacity_bytes or self._ghost.get(key, 0) < self.admit_threshold:
                self.rejects += 1
                return False
            while self._bytes + nbytes > self.capacity_bytes and self._entries:
                _, old = self._entries.popitem(last=False)
                self._bytes -= len(old.get("data", b""))
                self.evictions += 1
            self._entries[key] = payload
            self._bytes += nbytes
            self.admits += 1
            return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / total) if total else 0.0,
                "admits": self.admits,
                "rejects": self.rejects,
                "evictions": self.evictions,
                "ghost_entries": len(self._ghost),
            }


# ---------------------------------------------------------------------------
# Migration counters for the `kv_migration` provider
# ---------------------------------------------------------------------------


class KVMigrationStats:
    """Counters behind the ``kv_migration`` observability provider."""

    def __init__(self) -> None:
        self._lock = _named_lock("serving.kv_transfer.KVMigrationStats._lock")
        self.ships = 0
        self.pages_shipped = 0
        self.wire_bytes = 0
        self.fp32_bytes = 0
        self.quantized_ships = 0
        self.exports = 0
        self.installs = 0
        self.install_ms_total = 0.0
        self.failover_ship = 0
        self.failover_reprefill = 0
        self.migrate_fallback = 0
        self.warm_hits = 0

    def note_ship(self, npages: int, wire_bytes: int, fp32_bytes: int, quantized: bool) -> None:
        with self._lock:
            self.ships += 1
            self.pages_shipped += int(npages)
            self.wire_bytes += int(wire_bytes)
            self.fp32_bytes += int(fp32_bytes)
            if quantized:
                self.quantized_ships += 1

    def note_install(self, ms: float) -> None:
        with self._lock:
            self.installs += 1
            self.install_ms_total += float(ms)

    def note_export(self) -> None:
        with self._lock:
            self.exports += 1

    def note_warm_hit(self) -> None:
        with self._lock:
            self.warm_hits += 1

    def note_fallback(self) -> None:
        with self._lock:
            self.migrate_fallback += 1

    def note_failover(self, ship: bool) -> None:
        with self._lock:
            if ship:
                self.failover_ship += 1
            else:
                self.failover_reprefill += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "ships": self.ships,
                "pages_shipped": self.pages_shipped,
                "wire_bytes": self.wire_bytes,
                "fp32_bytes": self.fp32_bytes,
                "transit_quantized_fraction": (
                    self.quantized_ships / self.ships if self.ships else 0.0
                ),
                "exports": self.exports,
                "installs": self.installs,
                "install_ms_avg": (
                    self.install_ms_total / self.installs if self.installs else 0.0
                ),
                "failover_ship": self.failover_ship,
                "failover_reprefill": self.failover_reprefill,
                "migrate_fallback": self.migrate_fallback,
                "warm_hits": self.warm_hits,
            }
