"""Continuous batching for causal-LM generation (paged KV cache).

The static-batch decode loop (``GPTForCausalLM.generate``) holds the whole
batch until its slowest sequence finishes, and its KV cache grows one token
per step — a new XLA program per step. Serving inverts both decisions:

- the KV cache is a fixed-size **page pool** ``[num_pages, page_len, heads,
  dim]`` per layer (``serving.paged_kv``): each sequence holds a page
  *table* instead of a ``max_seq_len`` slot row, requests sharing a system
  prompt share its ref-counted pages through the **prefix cache** (no
  re-prefill), and admission is bounded by pool pages, not worst-case slot
  length;
- each sequence owns a slot only while it is generating — a finished
  sequence releases its slot (and pages) and a queued prompt joins
  mid-flight at the next step boundary; slot-join order is
  **deadline-aware** (earliest deadline first; expired requests shed
  before prefill);
- prefill, decode, and speculative verify are ONE executable family: a
  fixed-shape **window step** that embeds ``W`` tokens per slot, writes
  their K/V through the page tables, attends length-masked against the
  gathered pages, and returns the greedy argmax at every window position.
  ``W=1`` is classic decode; ``W=k+1`` scores a draft model's ``k``
  proposals in one call (speculative decoding — emitted tokens are always
  the target model's own argmaxes, so the output is token-for-token the
  greedy path); ``W=bucket`` prefills a prompt suffix. Every ``W`` comes
  from a closed set, so steady state never retraces.

Greedy decoding (matching ``generate``'s argmax contract).
"""
from __future__ import annotations

import itertools
import math
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .base import (BadRequest, DeadlineExceeded, EngineBase, EngineClosed,
                   _oom_guard, _tracer)
from .paged_kv import (HostPagePool, PagedKVPool, PoolExhausted,
                       token_blocks)
from .speculative import greedy_accept

__all__ = ["GenerationConfig", "GenerationEngine", "flatten_gpt_params",
           "nest_gpt_params"]

_GEN_NO = itertools.count(1)

# EDF fairness bound: a request WITHOUT a deadline is ordered as if due
# this long after arrival, so sustained deadline-bearing traffic can
# delay it by at most the horizon — never starve it. Ordering only;
# shedding still applies to explicit deadlines alone.
_EDF_DEFAULT_HORIZON_S = 300.0

# Size-distribution histograms the online tuner derives serving shapes
# from. Edges must be fine enough that a quantile-cover over bucket
# UPPER bounds still lands near the true p99 (derivation collapses each
# bucket to its upper edge), and identical across every replica so the
# fleet merge is exact.
PROMPT_TOKEN_BUCKETS = (1, 2, 4, 8, 12, 16, 24, 32, 48, 64, 96, 128,
                        192, 256, 384, 512, 768, 1024, 1536, 2048, 4096)
SLOT_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 128)


def _injector():
    from ..distributed.resilience.faults import injector

    return injector()


class GenerationConfig:
    """Page pool + prompt bucket + speculative-decode declaration."""

    def __init__(self, max_slots: int = 4, max_seq_len: Optional[int] = None,
                 prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128),
                 max_queue: int = 256, eos_token_id: Optional[int] = None,
                 donate_cache: bool = True, page_len: int = 16,
                 num_pages: Optional[int] = None, prefix_cache: bool = True,
                 draft_model=None, spec_tokens: int = 4,
                 warm_pool_bytes: int = 0, warm_admit_threshold: int = 2):
        self.max_slots = int(max_slots)
        self.max_seq_len = max_seq_len  # None: model max_position_embeddings
        self.prefill_buckets = tuple(sorted({int(b)
                                             for b in prefill_buckets}))
        self.max_queue = int(max_queue)
        self.eos_token_id = eos_token_id
        self.donate_cache = donate_cache
        self.page_len = int(page_len)
        # None: slots' worst case + a couple of cached prefixes' worth
        self.num_pages = num_pages
        self.prefix_cache = bool(prefix_cache)
        self.draft_model = draft_model       # GPTForCausalLM or None
        self.spec_tokens = int(spec_tokens)  # draft proposals per round
        # warm tier: evicted prefix pages spill (int8) to host RAM and
        # restore instead of re-prefilling. 0 = off (the default keeps
        # the device tier bit-exact; int8 restores are approximate KV)
        self.warm_pool_bytes = int(warm_pool_bytes)
        self.warm_admit_threshold = int(warm_admit_threshold)


class _GenRequest:
    __slots__ = ("prompt", "max_new_tokens", "future", "t_submit",
                 "generated", "trace", "t_decode0", "deadline",
                 "blocks", "total_blocks", "on_token", "logprobs",
                 "want_logprobs")

    def __init__(self, prompt, max_new_tokens, future, t_submit,
                 deadline=None, on_token=None, want_logprobs=False):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.on_token = on_token  # per-token stream callback, or None
        self.want_logprobs = bool(want_logprobs)
        self.generated: List[int] = []
        self.logprobs: List[float] = []  # behavior logprob per token
        self.trace = None      # request-scoped trace id
        self.t_decode0 = None  # decode-phase start (prefill done)
        # immutable paging facts, computed ONCE at submit (the admission
        # scan runs under the engine lock and must stay cheap)
        self.blocks: List[Tuple[int, ...]] = []  # full prompt token-blocks
        self.total_blocks = 0                    # worst-case pages

    def edf_key(self) -> Tuple[float, float]:
        eff = self.deadline if self.deadline is not None \
            else self.t_submit + _EDF_DEFAULT_HORIZON_S
        return (eff, self.t_submit)


class _Slot:
    __slots__ = ("req", "length", "last_token", "t0", "table", "blocks",
                 "shared")

    def __init__(self, n_blocks: int):
        self.req: Optional[_GenRequest] = None
        self.length = 0
        self.last_token = 0
        self.t0 = 0.0  # residency start (occupancy track)
        self.table = np.zeros(n_blocks, dtype=np.int32)  # page ids (0=scratch)
        self.blocks = 0   # allocated entries of `table`
        self.shared = 0   # leading entries borrowed from the prefix cache


def _extract_gpt_params(model):
    """Read the live weights of a ``GPTForCausalLM`` as a jax pytree (the
    decode step closes over nothing — set_state_dict + a new engine picks
    up new weights)."""
    g = model.gpt

    def a(t):
        return t.data

    return {
        "embed": a(g.embed_tokens.weight),          # [vocab, h]
        "pos": a(g.embed_positions.weight),         # [P, h]
        "lnf_w": a(g.ln_f.weight), "lnf_b": a(g.ln_f.bias),
        "layers": [
            {"ln1_w": a(L.ln_1.weight), "ln1_b": a(L.ln_1.bias),
             "qkv_w": a(L.attn.qkv_proj.weight),
             "qkv_b": a(L.attn.qkv_proj.bias),
             "out_w": a(L.attn.out_proj.weight),
             "out_b": a(L.attn.out_proj.bias),
             "ln2_w": a(L.ln_2.weight), "ln2_b": a(L.ln_2.bias),
             "fc_in_w": a(L.fc_in.weight), "fc_in_b": a(L.fc_in.bias),
             "fc_out_w": a(L.fc_out.weight), "fc_out_b": a(L.fc_out.bias)}
            for L in g.layers],
    }


def flatten_gpt_params(tree) -> Dict[str, Any]:
    """Flatten the engine param pytree to ``{dotted_name: array}`` — the
    wire shape the post-training weight service streams (stable names,
    no nesting to re-derive on the far side)."""
    flat = {"embed": tree["embed"], "pos": tree["pos"],
            "lnf_w": tree["lnf_w"], "lnf_b": tree["lnf_b"]}
    for i, L in enumerate(tree["layers"]):
        for k, v in L.items():
            flat[f"layers.{i}.{k}"] = v
    return flat


def nest_gpt_params(flat) -> Dict[str, Any]:
    """Inverse of :func:`flatten_gpt_params`."""
    tree: Dict[str, Any] = {"layers": []}
    layers: Dict[int, Dict[str, Any]] = {}
    for name, v in flat.items():
        if name.startswith("layers."):
            _, idx, key = name.split(".", 2)
            layers.setdefault(int(idx), {})[key] = v
        else:
            tree[name] = v
    for i in sorted(layers):
        if i != len(tree["layers"]):
            raise ValueError(f"non-contiguous layer index {i}")
        tree["layers"].append(layers[i])
    return tree


def _build_decode_step(cfg, max_slots: int, max_len: int, donate: bool,
                       label: str):
    """One fixed-shape SLOT-ARENA executable: token+position embed,
    per-layer pre-LN attention against ``[S, max_len, nh, hd]`` caches
    (length-masked), MLP, tied head, greedy argmax. The draft model's
    decode path — small enough that a dense per-slot arena beats paging
    overhead. Cache buffers are donated so XLA updates in place."""
    import jax
    import jax.numpy as jnp

    nh = cfg.num_attention_heads
    hd = cfg.hidden_size // nh
    eps = cfg.layer_norm_epsilon
    scale = 1.0 / math.sqrt(hd)

    def ln(x, w, b):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + eps) * w + b

    def step(params, k_caches, v_caches, tokens, lengths):
        # tokens/lengths: [slots] int32; caches: per-layer [S, max_len, nh, hd]
        S = max_slots
        pos_idx = jnp.minimum(lengths, params["pos"].shape[0] - 1)
        x = params["embed"][tokens] + params["pos"][pos_idx]        # [S, h]
        pos = jnp.arange(max_len)
        mask = pos[None, :] <= lengths[:, None]                    # [S, L]
        slot_idx = jnp.arange(S)
        wr = jnp.minimum(lengths, max_len - 1)
        new_k, new_v = [], []
        for p, kc, vc in zip(params["layers"], k_caches, v_caches):
            h1 = ln(x, p["ln1_w"], p["ln1_b"])
            qkv = (h1 @ p["qkv_w"] + p["qkv_b"]).reshape(S, 3, nh, hd)
            q, k1, v1 = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kc = kc.at[slot_idx, wr].set(k1)
            vc = vc.at[slot_idx, wr].set(v1)
            logits = jnp.einsum("shd,sLhd->shL", q, kc)
            logits = logits.astype(jnp.float32) * scale
            logits = jnp.where(mask[:, None, :], logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
            ctx = jnp.einsum("shL,sLhd->shd", probs, vc).reshape(S, nh * hd)
            x = x + (ctx @ p["out_w"] + p["out_b"])
            h2 = ln(x, p["ln2_w"], p["ln2_b"])
            m = jax.nn.gelu(h2 @ p["fc_in_w"] + p["fc_in_b"],
                            approximate=True)
            x = x + (m @ p["fc_out_w"] + p["fc_out_b"])
            new_k.append(kc)
            new_v.append(vc)
        xf = ln(x, params["lnf_w"], params["lnf_b"])
        logits = xf @ params["embed"].T                            # [S, vocab]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return nxt, new_k, new_v

    from ..jit import persistent_cache

    return persistent_cache.cached_jit(
        step, donate_argnums=(1, 2) if donate else (), label=label)


def _build_window_step(cfg, max_slots: int, n_blocks: int, page_len: int,
                       window: int, donate: bool, label: str,
                       fused: bool = False):
    """The PAGED executable family: embed ``W = window`` tokens per slot
    at positions ``lengths + [0..W)``, write their K/V through the page
    tables into the pool arenas, attend each window token causally against
    the page pool, and return the greedy argmax at every window position.

    One shape serves three roles — W=1 is the decode step, W=k+1 scores a
    draft model's k proposals (speculative verify), W=bucket prefills a
    prompt suffix (cold prefill is the zero-prefix special case). Rows
    whose page table is all-zero write only the scratch page, so a prefill
    call touches exactly one request's pages.

    ``fused=True`` (registry-gated: ``FLAGS_fused_kernels``) attends
    straight against the page table through the Pallas paged-attention
    kernel — the dense ``kc[tables]`` gathered context never
    materializes; ``fused=False`` keeps the composed gather-then-attend
    path (the CPU production path and the TPU A/B reference).
    """
    import jax
    import jax.numpy as jnp

    if fused:
        from ..kernels.pallas.paged_attention import paged_attention

    nh = cfg.num_attention_heads
    hd = cfg.hidden_size // nh
    eps = cfg.layer_norm_epsilon
    scale = 1.0 / math.sqrt(hd)
    S, B, W, PL = max_slots, n_blocks, window, page_len
    L = B * PL  # gathered context length per slot

    def ln(x, w, b):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + eps) * w + b

    def step(params, k_arenas, v_arenas, tables, tokens, lengths):
        # tables: [S, B] page ids; tokens: [S, W]; lengths: [S] (int32)
        P = k_arenas[0].shape[0]
        pos = lengths[:, None] + jnp.arange(W)                     # [S, W]
        pos_idx = jnp.minimum(pos, params["pos"].shape[0] - 1)
        x = params["embed"][tokens] + params["pos"][pos_idx]       # [S, W, h]
        j = jnp.arange(L)
        mask = j[None, None, :] <= pos[:, :, None]                 # [S, W, L]
        # write positions: page-table lookup of each window token's block;
        # blocks past the table (or past a request's allocation: table
        # entry 0) land in the scratch page — never another slot's pages
        blk = pos // PL
        pidx = jnp.take_along_axis(tables, jnp.minimum(blk, B - 1), axis=1)
        pidx = jnp.where(blk < B, pidx, 0)                         # [S, W]
        flat = (pidx * PL + pos % PL).reshape(-1)                  # [S*W]
        new_k, new_v = [], []
        for p, kc, vc in zip(params["layers"], k_arenas, v_arenas):
            h1 = ln(x, p["ln1_w"], p["ln1_b"])
            qkv = (h1 @ p["qkv_w"] + p["qkv_b"]).reshape(S, W, 3, nh, hd)
            q, k1, v1 = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            kc = kc.reshape(P * PL, nh, hd).at[flat].set(
                k1.reshape(S * W, nh, hd)).reshape(P, PL, nh, hd)
            vc = vc.reshape(P * PL, nh, hd).at[flat].set(
                v1.reshape(S * W, nh, hd)).reshape(P, PL, nh, hd)
            if fused:
                # attend against the page table directly (per-page online
                # softmax); key j visible iff j <= pos[s, w] — the same
                # containment the composed mask enforces
                # impl resolves through the registry: Pallas on TPU, the
                # composed twin on CPU, interpreter under
                # PT_PALLAS_INTERPRET=1 (parity tests)
                ctx = paged_attention(q, kc, vc, tables, pos, scale=scale)
            else:
                kk = kc[tables].reshape(S, L, nh, hd)
                vv = vc[tables].reshape(S, L, nh, hd)
                logits = jnp.einsum("swhd,sLhd->swhL", q, kk)
                logits = logits.astype(jnp.float32) * scale
                logits = jnp.where(mask[:, :, None, :], logits, -1e30)
                probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
                ctx = jnp.einsum("swhL,sLhd->swhd", probs, vv)
            ctx = ctx.reshape(S, W, nh * hd)
            x = x + (ctx @ p["out_w"] + p["out_b"])
            h2 = ln(x, p["ln2_w"], p["ln2_b"])
            m = jax.nn.gelu(h2 @ p["fc_in_w"] + p["fc_in_b"],
                            approximate=True)
            x = x + (m @ p["fc_out_w"] + p["fc_out_b"])
            new_k.append(kc)
            new_v.append(vc)
        xf = ln(x, params["lnf_w"], params["lnf_b"])
        logits = xf @ params["embed"].T                        # [S, W, vocab]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # behavior logprob of the greedy pick at every window position —
        # the post-training ledger rides it (f32: bf16 logits renormalize
        # poorly and these numbers cross processes)
        lf = logits.astype(jnp.float32)
        logp = (jnp.max(lf, axis=-1) -
                jax.scipy.special.logsumexp(lf, axis=-1))      # [S, W] f32
        return nxt, logp, new_k, new_v

    from ..jit import persistent_cache

    return persistent_cache.cached_jit(
        step, donate_argnums=(1, 2) if donate else (), label=label)


class GenerationEngine(EngineBase):
    """Continuous-batching generation server over a ``GPTForCausalLM``.

    ::

        eng = GenerationEngine(model, GenerationConfig(max_slots=4))
        eng.start()
        fut = eng.submit(prompt_ids, max_new_tokens=8, deadline_ms=None)
        full = fut.result()          # np.int64 [len(prompt) + generated]
        eng.stats()
        eng.close()

    Requests queue under admission control (``QueueFull`` beyond
    ``max_queue``); a prompt joins the decode batch as soon as a slot AND
    enough KV pages free — it never waits for the running sequences to
    finish. Slot-join order is earliest-deadline-first; requests that
    expire while queued are shed with ``DeadlineExceeded`` before any
    device time is spent. With ``prefix_cache`` on, a prompt whose leading
    page-blocks are already cached reuses those pages and prefills only
    its suffix. With a ``draft_model``, each decode round proposes
    ``spec_tokens`` draft tokens and verifies them in one window-step call
    — output stays token-for-token the target model's greedy path.
    """

    _close_timeout = 60.0  # an in-flight decode batch may take a while

    def __init__(self, model, config: Optional[GenerationConfig] = None,
                 name: Optional[str] = None):
        self.config = config or GenerationConfig()
        super().__init__(name or f"gen#{next(_GEN_NO)}")

        model.eval()  # serving semantics: dropout off
        self.model = model
        mcfg = model.config
        self.max_len = int(self.config.max_seq_len
                           or mcfg.max_position_embeddings)
        if self.max_len > mcfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.max_len} exceeds the model's position "
                f"table ({mcfg.max_position_embeddings})")
        for b in self.config.prefill_buckets:
            if b > self.max_len:
                raise ValueError(
                    f"prefill bucket {b} exceeds max_seq_len {self.max_len}")
        self._params = _extract_gpt_params(model)
        dtype = self._params["embed"].dtype
        nh = mcfg.num_attention_heads
        hd = mcfg.hidden_size // nh
        S = self.config.max_slots
        pl = self.config.page_len
        self._pl = pl
        self._n_blocks = B = -(-self.max_len // pl)  # ceil
        num_pages = self.config.num_pages
        if num_pages is None:
            # every slot's worst case + two cached prefixes' worth + scratch
            num_pages = S * B + 2 * B + 1
        warm = None
        if self.config.warm_pool_bytes and self.config.prefix_cache:
            warm = HostPagePool(
                capacity_bytes=self.config.warm_pool_bytes,
                admit_threshold=self.config.warm_admit_threshold)
        self._pool = PagedKVPool(mcfg.num_hidden_layers, num_pages, pl,
                                 nh, hd, dtype,
                                 prefix_cache=self.config.prefix_cache,
                                 warm_pool=warm)
        # cross-thread ops the worker must execute (the allocator and
        # the arenas are worker-owned): (fn, Future) pairs — the KV
        # export/install seam the page shipper rides
        self._ops: deque = deque()

        import jax

        donate = self.config.donate_cache and jax.default_backend() != "cpu"
        self._donate = donate
        self._mcfg = mcfg
        self._windows: Dict[int, Any] = {}  # W -> compiled window step

        # -- speculative decoding (draft model) --------------------------------
        self.spec_k = 0
        self._spec_on = True  # brownout toggle: set_speculative(False)
        if self.config.draft_model is not None:
            import jax.numpy as jnp

            dm = self.config.draft_model
            dm.eval()
            dcfg = dm.config
            if dcfg.vocab_size != mcfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dcfg.vocab_size} != target vocab "
                    f"{mcfg.vocab_size}")
            if dcfg.max_position_embeddings < self.max_len:
                raise ValueError(
                    f"draft position table ({dcfg.max_position_embeddings}) "
                    f"shorter than max_seq_len {self.max_len}")
            self.spec_k = max(1, self.config.spec_tokens)
            self._draft = dm
            self._dparams = _extract_gpt_params(dm)
            dnh = dcfg.num_attention_heads
            dhd = dcfg.hidden_size // dnh
            ddtype = self._dparams["embed"].dtype
            dlen = B * pl
            self._dk = [jnp.zeros((S, dlen, dnh, dhd), ddtype)
                        for _ in range(dcfg.num_hidden_layers)]
            self._dv = [jnp.zeros((S, dlen, dnh, dhd), ddtype)
                        for _ in range(dcfg.num_hidden_layers)]
            from .. import jit as jit_mod

            dlabel = f"serving:{self.name}:draft_decode"
            self._draft_step = jit_mod._maybe_audit(
                dlabel, _build_decode_step(dcfg, S, dlen, donate,
                                           label=dlabel))
            ilabel = f"serving:{self.name}:draft_insert"
            self._dinsert = jit_mod._maybe_audit(
                ilabel, jit_mod.persistent_cache.cached_jit(
                    lambda cache, kv, slot: jax.lax.dynamic_update_slice(
                        cache, kv, (slot, 0, 0, 0)),
                    donate_argnums=(0,) if donate else (), label=ilabel))

        self._slots = [_Slot(B) for _ in range(S)]
        # in-place weight push (post-training): a pending swap applies at
        # the first ZERO-ACTIVE step boundary — admission pauses while it
        # pends so in-flight requests finish on the version they started
        self._pending_swap = None  # (params_tree, version, Future) or None
        # memory truth: the page pool's K/V bytes (plus the draft model's
        # slot arena) ride in the `memory` provider — the fixed device
        # buffers continuous batching holds
        try:
            from ..observability.memory import register_component

            register_component(f"serving:{self.name}:kv_pages",
                               type(self)._kv_pool_bytes, owner=self)
        except Exception:
            pass
        # hub families: prefix-cache and speculative-decode truth for the
        # process-wide /metrics surface (per-engine labels)
        try:
            from ..observability import family, histogram

            self._fam_prefix = family("prefix_cache", ("engine", "event"))
            self._fam_spec = family("speculative", ("engine", "event"))
            # time-to-first-token: observed HERE (the replica knows when
            # its first token left prefill), so the fleet's SLO layer can
            # compute TTFT percentiles from merged buckets alone
            self._hist_ttft = histogram("ttft_ms")
            # request-size / occupancy truth for the online tuner: the
            # merged fleet feed of these two histograms is what derives
            # prefill buckets and slot counts (paddle_tpu.tuning.shapes)
            self._hist_prompt = histogram("prompt_tokens",
                                          PROMPT_TOKEN_BUCKETS)
            self._hist_slots = histogram("gen_active_slots", SLOT_BUCKETS)
        except Exception:
            self._fam_prefix = self._fam_spec = self._hist_ttft = None
            self._hist_prompt = self._hist_slots = None
        # slot-occupancy history: (slot, t0, t1, tokens) per residency —
        # the timeline track behind the pd_top occupancy view and the
        # chrome-trace slots:<engine> process
        self._slot_hist: deque = deque(maxlen=512)
        self._residencies = 0
        self._t_start = time.monotonic()
        self.metrics.gauge("slot_occupancy", self.slot_occupancy)
        self.metrics.gauge("kv_headroom", self.kv_headroom)
        # prefix-cache truth (hits/misses/evictions) rides the snapshot
        # so pd_top / render_snapshot show the warm-tier tuning baseline
        self.metrics.gauge("prefix_cache", self._prefix_cache_stats)

    def _prefix_cache_stats(self) -> Dict[str, Any]:
        trie = self._pool.trie
        if trie is None:
            return {}
        st = trie.stats()
        st["misses"] = st["lookups"] - st["hits"]
        if self._pool.warm is not None:
            st["warm"] = self._pool.warm.stats()
        return st

    # -- executables ----------------------------------------------------------
    def _window(self, W: int):
        """The compiled window step for window size ``W`` (built once per
        size; sizes come from the closed set {1, spec_k+1} ∪ buckets, so
        steady state never retraces)."""
        fn = self._windows.get(W)
        if fn is None:
            from .. import jit as jit_mod
            from ..kernels.registry import fused_enabled

            # build-time decision (executables are cached per engine);
            # the ":fused" label suffix keeps the retrace audit and the
            # persistent-cache keyspace honest about which path compiled
            fused = fused_enabled("paged_attention")
            label = f"serving:{self.name}:window{W}" + \
                (":fused" if fused else "")
            fn = jit_mod._maybe_audit(
                label, _build_window_step(self._mcfg, self.config.max_slots,
                                          self._n_blocks, self._pl, W,
                                          self._donate, label=label,
                                          fused=fused))
            self._windows[W] = fn
        return fn

    def warmup(self):
        """Compile the whole steady-state executable set up front (decode,
        speculative verify, every prefill bucket, draft steps) against the
        scratch page — a warm replica restarting under the persistent
        cache loads them all from disk with zero fresh XLA compiles."""
        import jax.numpy as jnp

        S, B = self.config.max_slots, self._n_blocks
        tables = jnp.zeros((S, B), jnp.int32)
        lengths = jnp.zeros(S, jnp.int32)
        sizes = [1] + ([self.spec_k + 1] if self.spec_k else []) + \
            [b for b in self.config.prefill_buckets]
        for W in sorted(set(sizes)):
            tokens = jnp.zeros((S, W), jnp.int32)
            _n, _lp, self._pool.k, self._pool.v = self._window(W)(
                self._params, self._pool.k, self._pool.v, tables, tokens,
                lengths)
        if self.spec_k:
            toks = jnp.zeros(S, jnp.int32)
            _n, self._dk, self._dv = self._draft_step(
                self._dparams, self._dk, self._dv, toks, lengths)
            # the draft PREFILL path too (its per-bucket insert
            # executables + the draft forward's op set) — slot 0's
            # garbage rows are overwritten at the first real admit
            for b in self.config.prefill_buckets:
                self._draft_prefill(0, np.zeros(b, dtype=np.int64))
        self.metrics.inc("warmup_runs")
        return self

    def _kv_pool_bytes(self) -> int:
        """Bytes held by the paged K/V pool (all layers), plus the draft
        model's slot arena when speculative decoding is on."""
        n = self._pool.bytes()
        if self.spec_k:
            n += sum(int(c.nbytes) for c in self._dk) + \
                sum(int(c.nbytes) for c in self._dv)
        return n

    # -- submission -----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               on_token=None, return_logprobs: bool = False,
               trace_parent: Optional[str] = None) -> "Future":
        """Queue one prompt (1-D int array). The future resolves to the
        full sequence (prompt + generated) as a 1-D np.int64 array. A
        ``deadline_ms`` bounds QUEUE time: expired requests are shed with
        ``DeadlineExceeded`` before prefill, and queued requests join
        slots earliest-deadline-first. ``on_token(t)`` (optional) fires
        once per emitted token IN ORDER, before the future resolves — the
        streaming seam the fleet RPC uses for replay/dedup bookkeeping;
        callbacks run on the engine worker thread and must be cheap.

        ``return_logprobs=True`` makes the future resolve to ``(full_seq,
        logprobs)`` — a float32 array, one behavior logprob per GENERATED
        token (the greedy pick's log-softmax under the weights that
        emitted it) — and calls ``on_token(t, lp)`` with two arguments.
        This is the post-training trajectory ledger: a replayed-after-
        failover request re-derives the same logprobs because greedy
        decoding re-walks the same tokens under the same weights."""
        self.metrics.inc("requests_total")
        fut: Future = Future()
        prompt = np.asarray(prompt_ids)
        if prompt.ndim != 1 or prompt.size == 0 or \
                not np.issubdtype(prompt.dtype, np.integer):
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest(
                "prompt must be a non-empty 1-D integer array"))
            return fut
        if max_new_tokens < 1:
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest("max_new_tokens must be >= 1"))
            return fut
        # observed BEFORE the bucket check: the tuner must see the true
        # request-size distribution, rejected oversizes included — a
        # shape that keeps rejecting traffic is exactly what it fixes
        if self._hist_prompt is not None:
            self._hist_prompt.observe(len(prompt))
        bucket = self._prefill_bucket(len(prompt))
        if bucket is None:
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest(
                f"prompt length {len(prompt)} exceeds the largest prefill "
                f"bucket {self.config.prefill_buckets[-1]}"))
            return fut
        if len(prompt) + max_new_tokens > self.max_len:
            # the model's position table (max_seq_len) cannot address the
            # asked-for continuation (len(out) == len(prompt) +
            # max_new_tokens is part of the contract)
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len {self.max_len}"))
            return fut
        needed = -(-(len(prompt) + max_new_tokens) // self._pl)
        if needed > self._pool.allocator.usable_pages:
            # paged admission bound: POOL capacity, not slot length — a
            # request that could never hold enough pages is rejected; one
            # that merely has to wait for pages stays queued
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest(
                f"request needs {needed} KV pages; the pool holds "
                f"{self._pool.allocator.usable_pages}"))
            return fut
        t_submit = time.monotonic()
        deadline = None if deadline_ms is None \
            else t_submit + deadline_ms / 1000.0
        req = _GenRequest(prompt.astype(np.int64), int(max_new_tokens), fut,
                          t_submit, deadline, on_token=on_token,
                          want_logprobs=return_logprobs)
        req.blocks = token_blocks(req.prompt, self._pl)
        req.total_blocks = needed
        # ``trace_parent`` is the fleet-minted context carried over the
        # submit frame: this engine's spans nest under it when the
        # supervisor's collector merges traces across processes
        tr = _tracer()
        req.trace = tr.start(self.name, kind="generate",
                             parent=trace_parent,
                             prompt_len=len(prompt),
                             max_new_tokens=int(max_new_tokens),
                             deadline_ms=deadline_ms)
        tr.span(req.trace, "admission", req.t_submit, time.monotonic())
        try:
            self._enqueue(req, self.config.max_queue)
        except Exception as e:  # QueueFull/EngineClosed backpressure
            tr.finish(req.trace, ok=False, error=type(e).__name__)
            raise
        return fut

    def _prefill_bucket(self, n: int) -> Optional[int]:
        for b in self.config.prefill_buckets:
            if b >= n:
                return b if b <= self.max_len else None
        return None

    def set_speculative(self, enabled: bool) -> None:
        """Brownout lever: toggle draft-model speculation per decode
        round. Off = classic W=1 decode (already warmed), shedding the
        draft's k dense steps per round under overload. The draft's
        prompt prefill keeps running so a later re-enable stays correct —
        only its proposal quality degrades until its cache catches up
        (the target verifies every token, so output never changes)."""
        self._spec_on = bool(enabled)

    def speculative_enabled(self) -> bool:
        return bool(self.spec_k) and self._spec_on

    # -- in-place weight push (post-training fast path) -----------------------
    def _coerce_swap_state(self, state) -> Dict[str, Any]:
        """Validate an incoming weight set against the live tree and land
        it device-ready. Accepts a ``GPTForCausalLM``, the nested param
        pytree, or the flat ``{dotted_name: array}`` wire shape."""
        import jax.numpy as jnp

        if hasattr(state, "gpt"):
            state = _extract_gpt_params(state)
        if "layers" not in state:
            state = nest_gpt_params(dict(state))

        def conv(old, new, path):
            if new is None:
                raise ValueError(f"swap_weights: missing param {path!r}")
            arr = jnp.asarray(np.asarray(new), dtype=old.dtype)
            if arr.shape != old.shape:
                raise ValueError(
                    f"swap_weights: {path!r} shape {arr.shape} != live "
                    f"shape {old.shape}")
            return arr

        if len(state.get("layers", ())) != len(self._params["layers"]):
            raise ValueError(
                f"swap_weights: {len(state.get('layers', ()))} layers != "
                f"live {len(self._params['layers'])}")
        new = {k: conv(v, state.get(k), k)
               for k, v in self._params.items() if k != "layers"}
        new["layers"] = [
            {k: conv(v, state["layers"][i].get(k), f"layers.{i}.{k}")
             for k, v in L.items()}
            for i, L in enumerate(self._params["layers"])]
        return new

    def swap_weights(self, state, version: Optional[int] = None,
                     timeout: Optional[float] = None) -> int:
        """Replace the TARGET model's served weights in place — the
        weight-push fast path (seconds, not a respawn). The swap is
        staged and applied by the worker at the first step boundary with
        zero active slots: admission pauses while it pends, so every
        in-flight request finishes bit-identically on the weight version
        it started on, and the first request admitted afterwards runs
        the new version. The prefix cache is dropped at the boundary
        (old-version KV pages are garbage under new weights). The draft
        model keeps its weights — it only PROPOSES; the swapped target
        verifies every token, so output correctness is version-pure
        (only acceptance rate can drift). Returns the new
        ``weight_version`` once applied."""
        params = self._coerce_swap_state(state)
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise EngineClosed("engine closed")
            if self._pending_swap is not None:
                raise RuntimeError("a weight swap is already pending")
            ver = int(version) if version is not None \
                else self.weight_version + 1
            self._pending_swap = (params, ver, fut)
            self._cond.notify_all()
            started = self._thread is not None
        if not started:
            self._apply_swap()  # no worker: nothing in flight to drain
        return fut.result(timeout=120.0 if timeout is None else timeout)

    def _apply_swap(self) -> None:
        """Land the staged weights (worker thread at a zero-active
        boundary, or inline when no worker runs)."""
        with self._cond:
            pend, self._pending_swap = self._pending_swap, None
        if pend is None:
            return
        params, ver, fut = pend
        try:
            self._params = params
            trie = self._pool.trie
            if trie is not None:  # cached prefixes are old-version KV
                trie.release_all(self._pool.allocator)
            self.weight_version = ver
            self.metrics.inc("weight_swaps")
            if not fut.done():
                fut.set_result(ver)
        except Exception as e:  # pragma: no cover - validation ran already
            if not fut.done():
                fut.set_exception(e)

    # -- router probes --------------------------------------------------------
    def kv_headroom(self) -> float:
        """Free fraction of the KV page pool (load-aware dispatch input)."""
        a = self._pool.allocator
        return round(a.free_pages / max(a.usable_pages, 1), 4)

    def prefix_match_tokens(self, prompt_ids, blocks=None) -> int:
        """Tokens of ``prompt_ids`` whose KV pages this engine already
        caches (prefix-affinity probe; takes no refs, bumps no LRU). A
        caller probing several replicas may pass the precomputed
        ``token_blocks(prompt, page_len, limit=(p-1)//page_len)``."""
        trie = self._pool.trie
        if trie is None:
            return 0
        if blocks is None:
            prompt = np.asarray(prompt_ids).reshape(-1)
            blocks = token_blocks(prompt, self._pl,
                                  limit=(len(prompt) - 1) // self._pl)
        return trie.match_len(blocks) * self._pl

    # -- KV page transfer (disaggregated prefill/decode) ----------------------
    def _run_on_worker(self, fn, timeout: float = 60.0):
        """Run ``fn()`` on the engine worker thread and return its result
        — the allocator and the K/V arenas are worker-owned, so export/
        install must serialize with decode at a step boundary. Runs
        inline when no worker thread exists yet."""
        with self._cond:
            if self._closed:
                raise EngineClosed("engine closed")
            started = self._thread is not None
            if started:
                fut: Future = Future()
                self._ops.append((fn, fut))
                self._cond.notify_all()
        if not started:
            return fn()
        return fut.result(timeout=timeout)

    def _drain_ops(self) -> None:
        """Execute queued cross-thread ops (worker thread, step boundary)."""
        while True:
            with self._cond:
                if not self._ops:
                    return
                fn, fut = self._ops.popleft()
            try:
                res = fn()
            except Exception as e:
                if not fut.done():
                    fut.set_exception(e)
            else:
                if not fut.done():
                    fut.set_result(res)

    def export_kv_pages(self, prompt_ids):
        """Read the cached KV of ``prompt_ids``' full prompt blocks out of
        the page pool as host arrays — the page shipper's source side.
        Returns ``(n_pages, k_stacks, v_stacks)`` with per-layer
        ``[n, page_len, heads, dim]`` stacks. Raises ``KeyError`` when the
        prompt's blocks are not all cached (caller falls back to
        re-prefill)."""
        prompt = np.asarray(prompt_ids).reshape(-1)
        blocks = token_blocks(prompt, self._pl)

        def _export():
            trie = self._pool.trie
            if trie is None:
                raise KeyError("prefix cache disabled: nothing to export")
            if not blocks:
                return 0, [], []
            pages = trie.match(blocks, self._pl, self._pool.allocator)
            try:
                if len(pages) < len(blocks):
                    raise KeyError(
                        f"only {len(pages)}/{len(blocks)} prompt blocks "
                        f"cached — cannot export")
                k_stacks, v_stacks = self._pool.read_pages(pages)
                return len(pages), k_stacks, v_stacks
            finally:
                for pg in pages:
                    self._pool.allocator.release(pg)

        out = self._run_on_worker(_export)
        self.metrics.inc("kv_exports")
        return out

    def install_kv_pages(self, prompt_ids, k_stacks, v_stacks) -> int:
        """Install shipped page CONTENTS for ``prompt_ids``' full prompt
        blocks: allocate pages, scatter-write the K/V, and adopt the
        chain into the prefix cache — the page shipper's sink side. The
        next submit sharing this prompt prefix reuses the pages instead
        of prefilling. Returns pages newly adopted (blocks already
        cached keep their pages — first writer wins)."""
        prompt = np.asarray(prompt_ids).reshape(-1)
        blocks = token_blocks(prompt, self._pl)
        n = len(blocks)
        got = int(k_stacks[0].shape[0]) if k_stacks else 0
        if got != n:
            raise BadRequest(
                f"{got} shipped pages != {n} full prompt blocks")

        def _install():
            trie = self._pool.trie
            if trie is None:
                raise BadRequest("prefix cache disabled: cannot install")
            if n == 0:
                return 0
            pages = self._pool.allocate(n)
            try:
                self._pool.write_pages(pages, k_stacks, v_stacks)
                adopted = trie.insert(blocks, pages, self._pool.allocator)
            finally:
                # the trie holds its own refs on adopted pages; ours drop
                # (unadopted duplicates free harmlessly here)
                for pg in pages:
                    self._pool.allocator.release(pg)
            return adopted

        out = self._run_on_worker(_install)
        self.metrics.inc("kv_installs")
        self.metrics.inc("kv_pages_installed", out)
        return out

    # -- the continuous-batching loop -----------------------------------------
    def _active(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s.req is not None]

    def _blocks_needed(self, req: _GenRequest) -> int:
        """Pages a request must be able to allocate at join time (worst
        case, minus what the prefix cache already holds). Block tuples are
        precomputed at submit — only the trie walk runs here."""
        trie = self._pool.trie
        if trie is None:
            return req.total_blocks
        m = trie.match_len(req.blocks[: (len(req.prompt) - 1) // self._pl])
        return req.total_blocks - m

    def _next_request(self) -> Optional[_GenRequest]:
        """Shed expired queued requests, then pick the earliest-deadline
        queued request whose KV pages can be allocated right now."""
        now = time.monotonic()
        shed: List[_GenRequest] = []
        picked: Optional[_GenRequest] = None
        with self._cond:
            for r in list(self._queue):
                if r.deadline is not None and now > r.deadline:
                    self._queue.remove(r)
                    shed.append(r)
            order = sorted(self._queue, key=_GenRequest.edf_key)
            for r in order:
                if self._pool.can_allocate(self._blocks_needed(r)):
                    self._queue.remove(r)
                    picked = r
                    break
        for r in shed:  # outside the lock: future callbacks may re-submit
            self.metrics.inc("shed_total")
            if not r.future.done():
                r.future.set_exception(DeadlineExceeded(
                    "deadline expired while queued"))
            _tracer().finish(r.trace, ok=False, error="DeadlineExceeded")
        return picked

    def _worker(self):
        while True:
            # cross-thread ops (KV export/install) land at the step
            # boundary, before admission — an installed prefix is
            # visible to the very next admit
            self._drain_ops()
            # a staged weight swap lands at the first zero-active step
            # boundary (admission pauses below until it does, so the
            # active set drains and in-flight work stays version-pure)
            if self._pending_swap is not None and not self._active():
                self._apply_swap()
            # admit queued prompts into free slots (join mid-flight,
            # earliest deadline first, bounded by KV page headroom)
            while self._pending_swap is None:
                free = next((i for i, s in enumerate(self._slots)
                             if s.req is None), None)
                if free is None:
                    break
                req = self._next_request()
                if req is None:
                    break
                try:
                    self._admit(free, req)
                except PoolExhausted:
                    # transient: pages freed by in-flight releases will
                    # cover it — requeue at the front, decode meanwhile
                    with self._cond:
                        self._queue.appendleft(req)
                    break
                except Exception as e:  # isolate: fail this prompt only
                    if not req.future.done():
                        req.future.set_exception(e)
                    _tracer().finish(req.trace, ok=False,
                                     error=type(e).__name__)
                    self.metrics.inc("errors_total")
                    self._release_pages(self._slots[free])
                    slot = self._slots[free]
                    slot.req, slot.length, slot.last_token = None, 0, 0
            active = self._active()
            if not active:
                with self._cond:
                    if self._closed and not self._queue:
                        pend, self._pending_swap = self._pending_swap, None
                        if pend is not None and not pend[2].done():
                            pend[2].set_exception(
                                EngineClosed("engine closed"))
                        while self._ops:
                            _fn, fut = self._ops.popleft()
                            if not fut.done():
                                fut.set_exception(
                                    EngineClosed("engine closed"))
                        return
                    if not self._queue and not self._ops:
                        # untimed: submit/close/op notify — no idle polling
                        self._cond.wait()
                continue
            if self._hist_slots is not None:
                # concurrent-occupancy sample per decode window: the
                # distribution the tuner derives max_slots from
                self._hist_slots.observe(len(active))
            try:
                self._decode_once(active)
            except Exception as e:  # decode fault: fail the in-flight batch
                now = time.monotonic()
                for i in active:
                    s = self._slots[i]
                    if s.req is not None:
                        if not s.req.future.done():
                            s.req.future.set_exception(e)
                        self._release_slot(i, now, failed=True,
                                           error=type(e).__name__)
                    else:
                        self._release_pages(s)
                        s.req, s.length, s.last_token = None, 0, 0
                self.metrics.inc("errors_total", len(active))
                self.metrics.inc("batch_failures")

    def _admit(self, slot_no: int, req: _GenRequest):
        """Join a prompt: borrow its cached prefix pages, allocate private
        pages for the rest, prefill ONLY the uncached suffix through the
        window step, and adopt its full prompt blocks into the prefix
        cache. The first generated token is the window's argmax at the
        last real prompt position (matching ``generate``'s contract)."""
        import jax.numpy as jnp

        p = len(req.prompt)
        pl = self._pl
        total_blocks = req.total_blocks
        t0 = time.monotonic()
        s = self._slots[slot_no]
        s.table[:] = 0
        # prefix reuse: longest cached chain of full prompt blocks, capped
        # so at least one suffix token remains to produce the first logits
        shared_pages: List[int] = []
        trie = self._pool.trie
        all_blocks = req.blocks
        if trie is not None:
            if self._pool.warm is not None:
                # warm tier: restore spilled pages for this chain before
                # matching, so a previously-evicted prefix costs a host
                # dequantize instead of a re-prefill
                self._pool.warm_restore(all_blocks[: (p - 1) // pl])
            shared_pages = trie.match(all_blocks[: (p - 1) // pl], pl,
                                      self._pool.allocator)
        m = len(shared_pages)
        try:
            private = self._pool.allocate(total_blocks - m)
        except PoolExhausted:
            for pg in shared_pages:
                self._pool.allocator.release(pg)
            raise
        # the queue span lands only once the join is certain — a
        # PoolExhausted requeue above must not double-record queue time
        _tracer().span(req.trace, "queue", req.t_submit, t0)
        s.table[:m] = shared_pages
        s.table[m:total_blocks] = private
        s.blocks, s.shared = total_blocks, m
        # COW hook: every block the decode path will write must be
        # exclusively ours. By construction they already are (the trie
        # shares FULL prompt blocks only), so this is a no-op guard — but
        # a future partial-block sharing scheme lands here.
        for bi in range(p // pl, total_blocks):
            pg, copied = self._pool.ensure_writable(int(s.table[bi]))
            if copied:
                s.table[bi] = pg
        # suffix prefill: one window-step call, this slot's pages only
        start = m * pl
        suffix = req.prompt[start:p]
        W = self._prefill_bucket(len(suffix))
        S, B = self.config.max_slots, self._n_blocks
        tokens = np.zeros((S, W), dtype=np.int32)
        tokens[slot_no, :len(suffix)] = suffix
        lengths = np.zeros(S, dtype=np.int32)
        lengths[slot_no] = start
        tables = np.zeros((S, B), dtype=np.int32)
        tables[slot_no] = s.table
        with _oom_guard("generation", label=f"serving:{self.name}:prefill",
                        engine=self.name, bucket=W):
            nxt, lp, self._pool.k, self._pool.v = self._window(W)(
                self._params, self._pool.k, self._pool.v,
                jnp.asarray(tables), jnp.asarray(tokens),
                jnp.asarray(lengths))
        first = int(np.asarray(nxt)[slot_no, len(suffix) - 1])
        first_lp = float(np.asarray(lp)[slot_no, len(suffix) - 1])
        # draft model prefills the WHOLE prompt through its own forward
        # (the draft is small; its dense slot arena has no prefix cache)
        if self.spec_k:
            self._draft_prefill(slot_no, req.prompt)
        # adopt this prompt's full blocks into the prefix cache so the
        # next same-prefix request skips their prefill
        if trie is not None:
            fp = p // pl
            trie.insert(all_blocks[:fp], [int(x) for x in s.table[:fp]],
                        self._pool.allocator)
            self.metrics.inc("prefix_hit_tokens", m * pl)
            if self._fam_prefix is not None:
                self._fam_prefix.inc((self.name, "lookup_tokens"), p)
                self._fam_prefix.inc((self.name, "hit_tokens"), m * pl)
        self.metrics.inc("prompt_tokens_total", p)
        self.metrics.inc("prefills_total")
        if m:
            self.metrics.inc("prefix_hits")
        self.metrics.observe_queue_wait((t0 - req.t_submit) * 1e3)
        t1 = time.monotonic()
        _tracer().span(req.trace, "prefill", t0, t1, bucket=W,
                       prompt_len=p, slot=slot_no, prefix_blocks=m)
        if self._hist_ttft is not None:
            self._hist_ttft.observe((t1 - req.t_submit) * 1e3)
        req.t_decode0 = t1

        s.req = req
        s.length = p
        s.last_token = first
        s.t0 = t1  # slot residency opens (occupancy track)
        self._note_token(req, first, first_lp)
        self._emit_finish_check(slot_no)

    def _note_token(self, req: _GenRequest, t: int, lp: float) -> None:
        """One emitted token: record it (token + behavior logprob) and
        fire the stream callback (a client callback must never sink the
        decode batch)."""
        req.generated.append(int(t))
        req.logprobs.append(float(lp))
        if req.on_token is not None:
            try:
                if req.want_logprobs:
                    req.on_token(int(t), float(lp))
                else:
                    req.on_token(int(t))
            except Exception:
                pass

    def _draft_prefill(self, slot_no: int, prompt: np.ndarray):
        """Land the draft model's K/V for the whole prompt in its slot
        arena (the draft proposes from position ``len(prompt)`` on)."""
        import jax.numpy as jnp

        from ..core import autograd
        from ..core.tensor import Tensor

        p = len(prompt)
        bucket = self._prefill_bucket(p)
        padded = np.zeros((1, bucket), dtype=np.int64)
        padded[0, :p] = prompt
        with autograd.no_grad():
            _h, caches = self._draft.gpt(Tensor(jnp.asarray(padded)),
                                         use_cache=True)
        slot = np.int32(slot_no)
        for li, (k, v) in enumerate(caches):
            self._dk[li] = self._dinsert(self._dk[li], k.data, slot)
            self._dv[li] = self._dinsert(self._dv[li], v.data, slot)

    def _decode_once(self, active: List[int]):
        """One decode round. Without a draft model this is the classic
        W=1 step (one token per active slot). With one, the draft
        proposes ``k`` tokens per slot (k dense decode steps), the target
        scores all k+1 window positions in ONE verify call, and each slot
        advances by its accepted run plus the target's own next token —
        emitted tokens are target argmaxes, so greedy output is unchanged.
        """
        from .. import profiler

        S, B = self.config.max_slots, self._n_blocks
        k = self.spec_k if self._spec_on else 0
        W = k + 1
        tokens = np.zeros((S, W), dtype=np.int32)
        lengths = np.zeros(S, dtype=np.int32)
        tables = np.zeros((S, B), dtype=np.int32)
        for i in active:
            s = self._slots[i]
            tokens[i, 0] = s.last_token
            lengths[i] = min(s.length, self.max_len - 1)
            tables[i] = s.table
        # chaos site: scripted decode fault at an exact decode-step index
        # (PT_FAULTS="decode_fault@step=2") — the in-flight requests fail,
        # their slots release, queued prompts keep being admitted
        self._decode_no = getattr(self, "_decode_no", -1) + 1
        _injector().check("decode_fault", engine=self.name,
                          step=self._decode_no)
        t_dec = time.monotonic()
        import jax.numpy as jnp

        with profiler.RecordEvent(
                f"serving::decode[{self.name} n{len(active)}]", "Serving"):
            if k:  # draft proposal: k dense decode steps, all slots batched
                cur = jnp.asarray(tokens[:, 0])
                for j in range(k):
                    with _oom_guard("generation",
                                    label=f"serving:{self.name}:draft",
                                    engine=self.name, step=self._decode_no):
                        nd, self._dk, self._dv = self._draft_step(
                            self._dparams, self._dk, self._dv, cur,
                            jnp.asarray(lengths + j))
                    tokens[:, j + 1] = np.asarray(nd)
                    cur = nd
            with _oom_guard("generation", label=f"serving:{self.name}:decode",
                            engine=self.name, step=self._decode_no):
                nxt, lp, self._pool.k, self._pool.v = self._window(W)(
                    self._params, self._pool.k, self._pool.v,
                    jnp.asarray(tables), jnp.asarray(tokens),
                    jnp.asarray(lengths))
        n = np.asarray(nxt)  # [S, W] target argmax at each window position
        lpn = np.asarray(lp)  # [S, W] its behavior logprob (f32)
        fr = self._flight()
        if fr is not None:  # decode steps land in the flight ring
            fr.record_serving_step(self.name, "decode",
                                   (time.monotonic() - t_dec) * 1e3,
                                   len(active))
        self.metrics.inc("decode_steps")
        self.metrics.inc("slot_rounds", len(active))
        self.metrics.observe_occupancy(len(active) / S)
        emitted_total = 0
        for i in active:
            s = self._slots[i]
            if k:
                a = greedy_accept(tokens[i, 1:k + 1], n[i, :k])
                # cap the advance at k so the draft cache stays in sync
                # (the all-accepted bonus would outrun what the draft saw)
                adv = min(a + 1, k)
                emit = [int(tokens[i, j + 1]) for j in range(adv - 1)]
                emit.append(int(n[i, adv - 1]))
                self.metrics.inc("spec_proposed", k)
                self.metrics.inc("spec_accepted", adv - 1)
                if self._fam_spec is not None:
                    self._fam_spec.inc((self.name, "proposed"), k)
                    self._fam_spec.inc((self.name, "accepted"), adv - 1)
            else:
                emit = [int(n[i, 0])]
            # every emitted token e IS the target argmax at window
            # position e (greedy_accept admits a draft token only when it
            # equals n[i, e]), so lpn[i, e] is its behavior logprob
            for e, t in enumerate(emit):
                s.length += 1
                s.last_token = t
                self._note_token(s.req, t, lpn[i, e])
                emitted_total += 1
                if self._emit_finish_check(i):
                    break
        self.metrics.inc("tokens_total", emitted_total)
        if k:
            self.metrics.inc("spec_rounds")
            if self._fam_spec is not None:
                self._fam_spec.inc((self.name, "rounds"))
                self._fam_spec.inc((self.name, "emitted"), emitted_total)

    def _emit_finish_check(self, slot_no: int) -> bool:
        """Finish-and-release when the slot's request is done (budget
        reached, EOS, or context exhausted). Returns True when released."""
        s = self._slots[slot_no]
        req = s.req
        eos = self.config.eos_token_id
        done = (len(req.generated) >= req.max_new_tokens
                or (eos is not None and req.generated[-1] == eos)
                or s.length >= self.max_len - 1)
        if not done:
            return False
        full = np.concatenate([req.prompt,
                               np.asarray(req.generated, dtype=np.int64)])
        if not req.future.done():
            if req.want_logprobs:
                req.future.set_result(
                    (full, np.asarray(req.logprobs, dtype=np.float32)))
            else:
                req.future.set_result(full)
        now = time.monotonic()
        self.metrics.observe_latency((now - req.t_submit) * 1e3)
        self.metrics.inc("responses_total")
        self.metrics.mark_done()
        self._release_slot(slot_no, now, failed=False)
        return True

    def _release_pages(self, s: _Slot) -> None:
        """Drop this slot's page refs (shared AND private; pages the trie
        adopted survive on its ref and stay reusable)."""
        for bi in range(s.blocks):
            self._pool.allocator.release(int(s.table[bi]))
        s.table[:] = 0
        s.blocks = s.shared = 0

    def _release_slot(self, slot_no: int, now: float, failed: bool,
                      error: Optional[str] = None):
        """Close the residency: decode span + completion on the request's
        trace, one span on the slot-occupancy track, history row for the
        pd_top occupancy view — and the KV pages go back to the pool."""
        s = self._slots[slot_no]
        req = s.req
        if req is not None:
            tr = _tracer()
            tokens = len(req.generated)
            if req.t_decode0 is not None:
                tr.span(req.trace, "decode", req.t_decode0, now,
                        tokens=tokens, slot=slot_no)
            tr.finish(req.trace, ok=not failed, error=error,
                      latency_ms=round((now - req.t_submit) * 1e3, 3))
            t0 = s.t0 or now
            tr.slot_span(self.name, slot_no, t0, now, req.trace,
                         tokens=tokens)
            self._slot_hist.append((slot_no, t0, now, tokens))
            self._residencies += 1
        self._release_pages(s)
        s.req = None
        s.length = 0
        s.last_token = 0
        s.t0 = 0.0

    # -- observability --------------------------------------------------------
    def slot_occupancy(self, window_s: float = 60.0) -> Dict[str, Any]:
        """Per-slot busy fraction over the recent window (history + live
        residencies) — the compact occupancy view pd_top renders."""
        now = time.monotonic()
        horizon = max(now - window_s, self._t_start)
        span = max(now - horizon, 1e-6)
        busy = {i: 0.0 for i in range(self.config.max_slots)}
        for slot, t0, t1, _tokens in list(self._slot_hist):
            lo, hi = max(t0, horizon), min(t1, now)
            if hi > lo:
                busy[slot] = busy.get(slot, 0.0) + (hi - lo)
        for i, s in enumerate(self._slots):
            if s.req is not None and s.t0:
                busy[i] = busy.get(i, 0.0) + (now - max(s.t0, horizon))
        return {
            "slots": self.config.max_slots,
            "active": len(self._active()),
            "busy_frac": {str(i): round(min(b / span, 1.0), 4)
                          for i, b in busy.items()},
            "residencies": self._residencies,
            "window_s": round(span, 1),
        }

    def stats(self) -> Dict[str, Any]:
        snap = self._stats_base()
        snap["max_slots"] = self.config.max_slots
        snap["active_slots"] = len(self._active())
        snap["kv_pages"] = self._pool.stats()
        c = snap["counters"]
        pt = c.get("prompt_tokens_total", 0)
        snap["prefix_hit_rate"] = round(
            c.get("prefix_hit_tokens", 0) / pt, 4) if pt else 0.0
        rounds = c.get("slot_rounds", 0)  # per-SEQUENCE decode rounds
        snap["effective_tokens_per_step"] = round(
            c.get("tokens_total", 0) / rounds, 3) if rounds else 0.0
        if self.spec_k:
            prop = c.get("spec_proposed", 0)
            snap["spec_acceptance"] = round(
                c.get("spec_accepted", 0) / prop, 4) if prop else 0.0
        return snap
