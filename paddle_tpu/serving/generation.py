"""Continuous batching for causal-LM generation (slot-based KV cache).

The static-batch decode loop (``GPTForCausalLM.generate``) holds the whole
batch until its slowest sequence finishes, and its KV cache grows one token
per step — a new XLA program per step. Serving inverts both decisions:

- the KV cache is a fixed-shape slot arena ``[slots, max_len, heads, dim]``
  per layer, so ONE decode executable serves every step (zero retraces);
- each sequence owns a slot only while it is generating — a finished
  sequence releases its slot and a queued prompt joins mid-flight at the
  next step boundary (the vLLM/Orca-style continuous-batching contract).

Prefill reuses ``models.gpt``'s KV-cache forward (``use_cache=True``) on
the user's model, padded to a small set of prompt buckets; the per-layer
K/V it returns is copied into the slot arena. The decode step re-reads the
SAME model weights (no duplication of math: qkv/out/fc projections, pre-LN,
tied embedding head — the GPT-2 recipe) but runs them at fixed shapes with
per-slot length masks, compiled once.

Greedy decoding (matching ``generate``'s argmax contract).
"""
from __future__ import annotations

import itertools
import math
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .base import BadRequest, EngineBase, _oom_guard, _tracer

__all__ = ["GenerationConfig", "GenerationEngine"]

_GEN_NO = itertools.count(1)


def _injector():
    from ..distributed.resilience.faults import injector

    return injector()


class GenerationConfig:
    """Slot arena + prompt bucket shape declaration."""

    def __init__(self, max_slots: int = 4, max_seq_len: Optional[int] = None,
                 prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128),
                 max_queue: int = 256, eos_token_id: Optional[int] = None,
                 donate_cache: bool = True):
        self.max_slots = int(max_slots)
        self.max_seq_len = max_seq_len  # None: model max_position_embeddings
        self.prefill_buckets = tuple(sorted({int(b)
                                             for b in prefill_buckets}))
        self.max_queue = int(max_queue)
        self.eos_token_id = eos_token_id
        self.donate_cache = donate_cache


class _GenRequest:
    __slots__ = ("prompt", "max_new_tokens", "future", "t_submit",
                 "generated", "trace", "t_decode0")

    def __init__(self, prompt, max_new_tokens, future, t_submit):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.future = future
        self.t_submit = t_submit
        self.generated: List[int] = []
        self.trace = None      # request-scoped trace id
        self.t_decode0 = None  # decode-phase start (prefill done)


class _Slot:
    __slots__ = ("req", "length", "last_token", "t0")

    def __init__(self):
        self.req: Optional[_GenRequest] = None
        self.length = 0
        self.last_token = 0
        self.t0 = 0.0  # residency start (occupancy track)


def _extract_gpt_params(model):
    """Read the live weights of a ``GPTForCausalLM`` as a jax pytree (the
    decode step closes over nothing — set_state_dict + a new engine picks
    up new weights)."""
    g = model.gpt

    def a(t):
        return t.data

    return {
        "embed": a(g.embed_tokens.weight),          # [vocab, h]
        "pos": a(g.embed_positions.weight),         # [P, h]
        "lnf_w": a(g.ln_f.weight), "lnf_b": a(g.ln_f.bias),
        "layers": [
            {"ln1_w": a(L.ln_1.weight), "ln1_b": a(L.ln_1.bias),
             "qkv_w": a(L.attn.qkv_proj.weight),
             "qkv_b": a(L.attn.qkv_proj.bias),
             "out_w": a(L.attn.out_proj.weight),
             "out_b": a(L.attn.out_proj.bias),
             "ln2_w": a(L.ln_2.weight), "ln2_b": a(L.ln_2.bias),
             "fc_in_w": a(L.fc_in.weight), "fc_in_b": a(L.fc_in.bias),
             "fc_out_w": a(L.fc_out.weight), "fc_out_b": a(L.fc_out.bias)}
            for L in g.layers],
    }


def _build_decode_step(cfg, max_slots: int, max_len: int, donate: bool):
    """One fixed-shape executable: token+position embed, per-layer pre-LN
    attention against the slot arena (length-masked), MLP, tied head,
    greedy argmax. Cache buffers are donated so XLA updates in place."""
    import jax
    import jax.numpy as jnp

    nh = cfg.num_attention_heads
    hd = cfg.hidden_size // nh
    eps = cfg.layer_norm_epsilon
    scale = 1.0 / math.sqrt(hd)

    def ln(x, w, b):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + eps) * w + b

    def step(params, k_caches, v_caches, tokens, lengths):
        # tokens/lengths: [slots] int32; caches: per-layer [S, max_len, nh, hd]
        S = max_slots
        x = params["embed"][tokens] + params["pos"][lengths]       # [S, h]
        pos = jnp.arange(max_len)
        mask = pos[None, :] <= lengths[:, None]                    # [S, L]
        slot_idx = jnp.arange(S)
        new_k, new_v = [], []
        for p, kc, vc in zip(params["layers"], k_caches, v_caches):
            h1 = ln(x, p["ln1_w"], p["ln1_b"])
            qkv = (h1 @ p["qkv_w"] + p["qkv_b"]).reshape(S, 3, nh, hd)
            q, k1, v1 = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kc = kc.at[slot_idx, lengths].set(k1)
            vc = vc.at[slot_idx, lengths].set(v1)
            logits = jnp.einsum("shd,sLhd->shL", q, kc)
            logits = logits.astype(jnp.float32) * scale
            logits = jnp.where(mask[:, None, :], logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
            ctx = jnp.einsum("shL,sLhd->shd", probs, vc).reshape(S, nh * hd)
            x = x + (ctx @ p["out_w"] + p["out_b"])
            h2 = ln(x, p["ln2_w"], p["ln2_b"])
            m = jax.nn.gelu(h2 @ p["fc_in_w"] + p["fc_in_b"],
                            approximate=True)
            x = x + (m @ p["fc_out_w"] + p["fc_out_b"])
            new_k.append(kc)
            new_v.append(vc)
        xf = ln(x, params["lnf_w"], params["lnf_b"])
        logits = xf @ params["embed"].T                            # [S, vocab]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return nxt, new_k, new_v

    donate_argnums = (1, 2) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


class GenerationEngine(EngineBase):
    """Continuous-batching generation server over a ``GPTForCausalLM``.

    ::

        eng = GenerationEngine(model, GenerationConfig(max_slots=4))
        eng.start()
        fut = eng.submit(prompt_ids, max_new_tokens=8)
        full = fut.result()          # np.int64 [len(prompt) + generated]
        eng.stats()
        eng.close()

    Requests queue under admission control (``QueueFull`` beyond
    ``max_queue``); a prompt joins the decode batch as soon as a slot frees
    — it never waits for the running sequences to finish.
    """

    _close_timeout = 60.0  # an in-flight decode batch may take a while

    def __init__(self, model, config: Optional[GenerationConfig] = None,
                 name: Optional[str] = None):
        import jax.numpy as jnp

        self.config = config or GenerationConfig()
        super().__init__(name or f"gen#{next(_GEN_NO)}")

        model.eval()  # serving semantics: dropout off
        self.model = model
        mcfg = model.config
        self.max_len = int(self.config.max_seq_len
                           or mcfg.max_position_embeddings)
        if self.max_len > mcfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.max_len} exceeds the model's position "
                f"table ({mcfg.max_position_embeddings})")
        for b in self.config.prefill_buckets:
            if b > self.max_len:
                raise ValueError(
                    f"prefill bucket {b} exceeds max_seq_len {self.max_len}")
        self._params = _extract_gpt_params(model)
        dtype = self._params["embed"].dtype
        nh = mcfg.num_attention_heads
        hd = mcfg.hidden_size // nh
        S = self.config.max_slots
        self._k = [jnp.zeros((S, self.max_len, nh, hd), dtype)
                   for _ in range(mcfg.num_hidden_layers)]
        self._v = [jnp.zeros((S, self.max_len, nh, hd), dtype)
                   for _ in range(mcfg.num_hidden_layers)]

        import jax

        donate = self.config.donate_cache and jax.default_backend() != "cpu"
        from .. import jit as jit_mod

        self._decode = jit_mod._maybe_audit(
            f"serving:{self.name}:decode",
            _build_decode_step(mcfg, S, self.max_len, donate))
        self._insert = jax.jit(
            lambda cache, kv, slot: jax.lax.dynamic_update_slice(
                cache, kv, (slot, 0, 0, 0)),
            donate_argnums=(0,) if donate else ())

        self._slots = [_Slot() for _ in range(S)]
        # memory truth: the slot arena's K/V bytes ride in the `memory`
        # provider (the one fixed-shape buffer continuous batching holds)
        try:
            from ..observability.memory import register_component

            register_component(f"serving:{self.name}:kv_arena",
                               type(self)._kv_arena_bytes, owner=self)
        except Exception:
            pass
        # slot-occupancy history: (slot, t0, t1, tokens) per residency —
        # the timeline track behind the pd_top occupancy view and the
        # chrome-trace slots:<engine> process
        self._slot_hist: deque = deque(maxlen=512)
        self._residencies = 0
        self._t_start = time.monotonic()
        self.metrics.gauge("slot_occupancy", self.slot_occupancy)

    def _kv_arena_bytes(self) -> int:
        """Bytes held by the fixed-shape slot K/V arena (all layers)."""
        return sum(int(c.nbytes) for c in self._k) + \
            sum(int(c.nbytes) for c in self._v)

    # -- submission -----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 16) -> "Future":
        """Queue one prompt (1-D int array). The future resolves to the
        full sequence (prompt + generated) as a 1-D np.int64 array."""
        self.metrics.inc("requests_total")
        fut: Future = Future()
        prompt = np.asarray(prompt_ids)
        if prompt.ndim != 1 or prompt.size == 0 or \
                not np.issubdtype(prompt.dtype, np.integer):
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest(
                "prompt must be a non-empty 1-D integer array"))
            return fut
        if max_new_tokens < 1:
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest("max_new_tokens must be >= 1"))
            return fut
        bucket = self._prefill_bucket(len(prompt))
        if bucket is None:
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest(
                f"prompt length {len(prompt)} exceeds the largest prefill "
                f"bucket {self.config.prefill_buckets[-1]}"))
            return fut
        if len(prompt) + max_new_tokens > self.max_len:
            # don't silently truncate: the slot arena cannot hold the asked-
            # for continuation (len(out) == len(prompt) + max_new_tokens is
            # part of the contract)
            self.metrics.inc("errors_total")
            fut.set_exception(BadRequest(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len {self.max_len}"))
            return fut
        req = _GenRequest(prompt.astype(np.int64), int(max_new_tokens), fut,
                          time.monotonic())
        tr = _tracer()
        req.trace = tr.start(self.name, kind="generate",
                             prompt_len=len(prompt),
                             max_new_tokens=int(max_new_tokens))
        tr.span(req.trace, "admission", req.t_submit, time.monotonic())
        try:
            self._enqueue(req, self.config.max_queue)
        except Exception as e:  # QueueFull/EngineClosed backpressure
            tr.finish(req.trace, ok=False, error=type(e).__name__)
            raise
        return fut

    def _prefill_bucket(self, n: int) -> Optional[int]:
        for b in self.config.prefill_buckets:
            if b >= n:
                return b if b <= self.max_len else None
        return None

    # -- the continuous-batching loop -----------------------------------------
    def _active(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s.req is not None]

    def _worker(self):
        while True:
            # admit queued prompts into free slots (join mid-flight)
            admitted = True
            while admitted:
                admitted = False
                free = next((i for i, s in enumerate(self._slots)
                             if s.req is None), None)
                if free is None:
                    break
                with self._cond:
                    req = self._queue.popleft() if self._queue else None
                if req is None:
                    break
                try:
                    self._admit(free, req)
                except Exception as e:  # isolate: fail this prompt only
                    if not req.future.done():
                        req.future.set_exception(e)
                    _tracer().finish(req.trace, ok=False,
                                     error=type(e).__name__)
                    self.metrics.inc("errors_total")
                    slot = self._slots[free]
                    slot.req, slot.length, slot.last_token = None, 0, 0
                admitted = True
            active = self._active()
            if not active:
                with self._cond:
                    if self._closed and not self._queue:
                        return
                    if not self._queue:
                        # untimed: submit/close notify — no idle polling
                        self._cond.wait()
                continue
            try:
                self._decode_once(active)
            except Exception as e:  # decode fault: fail the in-flight batch
                now = time.monotonic()
                for i in active:
                    s = self._slots[i]
                    if s.req is not None:
                        if not s.req.future.done():
                            s.req.future.set_exception(e)
                        self._release_slot(i, now, failed=True,
                                           error=type(e).__name__)
                    else:
                        s.req, s.length, s.last_token = None, 0, 0
                self.metrics.inc("errors_total", len(active))
                self.metrics.inc("batch_failures")

    def _admit(self, slot_no: int, req: _GenRequest):
        """Prefill the prompt through the model's own KV-cache forward and
        land its K/V in the slot arena; the first generated token comes from
        the prefill logits (matching ``generate``'s contract)."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        p = len(req.prompt)
        bucket = self._prefill_bucket(p)
        padded = np.zeros((1, bucket), dtype=np.int64)
        padded[0, :p] = req.prompt
        t0 = time.monotonic()
        _tracer().span(req.trace, "queue", req.t_submit, t0)
        from ..core import autograd

        with autograd.no_grad():
            hidden, caches = self.model.gpt(Tensor(jnp.asarray(padded)),
                                            use_cache=True)
        # per-layer K/V [1, bucket, nh, hd] -> arena rows (tail is garbage
        # from padded positions; decode masks j <= length so it is never
        # read before being overwritten)
        slot = np.int32(slot_no)
        for li, (k, v) in enumerate(caches):
            self._k[li] = self._insert(self._k[li], k.data, slot)
            self._v[li] = self._insert(self._v[li], v.data, slot)
        # first token: argmax of the tied-head logits at the last REAL
        # prompt position (hidden[:, p-1])
        logits = hidden.data[0, p - 1, :] @ self._params["embed"].T
        first = int(np.asarray(jnp.argmax(logits)))
        self.metrics.inc("prefills_total")
        self.metrics.observe_queue_wait((t0 - req.t_submit) * 1e3)
        t1 = time.monotonic()
        _tracer().span(req.trace, "prefill", t0, t1, bucket=bucket,
                       prompt_len=p, slot=slot_no)
        req.t_decode0 = t1

        s = self._slots[slot_no]
        s.req = req
        s.length = p
        s.last_token = first
        s.t0 = t1  # slot residency opens (occupancy track)
        req.generated.append(first)
        self._maybe_finish(slot_no)

    def _decode_once(self, active: List[int]):
        from .. import profiler

        S = self.config.max_slots
        tokens = np.zeros(S, dtype=np.int32)
        lengths = np.zeros(S, dtype=np.int32)
        for i, s in enumerate(self._slots):
            if s.req is not None:
                tokens[i] = s.last_token
                # write position: current length (clamped defensively; a
                # slot at max_len is finished before decode in
                # _maybe_finish, so the clamp never fires for active slots)
                lengths[i] = min(s.length, self.max_len - 1)
        # chaos site: scripted decode fault at an exact decode-step index
        # (PT_FAULTS="decode_fault@step=2") — the in-flight requests fail,
        # their slots release, queued prompts keep being admitted
        self._decode_no = getattr(self, "_decode_no", -1) + 1
        _injector().check("decode_fault", engine=self.name,
                          step=self._decode_no)
        t_dec = time.monotonic()
        with profiler.RecordEvent(
                f"serving::decode[{self.name} n{len(active)}]", "Serving"):
            with _oom_guard("generation", label=f"serving:{self.name}:decode",
                            engine=self.name, step=self._decode_no):
                nxt, self._k, self._v = self._decode(
                    self._params, self._k, self._v, tokens, lengths)
        nxt = np.asarray(nxt)
        fr = self._flight()
        if fr is not None:  # decode steps land in the flight ring
            fr.record_serving_step(self.name, "decode",
                                   (time.monotonic() - t_dec) * 1e3,
                                   len(active))
        self.metrics.inc("decode_steps")
        self.metrics.inc("tokens_total", len(active))
        self.metrics.observe_occupancy(len(active) / S)
        for i in active:
            s = self._slots[i]
            s.length += 1
            s.last_token = int(nxt[i])
            s.req.generated.append(s.last_token)
            self._maybe_finish(i)

    def _maybe_finish(self, slot_no: int):
        s = self._slots[slot_no]
        req = s.req
        eos = self.config.eos_token_id
        done = (len(req.generated) >= req.max_new_tokens
                or (eos is not None and req.generated[-1] == eos)
                or s.length >= self.max_len - 1)
        if not done:
            return
        full = np.concatenate([req.prompt,
                               np.asarray(req.generated, dtype=np.int64)])
        if not req.future.done():
            req.future.set_result(full)
        now = time.monotonic()
        self.metrics.observe_latency((now - req.t_submit) * 1e3)
        self.metrics.inc("responses_total")
        self.metrics.mark_done()
        self._release_slot(slot_no, now, failed=False)

    def _release_slot(self, slot_no: int, now: float, failed: bool,
                      error: Optional[str] = None):
        """Close the residency: decode span + completion on the request's
        trace, one span on the slot-occupancy track, history row for the
        pd_top occupancy view."""
        s = self._slots[slot_no]
        req = s.req
        if req is not None:
            tr = _tracer()
            tokens = len(req.generated)
            if req.t_decode0 is not None:
                tr.span(req.trace, "decode", req.t_decode0, now,
                        tokens=tokens, slot=slot_no)
            tr.finish(req.trace, ok=not failed, error=error,
                      latency_ms=round((now - req.t_submit) * 1e3, 3))
            t0 = s.t0 or now
            tr.slot_span(self.name, slot_no, t0, now, req.trace,
                         tokens=tokens)
            self._slot_hist.append((slot_no, t0, now, tokens))
            self._residencies += 1
        s.req = None
        s.length = 0
        s.last_token = 0
        s.t0 = 0.0

    # -- observability --------------------------------------------------------
    def slot_occupancy(self, window_s: float = 60.0) -> Dict[str, Any]:
        """Per-slot busy fraction over the recent window (history + live
        residencies) — the compact occupancy view pd_top renders."""
        now = time.monotonic()
        horizon = max(now - window_s, self._t_start)
        span = max(now - horizon, 1e-6)
        busy = {i: 0.0 for i in range(self.config.max_slots)}
        for slot, t0, t1, _tokens in list(self._slot_hist):
            lo, hi = max(t0, horizon), min(t1, now)
            if hi > lo:
                busy[slot] = busy.get(slot, 0.0) + (hi - lo)
        for i, s in enumerate(self._slots):
            if s.req is not None and s.t0:
                busy[i] = busy.get(i, 0.0) + (now - max(s.t0, horizon))
        return {
            "slots": self.config.max_slots,
            "active": len(self._active()),
            "busy_frac": {str(i): round(min(b / span, 1.0), 4)
                          for i, b in busy.items()},
            "residencies": self._residencies,
            "window_s": round(span, 1),
        }

    def stats(self) -> Dict[str, Any]:
        snap = self._stats_base()
        snap["max_slots"] = self.config.max_slots
        snap["active_slots"] = len(self._active())
        return snap
