"""Load-aware multi-replica router: the serving fleet's front door.

Reference lineage: the reference serves a *fleet* — ``dist_model.cc``
drives N predictor ranks behind a dispatcher. Here N ``GenerationEngine``
replicas (threads or processes warm-started from the shared persistent
executable cache) sit behind ONE admission-controlled ``ReplicaRouter``:

- **admission control**: a fleet-wide queue bound plus per-tenant
  in-flight quotas (``TenantQuotaExceeded`` — a ``QueueFull`` subclass, so
  existing backpressure handling applies);
- **load-aware dispatch**: each submit scores every healthy replica from
  its REAL state — queue depth (backpressure), KV-page headroom (the
  PR-8 memory gauges' serving twin), and the p95 of its recent request
  latencies (PR-7's trace-fed latency window) — and picks the cheapest;
- **prefix affinity**: a prompt whose leading page-blocks are already in
  some replica's prefix cache is steered there (its pages are reusable
  *only* on the replica that holds them), unless that replica is
  overloaded — affinity is a bounded bonus, not a hard pin;
- **fault routing**: a replica whose submit raises ``EngineClosed`` (or
  dies outright) is marked down and traffic re-dispatches to survivors;
  the queue keeps draining.

The router is thread-safe and engine-shaped: ``submit() -> Future``,
``stats()``, context-manager lifecycle.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import (BadRequest, DeadlineExceeded, EngineClosed, QueueFull,
                   ReplicaFault)
from .generation import GenerationEngine
from .paged_kv import token_blocks

__all__ = ["RouterConfig", "ReplicaRouter", "TenantQuotaExceeded",
           "classify_submit_error", "score_candidates"]


class TenantQuotaExceeded(QueueFull):
    """The tenant's in-flight quota is exhausted (admission control)."""


def classify_submit_error(e: BaseException) -> str:
    """What a replica's ``submit`` raising ``e`` means for FENCING:

    - ``"busy"``: backpressure (``QueueFull``) — try the next candidate,
      the replica is healthy;
    - ``"request"``: the REQUEST is at fault (malformed payload, expired
      deadline, unexpected programming error) — surface it to the caller
      and leave the replica in the candidate set;
    - ``"fault"``: the REPLICA is at fault (closed, lost RPC connection,
      dead process) — fence it and re-dispatch through the survivors.

    Order matters: ``DeadlineExceeded`` IS a ``TimeoutError`` which IS an
    ``OSError`` in py3, so request shapes are matched before the
    connection-error shapes. Unknown exceptions default to ``"request"``
    — fencing a healthy replica on every stray bug starves the fleet one
    exception at a time (the PR-15 satellite's regression)."""
    if isinstance(e, QueueFull):
        return "busy"
    if isinstance(e, (BadRequest, DeadlineExceeded)):
        return "request"
    if isinstance(e, (EngineClosed, ReplicaFault, ConnectionError,
                      BrokenPipeError, OSError)):
        return "fault"
    return "request"


def score_candidates(cfg: "RouterConfig", prompt,
                     candidates: Sequence[Any],
                     pool: Optional[str] = None
                     ) -> Tuple[List[float], List[int]]:
    """(score, matched-prefix-tokens) per candidate, lower score wins —
    the load/affinity dispatch policy shared by ``ReplicaRouter`` (thread
    replicas) and ``ServingFleet`` (process replicas). The prefix match
    is probed ONCE here and reused for the affinity accounting — a
    post-submit probe would count the request's own just-inserted blocks
    as a hit.

    ``pool`` specializes the formula for a disaggregated fleet:
    ``"prefill"`` replicas are picked for the compute-bound first leg —
    queue depth dominates (a deep queue head-of-line-blocks the whole
    prefill) and KV pressure barely matters (pages are shipped out
    right after); ``"decode"`` replicas are picked for where the pages
    LAND — KV headroom and prefix/page affinity dominate (the request
    lives there for its whole decode). ``None`` keeps the classic fused
    weighting."""
    p = max(len(prompt), 1)
    # the prefix-match probe runs FIRST: for an RPC-backed replica it
    # is the combined probe whose reply also carries queue depth /
    # headroom / p95, so the reads below are cache hits — one round
    # trip per candidate, not four. Token-block chains are built ONCE
    # per page size, not once per replica — for an in-process engine
    # the probe is then just a trie walk.
    blk_cache: Dict[int, Any] = {}
    matches = []
    for r in candidates:
        pl = getattr(getattr(r, "config", None), "page_len", None)
        if pl is None:
            matches.append(r.prefix_match_tokens(prompt))
            continue
        if pl not in blk_cache:
            blk_cache[pl] = token_blocks(prompt, pl,
                                         limit=(len(prompt) - 1) // pl)
        matches.append(r.prefix_match_tokens(prompt, blocks=blk_cache[pl]))
    depths = [r.queue_depth() for r in candidates]
    p95s = [r.metrics.latency_percentile(95) for r in candidates]
    p95_hi = max(max(p95s), 1e-9)
    q_hi = max(max(depths), 1)
    if pool == "prefill":
        wq, wm, wl, wa = 2.0 * cfg.w_queue, 0.1 * cfg.w_memory, \
            cfg.w_latency, 0.5 * cfg.w_affinity
    elif pool == "decode":
        wq, wm, wl, wa = 0.5 * cfg.w_queue, 2.0 * cfg.w_memory, \
            cfg.w_latency, 2.0 * cfg.w_affinity
    else:
        wq, wm, wl, wa = cfg.w_queue, cfg.w_memory, cfg.w_latency, \
            cfg.w_affinity
    scores = []
    for r, d, p95, match in zip(candidates, depths, p95s, matches):
        s = wq * (d / q_hi) \
            + wm * (1.0 - r.kv_headroom()) \
            + wl * (p95 / p95_hi) \
            - wa * (match / p)
        scores.append(s)
    return scores, matches


@dataclass
class RouterConfig:
    """Dispatch-policy knobs. Score = lower-is-better; the affinity bonus
    subtracts, everything else adds."""

    max_inflight: int = 1024            # fleet-wide admission bound
    tenant_quotas: Dict[str, int] = field(default_factory=dict)
    default_quota: Optional[int] = None  # None: unlimited per tenant
    w_queue: float = 1.0                # per queued request (normalized)
    w_memory: float = 0.5               # (1 - kv headroom)
    w_latency: float = 0.5              # p95 normalized across replicas
    w_affinity: float = 2.0             # * matched-prefix fraction

    def quota_for(self, tenant: str) -> Optional[int]:
        return self.tenant_quotas.get(tenant, self.default_quota)


class ReplicaRouter:
    """Admission-controlled front door over N ``GenerationEngine``
    replicas.

    ::

        router = ReplicaRouter([eng_a, eng_b], RouterConfig(
            tenant_quotas={"free": 4}, default_quota=64))
        fut = router.submit(prompt, max_new_tokens=8, tenant="free")
        fut.result()
        router.stats()     # fleet + per-replica snapshot
        router.close()
    """

    def __init__(self, replicas: Sequence[GenerationEngine],
                 config: Optional[RouterConfig] = None,
                 name: str = "router"):
        if not replicas:
            raise ValueError("need at least one replica")
        self.name = name
        self.config = config or RouterConfig()
        self._replicas = list(replicas)
        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        self._lock = _named_lock(f"serving.Router[{name}]._lock")
        self._down: set = set()          # replica names marked unhealthy
        self._inflight: Dict[str, int] = {}   # per-tenant in-flight
        self._inflight_total = 0
        self._routed: Dict[str, int] = {r.name: 0 for r in self._replicas}
        self._affinity_hits = 0
        self._readmitted = 0
        self._rejected = {"quota": 0, "capacity": 0}
        self._closed = False
        self._t0 = time.monotonic()

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        for r in self._replicas:
            r.start()
        return self

    def close(self, drain: bool = True):
        with self._lock:
            self._closed = True
        for r in self._replicas:
            try:
                r.close(drain=drain)
            except Exception:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- health ---------------------------------------------------------------
    def mark_down(self, replica_name: str) -> None:
        with self._lock:
            self._down.add(replica_name)

    def mark_up(self, replica_name: str) -> None:
        with self._lock:
            self._down.discard(replica_name)

    def healthy(self) -> List[GenerationEngine]:
        with self._lock:
            down = set(self._down)
        return [r for r in self._replicas if r.name not in down]

    def probe_down(self) -> List[str]:
        """Health-probe every fenced replica and RE-ADMIT the ones that
        pass (fence -> probe -> re-admission): a replica fenced on a
        transient fault — or restarted by the fleet supervisor — rejoins
        the candidate set, and prefix-affinity routing resumes steering
        it the prefixes it still caches. A replica without a ``health``
        probe stays fenced (only positive evidence re-admits)."""
        with self._lock:
            down = set(self._down)
        readmitted = []
        for r in self._replicas:
            if r.name not in down:
                continue
            probe = getattr(r, "health", None)
            try:
                ok = bool(probe()) if probe is not None else False
            except Exception:
                ok = False
            if ok:
                self.mark_up(r.name)
                readmitted.append(r.name)
        if readmitted:
            with self._lock:
                self._readmitted += len(readmitted)
        return readmitted

    # -- dispatch -------------------------------------------------------------
    def _scores(self, prompt, candidates: List[GenerationEngine]
                ) -> Tuple[List[float], List[int]]:
        return score_candidates(self.config, prompt, candidates)

    def submit(self, prompt_ids, max_new_tokens: int = 16,
               tenant: str = "default",
               deadline_ms: Optional[float] = None):
        """Route one prompt to the best replica; returns its Future. The
        returned future resolves/fails exactly as the owning engine's
        would — the router adds admission control and placement only."""
        with self._lock:
            if self._closed:
                raise EngineClosed("router closed")
            if self._inflight_total >= self.config.max_inflight:
                self._rejected["capacity"] += 1
                raise QueueFull(
                    f"fleet at capacity ({self.config.max_inflight})")
            quota = self.config.quota_for(tenant)
            if quota is not None and \
                    self._inflight.get(tenant, 0) >= quota:
                self._rejected["quota"] += 1
                raise TenantQuotaExceeded(
                    f"tenant {tenant!r} at quota ({quota})")
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
            self._inflight_total += 1
        prompt = np.asarray(prompt_ids).reshape(-1)
        try:
            fut = self._dispatch(prompt, max_new_tokens, deadline_ms)
        except Exception:
            self._done(tenant)
            raise
        fut.add_done_callback(lambda _f: self._done(tenant))
        return fut

    def _dispatch(self, prompt, max_new_tokens, deadline_ms):
        last_exc: Optional[Exception] = None
        tried = 0
        probed = False
        while True:
            candidates = self.healthy()
            if not candidates and not probed:
                # last resort before failing the request: maybe a fenced
                # replica recovered (restarted by the fleet supervisor)
                probed = True
                if self.probe_down():
                    continue
            if not candidates:
                raise EngineClosed("no healthy replicas")
            scores, matches = self._scores(prompt, candidates)
            order = sorted(range(len(candidates)), key=scores.__getitem__)
            progressed = False
            for idx in order:
                r = candidates[idx]
                try:
                    fut = r.submit(prompt, max_new_tokens,
                                   deadline_ms=deadline_ms)
                except Exception as e:
                    kind = classify_submit_error(e)
                    if kind == "request":
                        # the REQUEST is at fault (malformed payload,
                        # expired deadline): the replica stays healthy —
                        # fencing here would let one bad client starve
                        # the fleet a replica at a time
                        raise
                    if kind == "busy":
                        last_exc = e
                        continue
                    # replica fault: fence it and keep draining through
                    # the survivors
                    self.mark_down(r.name)
                    last_exc = e
                    progressed = True
                    break  # re-score against the surviving set
                with self._lock:
                    self._routed[r.name] = self._routed.get(r.name, 0) + 1
                    if matches[idx] > 0:
                        self._affinity_hits += 1
                return fut
            if not progressed:
                raise last_exc or QueueFull("all replicas at capacity")
            tried += 1
            if tried > len(self._replicas):
                raise last_exc or EngineClosed("no healthy replicas")

    def _done(self, tenant: str) -> None:
        with self._lock:
            n = self._inflight.get(tenant, 0)
            if n > 0:
                self._inflight[tenant] = n - 1
                self._inflight_total -= 1

    # -- observability --------------------------------------------------------
    def queue_depth(self) -> int:
        return sum(r.queue_depth() for r in self._replicas)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            routed = dict(self._routed)
            down = sorted(self._down)
            inflight = dict(self._inflight)
            rejected = dict(self._rejected)
            affinity = self._affinity_hits
        per_replica = {}
        qps = 0.0
        for r in self._replicas:
            snap = r.stats()
            qps += snap.get("qps", 0.0)
            per_replica[r.name] = {
                "qps": snap.get("qps"),
                "queue_depth": r.queue_depth(),
                "active_slots": snap.get("active_slots"),
                "kv_headroom": r.kv_headroom(),
                "prefix_hit_rate": snap.get("prefix_hit_rate"),
                "p95_ms": snap.get("latency_ms", {}).get("p95"),
                "responses": snap.get("counters", {}).get(
                    "responses_total", 0),
                "retrace_events": snap.get("retrace_events"),
                "routed": routed.get(r.name, 0),
                "down": r.name in down,
            }
        return {"name": self.name, "replicas": per_replica,
                "fleet_qps": round(qps, 3), "down": down,
                "inflight": inflight, "rejected": rejected,
                "affinity_hits": affinity,
                "readmitted": self._readmitted,
                "uptime_s": round(time.monotonic() - self._t0, 1)}
