"""Paged KV cache: block-pool allocator + prefix trie (vLLM-style).

The slot-arena continuous batcher reserved ``max_slots * max_seq_len``
K/V rows up front — every admitted sequence paid for its worst case, and
two requests sharing a 500-token system prompt each re-prefilled and
re-stored it. This module replaces that arena with a **block pool**:

- one ``[num_pages, page_len, heads, dim]`` arena per layer
  (``PagedKVPool``) — the only device memory the KV cache ever holds;
- a free-list **allocator** (``PageAllocator``) hands fixed-size pages to
  requests; a request's KV is a *page table* (list of page ids), so its
  footprint is ``ceil(len/page_len)`` pages, not ``max_seq_len`` rows;
- pages are **ref-counted**: a page shared by N readers frees only when
  the last one releases it, and ``cow()`` gives a writer its own copy
  (copy-on-write) when the page is shared;
- a **prefix cache** (``PrefixCache``) — a hash-trie keyed by
  ``(parent, token-block)`` chains — maps full prompt blocks to the pages
  already holding their K/V, so a request sharing a system prompt reuses
  those pages instead of re-prefilling them. Eviction is LRU over
  *leaf* nodes whose page nobody else holds (trie-only refs), so a chain
  never dangles.

The control plane (allocator + trie) is pure Python — unit-testable
without a device. ``PagedKVPool`` adds the per-layer jax arenas and the
page-copy executable the engine uses for COW.

Page 0 is reserved as the **scratch page**: page-table rows of inactive
slots (and positions beyond a request's allocation) point at it, so the
fixed-shape decode executable always has somewhere harmless to write.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["PoolExhausted", "PageAllocator", "PrefixCache", "PagedKVPool",
           "HostPagePool", "token_blocks"]


class PoolExhausted(RuntimeError):
    """The page pool cannot serve the allocation (even after eviction)."""


def token_blocks(tokens, page_len: int, limit: Optional[int] = None
                 ) -> List[Tuple[int, ...]]:
    """The FULL ``page_len``-sized token blocks of a prompt — the trie's
    key units. A trailing partial block is never a key (it would receive
    decode writes)."""
    n = len(tokens) // page_len
    if limit is not None:
        n = min(n, limit)
    return [tuple(int(t) for t in tokens[i * page_len:(i + 1) * page_len])
            for i in range(n)]


class PageAllocator:
    """Free-list page allocator with ref counts (pure control plane).

    Invariants (asserted by ``check()``):
    - page 0 is reserved (never allocated, refcount pinned);
    - every page is either on the free list (ref 0) or live (ref >= 1);
    - ``free_pages + live_pages == num_pages - 1``.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (1 scratch + 1 usable), "
                             f"got {num_pages}")
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(1, num_pages))
        self._ref = [0] * num_pages
        self._ref[0] = 1  # scratch page: pinned forever
        self.alloc_total = 0
        self.free_total = 0
        self.cow_total = 0

    # -- queries --------------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def live_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    @property
    def usable_pages(self) -> int:
        """Pages a single request could ever hold (pool minus scratch)."""
        return self.num_pages - 1

    def ref(self, page: int) -> int:
        return self._ref[page]

    # -- alloc / retain / release ---------------------------------------------
    def alloc(self, n: int) -> List[int]:
        """n fresh pages at refcount 1, or ``PoolExhausted`` (all-or-
        nothing: a partial grab is never held across the raise)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"({self.live_pages} live) of {self.usable_pages} usable "
                f"[pool={self.num_pages} incl. scratch, "
                f"alloc_total={self.alloc_total}, "
                f"free_total={self.free_total}]")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        self.alloc_total += n
        return pages

    def retain(self, page: int) -> None:
        if page == 0:
            return  # scratch is pinned; sharing it is a no-op
        if self._ref[page] <= 0:
            raise RuntimeError(f"retain of free page {page}")
        self._ref[page] += 1

    def release(self, page: int) -> None:
        if page == 0:
            return
        r = self._ref[page]
        if r <= 0:
            raise RuntimeError(f"double free of page {page}")
        self._ref[page] = r - 1
        if r == 1:
            self._free.append(page)
            self.free_total += 1

    def cow(self, page: int) -> Tuple[int, bool]:
        """Copy-on-write: the caller wants to WRITE ``page``. Exclusive
        pages (ref 1) are returned as-is; shared pages cost one fresh page
        (caller must copy the contents device-side) and drop the shared
        ref. Returns ``(writable_page, copied)``."""
        if page != 0 and self._ref[page] == 1:
            return page, False
        new = self.alloc(1)[0]
        self.release(page)
        self.cow_total += 1
        return new, True

    def check(self) -> None:
        """Assert the allocator invariants (test hook)."""
        assert self._ref[0] >= 1, "scratch page unpinned"
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate page on free list"
        assert 0 not in free, "scratch page on free list"
        for p in range(1, self.num_pages):
            if p in free:
                assert self._ref[p] == 0, (p, self._ref[p])
            else:
                assert self._ref[p] >= 1, (p, self._ref[p])
        assert self.free_pages + self.live_pages == self.num_pages - 1


class _TrieNode:
    __slots__ = ("key", "parent", "page", "children", "last_used")

    def __init__(self, key, parent, page, last_used):
        self.key = key
        self.parent = parent      # parent key (None for depth-0 blocks)
        self.page = page
        self.children = 0         # live child count (eviction is leaf-only)
        self.last_used = last_used


class PrefixCache:
    """Hash-trie over token-block chains -> KV pages.

    A node's key is ``(parent_key, block_tokens)`` — the full token
    context is encoded in the chain, so equal blocks under different
    prefixes never collide. The trie holds ONE allocator ref per adopted
    page; ``evict()`` walks least-recently-used *leaves* whose page has no
    other holder, so eviction can never free a page out from under a
    reader or orphan a reachable child.
    """

    def __init__(self):
        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        self._lock = _named_lock("serving.PrefixCache._lock")
        self._nodes: Dict[Any, _TrieNode] = {}
        self._tick = 0
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.inserts = 0
        self.evictions = 0

    @staticmethod
    def _key(parent, block) -> Tuple:
        return (parent, block)

    @staticmethod
    def chain_key(blocks: Sequence[Tuple[int, ...]]):
        """The trie key of chain ``blocks`` (deterministic — computable
        without trie state, so warm-tier keys survive eviction)."""
        parent = None
        for block in blocks:
            parent = (parent, block)
        return parent

    # -- reads ----------------------------------------------------------------
    def match(self, blocks: Sequence[Tuple[int, ...]], page_len: int,
              allocator: Optional[PageAllocator] = None) -> List[int]:
        """Longest cached chain for ``blocks``; returns its pages. When an
        allocator is given each returned page is retained FOR THE CALLER
        (released by the caller when its request finishes)."""
        with self._lock:
            self._tick += 1
            self.lookups += 1
            self.lookup_tokens += len(blocks) * page_len
            pages: List[int] = []
            parent = None
            for block in blocks:
                node = self._nodes.get(self._key(parent, block))
                if node is None:
                    break
                node.last_used = self._tick
                pages.append(node.page)
                parent = node.key
            if pages:
                self.hits += 1
                self.hit_tokens += len(pages) * page_len
            if allocator is not None:
                for p in pages:
                    allocator.retain(p)
            return pages

    def match_len(self, blocks: Sequence[Tuple[int, ...]]) -> int:
        """Depth of the longest cached chain (no refs taken, no LRU bump)
        — the router's prefix-affinity probe."""
        with self._lock:
            depth, parent = 0, None
            for block in blocks:
                node = self._nodes.get(self._key(parent, block))
                if node is None:
                    break
                depth += 1
                parent = node.key
            return depth

    # -- writes ---------------------------------------------------------------
    def insert(self, blocks: Sequence[Tuple[int, ...]], pages: Sequence[int],
               allocator: PageAllocator) -> int:
        """Adopt ``pages[i]`` as the cached KV of chain ``blocks[:i+1]``.
        Existing nodes keep their page (first writer wins — both copies
        hold identical K/V); new nodes retain theirs. Returns the number
        of newly adopted pages."""
        assert len(blocks) == len(pages)
        adopted = 0
        with self._lock:
            self._tick += 1
            parent = None
            for block, page in zip(blocks, pages):
                key = self._key(parent, block)
                node = self._nodes.get(key)
                if node is None:
                    node = _TrieNode(key, parent, page, self._tick)
                    self._nodes[key] = node
                    allocator.retain(page)
                    if parent is not None:
                        self._nodes[parent].children += 1
                    self.inserts += 1
                    adopted += 1
                else:
                    node.last_used = self._tick
                parent = key
        return adopted

    def evict(self, n_pages: int, allocator: PageAllocator,
              on_evict=None) -> int:
        """Free up to ``n_pages`` pages by dropping LRU leaves whose page
        has no holder besides the trie (ref == 1). Returns pages freed.

        ``on_evict(key, page)`` — if given — is called for each victim
        BEFORE its page is released, while the page contents are still
        valid: the warm-tier spill hook."""
        freed = 0
        with self._lock:
            while freed < n_pages:
                victim = None
                for node in self._nodes.values():
                    if node.children:
                        continue
                    if allocator.ref(node.page) != 1:
                        continue  # someone is reading it right now
                    if victim is None or node.last_used < victim.last_used:
                        victim = node
                if victim is None:
                    break
                if on_evict is not None:
                    on_evict(victim.key, victim.page)
                del self._nodes[victim.key]
                if victim.parent is not None:
                    self._nodes[victim.parent].children -= 1
                allocator.release(victim.page)
                self.evictions += 1
                freed += 1
        return freed

    def release_all(self, allocator: PageAllocator) -> None:
        """Drop every node (engine close): release the trie's refs."""
        with self._lock:
            for node in self._nodes.values():
                allocator.release(node.page)
            self._nodes.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._nodes)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"nodes": len(self._nodes), "lookups": self.lookups,
                    "hits": self.hits, "hit_tokens": self.hit_tokens,
                    "lookup_tokens": self.lookup_tokens,
                    "inserts": self.inserts, "evictions": self.evictions,
                    "hit_rate": round(self.hit_tokens /
                                      max(self.lookup_tokens, 1), 4)}


class HostPagePool:
    """Replica-local warm tier: evicted prefix-cache pages spill here.

    Page contents live in host RAM, int8-quantized with per-page scales
    (~4x cheaper than device-resident fp32).  Admission is frequency
    gated — a chain key must be *seen* ``admit_threshold`` times before
    its bytes are kept (the PR-14 ``HotRowCache`` ghost-counter pattern)
    — and residency is LRU under a byte budget.  Keys are deterministic
    trie chain keys (``PrefixCache.chain_key``) so a warm page can be
    restored into a fresh trie after eviction.
    """

    def __init__(self, capacity_bytes: int = 64 << 20,
                 admit_threshold: int = 2, ghost_cap: int = 2048):
        from collections import OrderedDict

        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        self.capacity_bytes = int(capacity_bytes)
        self.admit_threshold = int(admit_threshold)
        self.ghost_cap = int(ghost_cap)
        self._entries = OrderedDict()   # key -> (k_q, k_s, v_q, v_s, nbytes)
        self._bytes = 0
        self._ghost: Dict[Any, int] = {}
        self.hits = 0
        self.misses = 0
        self.admits = 0
        self.rejects = 0
        self.evictions = 0
        self.restores = 0
        self._lock = _named_lock("serving.HostPagePool._lock")

    def note_access(self, key) -> None:
        with self._lock:
            self._ghost[key] = self._ghost.get(key, 0) + 1
            if len(self._ghost) > self.ghost_cap:
                self._ghost = {k: v // 2 for k, v in self._ghost.items()
                               if v // 2 > 0}

    def put(self, key, k_layers, v_layers) -> bool:
        """Spill one page (per-layer ``[page_len, heads, dim]`` arrays)."""
        import numpy as np

        from .kv_transfer import quantize_page

        with self._lock:
            seen = self._ghost.get(key, 0)
        if key is None or seen < self.admit_threshold:
            with self._lock:
                self.rejects += 1
            return False
        k_q, k_s, v_q, v_s = [], [], [], []
        nbytes = 0
        for arr in k_layers:
            q, s = quantize_page(np.asarray(arr))
            k_q.append(q); k_s.append(s); nbytes += q.nbytes
        for arr in v_layers:
            q, s = quantize_page(np.asarray(arr))
            v_q.append(q); v_s.append(s); nbytes += q.nbytes
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            if nbytes > self.capacity_bytes:
                self.rejects += 1
                return False
            while self._bytes + nbytes > self.capacity_bytes and self._entries:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old[4]
                self.evictions += 1
            self._entries[key] = (k_q, k_s, v_q, v_s, nbytes)
            self._bytes += nbytes
            self.admits += 1
            return True

    def get(self, key, dtype=None):
        """Dequantized ``(k_layers, v_layers)`` for ``key``, or None."""
        from .kv_transfer import dequantize_page

        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            k_q, k_s, v_q, v_s, _ = ent
        import numpy as np

        dt = dtype or np.float32
        return ([dequantize_page(q, s, dt) for q, s in zip(k_q, k_s)],
                [dequantize_page(q, s, dt) for q, s in zip(v_q, v_s)])

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "capacity_bytes": self.capacity_bytes,
                    "hits": self.hits, "misses": self.misses,
                    "hit_rate": round(self.hits / total, 4) if total else 0.0,
                    "admits": self.admits, "rejects": self.rejects,
                    "evictions": self.evictions, "restores": self.restores}


class PagedKVPool:
    """The device half: per-layer K/V page arenas + the control plane.

    ``allocate(n)`` serves from the free list, evicting LRU prefix-cache
    entries when short — so a hot serving process naturally trades cold
    cached prefixes for live requests. With a ``warm_pool``, evicted
    prefix pages spill (int8) to host RAM and can be restored by
    ``warm_restore`` instead of re-prefilling.
    """

    def __init__(self, num_layers: int, num_pages: int, page_len: int,
                 num_heads: int, head_dim: int, dtype,
                 prefix_cache: bool = True,
                 warm_pool: Optional[HostPagePool] = None):
        import jax.numpy as jnp

        self.page_len = int(page_len)
        self.num_pages = int(num_pages)
        self.allocator = PageAllocator(num_pages)
        self.trie: Optional[PrefixCache] = PrefixCache() if prefix_cache \
            else None
        self.warm = warm_pool
        self.k = [jnp.zeros((num_pages, page_len, num_heads, head_dim),
                            dtype) for _ in range(num_layers)]
        self.v = [jnp.zeros((num_pages, page_len, num_heads, head_dim),
                            dtype) for _ in range(num_layers)]

    # -- control plane --------------------------------------------------------
    def allocate(self, n: int) -> List[int]:
        """n pages, evicting cached prefixes if the free list is short."""
        short = n - self.allocator.free_pages
        if short > 0 and self.trie is not None:
            self.trie.evict(short, self.allocator,
                            on_evict=self._spill if self.warm is not None
                            else None)
        return self.allocator.alloc(n)

    def _spill(self, key, page: int) -> None:
        """Warm-tier spill hook: page contents -> host RAM (int8)."""
        import numpy as np

        self.warm.note_access(key)
        k_layers = [np.asarray(a[page]) for a in self.k]
        v_layers = [np.asarray(a[page]) for a in self.v]
        self.warm.put(key, k_layers, v_layers)

    def warm_restore(self, blocks: Sequence[Tuple[int, ...]]) -> int:
        """Extend the trie's cached chain for ``blocks`` from the warm
        tier: for each block past the device-resident match depth with a
        warm hit, allocate a page, dequantize-write its contents, and
        adopt it into the trie. Returns pages restored."""
        if self.trie is None or self.warm is None or not blocks:
            return 0
        import numpy as np

        depth = self.trie.match_len(blocks)
        # note accesses for the whole tail so repeat traffic becomes
        # admittable even before anything is ever spilled
        for j in range(depth, len(blocks)):
            self.warm.note_access(PrefixCache.chain_key(blocks[:j + 1]))
        if depth >= len(blocks):
            return 0
        chain_pages = self.trie.match(blocks[:depth], self.page_len)
        restored = 0
        for j in range(depth, len(blocks)):
            key = PrefixCache.chain_key(blocks[:j + 1])
            ent = self.warm.get(key, dtype=self.k[0].dtype)
            if ent is None:
                break
            try:
                page = self.allocate(1)[0]
            except PoolExhausted:
                break
            k_layers, v_layers = ent
            self.write_pages([page],
                             [kl[np.newaxis] for kl in k_layers],
                             [vl[np.newaxis] for vl in v_layers])
            chain_pages.append(page)
            adopted = self.trie.insert(blocks[:j + 1], chain_pages,
                                       self.allocator)
            self.allocator.release(page)  # trie owns it now
            if not adopted:
                break  # raced: an identical chain landed first
            self.warm.restores += 1
            restored += 1
        return restored

    def can_allocate(self, n: int) -> bool:
        free = self.allocator.free_pages
        if n <= free:
            return True
        if self.trie is None:
            return False
        # leaf-only eviction frees parents as it goes, so every trie-only
        # page is ultimately reachable: count all of them
        evictable = sum(1 for node in self.trie._nodes.values()
                        if self.allocator.ref(node.page) == 1)
        return n <= free + evictable

    def ensure_writable(self, page: int) -> Tuple[int, bool]:
        """COW hook: give the caller a page it may write. When the page is
        shared, a fresh page is allocated and the K/V CONTENT IS COPIED
        device-side before returning."""
        new, copied = self.allocator.cow(page)
        if copied:
            self._copy_page(page, new)
        return new, copied

    def _copy_page(self, src: int, dst: int) -> None:
        import jax

        fn = getattr(self, "_copy_fn", None)
        if fn is None:
            def copy(arena, s, d):
                return arena.at[d].set(arena[s])

            fn = self._copy_fn = jax.jit(copy)
        import numpy as np

        s, d = np.int32(src), np.int32(dst)
        self.k = [fn(a, s, d) for a in self.k]
        self.v = [fn(a, s, d) for a in self.v]

    # -- page transfer (export / install) -------------------------------------
    def read_pages(self, pages: Sequence[int]):
        """Page CONTENTS as per-layer host arrays ``[n, page_len, h, d]``
        (the export path). Caller must hold refs on ``pages``."""
        import jax.numpy as jnp
        import numpy as np

        idx = jnp.asarray(list(pages), dtype=jnp.int32)
        return ([np.asarray(a[idx]) for a in self.k],
                [np.asarray(a[idx]) for a in self.v])

    def write_pages(self, pages: Sequence[int], k_stacks, v_stacks) -> None:
        """Scatter-write page CONTENTS into the arenas (the install
        path). ``k_stacks[li]``/``v_stacks[li]`` are ``[n, page_len, h,
        d]`` arrays; data is cast to the arena dtype."""
        import jax
        import jax.numpy as jnp

        fn = getattr(self, "_install_fn", None)
        if fn is None:
            def put(arena, idx, data):
                return arena.at[idx].set(data)

            fn = self._install_fn = jax.jit(put)
        idx = jnp.asarray(list(pages), dtype=jnp.int32)
        self.k = [fn(a, idx, jnp.asarray(d, dtype=a.dtype))
                  for a, d in zip(self.k, k_stacks)]
        self.v = [fn(a, idx, jnp.asarray(d, dtype=a.dtype))
                  for a, d in zip(self.v, v_stacks)]

    # -- observability --------------------------------------------------------
    def bytes(self) -> int:
        return sum(int(a.nbytes) for a in self.k) + \
            sum(int(a.nbytes) for a in self.v)

    def stats(self) -> Dict[str, Any]:
        a = self.allocator
        out = {"pages_total": a.num_pages, "page_len": self.page_len,
               "pages_free": a.free_pages, "pages_live": a.live_pages,
               "pool_bytes": self.bytes(),
               "alloc_total": a.alloc_total, "cow_total": a.cow_total,
               "headroom": round(a.free_pages / max(a.usable_pages, 1), 4)}
        if self.trie is not None:
            out["prefix"] = self.trie.stats()
        if self.warm is not None:
            out["warm"] = self.warm.stats()
        return out
