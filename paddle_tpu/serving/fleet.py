"""Fault-tolerant multi-process serving fleet: supervised replicas,
health-checked failover, hedged re-prefill, brownout degradation, and
zero-downtime rolling restarts.

PR 11/12's ``ReplicaRouter`` load-balances replicas that share one
process — a single crash, hang, or OOM takes the whole tier down. This
module applies PR 10's fleet-supervision protocol (heartbeats into the
pure ``FleetStateMachine``, fence within the grace window, bounded-
backoff restart) to an Orca/vLLM-style continuous-batching tier:

- **process replicas**: each ``GenerationEngine`` runs in its OWN
  process (``replica_main``), spawned with a per-replica
  ``PT_FLIGHT_DIR``, warmed buckets (``engine.warmup()`` before the
  ready publish — a shared persistent cache makes restarts warm), and a
  control-plane ``TCPStore`` client it heartbeats through;
- **RPC**: a small length-prefixed JSON socket protocol —
  submit/stream(tokens)/cancel/drain/config/shutdown — served by a
  single-threaded event loop, so a wedged serve loop stops the
  heartbeat too (the hung-not-dead failure mode is detectable);
- **failover with replay**: in-flight requests on a fenced replica are
  resubmitted onto a survivor as ``prompt + already-streamed tokens``
  (the prefix cache re-prefills cheaply), and the emitted-token ledger
  dedups the stream — the client never sees a repeated or missing
  token, and greedy determinism makes the replayed tail bit-identical
  to an uninterrupted run;
- **hedging**: a request with no token progress past ``hedge_ms`` gets
  a speculative second submission on another replica; first completion
  wins, the loser is cancelled;
- **brownout**: overload degrades in stages instead of collapsing —
  (1) disable speculative decoding, (2) clamp ``max_new_tokens`` for
  non-interactive deadline classes, (3) shed the lowest-priority work;
- **rolling restarts**: ``rolling_restart()`` drains one replica at a
  time (fence-new-work -> finish in-flight -> restart -> warm ->
  re-admit) for zero-downtime config/weight rollouts;
- **disaggregated prefill/decode** (``pools=``): a prefill replica
  runs exactly one token (filling paged KV for the prompt), the fleet
  ships the pages to a decode replica over the same frame protocol
  (chunked, SHA-256-verified, optionally int8-quantized in transit —
  ``serving/kv_transfer.py``), installs them into its ``PagedKVPool``
  and continues the stream bit-identically; failover gains a
  ship-pages fast path (``failover_ship`` vs ``failover_reprefill``),
  and a supervisor-side ``FleetKVCache`` keeps warm payloads for
  repeat prompts.

Chaos drill: ``tools/serving_fleet_drill.py`` (CI-gated). Deterministic
fault kinds (``replica_crash@name&seq``, ``replica_hang@name&seq``,
``replica_slow@name``) fire inside the replica worker. The
``serving_fleet`` hub provider serves per-replica health, the
fence/restart timeline, and the hedge/replay/brownout counters.
"""
from __future__ import annotations

import itertools
import json
import os
import select
import socket
import struct
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from enum import Enum
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import (BadRequest, DeadlineExceeded, EngineClosed, QueueFull,
                   ReplicaFault, RequestCancelled, _tracer)
from .kv_transfer import (FleetKVCache, KVMigrationStats,
                          prompt_cache_key)
from .metrics import MetricsRegistry
from .router import RouterConfig, classify_submit_error, score_candidates

__all__ = [
    "ServingFleet", "ServingFleetPolicy", "ReplicaClient", "ReplicaState",
    "BrownoutShed", "BROWNOUT_STAGES", "brownout_stage", "brownout_max_new",
    "brownout_sheds", "stitch_replay", "replica_main", "resolve_builder",
]

_MAX_FRAME = 16 << 20
_CRASH_EXIT = 43  # replica_crash's os._exit code (classified as crash)


class BrownoutShed(QueueFull):
    """Stage-3 brownout: the fleet is overloaded and this request's
    priority class is being shed (a ``QueueFull`` subclass, so existing
    backpressure handling applies)."""


# ---------------------------------------------------------------------------
# wire protocol: 4-byte big-endian length + JSON
# ---------------------------------------------------------------------------

def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


def send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    data = json.dumps(obj, separators=(",", ":"),
                      default=_json_default).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def recv_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """One frame, or None on a clean EOF."""
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack(">I", hdr)
    if n > _MAX_FRAME:
        raise ReplicaFault(f"oversized frame ({n} bytes)")
    data = _recv_exact(sock, n)
    if data is None:
        return None
    return json.loads(data.decode())


# ---------------------------------------------------------------------------
# policy + pure decision helpers (unit-testable without processes)
# ---------------------------------------------------------------------------

@dataclass
class ServingFleetPolicy:
    """Knobs of the serving recovery/overload protocol
    (docs/resilience.md "Serving fleet" lists each)."""

    heartbeat_interval: float = 0.3
    heartbeat_timeout: float = 3.0   # the fence grace window
    max_restarts: int = 3            # per replica (planned rolls are free)
    backoff_base_s: float = 0.25
    backoff_max_s: float = 10.0
    start_timeout_s: float = 180.0   # spawn -> ready publish
    drain_timeout_s: float = 30.0    # rolling restart: finish in-flight
    poll_interval: float = 0.05
    rpc_timeout_s: float = 30.0
    # hedging: a request with no token progress for hedge_ms gets a
    # speculative second submission on another replica (None: off)
    hedge_ms: Optional[float] = None
    # brownout: load = fleet in-flight / (ready replicas * capacity)
    replica_capacity: int = 8
    brownout_spec_load: float = 0.7    # stage 1: speculation off
    brownout_clamp_load: float = 0.85  # stage 2: clamp batch-class budgets
    brownout_shed_load: float = 0.95   # stage 3: shed low priority
    brownout_hysteresis: float = 0.2   # exit threshold = entry - this
    brownout_clamp_tokens: int = 8
    interactive_deadline_ms: float = 2000.0
    brownout_keep_priority: int = 1    # stage 3 sheds priority < this
    # fleet observability plane (docs/observability.md "Fleet plane"):
    # the collector thread scrapes each replica's hub snapshot +
    # finished traces every telemetry_interval_s; the SLO layer derives
    # burn rate from the merged request-latency histograms against
    # target_ms at the given objective over a sliding window
    telemetry_interval_s: float = 2.0
    slo_target_ms: float = 1000.0
    slo_objective: float = 0.99
    slo_window_s: float = 60.0

    def fleet_policy(self):
        """The FleetStateMachine view of these knobs."""
        from ..distributed.fleet.runtime import FleetPolicy

        return FleetPolicy(
            heartbeat_interval=self.heartbeat_interval,
            heartbeat_timeout=self.heartbeat_timeout,
            max_restarts=self.max_restarts,
            backoff_base_s=self.backoff_base_s,
            backoff_max_s=self.backoff_max_s,
            drain_timeout_s=self.drain_timeout_s,
            start_timeout_s=self.start_timeout_s,
            poll_interval=self.poll_interval)


BROWNOUT_STAGES = ("normal", "no_spec", "clamp", "shed")


def brownout_stage(prev: int, load: float,
                   policy: ServingFleetPolicy) -> int:
    """Staged degradation with hysteresis: enter stage i when load
    crosses its threshold; leave (one stage per evaluation) only when
    load drops below the entry threshold minus the hysteresis margin —
    a load hovering at a boundary never flaps the spec toggle."""
    up = (policy.brownout_spec_load, policy.brownout_clamp_load,
          policy.brownout_shed_load)
    stage = 0
    for i, t in enumerate(up):
        if load >= t:
            stage = i + 1
    if stage < prev:
        exit_at = up[prev - 1] - policy.brownout_hysteresis
        stage = prev if load > exit_at else prev - 1
    return stage


def brownout_max_new(stage: int, deadline_ms: Optional[float],
                     max_new: int, policy: ServingFleetPolicy) -> int:
    """Stage >= 2 clamps the token budget of NON-interactive requests
    (no deadline, or a lax one) — interactive traffic keeps its budget,
    batch traffic gets shorter answers instead of no answers."""
    if stage < 2:
        return max_new
    interactive = deadline_ms is not None and \
        deadline_ms <= policy.interactive_deadline_ms
    return max_new if interactive else \
        max(1, min(max_new, policy.brownout_clamp_tokens))


def brownout_sheds(stage: int, priority: int,
                   policy: ServingFleetPolicy) -> bool:
    """Stage 3 sheds work below the keep-priority line."""
    return stage >= 3 and priority < policy.brownout_keep_priority


def stitch_replay(prompt: Sequence[int], emitted: Sequence[int],
                  replica_seq: Sequence[int]) -> List[int]:
    """The replay dedup rule: ``replica_seq`` is the replayed
    submission's full output (``prompt + emitted`` re-prefilled, plus
    freshly generated tokens). The client-visible sequence appends only
    the fresh tail — already-streamed tokens are never repeated and the
    prefix is never lost."""
    base = len(prompt) + len(emitted)
    return list(prompt) + list(emitted) + [int(t)
                                           for t in replica_seq[base:]]


def resolve_builder(spec: str) -> Callable[[], Any]:
    """``pkg.mod:fn`` (import path) or ``/path/to/file.py:fn`` (loaded
    by file — the drill/test builders live outside the package)."""
    mod_s, _, fn_s = spec.rpartition(":")
    if not mod_s or not fn_s:
        raise ValueError(f"builder spec {spec!r} is not 'module:function'")
    if mod_s.endswith(".py"):
        import importlib.util

        name = "_pt_replica_builder_" + \
            os.path.splitext(os.path.basename(mod_s))[0]
        s = importlib.util.spec_from_file_location(name, mod_s)
        mod = importlib.util.module_from_spec(s)
        s.loader.exec_module(mod)
    else:
        import importlib

        mod = importlib.import_module(mod_s)
    return getattr(mod, fn_s)


# ---------------------------------------------------------------------------
# replica worker (the child process)
# ---------------------------------------------------------------------------

def _injector():
    from ..distributed.resilience.faults import injector

    return injector()


class _ReplicaServer:
    """The worker-side RPC server: ONE event loop thread handles frames
    AND publishes heartbeats, so a wedged serve loop (``replica_hang``)
    stops the beat and the supervisor fences within the grace window.
    Engine worker threads hand outbound frames (token stream, done,
    errors) to the loop through a queue + self-pipe wakeup."""

    def __init__(self, name: str, engine, store=None,
                 hb_interval: float = 0.3, incarnation: int = 0):
        self.name = name
        self.engine = engine
        self._store = store
        self._hb = float(hb_interval)
        self._inc = int(incarnation)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("127.0.0.1", 0))
        self._listen.listen(4)
        self.port = self._listen.getsockname()[1]
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        self._conns: Dict[socket.socket, bytearray] = {}
        self._out: deque = deque()           # (conn, frame)
        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        self._out_lock = _named_lock(
            f"serving.fleet._ReplicaServer[{name}]._out_lock")
        self._futs: Dict[int, Future] = {}   # rid -> engine future
        self._dead_rids: set = set()         # cancelled: frames suppressed
        self._seq = 0                        # submit counter (fault ids)
        self._hung = False
        self._shutdown = False
        self._store_failures = 0
        self._subscriber = None              # weight-service subscriber
        # KV page-migration staging (disaggregated prefill/decode):
        # export handles -> chunk lists, install handles -> partial
        # uploads. Both bounded FIFO — an abandoned transfer can never
        # pin memory.
        self._kv_handle = 0
        self._kv_out: Dict[int, List[Dict[str, Any]]] = {}
        self._kv_in: Dict[int, Dict[str, Any]] = {}
        # fleet trace flush: finished fleet-parented traces buffered
        # here, published opportunistically on heartbeat frames (crash-
        # adjacent spans survive to the supervisor) and drained by the
        # `trace` RPC pull. The supervisor dedups by trace id, so both
        # delivery paths may overlap safely.
        self._pending_traces: List[Dict[str, Any]] = []
        self._trace_seq = 0        # bumps when new traces arrive
        self._trace_pub_seq = -1   # last seq published on a beat
        self._takes_trace: Optional[bool] = None  # engine.submit kwarg?

    # -- outbound (called from engine worker threads) -------------------------
    def _post(self, conn, frame: Dict[str, Any]) -> None:
        rid = frame.get("rid")
        if rid is not None and rid in self._dead_rids:
            return  # cancelled request: the supervisor moved on
        with self._out_lock:
            self._out.append((conn, frame))
        try:
            os.write(self._wake_w, b"x")
        except BlockingIOError:
            pass  # pipe full: the loop is already awake

    def _flush_out(self) -> None:
        while True:
            with self._out_lock:
                if not self._out:
                    return
                conn, frame = self._out.popleft()
            if conn not in self._conns:
                continue  # connection already gone
            try:
                send_frame(conn, frame)
            except OSError:
                self._drop(conn)

    def _drop(self, conn) -> None:
        self._conns.pop(conn, None)
        try:
            conn.close()
        except OSError:
            pass

    # -- store ----------------------------------------------------------------
    def _key(self, leaf: str) -> str:
        return f"svfleet/{self.name}/{self._inc}/{leaf}"

    def _publish(self, leaf: str, value) -> None:
        from ..distributed.fleet.runtime import _publish

        _publish(self._store, self._key(leaf), value)

    def _drain_traces(self) -> None:
        """Move finished fleet-parented traces from the process tracer
        into the bounded publish buffer (oldest dropped past 256)."""
        try:
            got = _tracer().drain_finished(max_n=64, require_parent=True)
        except Exception:
            return
        if got:
            self._pending_traces.extend(got)
            if len(self._pending_traces) > 256:
                del self._pending_traces[:len(self._pending_traces) - 256]
            self._trace_seq += 1

    def _beat(self, now: float) -> None:
        if self._store is None or self._hung:
            return
        try:
            self._publish("beat", {"ts": now, "seq": self._seq})
            # piggyback: a bounded batch of finished traces rides each
            # beat WITHOUT clearing the buffer (a crash between beats
            # loses nothing already published; the RPC pull clears)
            self._drain_traces()
            if self._pending_traces and \
                    self._trace_seq != self._trace_pub_seq:
                self._publish("traces", {"seq": self._trace_seq,
                                         "traces":
                                         self._pending_traces[-16:]})
                self._trace_pub_seq = self._trace_seq
            self._store_failures = 0
        except Exception:
            # a dead control plane means nobody will fence or restart
            # us: exit cleanly rather than serve as an orphan
            self._store_failures += 1
            if self._store_failures >= 3:
                from ..distributed.fleet.runtime import EXIT_COORD_LOST

                sys.stdout.flush()
                sys.stderr.flush()
                os._exit(EXIT_COORD_LOST)

    # -- the loop -------------------------------------------------------------
    def serve(self) -> None:
        if self._store is not None:
            self._publish("port", {"port": self.port, "pid": os.getpid()})
        last_beat = 0.0
        while not self._shutdown:
            rs = [self._listen, self._wake_r] + list(self._conns)
            try:
                ready, _, _ = select.select(rs, [], [], self._hb / 2)
            except OSError:
                ready = []
            for s in ready:
                if s is self._listen:
                    conn, _ = self._listen.accept()
                    self._conns[conn] = bytearray()
                elif s is self._wake_r:
                    try:
                        os.read(self._wake_r, 4096)
                    except BlockingIOError:
                        pass
                else:
                    self._readable(s)
                if self._shutdown:
                    break
            self._flush_out()
            now = time.time()
            if now - last_beat >= self._hb:
                self._beat(now)
                last_beat = now
        # graceful exit (rolling restart): the supervisor drained us
        # first, so the engine is idle; close it and leave fast.
        self._flush_out()
        for c in list(self._conns):
            self._drop(c)
        try:
            self._listen.close()
        except OSError:
            pass
        if self._subscriber is not None:
            try:
                self._subscriber.stop()
            except Exception:
                pass
        try:
            self.engine.close(drain=True, timeout=10)
        except Exception:
            pass

    def _readable(self, conn) -> None:
        try:
            data = conn.recv(65536)
        except OSError:
            data = b""
        if not data:
            self._drop(conn)
            return
        buf = self._conns[conn]
        buf += data
        while len(buf) >= 4:
            (n,) = struct.unpack(">I", bytes(buf[:4]))
            if len(buf) < 4 + n:
                break
            frame = json.loads(bytes(buf[4:4 + n]).decode())
            del buf[:4 + n]
            self._handle(conn, frame)
            if self._shutdown:
                break

    # -- ops ------------------------------------------------------------------
    def _handle(self, conn, msg: Dict[str, Any]) -> None:
        op = msg.get("op")
        rid = msg.get("rid")
        if op == "submit":
            self._submit(conn, rid, msg)
        elif op == "probe":
            reply = self._probe_reply(msg)
            reply.update(rid=rid, event="reply")
            self._post(conn, reply)
        elif op == "stats":
            try:
                st = self.engine.stats()
            except Exception as e:
                st = {"error": str(e)[:200]}
            self._post(conn, {"rid": rid, "event": "reply", "stats": st})
        elif op == "config":
            if "spec_decode" in msg and \
                    hasattr(self.engine, "set_speculative"):
                self.engine.set_speculative(bool(msg["spec_decode"]))
            self._post(conn, {"rid": rid, "event": "reply", "ok": True})
        elif op == "subscribe_weights":
            try:
                self._start_subscriber(msg)
                self._post(conn, {"rid": rid, "event": "reply",
                                  "ok": True})
            except Exception as e:
                self._post(conn, {"rid": rid, "event": "error",
                                  "kind": type(e).__name__,
                                  "msg": str(e)[:300]})
        elif op == "telemetry":
            # the fleet scrape: this replica's full observability-hub
            # snapshot (histograms carry exact sums/raw buckets for the
            # supervisor's bucket-wise merge) + our pid
            try:
                from ..observability import snapshot as _hub_snapshot

                snap = _hub_snapshot()
            except Exception as e:
                snap = {"error": str(e)[:200]}
            self._post(conn, {"rid": rid, "event": "reply",
                              "telemetry": snap, "pid": os.getpid()})
        elif op == "trace":
            # the collector pull: everything pending, buffer cleared
            # (the beat piggyback republishes only NEW arrivals)
            self._drain_traces()
            batch, self._pending_traces = self._pending_traces, []
            self._post(conn, {"rid": rid, "event": "reply",
                              "traces": batch, "pid": os.getpid()})
        elif op == "kv_export":
            self._kv_export(conn, rid, msg)
        elif op == "kv_chunk":
            self._kv_chunk(conn, rid, msg)
        elif op == "kv_install_begin":
            self._kv_install_begin(conn, rid, msg)
        elif op == "kv_install_chunk":
            self._kv_install_chunk(conn, rid, msg)
        elif op == "kv_install_commit":
            self._kv_install_commit(conn, rid, msg)
        elif op == "drain":
            self.engine.fence()
            self._post(conn, {"rid": rid, "event": "reply",
                              "draining": True})
        elif op == "cancel":
            target = msg.get("target")
            fut = self._futs.get(target)
            dequeued = False
            if fut is not None and hasattr(self.engine, "cancel"):
                dequeued = bool(self.engine.cancel(fut))
            self._dead_rids.add(target)
            if len(self._dead_rids) > 8192:  # bounded: retired rids only
                self._dead_rids.clear()
            self._post(conn, {"rid": rid, "event": "reply",
                              "cancelled": dequeued})
        elif op == "shutdown":
            self._post(conn, {"rid": rid, "event": "reply", "ok": True})
            self._flush_out()
            self._shutdown = True
        else:
            self._post(conn, {"rid": rid, "event": "error",
                              "kind": "BadRequest",
                              "msg": f"unknown op {op!r}"})

    def _submit(self, conn, rid, msg) -> None:
        self._seq += 1
        inj = _injector()
        # deterministic chaos sites — every drill scenario injectable
        # without real kills (PT_FAULTS reaches this process by env).
        # `inc` is a match id: a RESTARTED replica re-parses PT_FAULTS,
        # so a rule pinning inc=0 fires once per drill, not once per
        # incarnation (the restarted process walks seq from 1 again).
        if inj.peek("replica_crash", name=self.name, seq=self._seq,
                    inc=self._inc):
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(_CRASH_EXIT)  # a crash does not unwind
        if inj.peek("replica_hang", name=self.name, seq=self._seq,
                    inc=self._inc):
            # wedge the serve loop: beats stop, the supervisor must
            # fence within the grace window and SIGTERM us
            self._hung = True
            time.sleep(3600)
        # replica_slow DEFERS the submit by the rule's ms (a slow
        # replica, not a dead one: heartbeats keep flowing, the request
        # makes no progress — exactly the hedging trigger). _take is
        # the injector's matching core; peek() would eat the rule but
        # drop its sleep_ms.
        slow = inj._take("replica_slow", {"name": self.name})
        if slow is not None and slow.sleep_ms:
            t = threading.Timer(slow.sleep_ms / 1e3, self._do_submit,
                                args=(conn, rid, msg))
            # Timer threads are non-daemon by default (CC003): an armed
            # timer outliving the replica would hold the process open
            t.daemon = True
            t.name = f"pt-serving-slow-submit-{self.name}"
            t.start()
            return
        self._do_submit(conn, rid, msg)

    def _do_submit(self, conn, rid, msg) -> None:
        post = partial(self._post, conn)
        kw: Dict[str, Any] = {}
        if msg.get("logprobs"):
            # behavior-logprob requests: each token frame carries the
            # per-token logprob alongside the token (the rollout
            # trajectory ledger), and the done frame the full vector
            kw["return_logprobs"] = True
            kw["on_token"] = lambda t, lp, _p=post, _r=rid: _p(
                {"rid": _r, "event": "token", "t": int(t),
                 "lp": float(lp)})
        else:
            kw["on_token"] = lambda t, _p=post, _r=rid: _p(
                {"rid": _r, "event": "token", "t": int(t)})
        trace = msg.get("trace")
        if trace and self._engine_takes_trace():
            # the fleet trace context: this request's engine spans
            # (admission/queue/prefill/decode, slot residency) nest
            # under the supervisor-minted fleet-<id>
            kw["trace_parent"] = str(trace)
        try:
            fut = self.engine.submit(
                np.asarray(msg["prompt"], dtype=np.int64),
                int(msg.get("max_new_tokens", 16)),
                deadline_ms=msg.get("deadline_ms"), **kw)
        except Exception as e:
            post({"rid": rid, "event": "error", "kind": type(e).__name__,
                  "msg": str(e)[:300]})
            return
        self._futs[rid] = fut
        fut.add_done_callback(partial(self._req_done, rid, post))

    def _engine_takes_trace(self) -> bool:
        """Does this engine's submit() accept ``trace_parent``? Checked
        once — a custom builder with a narrow signature keeps working."""
        if self._takes_trace is None:
            try:
                import inspect

                self._takes_trace = "trace_parent" in \
                    inspect.signature(self.engine.submit).parameters
            except (TypeError, ValueError):
                self._takes_trace = False
        return self._takes_trace

    def _req_done(self, rid, post, fut) -> None:
        self._futs.pop(rid, None)
        try:
            res = fut.result()
        except BaseException as e:
            post({"rid": rid, "event": "error", "kind": type(e).__name__,
                  "msg": str(e)[:300]})
        else:
            if isinstance(res, tuple):  # (seq, logprobs)
                seq, lps = res
                post({"rid": rid, "event": "done",
                      "seq": [int(x) for x in seq],
                      "lp": [float(x) for x in lps]})
            else:
                post({"rid": rid, "event": "done",
                      "seq": [int(x) for x in res]})

    def _probe_reply(self, msg) -> Dict[str, Any]:
        eng = self.engine
        reply: Dict[str, Any] = {
            "queue_depth": int(eng.queue_depth()),
            "kv_headroom": float(eng.kv_headroom())
            if hasattr(eng, "kv_headroom") else 1.0,
            "p95": float(eng.metrics.latency_percentile(95)),
            "seq": self._seq,
            "weight_version": int(getattr(eng, "weight_version", 0) or 0),
        }
        if hasattr(eng, "_active"):
            try:
                reply["active"] = len(eng._active())
            except Exception:
                pass
        if "prompt" in msg and hasattr(eng, "prefix_match_tokens"):
            try:
                reply["match"] = int(eng.prefix_match_tokens(
                    np.asarray(msg["prompt"], dtype=np.int64)))
            except Exception:
                reply["match"] = 0
        return reply

    # -- kv page migration (disaggregated prefill/decode) ---------------------
    # The worker round trip blocks the event loop; that is bounded by
    # the engine worker's op drain (one step), far inside the heartbeat
    # grace window — pages for one prompt are small next to weights.
    def _kv_export(self, conn, rid, msg) -> None:
        from .kv_transfer import chunk_blob, pack_kv_pages  # lazy

        t0 = time.monotonic()
        try:
            npages, k_st, v_st = self.engine.export_kv_pages(
                np.asarray(msg["prompt"], dtype=np.int64))
            blob, manifest, meta = pack_kv_pages(
                k_st, v_st, quantize=bool(msg.get("quantize")))
            chunks = chunk_blob(blob,
                                int(msg.get("chunk_bytes", 1 << 20)))
        except Exception as e:
            self._post(conn, {"rid": rid, "event": "error",
                              "kind": type(e).__name__,
                              "msg": str(e)[:300]})
            return
        self._kv_handle += 1
        handle = self._kv_handle
        self._kv_out[handle] = chunks
        while len(self._kv_out) > 8:     # bounded staging, oldest out
            self._kv_out.pop(min(self._kv_out))
        if msg.get("trace"):
            # the export work, visible from THIS pid in the merged
            # fleet trace (the supervisor records the wire span)
            try:
                tr = _tracer()
                tid = tr.start(self.name, kind="kv_export",
                               parent=str(msg["trace"]), t0=t0)
                tr.span(tid, "kv_pack", t0, time.monotonic(),
                        npages=int(npages), chunks=len(chunks),
                        wire_bytes=int(meta.get("wire_bytes", 0)))
                tr.finish(tid, ok=True)
            except Exception:
                pass
        reply = {"rid": rid, "event": "reply", "handle": handle,
                 "nchunks": len(chunks), "manifest": manifest}
        reply.update(meta)
        self._post(conn, reply)

    def _kv_chunk(self, conn, rid, msg) -> None:
        chunks = self._kv_out.get(msg.get("handle"))
        idx = int(msg.get("idx", -1))
        if chunks is None or not 0 <= idx < len(chunks):
            self._post(conn, {"rid": rid, "event": "error",
                              "kind": "KeyError",
                              "msg": f"kv export handle/chunk "
                                     f"{msg.get('handle')}/{idx}"})
            return
        ch = dict(chunks[idx])
        ch.update(rid=rid, event="reply")
        self._post(conn, ch)

    def _kv_install_begin(self, conn, rid, msg) -> None:
        self._kv_handle += 1
        handle = self._kv_handle
        self._kv_in[handle] = {
            "prompt": [int(x) for x in msg["prompt"]],
            "manifest": msg["manifest"], "digest": msg.get("digest"),
            "nchunks": int(msg["nchunks"]), "chunks": {},
            "trace": msg.get("trace"), "t0": time.monotonic()}
        while len(self._kv_in) > 8:
            self._kv_in.pop(min(self._kv_in))
        self._post(conn, {"rid": rid, "event": "reply",
                          "handle": handle})

    def _kv_install_chunk(self, conn, rid, msg) -> None:
        import base64
        import hashlib

        st = self._kv_in.get(msg.get("handle"))
        if st is None:
            self._post(conn, {"rid": rid, "event": "error",
                              "kind": "KeyError",
                              "msg": "unknown kv install handle"})
            return
        idx = int(msg["idx"])
        raw = base64.b64decode(msg["data"])
        if hashlib.sha256(raw).hexdigest() != msg.get("sha"):
            # reject NOW: the shipper resends just this chunk
            self._post(conn, {"rid": rid, "event": "error",
                              "kind": "ValueError",
                              "msg": f"kv chunk {idx} digest mismatch"})
            return
        st["chunks"][idx] = {"idx": idx, "data": msg["data"],
                             "sha": msg["sha"]}
        self._post(conn, {"rid": rid, "event": "reply", "ok": True,
                          "have": len(st["chunks"])})

    def _kv_install_commit(self, conn, rid, msg) -> None:
        from .kv_transfer import assemble_chunks, unpack_kv_pages

        st = self._kv_in.pop(msg.get("handle"), None)
        t0 = time.monotonic()
        try:
            if st is None:
                raise KeyError("unknown kv install handle")
            if len(st["chunks"]) != st["nchunks"]:
                raise ValueError(
                    f"kv install incomplete: {len(st['chunks'])}/"
                    f"{st['nchunks']} chunks")
            blob = assemble_chunks(
                [st["chunks"][i] for i in range(st["nchunks"])],
                digest=st.get("digest"))
            k_st, v_st = unpack_kv_pages(blob, st["manifest"])
            installed = self.engine.install_kv_pages(
                np.asarray(st["prompt"], dtype=np.int64), k_st, v_st)
        except Exception as e:
            self._post(conn, {"rid": rid, "event": "error",
                              "kind": type(e).__name__,
                              "msg": str(e)[:300]})
            return
        if st.get("trace"):
            try:
                tr = _tracer()
                tb = float(st.get("t0") or t0)
                tid = tr.start(self.name, kind="kv_install",
                               parent=str(st["trace"]), t0=tb)
                tr.span(tid, "kv_install", tb, time.monotonic(),
                        installed=int(installed),
                        nchunks=int(st["nchunks"]))
                tr.finish(tid, ok=True)
            except Exception:
                pass
        self._post(conn, {"rid": rid, "event": "reply",
                          "installed": int(installed),
                          "ms": round((time.monotonic() - t0) * 1e3, 3)})

    def _start_subscriber(self, msg: Dict[str, Any]) -> None:
        """Attach this replica to a WeightPublisher (post_training
        weight service): a subscriber thread pulls new weight versions
        and applies them in place through ``engine.swap_weights``. The
        supervisor re-sends the endpoint after every respawn, so
        idempotence on (host, port) matters here."""
        from ..post_training.weights import WeightSubscriber  # lazy

        host, port = str(msg["host"]), int(msg["port"])
        if self._subscriber is not None:
            if self._subscriber.endpoint == (host, port) and \
                    self._subscriber.alive():
                return
            self._subscriber.stop()
        sub = WeightSubscriber(
            host, port, engine=self.engine, name=self.name,
            poll_interval=float(msg.get("poll_s", 0.25)))
        sub.start()
        self._subscriber = sub
        if msg.get("trace"):
            # weight-push frames carry the fleet ops context too: the
            # subscribe lands as a marker span from this pid
            try:
                tr = _tracer()
                t0 = time.monotonic()
                tid = tr.start(self.name, kind="weights",
                               parent=str(msg["trace"]), t0=t0)
                tr.span(tid, "subscribe", t0, time.monotonic(),
                        host=host, port=port)
                tr.finish(tid, ok=True)
            except Exception:
                pass


def replica_main() -> int:
    """The replica worker entry (``python -m paddle_tpu.serving.fleet``):
    build the engine from ``PT_REPLICA_BUILDER``, warm every bucket,
    publish readiness to the control-plane store, then serve RPC +
    heartbeats until shutdown."""
    name = os.environ.get("PT_REPLICA_NAME", "replica0")
    inc = int(os.environ.get("PT_REPLICA_INCARNATION", "0"))
    hb = float(os.environ.get("PT_REPLICA_HB_INTERVAL", "0.3"))
    endpoint = os.environ.get("PT_SERVING_FLEET_ENDPOINT", "")
    spec = os.environ.get("PT_REPLICA_BUILDER", "")
    if not spec:
        raise SystemExit("PT_REPLICA_BUILDER not set")
    engine = resolve_builder(spec)()
    tuned = os.environ.get("PT_TUNED_SHAPE", "")
    if tuned:
        # online auto-tuner respec: the supervisor stamped a derived
        # serving shape into the env before this (rolling-restart)
        # respawn — apply it BEFORE warmup so the zero-retrace
        # invariant holds over the new bucket family too
        from ..tuning.serving_tuner import apply_tuned_shape

        engine = apply_tuned_shape(engine, json.loads(tuned))
    if hasattr(engine, "warmup"):
        engine.warmup()  # warmed buckets BEFORE the ready publish
    engine.start()
    store = None
    if endpoint:
        from ..distributed.store import TCPStore

        host, port = endpoint.rsplit(":", 1)
        store = TCPStore(host=host, port=int(port), world_size=1,
                         timeout=60)
    _ReplicaServer(name, engine, store=store, hb_interval=hb,
                   incarnation=inc).serve()
    return 0


# ---------------------------------------------------------------------------
# supervisor-side RPC client (GenerationEngine-shaped)
# ---------------------------------------------------------------------------

class _RemoteMetrics:
    """The ``r.metrics.latency_percentile(95)`` surface the router's
    scoring reads, backed by the client's cached probe."""

    def __init__(self, client: "ReplicaClient"):
        self._c = client

    def latency_percentile(self, q: int = 95) -> float:
        return float(self._c._probe().get("p95", 0.0))


class _Pending:
    __slots__ = ("future", "on_token", "streaming")

    def __init__(self, future, on_token=None, streaming=False):
        self.future = future
        self.on_token = on_token
        self.streaming = streaming


_EXC_MAP = {
    "BadRequest": BadRequest, "DeadlineExceeded": DeadlineExceeded,
    "QueueFull": QueueFull, "EngineClosed": EngineClosed,
    "RequestCancelled": RequestCancelled, "ReplicaFault": ReplicaFault,
}


class ReplicaClient:
    """The supervisor's handle on one replica process: engine-shaped
    (``submit() -> Future``, ``queue_depth``, ``kv_headroom``,
    ``prefix_match_tokens``, ``health``) over the socket RPC, with a
    short-TTL probe cache so the router's per-submit scoring does one
    round trip, not four. A lost connection fails every pending future
    with ``ReplicaFault`` — the shape the router/fleet fence on."""

    def __init__(self, name: str, host: str, port: int,
                 rpc_timeout_s: float = 30.0, probe_ttl_s: float = 0.05,
                 probe_timeout_s: float = 2.0):
        self.name = name
        self.metrics = _RemoteMetrics(self)
        self._timeout = float(rpc_timeout_s)
        self._probe_ttl = float(probe_ttl_s)
        # probes are SCORING inputs: a wedged replica must cost the
        # dispatcher this bound, not the full rpc timeout
        self._probe_timeout = float(probe_timeout_s)
        self._sock = socket.create_connection((host, port), timeout=10)
        self._sock.settimeout(None)
        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        self._send_lock = _named_lock(
            f"serving.fleet.ReplicaClient[{name}]._send_lock")
        self._lock = _named_lock(
            f"serving.fleet.ReplicaClient[{name}]._lock")
        self._rid = itertools.count(1)
        self._pending: Dict[int, _Pending] = {}
        self._alive = True
        self._probe_cache: Dict[str, Any] = {}
        self._probe_t = 0.0
        self._recv = threading.Thread(target=self._recv_loop,
                                      name=f"pt-replica-rx-{name}",
                                      daemon=True)
        self._recv.start()

    # -- transport ------------------------------------------------------------
    def _send(self, obj: Dict[str, Any]) -> None:
        if not self._alive:
            raise ReplicaFault(f"replica {self.name} connection lost")
        try:
            # _send_lock exists precisely to hold across the socket
            # write: frames from the submit path and the hedge timer
            # must not interleave mid-frame. Leaf lock, never nested.
            with self._send_lock:
                send_frame(self._sock, obj)  # pd-lint: disable=CC001
        except OSError as e:
            self._fail(ReplicaFault(
                f"replica {self.name} send failed: {e}"))
            raise ReplicaFault(f"replica {self.name} connection lost")

    def _recv_loop(self) -> None:
        try:
            while True:
                frame = recv_frame(self._sock)
                if frame is None:
                    break
                self._dispatch_frame(frame)
        except Exception:
            pass
        self._fail(ReplicaFault(f"replica {self.name} connection lost"))

    def _dispatch_frame(self, frame: Dict[str, Any]) -> None:
        rid = frame.get("rid")
        ev = frame.get("event")
        with self._lock:
            p = self._pending.get(rid)
            if p is not None and ev in ("done", "error", "reply"):
                del self._pending[rid]
        if p is None:
            return  # retired rid (cancelled request): frames ignored
        if ev == "token":
            if p.on_token is not None:
                try:
                    if "lp" in frame:  # logprob-carrying token stream
                        p.on_token(int(frame["t"]), float(frame["lp"]))
                    else:
                        p.on_token(int(frame["t"]))
                except Exception:
                    pass
        elif ev == "done":
            seq = np.asarray(frame["seq"], dtype=np.int64)
            if "lp" in frame:
                p.future.set_result(
                    (seq, np.asarray(frame["lp"], dtype=np.float32)))
            else:
                p.future.set_result(seq)
        elif ev == "reply":
            p.future.set_result(frame)
        elif ev == "error":
            cls = _EXC_MAP.get(frame.get("kind"), RuntimeError)
            p.future.set_exception(cls(frame.get("msg", "replica error")))

    def _fail(self, exc: Exception) -> None:
        with self._lock:
            if not self._alive:
                return
            self._alive = False
            pending = list(self._pending.values())
            self._pending.clear()
        try:
            self._sock.close()
        except OSError:
            pass
        for p in pending:  # outside the lock: callbacks may re-enter us
            if not p.future.done():
                p.future.set_exception(exc)

    def _rpc(self, op: str, timeout: Optional[float] = None,
             **kw) -> Dict[str, Any]:
        rid = next(self._rid)
        fut: Future = Future()
        with self._lock:
            if not self._alive:
                raise ReplicaFault(
                    f"replica {self.name} connection lost")
            self._pending[rid] = _Pending(fut)
        msg = {"op": op, "rid": rid}
        msg.update(kw)
        try:
            self._send(msg)
            return fut.result(timeout=self._timeout
                              if timeout is None else timeout)
        except ReplicaFault:
            raise
        except Exception as e:
            with self._lock:
                self._pending.pop(rid, None)
            raise ReplicaFault(
                f"replica {self.name} rpc {op} failed: {e}")

    # -- engine-shaped surface ------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               on_token=None, return_logprobs: bool = False,
               trace_parent: Optional[str] = None) -> Future:
        # client-side validation: a malformed REQUEST raises here — the
        # replica stays healthy and must not be fenced for it
        prompt = np.asarray(prompt_ids)
        if prompt.ndim != 1 or prompt.size == 0 or \
                not np.issubdtype(prompt.dtype, np.integer):
            raise BadRequest(
                "prompt must be a non-empty 1-D integer array")
        if max_new_tokens < 1:
            raise BadRequest("max_new_tokens must be >= 1")
        rid = next(self._rid)
        fut: Future = Future()
        fut._pt_rid = rid  # cancel() addresses the replica-side request
        with self._lock:
            if not self._alive:
                raise ReplicaFault(
                    f"replica {self.name} connection lost")
            self._pending[rid] = _Pending(fut, on_token=on_token,
                                          streaming=True)
        msg = {"op": "submit", "rid": rid,
               "prompt": [int(x) for x in prompt],
               "max_new_tokens": int(max_new_tokens),
               "deadline_ms": deadline_ms}
        if return_logprobs:
            msg["logprobs"] = True
        if trace_parent:
            msg["trace"] = str(trace_parent)
        try:
            self._send(msg)
        except Exception:
            with self._lock:
                self._pending.pop(rid, None)
            raise
        return fut

    def cancel(self, future) -> bool:
        rid = getattr(future, "_pt_rid", None)
        if rid is None:
            return False
        with self._lock:
            self._pending.pop(rid, None)
        try:
            reply = self._rpc("cancel", target=rid, timeout=5)
            return bool(reply.get("cancelled"))
        except Exception:
            return False

    def _probe(self, prompt=None, force: bool = False,
               timeout: Optional[float] = None) -> Dict[str, Any]:
        now = time.monotonic()
        if prompt is None and not force and \
                now - self._probe_t < self._probe_ttl:
            return self._probe_cache
        kw: Dict[str, Any] = {}
        if prompt is not None:
            kw["prompt"] = [int(x) for x in np.asarray(prompt).reshape(-1)]
        reply = self._rpc("probe", timeout=self._probe_timeout
                          if timeout is None else timeout, **kw)
        self._probe_cache = reply
        self._probe_t = time.monotonic()
        return reply

    def queue_depth(self) -> int:
        return int(self._probe().get("queue_depth", 0))

    def kv_headroom(self) -> float:
        return float(self._probe().get("kv_headroom", 1.0))

    def prefix_match_tokens(self, prompt_ids, blocks=None) -> int:
        return int(self._probe(prompt=prompt_ids).get("match", 0))

    def health(self, timeout: float = 2.0) -> bool:
        if not self._alive:
            return False
        try:
            self._probe(force=True, timeout=timeout)
            return True
        except Exception:
            return False

    def weight_version(self) -> int:
        """The weight generation the replica currently serves (probe-
        cached); -1 when unknown."""
        try:
            return int(self._probe().get("weight_version", -1))
        except Exception:
            return -1

    def subscribe_weights(self, host: str, port: int,
                          poll_interval: float = 0.25,
                          trace: Optional[str] = None) -> None:
        """Point the replica at a WeightPublisher endpoint; it pulls
        and applies new versions in place via engine.swap_weights()."""
        kw: Dict[str, Any] = {}
        if trace:
            kw["trace"] = str(trace)
        self._rpc("subscribe_weights", host=str(host), port=int(port),
                  poll_s=float(poll_interval), timeout=10, **kw)

    def telemetry(self) -> Dict[str, Any]:
        """This replica's full observability-hub snapshot + its pid —
        the fleet telemetry scrape input."""
        return self._rpc("telemetry", timeout=10)

    def pull_traces(self) -> List[Dict[str, Any]]:
        """Drain the replica's finished fleet-parented traces."""
        return list(self._rpc("trace", timeout=10).get("traces") or [])

    def stats(self) -> Dict[str, Any]:
        return self._rpc("stats").get("stats", {})

    def set_spec(self, enabled: bool) -> None:
        self._rpc("config", spec_decode=bool(enabled), timeout=5)

    # -- kv page migration ----------------------------------------------------
    def kv_export(self, prompt_ids, quantize: bool = False,
                  chunk_bytes: int = 1 << 20,
                  trace: Optional[str] = None) -> Dict[str, Any]:
        """Pull the packed KV pages backing ``prompt_ids`` from this
        replica's prefix cache: a head RPC stages the blob replica-side,
        then each chunk is pulled and digest-verified (one resend per
        bad chunk — the PR-17 weight-transfer shape). Returns the
        payload dict ``kv_install`` accepts."""
        import base64
        import hashlib

        prompt = [int(x) for x in np.asarray(prompt_ids).reshape(-1)]
        kw: Dict[str, Any] = {}
        if trace:
            kw["trace"] = str(trace)
        head = self._rpc("kv_export", prompt=prompt,
                         quantize=bool(quantize),
                         chunk_bytes=int(chunk_bytes), **kw)
        parts: List[bytes] = []
        for i in range(int(head["nchunks"])):
            raw = None
            for _attempt in range(2):
                ch = self._rpc("kv_chunk", handle=head["handle"], idx=i)
                got = base64.b64decode(ch["data"])
                if hashlib.sha256(got).hexdigest() == ch.get("sha"):
                    raw = got
                    break
            if raw is None:
                raise ReplicaFault(
                    f"replica {self.name} kv chunk {i} digest mismatch")
            parts.append(raw)
        blob = b"".join(parts)
        if hashlib.sha256(blob).hexdigest() != head["digest"]:
            raise ReplicaFault(
                f"replica {self.name} kv blob digest mismatch")
        return {"prompt": prompt, "manifest": head["manifest"],
                "digest": head["digest"], "data": blob,
                "npages": int(head["npages"]),
                "wire_bytes": int(head["wire_bytes"]),
                "fp32_bytes": int(head["fp32_bytes"]),
                "quantized": bool(head["quantized"]),
                "chunks": int(head["nchunks"])}

    def kv_install(self, payload: Dict[str, Any],
                   chunk_bytes: int = 1 << 20,
                   trace: Optional[str] = None) -> Dict[str, Any]:
        """Ship a ``kv_export`` payload into this replica's paged pool
        (begin -> digest-verified chunks, one resend each -> commit:
        the replica assembles, dequantizes if needed, writes the pages
        and adopts them into its prefix trie). Returns
        ``{"installed": npages, "ms": install_ms}``."""
        from .kv_transfer import chunk_blob  # lazy

        chunks = chunk_blob(payload["data"], int(chunk_bytes))
        kw: Dict[str, Any] = {}
        if trace:
            kw["trace"] = str(trace)
        head = self._rpc("kv_install_begin", prompt=payload["prompt"],
                         manifest=payload["manifest"],
                         digest=payload["digest"], nchunks=len(chunks),
                         **kw)
        for ch in chunks:
            for attempt in range(2):
                try:
                    self._rpc("kv_install_chunk",
                              handle=head["handle"], **ch)
                    break
                except ReplicaFault:
                    if attempt or not self._alive:
                        raise
        return self._rpc("kv_install_commit", handle=head["handle"],
                         timeout=60)

    def drain(self) -> None:
        self._rpc("drain", timeout=5)

    def shutdown(self) -> None:
        try:
            self._rpc("shutdown", timeout=5)
        except Exception:
            pass

    def close(self) -> None:
        self._fail(ReplicaFault(f"replica {self.name} client closed"))


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

class ReplicaState(Enum):
    LAUNCHING = "launching"
    READY = "ready"
    DRAINING = "draining"    # rolling restart: fenced for NEW work only
    FENCED = "fenced"
    RESTARTING = "restarting"
    FAILED = "failed"        # restart budget exhausted: stays down


class _Assignment:
    """One submission of a fleet request to one replica (the primary, a
    replay of the primary, or a hedge). ``prefix`` is the prompt it was
    dispatched with (original prompt + tokens already streamed to the
    client at dispatch time) — the dedup baseline."""

    __slots__ = ("req", "replica", "prefix", "tokens", "lps", "fut",
                 "t_dispatch", "t_last", "hedge", "cancelled", "repin",
                 "stage")

    def __init__(self, req: "FleetRequest", replica: str,
                 prefix: List[int], hedge: bool = False,
                 repin: bool = False, stage: str = "decode"):
        self.req = req
        self.replica = replica
        self.prefix = prefix
        self.tokens: List[int] = []
        self.lps: List[float] = []     # behavior logprobs (want_lp)
        self.fut: Optional[Future] = None
        self.t_dispatch = time.monotonic()
        self.t_last = self.t_dispatch  # last token progress (hedge clock)
        self.hedge = hedge
        self.cancelled = False
        # a cross-version re-prefill: no same-weight-version survivor
        # existed, so this assignment restarts from the prompt alone
        # and is deduped against the ledger BY POSITION
        self.repin = repin
        # "prefill" marks a pool-split first leg: the assignment stops
        # after ONE token (the prompt's paged KV is now hot on this
        # replica) and hands the request to the migration queue
        self.stage = stage


class FleetRequest:
    __slots__ = ("id", "prompt", "max_new", "deadline", "deadline_ms",
                 "tenant", "priority", "future", "emitted", "on_token",
                 "primary", "hedge", "replays", "t_submit", "done",
                 "stream_lock", "delivered", "want_lp", "emitted_lp",
                 "weight_version", "kv_payload", "trace")

    def __init__(self, rid: int, prompt: List[int], max_new: int,
                 deadline_ms: Optional[float], tenant: str, priority: int,
                 on_token=None, want_lp: bool = False):
        self.id = rid
        self.prompt = prompt
        self.max_new = int(max_new)
        self.deadline_ms = deadline_ms
        self.deadline = None if deadline_ms is None \
            else time.monotonic() + deadline_ms / 1e3
        self.tenant = tenant
        self.priority = int(priority)
        self.future: Future = Future()
        self.future._pt_req = self     # rollout tier reads the version pin
        self.emitted: List[int] = []   # generated tokens streamed so far
        self.want_lp = bool(want_lp)
        self.emitted_lp: List[float] = []  # behavior-logprob ledger
        # weight generation the emitted prefix was produced under (the
        # replay version pin): None until first dispatch, -1 = unknown
        self.weight_version: Optional[int] = None
        # the shipped KV payload (pool mode): retained so failover can
        # re-install pages on a survivor instead of re-prefilling
        self.kv_payload: Optional[Dict[str, Any]] = None
        # the fleet-level trace context (``fleet-<pid>-<rid>``): ONE id
        # for this request's whole cross-process life — the supervisor
        # records its routing/wire spans under it and every frame RPC
        # carries it so replica-side spans nest under the same key
        self.trace: Optional[str] = None
        self.on_token = on_token
        self.primary: Optional[_Assignment] = None
        self.hedge: Optional[_Assignment] = None
        self.replays = 0
        self.t_submit = time.monotonic()
        self.done = False
        # client-stream delivery state: `delivered` tokens of `emitted`
        # have reached on_token; stream_lock serializes deliveries so
        # racing rx threads can never reorder them
        from ..analysis.lockdep import lock as _named_lock  # lazy: no cycle

        self.stream_lock = _named_lock(
            "serving.fleet.FleetRequest.stream_lock")
        self.delivered = 0


_TRACE_KW: Dict[type, bool] = {}


def _takes_trace_kw(client) -> bool:
    """Does this client's submit() accept ``trace_parent``? Cached per
    type — ReplicaClient always does; the test seam's engine-shaped
    stubs keep their narrow signatures (the ``return_logprobs`` rule)."""
    cls = type(client)
    ok = _TRACE_KW.get(cls)
    if ok is None:
        try:
            import inspect

            ok = "trace_parent" in \
                inspect.signature(cls.submit).parameters
        except (TypeError, ValueError, AttributeError):
            ok = False
        _TRACE_KW[cls] = ok
    return ok


class _ReplicaHandle:
    __slots__ = ("idx", "name", "state", "proc", "client", "incarnation",
                 "restart_at", "count_restart", "t_launch", "inflight",
                 "routed", "routed_since_ready", "log_path", "external",
                 "fence_rec", "pool")

    def __init__(self, idx: int, name: str, external=None):
        self.idx = idx
        self.name = name
        self.pool: Optional[str] = None   # "prefill"/"decode"/None
        self.state = ReplicaState.LAUNCHING
        self.proc: Optional[subprocess.Popen] = None
        self.client = external   # ReplicaClient, or the in-process engine
        self.incarnation = -1
        self.restart_at: Optional[float] = None
        self.count_restart = True
        self.t_launch = 0.0
        self.inflight: Dict[int, _Assignment] = {}  # req id -> assignment
        self.routed = 0
        self.routed_since_ready = 0
        self.log_path: Optional[str] = None
        self.external = external is not None
        self.fence_rec: Optional[Dict[str, Any]] = None  # open recovery


class ServingFleet:
    """Supervised multi-process serving: N ``GenerationEngine`` replica
    processes behind one reliability-aware front door.

    ::

        fleet = ServingFleet(builder="tools/serving_fleet_drill.py:"
                             "build_replica", n_replicas=3).start()
        fut = fleet.submit(prompt, max_new_tokens=8)
        fut.result()                # survives a replica crash mid-stream
        fleet.rolling_restart()     # zero-downtime weight/config rollout
        fleet.close()

    ``builder`` names a zero-arg function (``module:fn`` or
    ``/path.py:fn``) that constructs the replica's engine inside the
    worker process — every replica builds identical weights from the
    same seeded recipe (or loads the same checkpoint), which is what
    makes failover replay bit-identical under greedy decoding.

    Test seam: ``replicas=[...]`` (engine-shaped objects) runs the full
    dispatch/replay/hedge/brownout logic in-process with no spawning —
    the reliability protocol unit-tests without paying for processes.
    """

    def __init__(self, builder: Optional[str] = None, n_replicas: int = 2,
                 policy: Optional[ServingFleetPolicy] = None,
                 router_config: Optional[RouterConfig] = None,
                 names: Optional[Sequence[str]] = None,
                 flight_root: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 eos_token_id: Optional[int] = None,
                 replicas: Optional[Sequence[Any]] = None,
                 name: str = "serving_fleet",
                 pools: Optional[Dict[str, Sequence[str]]] = None,
                 kv_transit: str = "fp32",
                 kv_cache_bytes: int = 256 << 20,
                 min_ship_tokens: int = 8,
                 prom_path: Optional[str] = None):
        from ..distributed.fleet.runtime import FleetStateMachine

        if replicas is None and not builder:
            raise ValueError("need a builder spec (process mode) or "
                             "replicas=[...] (in-process mode)")
        self.name = name
        self.builder = builder
        self.policy = policy or ServingFleetPolicy()
        self.router_config = router_config or RouterConfig()
        self.flight_root = flight_root
        self.log_dir = log_dir
        self.extra_env = dict(extra_env or {})
        self.eos_token_id = eos_token_id
        self.metrics = MetricsRegistry()
        if replicas is not None:
            self._handles = [
                _ReplicaHandle(i, getattr(r, "name", f"replica{i}"),
                               external=r)
                for i, r in enumerate(replicas)]
        else:
            names = list(names or [f"replica{i}"
                                   for i in range(int(n_replicas))])
            self._handles = [_ReplicaHandle(i, n)
                             for i, n in enumerate(names)]
        self._external = replicas is not None
        # disaggregated prefill/decode: pools maps pool name ->
        # replica names; unlisted replicas belong to no pool and serve
        # only as the empty-pool fallback
        if kv_transit not in ("fp32", "int8"):
            raise ValueError("kv_transit must be 'fp32' or 'int8'")
        self.kv_transit = kv_transit
        self.min_ship_tokens = int(min_ship_tokens)
        self._pools_enabled = bool(pools)
        if pools:
            by_name = {h.name: h for h in self._handles}
            assigned: Dict[str, str] = {}
            for pool_name, members in pools.items():
                if pool_name not in ("prefill", "decode"):
                    raise ValueError(f"unknown pool {pool_name!r} "
                                     "(expected 'prefill'/'decode')")
                for m in members:
                    if m not in by_name:
                        raise ValueError(f"pool {pool_name!r} names "
                                         f"unknown replica {m!r}")
                    if m in assigned:
                        raise ValueError(
                            f"replica {m!r} is in two pools")
                    assigned[m] = pool_name
                    by_name[m].pool = pool_name
        self._kv_stats = KVMigrationStats()
        self._kv_cache = FleetKVCache(
            capacity_bytes=int(kv_cache_bytes))
        self._migrations: deque = deque()  # (req, prefill replica name)
        self.sm = FleetStateMachine(len(self._handles),
                                    self.policy.fleet_policy(),
                                    now=time.time())
        self._store = None
        from ..analysis.lockdep import rlock as _named_rlock  # lazy

        self._lock = _named_rlock("serving.fleet.ServingFleet._lock")
        self._req_no = itertools.count(1)
        self._requests: Dict[int, FleetRequest] = {}
        self._unplaced: deque = deque()
        self._inflight_total = 0
        self._tenant_inflight: Dict[str, int] = {}
        self._counters: Dict[str, int] = {}
        self._brownout = 0
        self._brownout_hist: List[Dict[str, Any]] = []
        self._beat_payload: Dict[int, float] = {}
        self._recoveries: List[Dict[str, Any]] = []
        self._closed = False
        self._monitor: Optional[threading.Thread] = None
        self._dispatcher: Optional[threading.Thread] = None
        # post-training weight service: remembered publisher endpoint
        # (re-sent to every respawned replica) + in-process subscribers
        self._weights_endpoint: Optional[Tuple[str, int, float]] = None
        self._local_subs: Dict[str, Any] = {}
        # fleet observability plane: the collector thread scrapes each
        # replica's hub snapshot + finished traces, merges histograms
        # bucket-wise, and derives the SLO signals (docs/observability.md
        # "Fleet plane"). All merged state lives behind _tele_lock —
        # never held across an RPC.
        from ..observability.fleet import (FleetTraceCollector, SloPolicy,
                                          SloTracker)
        from ..analysis.lockdep import lock as _named_lock

        self._tele_lock = _named_lock(
            "serving.fleet.ServingFleet._tele_lock")
        self._fleet_tele: Dict[str, Any] = {}
        self._slo_snap: Dict[str, Any] = {}
        self._slo = SloTracker(SloPolicy(
            target_ms=self.policy.slo_target_ms,
            objective=self.policy.slo_objective,
            window_s=self.policy.slo_window_s))
        self.traces = FleetTraceCollector()
        self._trace_batch_seen: Dict[Tuple[int, int], Any] = {}
        self._scrapes = 0
        self._collector: Optional[threading.Thread] = None
        if prom_path is None and log_dir:
            prom_path = os.path.join(log_dir, "fleet_metrics.prom")
        self.prom_path = prom_path
        self._prom_last = ""
        self._register_provider()

    # -- provider -------------------------------------------------------------
    def _register_provider(self) -> None:
        try:
            from ..observability import register_provider

            register_provider("serving_fleet", self.provider_snapshot)
            register_provider("kv_migration", self.kv_migration_snapshot)
            # the fleet plane: merged telemetry + SLO signals (reads of
            # collector-owned state only — no RPC inside a provider)
            register_provider("fleet_telemetry",
                              self.fleet_telemetry_snapshot)
            register_provider("slo", self.slo_snapshot)
            register_provider("fleet_trace", self.traces.snapshot)
        except Exception:
            pass

    def kv_migration_snapshot(self) -> Dict[str, Any]:
        """The page-migration view: pages/bytes shipped, transit-
        quantized fraction, install latency, the failover ship-vs-
        reprefill split, and the fleet-wide warm cache."""
        snap = self._kv_stats.snapshot()
        snap["transit"] = self.kv_transit
        snap["warm_cache"] = self._kv_cache.stats()
        with self._lock:
            snap["pools"] = {h.name: h.pool for h in self._handles
                             if h.pool is not None}
            snap["pending_migrations"] = len(self._migrations)
        return snap

    def _inc(self, counter: str, n: int = 1) -> None:
        self._counters[counter] = self._counters.get(counter, 0) + n

    def provider_snapshot(self) -> Dict[str, Any]:
        """The fleet's anomaly view: per-replica health, the fence/
        restart timeline, hedge/replay/brownout counters, recovery
        wall-clock breakdowns."""
        now = time.time()
        with self._lock:
            reps = {}
            beats = dict(self.sm._beats)
            for h in self._handles:
                wv = None
                if h.client is not None:
                    if h.external:
                        wv = getattr(h.client, "weight_version", None)
                        if callable(wv):
                            wv = None  # only plain attributes, no I/O
                    else:  # cached probe value only: no RPC under lock
                        wv = h.client._probe_cache.get("weight_version")
                reps[h.name] = {
                    "state": h.state.value,
                    "incarnation": h.incarnation,
                    "pool": h.pool,
                    "inflight": len(h.inflight),
                    "routed": h.routed,
                    "routed_since_ready": h.routed_since_ready,
                    "weight_version": wv,
                    "last_beat_age_s": round(now - beats[h.idx], 3)
                    if h.idx in beats else None,
                }
            sm = self.sm.snapshot()
            return {
                "name": self.name,
                "replicas": reps,
                "counters": dict(self._counters),
                "inflight": self._inflight_total,
                "brownout": {"stage": self._brownout,
                             "stage_name": BROWNOUT_STAGES[self._brownout],
                             "history": list(self._brownout_hist)},
                "timeline": sm["timeline"],
                "rank_restarts": sm.get("rank_restarts", {}),
                "recoveries": list(self._recoveries),
                "unplaced": len(self._unplaced),
                "policy": {
                    "heartbeat_timeout": self.policy.heartbeat_timeout,
                    "max_restarts": self.policy.max_restarts,
                    "hedge_ms": self.policy.hedge_ms,
                    "replica_capacity": self.policy.replica_capacity,
                },
            }

    def stats(self) -> Dict[str, Any]:
        return self.provider_snapshot()

    # -- lifecycle ------------------------------------------------------------
    def start(self, wait_ready: bool = True,
              timeout: Optional[float] = None) -> "ServingFleet":
        if self._external:
            for h in self._handles:
                if hasattr(h.client, "start"):
                    h.client.start()
                h.state = ReplicaState.READY
                h.incarnation = 0
        else:
            from ..distributed.store import TCPStore

            self._store = TCPStore(is_master=True, world_size=1,
                                   timeout=60)
            for h in self._handles:
                self._spawn(h)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name=f"pt-fleet-{self.name}",
                                         daemon=True)
        self._monitor.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name=f"pt-fleet-dispatch-{self.name}", daemon=True)
        self._dispatcher.start()
        self._collector = threading.Thread(
            target=self._telemetry_loop,
            name=f"pt-fleet-telemetry-{self.name}", daemon=True)
        self._collector.start()
        if wait_ready and not self._external:
            self.wait_ready(timeout=timeout
                            or self.policy.start_timeout_s)
        return self

    def wait_ready(self, timeout: float = 180.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if all(h.state is ReplicaState.READY
                       for h in self._handles):
                    return
                if all(h.state in (ReplicaState.READY, ReplicaState.FAILED)
                       for h in self._handles) and \
                        any(h.state is ReplicaState.READY
                            for h in self._handles):
                    return
            time.sleep(0.05)
        states = {h.name: h.state.value for h in self._handles}
        raise TimeoutError(f"fleet not ready within {timeout}s: {states}")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            live = list(self._requests.values())
            self._requests.clear()
            self._unplaced.clear()
            self._migrations.clear()
        for th in (self._monitor, self._dispatcher, self._collector):
            if th is not None:
                th.join(timeout=5)
        for sub in list(self._local_subs.values()):
            try:
                sub.stop()
            except Exception:
                pass
        self._local_subs.clear()
        for h in self._handles:
            c = h.client
            if c is not None and not h.external:
                try:
                    c.shutdown()
                except Exception:
                    pass
                try:
                    c.close()
                except Exception:
                    pass
            if h.external and hasattr(c, "close"):
                try:
                    c.close()
                except Exception:
                    pass
            if h.proc is not None and h.proc.poll() is None:
                try:
                    h.proc.terminate()
                except OSError:
                    pass
        for h in self._handles:
            if h.proc is not None:
                try:
                    h.proc.wait(timeout=10)
                except Exception:
                    try:
                        h.proc.kill()
                    except OSError:
                        pass
        if self._store is not None:
            try:
                self._store.close()
            except Exception:
                pass
        for req in live:
            if not req.future.done():
                req.future.set_exception(EngineClosed("fleet closed"))
            # close the fleet trace too — an unfinished trace would pin
            # the tracer's live table forever
            self._finish_trace(req, ok=False, error="EngineClosed")

    # -- spawning -------------------------------------------------------------
    def _spawn(self, h: _ReplicaHandle) -> None:
        """Launch one replica process (a fresh incarnation: fresh store
        keys, fresh log). The worker publishes its RPC port only after
        ``engine.warmup()`` — readiness means warmed buckets."""
        h.incarnation += 1
        for leaf in ("port", "beat", "traces"):
            key = f"svfleet/{h.name}/{h.incarnation}/{leaf}"
            self._store.delete_key(key)
            self._store.delete_key(f"{key}/published")
        self._beat_payload.pop(h.idx, None)
        env = dict(os.environ)
        env.update(self.extra_env)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        env.update({
            "PT_REPLICA_NAME": h.name,
            "PT_REPLICA_INCARNATION": str(h.incarnation),
            "PT_REPLICA_BUILDER": self.builder,
            "PT_REPLICA_HB_INTERVAL": str(self.policy.heartbeat_interval),
            "PT_SERVING_FLEET_ENDPOINT": f"127.0.0.1:{self._store.port}",
        })
        if self.flight_root:
            env["PT_FLIGHT_DIR"] = os.path.join(self.flight_root, h.name)
        log_fh = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            h.log_path = os.path.join(
                self.log_dir, f"{h.name}.{h.incarnation}.log")
            log_fh = open(h.log_path, "wb")
        h.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.fleet"], env=env,
            stdout=log_fh, stderr=subprocess.STDOUT if log_fh else None)
        if log_fh is not None:
            log_fh.close()  # the child holds its own fd
        h.state = ReplicaState.LAUNCHING
        h.t_launch = time.time()
        h.restart_at = None

    def _check_ready(self, h: _ReplicaHandle) -> None:
        from ..distributed.fleet.runtime import _probe_json

        info = _probe_json(
            self._store, f"svfleet/{h.name}/{h.incarnation}/port")
        if info is None:
            return
        try:
            client = ReplicaClient(
                h.name, "127.0.0.1", int(info["port"]),
                rpc_timeout_s=self.policy.rpc_timeout_s)
            client._probe(force=True)
        except Exception:
            return  # port published but not accepting yet: next poll
        with self._lock:
            if h.state is not ReplicaState.LAUNCHING:
                # fenced while we were connecting: stay fenced
                try:
                    client.close()
                except Exception:
                    pass
                return
            h.client = client
            h.state = ReplicaState.READY
            h.routed_since_ready = 0
            if h.fence_rec is not None:
                h.fence_rec["ready_ms"] = round(
                    (time.time() - h.fence_rec["fence_t"]) * 1e3, 1)
                h.fence_rec = None
            spec_off = self._brownout >= 1
        if spec_off:  # a replica restarted mid-brownout joins degraded
            try:
                client.set_spec(False)
            except Exception:
                pass
        # a respawned replica rejoins the weight stream: without the
        # re-subscribe it would serve stale weights forever
        self._subscribe_one(h, client)

    # -- the monitor loops ----------------------------------------------------
    # TWO threads on purpose: supervision (beats, exits, staleness,
    # respawn) must never wait on a replica's socket — hedge/brownout/
    # retry DISPATCH does blocking probe RPCs, and one wedged replica
    # stalling those must not delay the stale-heartbeat fence past the
    # grace window (the detection-latency contract the drill pins).
    def _monitor_loop(self) -> None:
        while not self._closed:
            try:
                self._monitor_once(time.time())
            except Exception:
                pass  # supervision must outlive any single bad poll
            time.sleep(self.policy.poll_interval)

    def _dispatch_loop(self) -> None:
        while not self._closed:
            try:
                self._check_hedges()
                self._eval_brownout(time.time())
                self._drain_migrations()
                self._drain_unplaced()
            except Exception:
                pass
            time.sleep(self.policy.poll_interval)

    def _monitor_once(self, now: float) -> None:
        if not self._external:
            self._pump_beats()
            for h in list(self._handles):
                st = h.state
                rc = h.proc.poll() if h.proc is not None else None
                if st in (ReplicaState.READY, ReplicaState.DRAINING):
                    if rc is not None:
                        self._fence(h, cause="crash", rc=rc)
                elif st is ReplicaState.LAUNCHING:
                    if rc is not None:
                        self._fence(h, cause="launch_crash", rc=rc)
                    elif now - h.t_launch > self.policy.start_timeout_s:
                        self._fence(h, cause="start_timeout")
                    else:
                        self._check_ready(h)
            stale = set(self.sm.stale_ranks(now))
            for h in list(self._handles):
                if h.idx in stale and h.state in (ReplicaState.READY,
                                                  ReplicaState.DRAINING):
                    self._fence(h, cause="stale_heartbeat")
        for h in list(self._handles):
            if h.state is ReplicaState.RESTARTING and \
                    h.restart_at is not None and now >= h.restart_at:
                self._respawn(h)

    def _pump_beats(self) -> None:
        """Worker beats -> the state machine, on the SUPERVISOR's clock,
        deduped on the worker payload ts (the PR-10 skew rule)."""
        from ..distributed.fleet.runtime import _probe_json

        now = time.time()
        for h in self._handles:
            if h.state not in (ReplicaState.LAUNCHING, ReplicaState.READY,
                               ReplicaState.DRAINING):
                continue
            beat = _probe_json(
                self._store, f"svfleet/{h.name}/{h.incarnation}/beat")
            if beat is None:
                continue
            try:
                ts = float(beat["ts"])
            except (KeyError, TypeError, ValueError):
                continue
            if self._beat_payload.get(h.idx) == ts:
                continue
            self._beat_payload[h.idx] = ts
            self.sm.heartbeat(h.idx, now)
            # beat-piggybacked trace batches (the crash-adjacent flush
            # path): probed only when the beat advanced — bounded store
            # traffic — and deduped per (replica, incarnation) on the
            # batch seq; the collector dedups again by trace id
            tb = _probe_json(
                self._store, f"svfleet/{h.name}/{h.incarnation}/traces")
            if tb and tb.get("seq") != \
                    self._trace_batch_seen.get((h.idx, h.incarnation)):
                self._trace_batch_seen[(h.idx, h.incarnation)] = \
                    tb.get("seq")
                try:
                    self.traces.add(tb.get("traces") or [])
                except Exception:
                    pass

    # -- fence + restart ------------------------------------------------------
    def _fence(self, h: _ReplicaHandle, cause: str,
               rc: Optional[int] = None) -> None:
        """Fence one replica: record it in the state machine timeline,
        fail over its in-flight requests (replay), and schedule a
        bounded-backoff restart. The survivors keep serving."""
        now = time.time()
        with self._lock:
            if h.state in (ReplicaState.FENCED, ReplicaState.RESTARTING,
                           ReplicaState.FAILED):
                return
            last_beat = self._beat_payload.get(h.idx)
            self.sm.replica_fence(h.idx, now, cause, rc=rc)
            self._inc("fences")
            h.state = ReplicaState.FENCED
            victims = list(h.inflight.values())
            h.inflight.clear()
            client = h.client
            if not h.external:
                h.client = None  # external objects stay for the respawn
            rec = {"replica": h.name, "cause": cause, "rc": rc,
                   "fence_t": now, "incarnation": h.incarnation,
                   "inflight_replayed": len(victims)}
            if cause == "stale_heartbeat" and last_beat is not None:
                rec["silent_s"] = round(now - last_beat, 3)
            self._recoveries.append(rec)
            h.fence_rec = rec  # closed with ready_ms at re-admission
            act = self.sm.replica_restart_decision(h.idx, now)
            if act.kind == "fail":
                h.state = ReplicaState.FAILED
                self._inc("failed_replicas")
            else:
                h.state = ReplicaState.RESTARTING
                h.restart_at = now + act.backoff_s
                h.count_restart = True
        # outside the lock: network teardown + replay dispatches
        if client is not None and not h.external:
            try:
                client.close()  # pending futures fail -> replay callbacks
            except Exception:
                pass
        if h.proc is not None and h.proc.poll() is None:
            try:
                h.proc.terminate()  # the hung-not-dead case
            except OSError:
                pass
        for asg in victims:
            self._assignment_failed(
                asg, ReplicaFault(f"replica {h.name} fenced: {cause}"))

    def fence_replica(self, name: str, cause: str = "operator") -> None:
        """Operator/test fence of one replica by name."""
        for h in self._handles:
            if h.name == name:
                self._fence(h, cause=cause)
                return
        raise KeyError(name)

    def _respawn(self, h: _ReplicaHandle) -> None:
        now = time.time()
        self.sm.replica_restarted(h.idx, now, count=h.count_restart)
        if h.external:
            # in-process seam: the replica object restarts itself
            replica = h.client
            if replica is not None:
                try:
                    if hasattr(replica, "restart"):
                        replica.restart()
                    elif hasattr(replica, "unfence"):
                        replica.unfence()
                except Exception:
                    pass
                with self._lock:
                    h.state = ReplicaState.READY
                    h.routed_since_ready = 0
                    h.restart_at = None
                    h.incarnation += 1
                    if h.fence_rec is not None:
                        h.fence_rec["ready_ms"] = round(
                            (now - h.fence_rec["fence_t"]) * 1e3, 1)
                        h.fence_rec = None
            if h.count_restart:
                self._inc("restarts")
            return
        with self._lock:
            if h.state is not ReplicaState.RESTARTING:
                return
        self._spawn(h)
        if h.count_restart:  # planned rolls spend no budget, count apart
            self._inc("restarts")

    # -- assignment lifecycle -------------------------------------------------
    def _on_tok(self, asg: _Assignment, t: int, lp=None) -> None:
        """One streamed token from a replica. Only the PRIMARY
        assignment advances the client-visible ledger — the dedup rule
        that makes failover exactly-once per token. A cross-version
        re-prefill (``asg.repin``) re-walks positions the ledger
        already holds; those dedup BY POSITION instead of extending."""
        deliver = False
        with self._lock:
            req = asg.req
            if asg.cancelled or req.done:
                return
            asg.tokens.append(int(t))
            if lp is not None:
                asg.lps.append(float(lp))
            asg.t_last = time.monotonic()
            if asg is req.primary:
                idx = (len(asg.prefix) - len(req.prompt)) + \
                    len(asg.tokens) - 1
                if idx == len(req.emitted):
                    req.emitted.append(int(t))
                    if req.want_lp:
                        req.emitted_lp.append(
                            0.0 if lp is None else float(lp))
                    deliver = True
        if deliver:
            self._deliver_stream(req)

    def _deliver_stream(self, req: FleetRequest) -> None:
        """Drain undelivered ledger tokens to the client callback IN
        ORDER. Racing rx threads (a primary token callback vs a hedge
        completion bulk-delivering the tail) serialize on the
        per-request stream lock and hand over the undelivered suffix —
        a preempted earlier caller can never deliver its token after a
        later one (the exactly-once-in-order stream contract)."""
        cb = req.on_token
        if cb is None:
            return
        with req.stream_lock:
            while True:
                with self._lock:
                    if req.delivered >= len(req.emitted):
                        return
                    t = req.emitted[req.delivered]
                    lp = None
                    if req.want_lp and \
                            req.delivered < len(req.emitted_lp):
                        lp = req.emitted_lp[req.delivered]
                    req.delivered += 1
                try:
                    if req.want_lp:
                        cb(int(t), lp)
                    else:
                        cb(int(t))
                except Exception:
                    pass

    def _asg_done_cb(self, asg: _Assignment, fut: Future) -> None:
        exc = fut.exception()
        if exc is None:
            self._assignment_completed(asg, fut.result())
        else:
            self._assignment_failed(asg, exc)

    def _assignment_completed(self, asg: _Assignment, res) -> None:
        cancel_target: Optional[Tuple[Any, Future]] = None
        loser: Optional[_Assignment] = None
        if isinstance(res, tuple):  # (seq, behavior logprobs)
            seq, seq_lp = res
        else:
            seq, seq_lp = res, None
        with self._lock:
            req = asg.req
            for h in self._handles:
                if h.name == asg.replica:
                    h.inflight.pop(req.id, None)
            if req.done or asg.cancelled:
                return
            gen_prefix = len(asg.prefix) - len(req.prompt)
            full_gen = list(asg.prefix[len(req.prompt):]) + \
                [int(t) for t in seq[len(asg.prefix):]]
            if full_gen[:len(req.emitted)] != req.emitted:
                # greedy determinism makes this impossible WITHIN one
                # weight version; a cross-version re-prefill (repin)
                # may legitimately diverge — either way the completed
                # result is authoritative over the partial stream
                self._inc("version_restitch" if asg.repin
                          else "stream_mismatch")
            req.emitted = full_gen
            if req.want_lp:
                # rebuild the logprob ledger the same way: ledger
                # entries for the dispatch prefix + this assignment's
                # logprobs for everything it generated
                tail = [] if seq_lp is None else \
                    [float(x) for x in seq_lp]
                req.emitted_lp = \
                    list(req.emitted_lp[:gen_prefix]) + tail
            handoff = False
            if asg.stage == "prefill":
                work_left = len(req.emitted) < req.max_new and not (
                    self.eos_token_id is not None and req.emitted and
                    req.emitted[-1] == self.eos_token_id)
                if work_left:
                    # the prefill leg is done — the prompt's paged KV
                    # is hot on this replica. Hand the request to the
                    # migration queue (ship pages -> decode pool)
                    # instead of finishing it; the dispatcher thread
                    # owns the blocking transfer RPCs.
                    handoff = True
                    req.primary = None
                    self._migrations.append((req, asg.replica))
            if not handoff:
                other = req.hedge if asg is req.primary else req.primary
                if other is not None and other is not asg:
                    other.cancelled = True
                    owner = self._handle_by_name(other.replica)
                    if owner is not None:
                        owner.inflight.pop(req.id, None)
                    if other.fut is not None and owner is not None and \
                            owner.client is not None and \
                            hasattr(owner.client, "cancel"):
                        cancel_target = (owner.client, other.fut)
                    loser = other
                    self._inc("hedge_cancelled")
                if asg.hedge:
                    self._inc("hedge_wins")
                self._finish_locked(req)
        # undelivered tail (a hedge win bulk-delivers it) goes through
        # the ordered per-request delivery path, BEFORE the future
        # resolves
        self._deliver_stream(req)
        if handoff:
            self._inc("prefill_handoffs")
            return
        if loser is not None:
            # the hedge loser's leg, marked cancelled under the SAME
            # fleet id — a sibling of the winner's route span
            self._trace_span(req, "hedge_loser", loser.t_dispatch,
                             replica=loser.replica, cancelled=True,
                             hedge=loser.hedge)
        if cancel_target is not None:
            try:
                cancel_target[0].cancel(cancel_target[1])
            except Exception:
                pass
        self._set_result(req)
        self._finish_trace(req, ok=True)
        self.metrics.observe_latency(
            (time.monotonic() - req.t_submit) * 1e3)
        self.metrics.mark_done()
        self._inc("completed")

    def _set_result(self, req: FleetRequest) -> None:
        """Resolve the request future from the ledger (safe outside the
        lock once ``req.done`` — the ledger no longer mutates)."""
        if req.future.done():
            return
        result = np.asarray(list(req.prompt) + req.emitted,
                            dtype=np.int64)
        if req.want_lp:
            req.future.set_result(
                (result, np.asarray(req.emitted_lp, dtype=np.float32)))
        else:
            req.future.set_result(result)

    def _assignment_failed(self, asg: _Assignment, exc: Exception) -> None:
        with self._lock:
            req = asg.req
            owner = self._handle_by_name(asg.replica)
            if owner is not None:
                cur = owner.inflight.get(req.id)
                if cur is asg:
                    owner.inflight.pop(req.id, None)
            if req.done or asg.cancelled:
                return
            if isinstance(exc, RequestCancelled):
                return  # fleet-initiated: the winner already resolved
            if asg.hedge:
                # a failed hedge is not a failed request: the primary
                # continues; just clear the hedge slot
                if req.hedge is asg:
                    req.hedge = None
                return
            if req.primary is not asg:
                return  # already replayed by the fence path
        kind = classify_submit_error(exc)
        if kind == "request":
            self._fail_request(req, exc)
            return
        if kind == "fault":
            # the RPC layer noticed the dead replica before the monitor
            # did (lost connection mid-request) — same fence, faster
            owner = self._handle_by_name(asg.replica)
            if owner is not None:
                self._fence(owner, cause="rpc_fault")
        # fault or busy: re-dispatch the request onto a survivor with
        # the already-streamed prefix (hedged re-prefill / replay)
        self._replay(req, asg, count=kind == "fault")

    def _handle_by_name(self, name: str) -> Optional[_ReplicaHandle]:
        for h in self._handles:
            if h.name == name:
                return h
        return None

    # -- fleet trace helpers (always best-effort: tracing must never
    # fail a dispatch) ---------------------------------------------------------
    def _trace_span(self, req: FleetRequest, name: str, t0: float,
                    t1: Optional[float] = None, **args) -> None:
        if req.trace is None:
            return
        try:
            _tracer().span(req.trace, name, t0,
                           time.monotonic() if t1 is None else t1, **args)
        except Exception:
            pass

    def _finish_trace(self, req: FleetRequest, ok: bool, **meta) -> None:
        if req.trace is None:
            return
        try:
            _tracer().finish(req.trace, ok=ok, replays=req.replays,
                             emitted=len(req.emitted), **meta)
        except Exception:
            pass

    def _fail_request(self, req: FleetRequest, exc: Exception) -> None:
        with self._lock:
            if req.done:
                return
            self._finish_locked(req)
        if not req.future.done():
            req.future.set_exception(exc)
        self._finish_trace(req, ok=False, error=type(exc).__name__)
        self._inc("failed")

    def _finish_locked(self, req: FleetRequest) -> None:
        req.done = True
        self._requests.pop(req.id, None)
        self._inflight_total = max(self._inflight_total - 1, 0)
        n = self._tenant_inflight.get(req.tenant, 0)
        if n > 0:
            self._tenant_inflight[req.tenant] = n - 1

    def _replay(self, req: FleetRequest, dead: Optional[_Assignment],
                count: bool = True) -> None:
        """Failover: resubmit ``prompt + emitted`` onto a survivor. The
        prefix cache re-prefills the shared part; the emitted ledger
        guarantees the client stream neither repeats nor loses a
        token."""
        with self._lock:
            if req.done:
                return
            if dead is not None and req.primary is not dead:
                return  # a newer assignment already owns the request
            if count:
                req.replays += 1
                self._inc("replays")
            remaining = req.max_new - len(req.emitted)
            if remaining <= 0 or (
                    self.eos_token_id is not None and req.emitted and
                    req.emitted[-1] == self.eos_token_id):
                # everything was already streamed; only the done frame
                # was lost in the crash — complete from the ledger
                self._finish_locked(req)
                ledger_done = True
            else:
                ledger_done = False
            exclude = {dead.replica} if dead is not None else set()
            if req.hedge is not None:
                # the hedge keeps racing on its replica: the replayed
                # primary must land elsewhere (one assignment per
                # replica per request — the inflight map's key)
                exclude.add(req.hedge.replica)
        t_r = time.monotonic()
        if ledger_done:
            # every token was already streamed: no replica span exists
            # for this leg — only the supervisor's completion marker
            self._trace_span(req, "replayed_complete", t_r, t_r,
                             source=dead.replica if dead else None)
            self._deliver_stream(req)  # any undelivered ledger tail
            self._set_result(req)
            self._finish_trace(req, ok=True, replayed_complete=True)
            self._inc("completed")
            self._inc("replayed_complete")
            return
        prefer = self._ship_failover(req, exclude) if count else None
        self._trace_span(req, "replay", t_r,
                         attempt=req.replays,
                         source=dead.replica if dead else None,
                         shipped=prefer is not None, counted=count)
        if prefer is not None:
            ok = self._dispatch(req, exclude=exclude, pool="decode",
                                prefer=prefer)
        else:
            ok = self._place(req, exclude=exclude)
        if not ok:
            with self._lock:
                if not req.done:
                    self._unplaced.append(req)

    def _ship_failover(self, req: FleetRequest,
                       exclude=()) -> Optional[str]:
        """The stitch-replay fast path: when the request still holds a
        shipped KV payload, install it on a survivor BEFORE the replay
        dispatch — the survivor's prefix cache absorbs the prompt pages
        and the replay re-prefills only the emitted suffix (bytes
        instead of recompute). Returns the preferred survivor name, or
        None (classic re-prefill)."""
        with self._lock:
            payload = req.kv_payload
        if payload is None:
            self._kv_stats.note_failover(ship=False)
            self._inc("failover_reprefill")
            return None
        pool = "decode" if self._pools_enabled else None
        for h, client in self._candidates(exclude=exclude, pool=pool):
            t_w0 = time.monotonic()
            try:
                rep = self._kv_push(client, payload, trace=req.trace)
            except Exception:
                continue
            self._trace_span(req, "wire_transfer", t_w0, dst=h.name,
                             bytes=int(payload["wire_bytes"]),
                             pages=int(payload["npages"]),
                             chunks=int(payload.get("chunks", 1)),
                             quantized=bool(payload["quantized"]),
                             failover=True)
            self._kv_stats.note_failover(ship=True)
            self._kv_stats.note_ship(
                payload["npages"], payload["wire_bytes"],
                payload["fp32_bytes"], payload["quantized"])
            self._kv_stats.note_install(float(rep.get("ms", 0.0)))
            self._inc("failover_ship")
            return h.name
        self._kv_stats.note_failover(ship=False)
        self._inc("failover_reprefill")
        return None

    # -- kv page migration (the prefill -> decode handoff) --------------------
    def _drain_migrations(self) -> None:
        while True:
            with self._lock:
                if not self._migrations:
                    return
                req, src = self._migrations.popleft()
                if req.done:
                    continue
            self._migrate_and_continue(req, src)

    def _kv_pull(self, client, prompt: List[int], quantize: bool,
                 trace: Optional[str] = None) -> Dict[str, Any]:
        """Export the packed pages for ``prompt`` from a replica: the
        chunked RPC on process replicas, a direct pack through the
        in-process seam. ``trace`` carries the fleet trace context over
        the frame — the replica records its pack span under it."""
        if hasattr(client, "kv_export"):
            try:
                return client.kv_export(prompt, quantize=quantize,
                                        trace=trace)
            except TypeError:
                return client.kv_export(prompt, quantize=quantize)
        from .kv_transfer import pack_kv_pages  # lazy

        _n, k_st, v_st = client.export_kv_pages(
            np.asarray(prompt, dtype=np.int64))
        blob, manifest, meta = pack_kv_pages(k_st, v_st,
                                             quantize=quantize)
        return {"prompt": [int(x) for x in prompt],
                "manifest": manifest, "digest": meta["digest"],
                "data": blob, "npages": int(meta["npages"]),
                "wire_bytes": int(meta["wire_bytes"]),
                "fp32_bytes": int(meta["fp32_bytes"]),
                "quantized": bool(meta["quantized"]),
                "chunks": 1}

    def _kv_push(self, client, payload: Dict[str, Any],
                 trace: Optional[str] = None) -> Dict[str, Any]:
        if hasattr(client, "kv_install"):
            try:
                return client.kv_install(payload, trace=trace)
            except TypeError:
                return client.kv_install(payload)
        from .kv_transfer import unpack_kv_pages  # lazy

        t0 = time.monotonic()
        k_st, v_st = unpack_kv_pages(payload["data"],
                                     payload["manifest"])
        installed = client.install_kv_pages(
            np.asarray(payload["prompt"], dtype=np.int64), k_st, v_st)
        return {"installed": int(installed),
                "ms": round((time.monotonic() - t0) * 1e3, 3)}

    def _migrate_and_continue(self, req: FleetRequest, src: str) -> None:
        """Move a prefilled request onto the decode pool: pull the
        packed pages from the prefill replica (or the fleet warm
        cache), install them on the best decode replica, then dispatch
        the decode leg preferring that replica. EVERY failure mode
        falls back to plain dispatch — the decode replica re-prefills
        ``prompt + first token`` and the stream stays bit-identical,
        just slower."""
        quantize = self.kv_transit == "int8"
        t_w0 = time.monotonic()   # the wire-transfer span: pull -> push
        reason = None             # why the migration fell back (if it did)
        warm = False
        key = prompt_cache_key(req.prompt, 1)  # whole-prompt identity
        payload = self._kv_cache.get(key) if key is not None else None
        if payload is not None:
            warm = True
            self._kv_stats.note_warm_hit()
        else:
            with self._lock:
                h = self._handle_by_name(src)
                client = h.client if h is not None and \
                    h.state is ReplicaState.READY else None
            if client is None:
                reason = "no_source"
            else:
                try:
                    payload = self._kv_pull(
                        client, list(req.prompt), quantize,
                        trace=req.trace)
                    self._kv_stats.note_export()
                    if key is not None:
                        self._kv_cache.put(key, payload)
                except Exception:
                    payload = None
                    reason = "export_failed"
        prefer = None
        if payload is not None:
            pool = "decode" if self._pools_enabled else None
            cands = self._candidates(exclude={src}, pool=pool)
            if not cands:
                reason = "no_candidates"
            parr = np.asarray(req.prompt, dtype=np.int64)
            try:
                scores, _m = score_candidates(
                    self.router_config, parr,
                    [c for _h, c in cands], pool=pool)
                order = sorted(range(len(cands)),
                               key=scores.__getitem__)
            except Exception:
                order = list(range(len(cands)))
            for i in order:
                h, client = cands[i]
                try:
                    rep = self._kv_push(client, payload,
                                        trace=req.trace)
                except Exception:
                    reason = "install_failed"
                    continue
                prefer = h.name
                self._trace_span(
                    req, "wire_transfer", t_w0, src="warm_cache"
                    if warm else src, dst=h.name,
                    bytes=int(payload["wire_bytes"]),
                    pages=int(payload["npages"]),
                    chunks=int(payload.get("chunks", 1)),
                    quantized=bool(payload["quantized"]),
                    install_ms=float(rep.get("ms", 0.0)))
                self._kv_stats.note_ship(
                    payload["npages"], payload["wire_bytes"],
                    payload["fp32_bytes"], payload["quantized"])
                self._kv_stats.note_install(float(rep.get("ms", 0.0)))
                with self._lock:
                    req.kv_payload = payload
                self._inc("migrations")
                break
        if prefer is None:
            # the fallback re-prefill leg, tagged with WHY the ship
            # failed — the decode dispatch below re-prefills from the
            # prompt and the stream stays bit-identical
            self._trace_span(req, "migrate_fallback", t_w0, src=src,
                             reason=reason or "no_payload")
            self._kv_stats.note_fallback()
            self._inc("migrate_fallback")
        if not self._dispatch(
                req, pool="decode" if self._pools_enabled else None,
                prefer=prefer):
            with self._lock:
                if not req.done:
                    self._unplaced.append(req)

    # -- dispatch -------------------------------------------------------------
    def _candidates(self, exclude=(), pool: Optional[str] = None
                    ) -> List[Tuple[_ReplicaHandle, Any]]:
        """(handle, client) pairs captured atomically — a concurrent
        fence nulls ``h.client``, so the submit below must use the
        reference taken HERE (a submit on a just-fenced client fails
        with the fault shape and the loop moves on). With ``pool`` set
        (split fleets) only that pool's replicas qualify; an EMPTY pool
        falls back to every ready replica (counted) — a dead prefill
        tier degrades to the classic fused path, not unavailability."""
        with self._lock:
            ready = [(h, h.client) for h in self._handles
                     if h.state is ReplicaState.READY
                     and h.client is not None and h.name not in exclude]
            if pool is not None and self._pools_enabled:
                pooled = [(h, c) for h, c in ready if h.pool == pool]
                if pooled:
                    return pooled
                if ready:
                    self._inc("pool_fallback")
            return ready

    def _dispatch(self, req: FleetRequest, exclude=(),
                  hedge: bool = False, pool: Optional[str] = None,
                  cap_new: Optional[int] = None, stage: str = "decode",
                  prefer: Optional[str] = None) -> bool:
        """Place one request (or its hedge) on the best ready replica —
        the router's load/affinity scoring over live probes, plus the
        fence-and-retry loop with classified errors. Returns False when
        no replica could take it (caller queues it)."""
        tried: set = set(exclude)
        while True:
            cands = self._candidates(exclude=tried, pool=pool)
            if not cands:
                return False
            with self._lock:
                if req.done:
                    return True
                pin = req.weight_version if req.emitted else None
                prefix = list(req.prompt) + list(req.emitted)
                remaining = req.max_new - len(req.emitted)
            repin = False
            if pin is not None and pin >= 0:
                # stitch-replay must be VERSION-PURE: resuming
                # prompt+emitted onto a replica serving different
                # weights would continue a v-N prefix under v-M — a
                # sequence neither version produces. Prefer a same-
                # version survivor; with none left, re-prefill from the
                # prompt alone on the new version (position-deduped
                # against the streamed ledger, counted below).
                vers = [self._replica_version(c) for _h, c in cands]
                same = [i for i, v in enumerate(vers) if v == pin]
                if same:
                    cands = [cands[i] for i in same]
                else:
                    repin = True
                    prefix = list(req.prompt)
                    remaining = req.max_new
            if cap_new is not None:
                # the prefill leg: emit exactly one token — the point
                # is the paged KV it leaves behind, not the stream
                remaining = min(remaining, int(cap_new))
            if remaining <= 0:
                self._replay(req, None, count=False)
                return True
            deadline_ms = None
            if req.deadline is not None:
                deadline_ms = (req.deadline - time.monotonic()) * 1e3
                if deadline_ms <= 0:
                    self._fail_request(req, DeadlineExceeded(
                        "deadline expired before placement"))
                    return True
            parr = np.asarray(prefix, dtype=np.int64)
            try:
                scores, _m = score_candidates(
                    self.router_config, parr, [c for _h, c in cands],
                    pool=pool)
            except Exception:
                scores = [float(i) for i in range(len(cands))]
            order = sorted(range(len(cands)), key=scores.__getitem__)
            if prefer is not None:
                # the migration path already installed this request's
                # pages on `prefer`: try it first, scores after
                pi = [i for i in order if cands[i][0].name == prefer]
                order = pi + [i for i in order if i not in pi]
            progressed = False
            for i in order:
                h, client = cands[i]
                asg = _Assignment(req, h.name, prefix, hedge=hedge,
                                  repin=repin, stage=stage)
                with self._lock:
                    if req.done:
                        return True
                    # the stream callback checks identity against
                    # req.primary/hedge — install BEFORE the submit so
                    # the first token frame cannot race the assignment
                    if hedge:
                        req.hedge = asg
                    else:
                        req.primary = asg
                kw: Dict[str, Any] = {}
                if req.want_lp:
                    # only pass the kwarg when asked: the test seam's
                    # engine-shaped stubs keep their narrow signature
                    kw["return_logprobs"] = True
                if req.trace is not None and _takes_trace_kw(client):
                    # the fleet trace context rides the submit frame:
                    # the replica's engine spans nest under fleet-<id>
                    kw["trace_parent"] = req.trace
                try:
                    fut = client.submit(
                        parr, remaining, deadline_ms=deadline_ms,
                        on_token=partial(self._on_tok, asg), **kw)
                except Exception as e:
                    kind = classify_submit_error(e)
                    with self._lock:
                        if hedge and req.hedge is asg:
                            req.hedge = None
                    if kind == "busy":
                        continue
                    if kind == "request":
                        if hedge:
                            return True  # the primary is still running
                        self._fail_request(req, e)
                        return True
                    tried.add(h.name)
                    self._fence(h, cause="submit_fault")
                    progressed = True
                    break
                asg.fut = fut
                self._trace_span(req, "route", asg.t_dispatch,
                                 replica=h.name, stage=stage,
                                 hedge=hedge, repin=repin,
                                 prefix_len=len(prefix))
                wv = self._replica_version(client)  # probe-cached RPC:
                # outside the lock (CC001)
                with self._lock:
                    h.inflight[req.id] = asg
                    h.routed += 1
                    h.routed_since_ready += 1
                    if not hedge and \
                            (repin or len(prefix) == len(req.prompt)):
                        # the emitted prefix (re)starts under THIS
                        # replica's weights: (re)pin the version
                        req.weight_version = wv
                    if repin:
                        self._inc("version_reprefill")
                fut.add_done_callback(partial(self._asg_done_cb, asg))
                return True
            if not progressed:
                return False

    @staticmethod
    def _replica_version(client) -> int:
        """Best-effort weight generation a replica serves: the probe-
        cached RPC accessor on ReplicaClient, the plain attribute on an
        in-process engine; -1 when unknowable."""
        try:
            wv = getattr(client, "weight_version", None)
            if callable(wv):
                wv = wv()
            if wv is None:
                return -1
            return int(wv)
        except Exception:
            return -1

    def _place(self, req: FleetRequest, exclude=()) -> bool:
        """Route one request through the pool topology: a fresh request
        starts on the prefill pool, capped to ONE token (the leg that
        fills paged KV), then migrates to the decode pool; anything
        with streamed progress, short prompts not worth a ship, and
        unsplit fleets go straight to the decode path."""
        if self._pools_enabled and not req.emitted and not req.done \
                and req.max_new > 1 \
                and len(req.prompt) >= self.min_ship_tokens:
            with self._lock:
                has_prefill = any(
                    h.pool == "prefill"
                    and h.state is ReplicaState.READY
                    and h.name not in exclude for h in self._handles)
            if has_prefill:
                return self._dispatch(req, exclude=exclude,
                                      pool="prefill", cap_new=1,
                                      stage="prefill")
        return self._dispatch(
            req, exclude=exclude,
            pool="decode" if self._pools_enabled else None)

    # -- submission -----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 16,
               tenant: str = "default",
               deadline_ms: Optional[float] = None, priority: int = 1,
               on_token=None, return_logprobs: bool = False) -> Future:
        """Route one prompt through the fleet. The future resolves to
        the full sequence (prompt + generated, np.int64) and SURVIVES
        replica failure: a fenced replica's in-flight work replays onto
        a survivor with the streamed prefix deduped. ``on_token`` (if
        given) streams each generated token exactly once, in order.
        ``priority`` feeds stage-3 brownout shedding: work below
        ``brownout_keep_priority`` (default 1) is sheddable — the
        default priority 1 opts OUT, so only explicitly low-priority
        traffic is ever dropped. With ``return_logprobs=True`` the
        future resolves to ``(full_seq, behavior_logprobs)`` (the
        per-token logprob ledger, float32, replay-identical across
        failover) and ``on_token`` receives ``(token, logprob)``."""
        prompt = np.asarray(prompt_ids).reshape(-1)
        if prompt.size == 0 or \
                not np.issubdtype(prompt.dtype, np.integer):
            raise BadRequest(
                "prompt must be a non-empty 1-D integer array")
        if max_new_tokens < 1:
            raise BadRequest("max_new_tokens must be >= 1")
        self.metrics.inc("requests_total")
        with self._lock:
            if self._closed:
                raise EngineClosed("fleet closed")
            stage = self._brownout
            if brownout_sheds(stage, priority, self.policy):
                self._inc("shed_brownout")
                raise BrownoutShed(
                    f"brownout stage {stage}: priority {priority} shed")
            if self._inflight_total >= self.router_config.max_inflight:
                self._inc("rejected_capacity")
                raise QueueFull(
                    f"fleet at capacity "
                    f"({self.router_config.max_inflight})")
            quota = self.router_config.quota_for(tenant)
            if quota is not None and \
                    self._tenant_inflight.get(tenant, 0) >= quota:
                self._inc("rejected_quota")
                from .router import TenantQuotaExceeded

                raise TenantQuotaExceeded(
                    f"tenant {tenant!r} at quota ({quota})")
            clamped = brownout_max_new(stage, deadline_ms,
                                       int(max_new_tokens), self.policy)
            if clamped != max_new_tokens:
                self._inc("clamped")
            req = FleetRequest(next(self._req_no),
                               [int(x) for x in prompt], clamped,
                               deadline_ms, tenant, priority,
                               on_token=on_token,
                               want_lp=return_logprobs)
            req.trace = f"fleet-{os.getpid():x}-{req.id:x}"
            self._requests[req.id] = req
            self._inflight_total += 1
            self._tenant_inflight[tenant] = \
                self._tenant_inflight.get(tenant, 0) + 1
            self._inc("requests")
        # the supervisor's own trace uses the fleet context AS its id:
        # its routing/wire spans and every replica's parented spans
        # share one key in the merged export
        try:
            _tracer().start(self.name, kind="fleet", trace_id=req.trace,
                            t0=req.t_submit, rid=req.id, tenant=tenant,
                            prompt_len=int(prompt.size),
                            max_new_tokens=int(clamped))
        except Exception:
            pass
        if not self._place(req):
            with self._lock:
                if not req.done:
                    self._unplaced.append(req)
        return req.future

    def _drain_unplaced(self) -> None:
        """Retry requests that had no ready replica at submit/replay
        time (e.g. mid-recovery with every survivor briefly saturated)."""
        while True:
            with self._lock:
                if not self._unplaced:
                    return
                req = self._unplaced.popleft()
                if req.done:
                    continue
            if req.deadline is not None and \
                    time.monotonic() > req.deadline:
                self._fail_request(req, DeadlineExceeded(
                    "deadline expired while awaiting a replica"))
                continue
            if not self._place(req):
                with self._lock:
                    if not req.done:
                        self._unplaced.appendleft(req)
                return

    # -- hedging --------------------------------------------------------------
    def _check_hedges(self) -> None:
        """Tail-latency insurance: a request whose primary has made no
        token progress for ``hedge_ms`` gets ONE speculative second
        submission on a different replica; first completion wins and
        the loser is cancelled."""
        hedge_ms = self.policy.hedge_ms
        if hedge_ms is None:
            return
        now = time.monotonic()
        with self._lock:
            due = [r for r in self._requests.values()
                   if not r.done and r.hedge is None
                   and r.primary is not None and r.primary.fut is not None
                   and r.primary.stage != "prefill"
                   and (now - r.primary.t_last) * 1e3 >= hedge_ms]
        for req in due:
            with self._lock:
                if req.done or req.hedge is not None or \
                        req.primary is None:
                    continue
                exclude = {req.primary.replica}
            if self._dispatch(req, exclude=exclude, hedge=True,
                              pool="decode" if self._pools_enabled
                              else None):
                with self._lock:
                    if req.hedge is not None:
                        self._inc("hedges")

    # -- brownout -------------------------------------------------------------
    def _eval_brownout(self, now: float) -> None:
        with self._lock:
            ready = [h for h in self._handles
                     if h.state is ReplicaState.READY]
            if not ready:
                return  # mid-outage: nothing to degrade; the unplaced
                # queue's deadlines own the overload story
            cap = max(1, len(ready) * self.policy.replica_capacity)
            load = self._inflight_total / cap
            prev = self._brownout
            stage = brownout_stage(prev, load, self.policy)
            if stage == prev:
                return
            self._brownout = stage
            self._inc("brownout_transitions")
            self._brownout_hist.append(
                {"t": round(now, 3), "stage": stage,
                 "name": BROWNOUT_STAGES[stage], "load": round(load, 3)})
            if len(self._brownout_hist) > 256:
                del self._brownout_hist[:-256]
            self.sm.note("brownout", now, stage=stage,
                         load=round(load, 3))
            flip_spec = (stage >= 1) != (prev >= 1)
            spec_on = stage < 1
            targets = [h.client for h in ready] if flip_spec else []
        if stage >= 3:
            self._shed_unplaced()
        for c in targets:  # stage-1 lever: speculation off fleet-wide
            try:
                if hasattr(c, "set_spec"):
                    c.set_spec(spec_on)
                elif hasattr(c, "set_speculative"):
                    c.set_speculative(spec_on)
            except Exception:
                pass

    def _shed_unplaced(self) -> None:
        with self._lock:
            keep, shed = deque(), []
            while self._unplaced:
                r = self._unplaced.popleft()
                if brownout_sheds(3, r.priority, self.policy):
                    shed.append(r)
                else:
                    keep.append(r)
            self._unplaced = keep
        for r in shed:
            self._inc("shed_brownout")
            self._fail_request(r, BrownoutShed(
                "brownout stage 3: queued low-priority request shed"))

    def brownout(self) -> Dict[str, Any]:
        with self._lock:
            return {"stage": self._brownout,
                    "name": BROWNOUT_STAGES[self._brownout],
                    "history": list(self._brownout_hist)}

    # -- fleet telemetry + trace collector ------------------------------------
    # ONE thread (pt-fleet-telemetry-<name>) owns scrape/merge/publish:
    # per-replica RPCs run with NO fleet lock held (CC001 — a wedged
    # replica costs one probe timeout, never a provider stall), the
    # merged result is swapped in under _tele_lock, and the Prometheus
    # file is rewritten only when its text changed.
    def _telemetry_loop(self) -> None:
        last = 0.0
        while not self._closed:
            now = time.time()
            if now - last >= self.policy.telemetry_interval_s:
                try:
                    self._scrape_once(now)
                except Exception:
                    pass  # the feed must outlive any single bad scrape
                last = now
            time.sleep(self.policy.poll_interval)

    def _collect_local_traces(self) -> None:
        """Finished traces born in THIS process: the supervisor's own
        fleet traces plus (in-process seam) engine traces parented under
        them — both land in the collector exactly like a process
        replica's pulled batch."""
        try:
            tr = _tracer()
            self.traces.add(tr.drain_finished(max_n=256,
                                              prefix="fleet-"))
            self.traces.add(tr.drain_finished(max_n=256,
                                              require_parent=True))
        except Exception:
            pass

    def _scrape_once(self, now: float) -> None:
        from ..observability import snapshot as hub_snapshot
        from ..observability.fleet import merge_replica_telemetry

        with self._lock:  # capture targets only; RPCs run below, unlocked
            beats = dict(self.sm._beats)
            targets = [(h.name, h.pool, h.incarnation, h.state.value,
                        len(h.inflight), h.idx, h.client, h.external)
                       for h in self._handles]
        replicas: Dict[str, Dict[str, Any]] = {}
        local_hub_done = False
        for name, pool, inc, state, inflight, idx, client, ext in targets:
            row: Dict[str, Any] = {
                "pool": pool, "incarnation": inc, "state": state,
                "inflight": inflight,
                "beat_age_s": round(now - beats[idx], 3)
                if idx in beats else None,
            }
            if client is not None and state == "ready":
                try:
                    row["queue_depth"] = int(client.queue_depth())
                    if hasattr(client, "kv_headroom"):
                        row["kv_headroom"] = float(client.kv_headroom())
                except Exception:
                    pass
                if ext:
                    # in-process seam: every engine shares THIS
                    # process's hub — attach ONE snapshot total (to the
                    # first ready row) or the merge double-counts
                    if not local_hub_done:
                        try:
                            row["snapshot"] = hub_snapshot()
                            local_hub_done = True
                        except Exception:
                            pass
                else:
                    try:
                        rep = client.telemetry()
                        row["snapshot"] = rep.get("telemetry") or {}
                    except Exception as e:
                        row["scrape_error"] = str(e)[:120]
                    try:
                        self.traces.add(client.pull_traces())
                    except Exception:
                        pass
            replicas[name] = row
        self._collect_local_traces()
        merged = merge_replica_telemetry(replicas)
        merged["scraped_at"] = now
        merged["interval_s"] = self.policy.telemetry_interval_s
        lat = merged.get("histograms", {}).get("request_latency_ms", {})
        slo = self._slo.update(now, per_pool=lat.get("per_pool") or {},
                               fleet=lat.get("fleet"),
                               extras=self._slo_extras(merged))
        with self._tele_lock:
            self._scrapes += 1
            merged["scrapes"] = self._scrapes
            self._fleet_tele = merged
            self._slo_snap = slo
        self._write_prom(merged, slo)

    @staticmethod
    def _slo_extras(merged: Dict[str, Any]) -> Dict[str, Any]:
        """Queue-depth / KV-headroom aggregates + TTFT percentiles —
        the non-latency SLO inputs, all derived from the SAME merged
        scrape (never supervisor-side sampling)."""
        from ..observability.fleet import histogram_quantile

        rows = merged.get("replicas", {})
        qd: Dict[str, int] = {}
        kv: Dict[str, float] = {}
        for r in rows.values():
            p = r.get("pool") or "unpooled"
            if r.get("queue_depth") is not None:
                qd[p] = qd.get(p, 0) + int(r["queue_depth"])
            if r.get("kv_headroom") is not None:
                kv[p] = min(kv.get(p, 1.0), float(r["kv_headroom"]))
        ttft: Dict[str, float] = {}
        tt = merged.get("histograms", {}).get("ttft_ms", {})
        for scope, snap in [("fleet", tt.get("fleet"))] + \
                list((tt.get("per_pool") or {}).items()):
            if snap:
                try:
                    ttft[f"{scope}_p95_ms"] = round(
                        histogram_quantile(snap, 0.95), 3)
                except Exception:
                    pass
        return {"queue_depth": qd,
                "kv_headroom": {p: round(v, 4) for p, v in kv.items()},
                "ttft": ttft}

    def _write_prom(self, merged: Dict[str, Any],
                    slo: Dict[str, Any]) -> None:
        """The fleet Prometheus endpoint-on-disk (atomic replace; a
        scraper never reads a torn file)."""
        path = self.prom_path
        if not path:
            return
        from ..observability.fleet import fleet_prometheus_text

        try:
            text = fleet_prometheus_text(merged, slo)
            if text == self._prom_last:
                return
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
            self._prom_last = text
        except Exception:
            pass

    def fleet_telemetry_snapshot(self) -> Dict[str, Any]:
        """The last merged scrape (the ``fleet_telemetry`` provider)."""
        with self._tele_lock:
            return dict(self._fleet_tele)

    def slo_snapshot(self) -> Dict[str, Any]:
        """The last SLO evaluation (the ``slo`` provider): per-pool
        p95/p99 + burn rate from MERGED histograms only."""
        with self._tele_lock:
            return dict(self._slo_snap)

    def scrape_now(self) -> Dict[str, Any]:
        """One synchronous scrape+merge (tests/drills skip the interval
        wait). Returns the merged fleet telemetry."""
        self._scrape_once(time.time())
        return self.fleet_telemetry_snapshot()

    def export_fleet_trace(self, path: str) -> str:
        """Pull outstanding traces from every replica AND this process,
        then write ONE merged chrome trace (spans from every pid that
        touched a fleet request, grouped under the fleet trace ids)."""
        with self._lock:
            targets = [h.client for h in self._handles
                       if not h.external and h.client is not None
                       and h.state is ReplicaState.READY]
        for client in targets:
            try:
                self.traces.add(client.pull_traces())
            except Exception:
                pass
        self._collect_local_traces()
        return self.traces.export_chrome(path)

    # -- weight distribution (post-training push path) ------------------------
    def subscribe_weights(self, host: str, port: int,
                          poll_interval: float = 0.25) -> None:
        """Point every replica at a ``WeightPublisher`` endpoint: each
        replica runs a subscriber that pulls new weight versions and
        applies them in place via ``engine.swap_weights()`` — a push
        costs seconds, not a respawn. The endpoint is remembered, so a
        replica that restarts (crash respawn OR rolling restart) is
        re-subscribed at re-admission."""
        with self._lock:
            self._weights_endpoint = (str(host), int(port),
                                      float(poll_interval))
            targets = [(h, h.client) for h in self._handles
                       if h.state is ReplicaState.READY
                       and h.client is not None]
        for h, client in targets:
            self._subscribe_one(h, client)

    def _subscribe_one(self, h: _ReplicaHandle, client) -> None:
        """Attach ONE replica to the remembered publisher endpoint
        (no-op without one). Process replicas get the subscribe RPC;
        in-process seam engines get a local subscriber thread."""
        with self._lock:
            ep = self._weights_endpoint
        if ep is None or client is None:
            return
        host, port, poll = ep
        try:
            if h.external:
                from ..post_training.weights import WeightSubscriber

                sub = self._local_subs.get(h.name)
                if sub is not None and sub.endpoint == (host, port) \
                        and sub.alive():
                    return
                if sub is not None:
                    sub.stop()
                sub = WeightSubscriber(host, port, engine=client,
                                       name=h.name, poll_interval=poll)
                sub.start()
                self._local_subs[h.name] = sub
            elif hasattr(client, "subscribe_weights"):
                # weight-push frames carry a fleet ops context too: the
                # replica's subscribe marker groups under it in the
                # merged trace
                try:
                    client.subscribe_weights(
                        host, port, poll_interval=poll,
                        trace=f"fleet-weights-{os.getpid():x}")
                except TypeError:
                    client.subscribe_weights(host, port,
                                             poll_interval=poll)
            else:
                return
            self._inc("weight_subscribes")
        except Exception:
            self._inc("weight_subscribe_errors")

    def replica_weight_versions(self) -> Dict[str, int]:
        """Live per-replica weight versions (one probe RPC per ready
        replica) — the rollout loop's barrier: after a publish, wait
        until every ready replica serves the new version before the
        next round. -1 marks a replica whose version is unknown."""
        with self._lock:
            targets = [(h.name, h.client) for h in self._handles
                       if h.state is ReplicaState.READY
                       and h.client is not None]
        out: Dict[str, int] = {}
        for name, client in targets:
            wv = getattr(client, "weight_version", None)
            try:
                out[name] = int(wv() if callable(wv) else wv)
            except Exception:
                out[name] = -1
        return out

    def push_weights(self, state, version: Optional[int] = None) -> Dict:
        """Directly swap ``state`` into every ready replica via
        ``engine.swap_weights()`` (the in-process seam / test path —
        process fleets push through the publisher/subscriber stream
        instead). Replicas whose engine cannot swap in place fall back
        to ``rolling_restart()``: the slow path costs a respawn, the
        builder re-creating the engine with current weights."""
        with self._lock:
            targets = [(h, h.client) for h in self._handles
                       if h.state is ReplicaState.READY
                       and h.client is not None]
        swapped: List[Dict[str, Any]] = []
        fallback = False
        for h, client in targets:
            fn = getattr(client, "swap_weights", None)
            if fn is None:
                fallback = True
                continue
            try:
                ver = fn(state, version=version)
                swapped.append({"replica": h.name, "version": int(ver)})
            except NotImplementedError:
                fallback = True
            except Exception as e:
                swapped.append({"replica": h.name,
                                "error": str(e)[:200]})
        self._inc("weight_pushes")
        out: Dict[str, Any] = {"swapped": swapped, "fallback": fallback}
        if fallback:
            out["rolled"] = self.rolling_restart()
        return out

    # -- online serving-shape retune ------------------------------------------
    def apply_serving_shape(self, shape: Dict[str, Any]) -> Dict:
        """Actuate a derived serving shape (the online tuner's bucket /
        slot / miss-cap proposal) across the fleet with zero downtime:
        stamp the shape into the replica spawn env and roll the fleet.
        Each replica re-applies the shape and AOT-warms the NEW bucket
        family before it re-publishes readiness, so the zero-retrace
        invariant holds across the cutover. Planned roll: no restart
        budget is spent."""
        payload = json.dumps(shape, sort_keys=True)
        with self._lock:
            self.extra_env["PT_TUNED_SHAPE"] = payload
        self.sm.note("serving_shape", time.time(),
                     digest=shape.get("digest", ""))
        self._inc("shape_applies")
        out = self.rolling_restart()
        out["shape"] = shape
        return out

    # -- rolling restart ------------------------------------------------------
    def rolling_restart(self, drain_timeout_s: Optional[float] = None,
                        ready_timeout_s: Optional[float] = None) -> Dict:
        """Zero-downtime rollout: one replica at a time — fence new
        work, finish its in-flight requests, restart the process, wait
        for it to warm and re-admit, then move on. Requests keep
        flowing through the other replicas the whole time; a planned
        roll spends NO restart budget."""
        drain_s = drain_timeout_s or self.policy.drain_timeout_s
        ready_s = ready_timeout_s or self.policy.start_timeout_s
        rolled = []
        for h in list(self._handles):
            if h.state is ReplicaState.FAILED:
                continue
            # a replica mid-recovery (fenced/restarting/launching) is
            # waited for, not skipped — the roll must cover the fleet
            deadline = time.monotonic() + ready_s
            while time.monotonic() < deadline:
                with self._lock:
                    if h.state in (ReplicaState.READY,
                                   ReplicaState.FAILED):
                        break
                time.sleep(self.policy.poll_interval)
            t0 = time.time()
            with self._lock:
                if h.state is not ReplicaState.READY:
                    continue  # stayed down past the wait: fence owns it
                h.state = ReplicaState.DRAINING
            self.sm.note("roll_drain", t0, rank=h.idx, replica=h.name)
            client = h.client
            try:  # engine-side fence too (belt and braces)
                if hasattr(client, "drain"):
                    client.drain()
                elif hasattr(client, "fence"):
                    client.fence()
            except Exception:
                pass
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline:
                with self._lock:
                    if not h.inflight:
                        break
                time.sleep(self.policy.poll_interval)
            with self._lock:
                leftovers = list(h.inflight.values())
                h.inflight.clear()
                h.state = ReplicaState.RESTARTING
                h.restart_at = None       # the roll owns the respawn
                h.count_restart = False   # planned: no budget spent
            for asg in leftovers:  # drain window expired: fail over
                self._assignment_failed(asg, ReplicaFault(
                    f"replica {h.name} drain timeout during roll"))
            if not self._external:
                try:
                    client.shutdown()
                except Exception:
                    pass
                try:
                    client.close()
                except Exception:
                    pass
                if h.proc is not None:
                    try:
                        h.proc.wait(timeout=15)
                    except Exception:
                        try:
                            h.proc.terminate()
                        except OSError:
                            pass
            self._respawn(h)
            deadline = time.monotonic() + ready_s
            while time.monotonic() < deadline:
                with self._lock:
                    if h.state is ReplicaState.READY:
                        break
                    if h.state in (ReplicaState.FENCED,
                                   ReplicaState.FAILED):
                        break
                time.sleep(0.05)
            with self._lock:
                ok = h.state is ReplicaState.READY
            self.sm.note("roll_done", time.time(), rank=h.idx,
                         replica=h.name, ok=ok,
                         ms=round((time.time() - t0) * 1e3, 1))
            self._inc("rolled_replicas")
            rolled.append({"replica": h.name, "ok": ok,
                           "incarnation": h.incarnation})
            if not ok:
                break
        self._inc("rolling_restarts")
        return {"rolled": rolled,
                "ok": all(r["ok"] for r in rolled) and bool(rolled)}


if __name__ == "__main__":  # the replica worker entry
    sys.exit(replica_main())

