"""paddle_tpu.serving: the batching inference server.

Reference lineage: the reference deploys ``AnalysisPredictor`` (one request
= one run) behind FleetExecutor's ``dist_model.cc`` multi-rank driver
(SURVEY §L9). TPU-native redesign: concurrency is won by COALESCING — a
thread-safe queue feeds a micro-batcher that pads concurrent requests into
pre-declared shape buckets, and every bucket is AOT-warmed so steady-state
traffic executes warm XLA programs only (asserted via
``analysis.retrace``).

The serving tier, bottom up:
- ``ServingEngine`` (+ ``BucketSpec``, ``ServingConfig``): generic batched
  inference over an ``inference.Predictor``, ``nn.Layer``, or array fn —
  admission control, deadlines, per-request error isolation;
- ``GenerationEngine`` (+ ``GenerationConfig``): continuous-batching
  causal-LM decode over a **paged KV cache** (``paged_kv``: block-pool
  allocator, ref-counted copy-on-write pages, prefix-cache reuse of
  shared system prompts), with optional draft-model **speculative
  decoding** (``speculative``) and deadline-aware slot joining;
- ``ReplicaRouter`` (+ ``RouterConfig``): N engine replicas behind an
  admission-controlled front door — per-tenant quotas, load-aware
  dispatch from real queue/KV-headroom/p95 state, prefix-affinity
  placement, fault fencing with classified errors and health-probe
  re-admission;
- ``ServingFleet`` (+ ``ServingFleetPolicy``, ``fleet``): the
  fault-tolerant MULTI-PROCESS tier — each replica engine in its own
  supervised process behind a socket RPC, heartbeat-fenced within a
  grace window, restarted with bounded backoff; in-flight work replays
  onto survivors with the token stream deduped, slow requests hedge,
  overload degrades in brownout stages, and ``rolling_restart()``
  rolls the fleet with zero downtime;
- ``MetricsRegistry``: QPS, latency percentiles, batch occupancy, queue
  depth, compile-cache hits/misses, exposed via ``engine.stats()`` and
  ``profiler.RecordEvent`` spans.

See docs/serving.md.
"""
from .buckets import BucketSpec  # noqa: F401
from .engine import (  # noqa: F401
    BadRequest, DeadlineExceeded, EngineClosed, QueueFull, ServingConfig,
    ServingEngine,
)
from .base import ReplicaFault, RequestCancelled  # noqa: F401
from .fleet import (  # noqa: F401
    BrownoutShed, ReplicaClient, ServingFleet, ServingFleetPolicy,
)
from .generation import GenerationConfig, GenerationEngine  # noqa: F401
from .kv_transfer import (  # noqa: F401
    FleetKVCache, KVMigrationStats, pack_kv_pages, prompt_cache_key,
    unpack_kv_pages,
)
from .metrics import LatencyWindow, MetricsRegistry  # noqa: F401
from .paged_kv import (  # noqa: F401
    HostPagePool, PageAllocator, PagedKVPool, PoolExhausted, PrefixCache,
    token_blocks,
)
from .router import ReplicaRouter, RouterConfig, TenantQuotaExceeded  # noqa: F401
from .speculative import greedy_accept, rejection_sample  # noqa: F401

__all__ = [
    "BucketSpec", "ServingConfig", "ServingEngine",
    "GenerationConfig", "GenerationEngine",
    "ReplicaRouter", "RouterConfig", "TenantQuotaExceeded",
    "ServingFleet", "ServingFleetPolicy", "ReplicaClient", "BrownoutShed",
    "ReplicaFault", "RequestCancelled",
    "PageAllocator", "PrefixCache", "PagedKVPool", "PoolExhausted",
    "HostPagePool", "token_blocks", "greedy_accept", "rejection_sample",
    "FleetKVCache", "KVMigrationStats", "pack_kv_pages",
    "unpack_kv_pages", "prompt_cache_key",
    "MetricsRegistry", "LatencyWindow",
    "QueueFull", "DeadlineExceeded", "EngineClosed", "BadRequest",
]
