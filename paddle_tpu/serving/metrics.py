"""Serving observability: counters, latency percentiles, QPS, occupancy.

Reference role: the reference deployment stack exposes per-predictor timing
through ``AnalysisPredictor``'s inference profiling switches and the
FleetExecutor's brpc metrics; here the serving engine owns one
``MetricsRegistry`` and snapshots it on demand — no background aggregation
thread, every structure is O(1) per observation under one lock.

Wired into ``paddle_tpu.profiler``: the engine brackets each batch execution
in a ``profiler.RecordEvent`` span (category "Serving"), so a running
``profiler.Profiler`` sees serving batches on the same host timeline as op
dispatch and dataloader spans.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict

import numpy as np

__all__ = ["MetricsRegistry", "LatencyWindow"]


class LatencyWindow:
    """Ring buffer of the most recent latencies (ms); percentiles on read.

    A fixed-size window keeps snapshot cost bounded and the percentiles
    honest about *recent* traffic rather than the whole process lifetime.
    """

    def __init__(self, capacity: int = 8192):
        self._buf = np.zeros(capacity, dtype=np.float64)
        self._capacity = capacity
        self._n = 0          # total observations ever
        self._count = 0      # filled entries (<= capacity)
        self._idx = 0

    def observe(self, ms: float) -> None:
        self._buf[self._idx] = ms
        self._idx = (self._idx + 1) % self._capacity
        self._count = min(self._count + 1, self._capacity)
        self._n += 1

    def percentiles(self, qs=(50, 95, 99)) -> Dict[str, float]:
        if self._count == 0:
            return {f"p{q}": 0.0 for q in qs}
        vals = np.percentile(self._buf[: self._count], qs)
        return {f"p{q}": round(float(v), 3) for q, v in zip(qs, vals)}

    @property
    def count(self) -> int:
        return self._n


class MetricsRegistry:
    """Thread-safe registry for one serving engine.

    - ``inc(name)``: monotonic counters (requests, responses, errors, shed,
      rejected, batches, compile-cache hits/misses, ...)
    - ``observe_latency(ms)``: end-to-end request latency (submit -> result)
    - ``observe_occupancy(frac)``: real rows / bucket rows per executed batch
    - ``mark_done()``: completion timestamp feeding the sliding-window QPS
    - ``gauge(name, fn)``: live values sampled at snapshot time (queue depth)
    """

    def __init__(self, qps_window_s: float = 30.0, latency_capacity: int = 8192):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._latency = LatencyWindow(latency_capacity)
        self._queue_wait = LatencyWindow(latency_capacity)
        self._occ_sum = 0.0
        self._occ_n = 0
        self._qps_window_s = qps_window_s
        self._done_ts: deque = deque()
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._t0 = time.monotonic()

    # -- writes ---------------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe_latency(self, ms: float) -> None:
        with self._lock:
            self._latency.observe(ms)

    def observe_queue_wait(self, ms: float) -> None:
        with self._lock:
            self._queue_wait.observe(ms)

    def observe_occupancy(self, frac: float) -> None:
        with self._lock:
            self._occ_sum += frac
            self._occ_n += 1

    def mark_done(self, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            for _ in range(n):
                self._done_ts.append(now)
            self._prune_locked(now)

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = fn

    def _prune_locked(self, now: float) -> None:
        horizon = now - self._qps_window_s
        while self._done_ts and self._done_ts[0] < horizon:
            self._done_ts.popleft()

    # -- reads ----------------------------------------------------------------
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def qps(self) -> float:
        """Completions per second over the sliding window (or since start
        when the process is younger than the window)."""
        now = time.monotonic()
        with self._lock:
            self._prune_locked(now)
            span = min(self._qps_window_s, max(now - self._t0, 1e-6))
            return len(self._done_ts) / span

    def snapshot(self) -> Dict:
        """One coherent stats dict: QPS, latency percentiles (ms), batch
        occupancy, counters, live gauges."""
        now = time.monotonic()
        with self._lock:
            self._prune_locked(now)
            span = min(self._qps_window_s, max(now - self._t0, 1e-6))
            snap = {
                "qps": round(len(self._done_ts) / span, 3),
                "latency_ms": self._latency.percentiles(),
                "queue_wait_ms": self._queue_wait.percentiles(),
                "batch_occupancy": round(self._occ_sum / self._occ_n, 4)
                if self._occ_n else 0.0,
                "counters": dict(self._counters),
            }
            gauges = {name: fn for name, fn in self._gauges.items()}
        # gauges sampled outside the lock: a gauge callback may itself take
        # the engine lock (queue depth), and lock nesting here could deadlock
        for name, fn in gauges.items():
            try:
                snap[name] = fn()
            except Exception:
                snap[name] = None
        return snap

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._latency = LatencyWindow(self._latency._capacity)
            self._queue_wait = LatencyWindow(self._queue_wait._capacity)
            self._occ_sum = 0.0
            self._occ_n = 0
            self._done_ts.clear()
            self._t0 = time.monotonic()
