"""Serving observability — thin alias over the framework-level registry.

``MetricsRegistry``/``LatencyWindow`` were born here (PR 2) and were
promoted to ``paddle_tpu.observability.registry`` when the process-wide
telemetry hub landed: the serving engine's counters are the same classes
every other subsystem now uses, and each engine's registry is registered
into ``observability.hub()`` (rows under ``registries["serving:<name>"]``
in ``observability.snapshot()``). This module stays as the import path
serving code and users already know.
"""
from ..observability.registry import LatencyWindow, MetricsRegistry  # noqa: F401

__all__ = ["MetricsRegistry", "LatencyWindow"]
