"""Speculative decoding primitives (Leviathan et al. / Chen et al.).

The engine's speculative path is greedy: the draft proposes, the target
scores every proposal in one window-step call, and the accepted run plus
the target's own next token is emitted — each emitted token is a target
argmax, so greedy output is token-for-token the non-speculative path
(``GenerationEngine`` pins this in tests).

This module carries the *sampled* counterpart as a standalone, framework-
free primitive: **standard rejection sampling** over draft vs target
distributions, which keeps the OUTPUT DISTRIBUTION exactly the target's
for any draft (the published correctness property). It operates on
numpy probability rows so it is unit-testable without a device and
usable by any engine that samples instead of argmaxing.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["rejection_sample", "greedy_accept"]


def greedy_accept(draft_tokens, target_argmax) -> int:
    """Length of the accepted draft run under GREEDY verification: draft
    token ``i`` survives iff it equals the target's argmax after the
    previous position (``target_argmax[i]``) and every earlier draft
    survived."""
    a = 0
    k = len(draft_tokens)
    while a < k and int(draft_tokens[a]) == int(target_argmax[a]):
        a += 1
    return a


def rejection_sample(draft_probs: np.ndarray, target_probs: np.ndarray,
                     draft_tokens: np.ndarray,
                     rng: Optional[np.random.RandomState] = None
                     ) -> Tuple[np.ndarray, int]:
    """Standard speculative rejection sampling.

    ``draft_probs[i]``/``target_probs[i]`` are the draft's and target's
    next-token distributions at proposal position ``i`` (``i < k``);
    ``target_probs[k]`` is the target's distribution after the full draft
    run (the bonus position). ``draft_tokens[i]`` was sampled from
    ``draft_probs[i]``.

    Draft token ``i`` is accepted with probability
    ``min(1, p_target(x_i) / p_draft(x_i))``; on the first rejection the
    replacement is sampled from ``normalize(max(p_target - p_draft, 0))``
    — the residual that makes the OUTPUT distribution exactly the
    target's. If every draft survives, one bonus token is sampled from
    ``target_probs[k]``.

    Returns ``(emitted_tokens, num_accepted)`` — ``len(emitted) ==
    num_accepted + 1`` always (the standard +1 advance per round).
    """
    rng = rng or np.random.RandomState()
    k = len(draft_tokens)
    assert draft_probs.shape[0] >= k and target_probs.shape[0] >= k + 1
    out = []
    for i in range(k):
        x = int(draft_tokens[i])
        p_t = float(target_probs[i, x])
        p_d = float(draft_probs[i, x])
        if p_d <= 0.0 or rng.uniform() < min(1.0, p_t / p_d):
            out.append(x)
            continue
        # rejected: sample the residual (target minus draft, clipped)
        resid = np.maximum(target_probs[i] - draft_probs[i], 0.0)
        z = resid.sum()
        if z <= 0.0:  # identical distributions: the draft token was fine
            out.append(x)
            continue
        out.append(int(rng.choice(len(resid), p=resid / z)))
        return np.asarray(out, dtype=np.int64), i
    bonus = target_probs[k]
    out.append(int(rng.choice(len(bonus), p=bonus / bonus.sum())))
    return np.asarray(out, dtype=np.int64), k
