"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py)."""


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
