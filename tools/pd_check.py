#!/usr/bin/env python
"""pd_check — run the paddle_tpu.analysis static passes from the shell.

No TPU required (set JAX_PLATFORMS=cpu); nothing is executed on device
except the tiny retrace demo loop. Examples:

    JAX_PLATFORMS=cpu python tools/pd_check.py            # all passes
    JAX_PLATFORMS=cpu python tools/pd_check.py --self     # repo self-lint
    JAX_PLATFORMS=cpu python tools/pd_check.py --concurrency  # CC lint
    JAX_PLATFORMS=cpu python tools/pd_check.py --json --models llama
    JAX_PLATFORMS=cpu python tools/pd_check.py --passes memory,spmd

Exit code 1 when any ERROR-severity diagnostic is produced (CI gate),
else 0. --strict also fails on warnings.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _bootstrap():
    # an 8-device host mesh lets the SPMD pass walk real shard_map programs;
    # must be set before jax initializes its backends
    if "--self" not in sys.argv and "--concurrency" not in sys.argv:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = \
                flags + " --xla_force_host_platform_device_count=8"
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _check_llama(A, cfg_kwargs):
    """Whole-train-step capture of the examples/train_llama_tpu.py recipe
    (tiny shape): program summary + memory + spmd over fwd+bwd+update."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-4, parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y), optimizer)
    ids = paddle.randint(0, cfg.vocab_size, [2, 32])
    prog = A.capture(step, ids, ids, label="llama.TrainStep")
    diags = A.run_passes(prog, **cfg_kwargs)
    return prog, diags


def _check_bert(A, cfg_kwargs):
    """Forward capture of the examples/finetune_bert.py model (tiny)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import BertConfig, BertForSequenceClassification

    paddle.seed(0)
    cfg = BertConfig.tiny()
    model = BertForSequenceClassification(cfg, num_classes=2)
    model.eval()
    ids = paddle.randint(0, cfg.vocab_size, [2, 16])
    prog = A.capture(lambda x: model(x), ids, label="bert.forward")
    diags = A.run_passes(prog, **cfg_kwargs)
    return prog, diags


def _check_gpt(A, cfg_kwargs):
    """to_static capture of the examples/generate_gpt.py model (tiny)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny())
    model.eval()
    ids = paddle.randint(0, 256, [1, 16])
    prog = A.capture(lambda x: model(x), ids, label="gpt.forward")
    diags = A.run_passes(prog, **cfg_kwargs)
    return prog, diags


def _check_pipeline(A, cfg_kwargs):
    """ppermute-pipeline program over a pp=2 host mesh (the
    examples/distributed_data_parallel.py-family program shape): the spmd
    pass walks the real stage-handoff collectives."""
    import jax.numpy as jnp

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.meta_parallel.pipeline import (
        ppermute_pipeline)
    from paddle_tpu.distributed.mesh import shard_map_compat
    from jax.sharding import PartitionSpec as P

    dist.reset_mesh()
    import jax as _jax

    env = dist.init_mesh(pp=2, dp=len(_jax.devices()) // 2)

    def stage(h):
        return jnp.tanh(h) * 1.1

    def piped(x_mb):
        def local(x_local):
            return ppermute_pipeline(stage, x_local, 2, remat=False)

        return shard_map_compat(local, mesh=env.mesh, in_specs=P(),
                                out_specs=P(), axis_names={"pp"},
                                check_vma=False)(x_mb)

    x = jnp.ones((4, 2, 8), jnp.float32)  # [M, mb, d]
    prog = A.capture(piped, x, label="pipeline.ppermute")
    diags = A.run_passes(prog, **cfg_kwargs)
    dist.reset_mesh()
    return prog, diags


def _retrace_demo(A):
    """Enable the auditor, run a toy loop with an induced dtype drift, and
    report the attributed recompiles — the end-to-end retrace pass."""
    import paddle_tpu as paddle

    A.retrace.reset()
    A.retrace.enable()
    try:
        a = paddle.ones([4, 4])
        _ = (a + a) * 2.0                     # baseline compiles
        b = paddle.ones([4, 4], dtype="int32")
        _ = (b + b) * 2                       # induced dtype drift
        c = paddle.ones([8, 4])
        _ = (c + c) * 2.0                     # induced shape drift
    finally:
        A.retrace.disable()
    return A.retrace.report()


MODEL_CHECKS = {
    "llama": _check_llama,
    "bert": _check_bert,
    "gpt": _check_gpt,
    "pipeline": _check_pipeline,
}


def main(argv=None):
    ap = argparse.ArgumentParser(prog="pd_check", description=__doc__)
    ap.add_argument("--self", action="store_true", dest="self_lint",
                    help="run the repo self-lint (AST footgun pass) only")
    ap.add_argument("--concurrency", action="store_true",
                    dest="concurrency_lint",
                    help="run the repo concurrency lint (CC codes: "
                         "blocking-under-lock, signal-handler locks, "
                         "thread/daemon audit, lock-order conflicts) only")
    ap.add_argument("--root", default=None,
                    help="lint root (default: the paddle_tpu package)")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--models", default="llama,bert,gpt,pipeline",
                    help=f"comma list from {sorted(MODEL_CHECKS)}")
    ap.add_argument("--passes", default=None,
                    help="comma list of jaxpr passes (default: all)")
    ap.add_argument("--hbm-gb", type=float, default=9.5,
                    help="HBM envelope for the memory/spmd passes")
    ap.add_argument("--frac", type=float, default=0.5,
                    help="fat-intermediate threshold as a fraction of HBM")
    ap.add_argument("--no-retrace-demo", action="store_true")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too")
    args = ap.parse_args(argv)

    import paddle_tpu.analysis as A

    all_diags = []
    blocks = []

    if args.self_lint or args.concurrency_lint:
        if args.self_lint:
            diags = A.selfcheck.run_selfcheck(args.root)
            all_diags += diags
            blocks.append(("selfcheck", None, diags))
        if args.concurrency_lint:
            diags = A.concurrency.run_concurrency(args.root)
            all_diags += diags
            blocks.append(("concurrency", None, diags))
    else:
        cfg = {"hbm_bytes": int(args.hbm_gb * 1e9), "hbm_frac": args.frac}
        if args.passes:
            cfg["passes"] = [p.strip() for p in args.passes.split(",")]
        for name in [m.strip() for m in args.models.split(",") if m.strip()]:
            if name not in MODEL_CHECKS:
                ap.error(f"unknown model {name!r}; "
                         f"choose from {sorted(MODEL_CHECKS)}")
            try:
                prog, diags = MODEL_CHECKS[name](A, cfg)
                blocks.append((name, prog.summary(), diags))
                all_diags += diags
            except NotImplementedError as e:  # old-jax shard_map gaps
                blocks.append((name, {"skipped": str(e)[:160]}, []))
        if not args.no_retrace_demo:
            # the demo INDUCES drift to prove the auditor works — its
            # warnings are expected output, not repo findings, so they are
            # shown but excluded from the exit-code gate
            blocks.append(("retrace-demo", None, _retrace_demo(A)))
        diags = A.selfcheck.run_selfcheck(args.root)
        blocks.append(("selfcheck", None, diags))
        all_diags += diags
        diags = A.concurrency.run_concurrency(args.root)
        blocks.append(("concurrency", None, diags))
        all_diags += diags

    if args.json:
        print(json.dumps({
            "blocks": [{"name": n, "summary": s,
                        "diagnostics": [d.to_dict() for d in ds]}
                       for n, s, ds in blocks],
            "max_severity": A.max_severity(all_diags),
        }, default=str))
    else:
        for name, summary, diags in blocks:
            header = f"== {name} =="
            if summary:
                header += f"  {json.dumps(summary, default=str)[:200]}"
            print(A.render(diags, header=header))
            print()
        worst = A.max_severity(all_diags)
        print(f"pd_check: {len(all_diags)} finding(s), "
              f"max severity: {worst or 'none'}")

    failing = ("error", "warning") if args.strict else ("error",)
    return 1 if any(d.severity in failing for d in all_diags) else 0


if __name__ == "__main__":
    _bootstrap()
    sys.exit(main())
