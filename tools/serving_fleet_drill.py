#!/usr/bin/env python
"""Serving-fleet chaos drill — the ISSUE-15 acceptance run.

A REAL 3-process CPU fleet (one ``GenerationEngine`` + draft model per
process, socket RPC, heartbeats through the control-plane TCPStore)
under continuous load, driven through every failure the supervisor must
survive:

1. ``replica_crash`` mid-stream: one replica hard-exits at its 4th
   submit while requests are in flight ⇒ the supervisor fences it,
   replays its work onto survivors, and EVERY accepted request
   completes with its exact expected token sequence (replayed requests
   bit-identical to the uninterrupted ``model.generate`` reference —
   no duplicate or missing streamed token); the replica restarts with
   bounded backoff and is re-admitted (serves traffic again);
2. ``replica_hang``: a replica wedges its serve loop ⇒ heartbeats stop
   and it is fenced within the heartbeat grace window (stale-silence
   measured and asserted), then restarted;
3. ``replica_slow`` + hedging: a per-request slowdown on one replica
   pushes requests past the hedge deadline ⇒ a speculative second
   submission on a survivor wins and the loser is cancelled;
4. brownout: a low-priority burst past capacity walks the stages
   (speculation off → clamp → shed) and decays back to normal;
5. ``rolling_restart()``: the whole fleet rolls one replica at a time
   under load with ZERO failed requests;
6. the ``serving_fleet`` hub provider and the telemetry dump carry the
   fence/restart timeline and the hedge/replay/brownout counters.

Exit code 0 only when every assertion holds.
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_CACHE_DIR = os.environ.setdefault(
    "PT_PERSISTENT_CACHE_DIR",
    tempfile.mkdtemp(prefix="pt_svfleet_cache_"))  # restarts warm from it

import numpy as np  # noqa: E402


def build_replica():
    """The replica builder (runs INSIDE each worker process): a tiny
    pattern-trained GPT + a pattern-trained draft — every process builds
    bit-identical weights from the same seeded recipe, which is what
    makes failover replay bit-identical under greedy decoding."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit, serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    def train(seed, hidden):
        cfg = GPTConfig(vocab_size=32, hidden_size=hidden,
                        num_hidden_layers=1, num_attention_heads=2,
                        max_position_embeddings=64, dtype="float32")
        paddle.seed(seed)
        model = GPTForCausalLM(cfg)
        optimizer = opt.AdamW(learning_rate=3e-3,
                              parameters=model.parameters())
        step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y),
                             optimizer)
        ids = paddle.to_tensor(
            np.tile(np.arange(8), 8)[None, :].astype("int64"))
        for _ in range(80):
            step(ids, ids)
        return model

    model = train(0, 32)
    draft = train(1, 16)
    return serving.GenerationEngine(
        model, serving.GenerationConfig(
            max_slots=2, max_seq_len=32, page_len=8,
            prefill_buckets=(8, 16, 24), draft_model=draft,
            spec_tokens=3))


def main():
    import paddle_tpu as paddle
    import paddle_tpu.observability as obs
    from paddle_tpu import serving
    from paddle_tpu.serving import BrownoutShed, ServingFleet, \
        ServingFleetPolicy
    from paddle_tpu.serving.router import RouterConfig

    pattern = np.tile(np.arange(8), 8)
    work_root = tempfile.mkdtemp(prefix="pt_svfleet_drill_")

    # the same recipe the workers run, for the uninterrupted reference
    t0 = time.time()
    ref_engine = build_replica()
    ref_model = ref_engine.model
    print(f"[drill] reference model built in {time.time() - t0:.1f}s",
          flush=True)

    def expect(prompt, max_new):
        return np.asarray(ref_model.generate(
            paddle.to_tensor(np.asarray(prompt, np.int64)[None]),
            max_new_tokens=max_new, use_cache=True).numpy())[0].tolist()

    # deterministic chaos, armed by env so the WORKERS inherit it:
    #   r1 crashes at its 4th submit; r2 wedges at its 6th submit
    #   (crash + hang in phase A/B); r3 serves 600ms slow forever —
    #   under the 3s grace window, over the 250ms hedge deadline.
    # inc=0 pins each rule to the FIRST incarnation: a restarted worker
    # re-parses PT_FAULTS, and without the pin r1 would crash again at
    # its 2nd post-restart submit, forever (budget-exhausting the
    # drill). Low seq thresholds keep the triggers robust to placement
    # spread (load-aware scoring decides who gets how many submits).
    os.environ["PT_FAULTS"] = (
        "replica_crash@name=r1&seq=2&inc=0,"
        "replica_hang@name=r2&seq=3&inc=0,"
        "replica_slow@name=r3&ms=600&times=-1")

    # hedging stays OFF for phases A/B so the crash/hang recovery runs
    # through the REPLAY path (with hedge_ms armed, the hedges complete
    # the victims before the fence gets to replay them — also correct,
    # but then the drill would not exercise replay at all); phase C
    # arms it
    policy = ServingFleetPolicy(
        heartbeat_interval=0.25, heartbeat_timeout=3.0,
        backoff_base_s=0.2, backoff_max_s=2.0, poll_interval=0.05,
        hedge_ms=None, replica_capacity=8, drain_timeout_s=30.0)
    fleet = ServingFleet(
        builder=os.path.abspath(__file__) + ":build_replica",
        n_replicas=3, names=["r1", "r2", "r3"], policy=policy,
        router_config=RouterConfig(),
        flight_root=os.path.join(work_root, "flight"),
        log_dir=os.path.join(work_root, "logs"))
    t0 = time.time()
    fleet.start(wait_ready=True, timeout=600)
    print(f"[drill] 3-process fleet ready in {time.time() - t0:.1f}s",
          flush=True)

    def run_load(jobs, tag):
        """Submit, collect streamed tokens per request, assert every
        request completes with its EXACT expected sequence and that the
        stream equals the result's generated tail (zero lost or
        duplicated tokens)."""
        futs = []
        for off, plen, mx in jobs:
            prompt = pattern[off:off + plen].astype(np.int64)
            streamed = []
            fut = fleet.submit(prompt, max_new_tokens=mx,
                               on_token=streamed.append)
            futs.append((prompt, mx, streamed, fut))
        for prompt, mx, streamed, fut in futs:
            out = fut.result(timeout=300).tolist()
            want = expect(prompt, mx)
            assert out == want, (tag, prompt.tolist(), out, want)
            assert streamed == out[len(prompt):], \
                (tag, "stream dup/loss", streamed, out[len(prompt):])
        return len(futs)

    # -- phase A: crash mid-stream -> fence, replay, bit-identical ------------
    # long generations (prompt + budget pinned so a replayed prefix
    # still fits the largest prefill bucket: plen + max_new - 1 <= 24)
    # keep requests IN FLIGHT when r1 dies at its 4th submit — the
    # replay path, not just re-dispatch, is what phase A must cross
    jobs = []
    for i in range(18):
        plen = 9 + (i % 3)
        jobs.append(((i * 3) % 8, plen, 24 - plen))
    n = run_load(jobs, "crash_phase")
    deadline = time.time() + 60
    while time.time() < deadline:
        snap = fleet.provider_snapshot()
        if snap["replicas"]["r1"]["state"] == "ready" and \
                snap["replicas"]["r1"]["incarnation"] >= 1:
            break
        time.sleep(0.2)
    snap = fleet.provider_snapshot()
    assert snap["replicas"]["r1"]["state"] == "ready", snap["replicas"]
    # the crash is detected by whichever layer sees it first: the
    # monitor's proc poll ("crash"), a lost RPC mid-request
    # ("rpc_fault"), or a failed submit send ("submit_fault") — the
    # same fence; the RPC layers usually beat the poll
    crash_recs = [r for r in snap["recoveries"]
                  if r["replica"] == "r1"
                  and r["cause"] in ("crash", "rpc_fault",
                                     "submit_fault")]
    assert crash_recs, snap["recoveries"]
    assert snap["counters"].get("fences", 0) >= 1
    print(f"[drill] phase A ok: {n} requests exact through a crash; "
          f"r1 fenced+restarted+re-admitted "
          f"(ready_ms={crash_recs[0].get('ready_ms')})", flush=True)

    # -- phase B: hang -> stale-heartbeat fence WITHIN the grace window -------
    n = run_load([((i * 5) % 8, 10 + (i % 2), 14 - (i % 2))
                  for i in range(10)], "hang_phase")
    deadline = time.time() + 60
    while time.time() < deadline:
        snap = fleet.provider_snapshot()
        stale = [r for r in snap["recoveries"]
                 if r["replica"] == "r2" and r["cause"] ==
                 "stale_heartbeat"]
        if stale and snap["replicas"]["r2"]["state"] == "ready":
            break
        time.sleep(0.2)
    snap = fleet.provider_snapshot()
    stale = [r for r in snap["recoveries"]
             if r["replica"] == "r2" and r["cause"] == "stale_heartbeat"]
    assert stale, ("r2 never fenced for staleness", snap["recoveries"])
    silent = stale[0].get("silent_s")
    assert silent is not None and \
        silent <= policy.heartbeat_timeout + 1.5, \
        ("fence exceeded the grace window", stale[0])
    assert snap["replicas"]["r2"]["state"] == "ready", snap["replicas"]
    print(f"[drill] phase B ok: r2 hang fenced after {silent:.2f}s "
          f"silence (grace {policy.heartbeat_timeout}s), restarted",
          flush=True)

    # -- phase C: slow replica -> hedged re-prefill, first wins ---------------
    fleet.policy.hedge_ms = 250.0  # arm hedging (read live per tick)
    run_load([((i * 7) % 8, 9, 5) for i in range(12)], "hedge_phase")
    snap = fleet.provider_snapshot()
    assert snap["counters"].get("hedges", 0) >= 1, snap["counters"]
    assert snap["counters"].get("hedge_wins", 0) >= 1, snap["counters"]
    print(f"[drill] phase C ok: hedges={snap['counters']['hedges']} "
          f"wins={snap['counters']['hedge_wins']}", flush=True)

    # -- phase D: brownout walks the stages and decays ------------------------
    fleet.policy.replica_capacity = 1  # tiny capacity: the burst overloads
    burst = [fleet.submit(pattern[:9].astype(np.int64), max_new_tokens=4)
             for _ in range(10)]
    deadline = time.time() + 30
    seen_stage = 0
    shed = 0
    while time.time() < deadline:
        seen_stage = max(seen_stage,
                         fleet.provider_snapshot()["brownout"]["stage"])
        try:
            fleet.submit(pattern[:9].astype(np.int64), max_new_tokens=2,
                         priority=0)  # sheddable class
        except BrownoutShed:
            shed += 1
        except serving.QueueFull:
            pass
        if seen_stage >= 3 and shed:
            break
        time.sleep(0.05)
    for f in burst:
        f.result(timeout=300)
    fleet.policy.replica_capacity = 8
    deadline = time.time() + 30
    while time.time() < deadline and \
            fleet.provider_snapshot()["brownout"]["stage"] != 0:
        time.sleep(0.1)
    snap = fleet.provider_snapshot()
    assert seen_stage >= 3, ("brownout never reached shed", seen_stage)
    assert shed >= 1
    assert snap["brownout"]["stage"] == 0, snap["brownout"]
    assert snap["counters"].get("brownout_transitions", 0) >= 2
    print(f"[drill] phase D ok: brownout peaked at stage {seen_stage}, "
          f"shed {shed} low-priority, decayed to normal", flush=True)

    # -- phase E: rolling restart under load, zero failed requests ------------
    # start from an all-ready fleet (phase C/D churn may have left a
    # replica mid-recovery)
    deadline = time.time() + 90
    while time.time() < deadline:
        snap = fleet.provider_snapshot()
        if all(r["state"] == "ready" for r in snap["replicas"].values()):
            break
        time.sleep(0.2)
    snap = fleet.provider_snapshot()
    assert all(r["state"] == "ready" for r in snap["replicas"].values()), \
        (snap["replicas"], snap["recoveries"], snap["rank_restarts"])

    import threading

    stop = threading.Event()
    roll_failures = []
    rolled_ok = {}

    def background_load():
        i = 0
        while not stop.is_set():
            try:
                run_load([((i * 3) % 8, 9 + (i % 2), 3)], "roll_phase")
            except Exception as e:  # pragma: no cover - the assertion
                roll_failures.append(repr(e))
            i += 1
            time.sleep(0.05)

    th = threading.Thread(target=background_load, daemon=True,
                          name="pt-drill-roll-load")
    th.start()
    res = fleet.rolling_restart()
    stop.set()
    th.join(timeout=120)
    rolled_ok = res
    assert res["ok"], res
    assert not roll_failures, roll_failures
    snap = fleet.provider_snapshot()
    assert snap["counters"].get("rolled_replicas", 0) == 3
    assert all(r["state"] == "ready" for r in snap["replicas"].values())
    print(f"[drill] phase E ok: rolling restart of 3 replicas under "
          f"load, zero failed requests ({rolled_ok})", flush=True)

    # -- provider + telemetry dump --------------------------------------------
    events = [e["event"] for e in snap["timeline"]]
    for needed in ("join", "evict", "fence", "restart", "roll_drain",
                   "roll_done", "brownout"):
        assert needed in events, (needed, events)
    for c in ("fences", "replays", "restarts", "hedges", "hedge_wins",
              "brownout_transitions", "shed_brownout", "completed"):
        assert snap["counters"].get(c, 0) >= 1, (c, snap["counters"])
    dump_path = os.path.join(work_root, "telemetry.json")
    obs.dump(dump_path)
    with open(dump_path) as f:
        tele = json.load(f)
    sf = tele["serving_fleet"]
    assert sf["counters"]["replays"] >= 1 and sf["timeline"], \
        "serving_fleet provider missing from the telemetry dump"
    print("[drill] telemetry ok: serving_fleet provider in dump")
    if os.environ.get("PT_LOCKDEP", "") not in ("", "0", "false"):
        # armed re-run (ci.sh): the whole chaos drill must complete with
        # the lock-order witness live and a cycle-free graph
        ld = tele.get("lockdep")
        assert ld and ld.get("armed"), \
            "PT_LOCKDEP=1 but the lockdep provider is missing/disarmed"
        assert ld["cycles"] == [], f"lock-order cycles: {ld['cycles']}"
        assert ld["locks"], "lockdep witnessed no locks"
        print(f"[drill] lockdep ok: {len(ld['locks'])} witnessed locks, "
              f"{len(ld['edges'])} order edges, zero cycles", flush=True)

    fleet.close()
    headline = {
        "replicas": 3,
        "completed": snap["counters"]["completed"],
        "fences": snap["counters"]["fences"],
        "replays": snap["counters"]["replays"],
        "restarts": snap["counters"]["restarts"],
        "hedge_wins": snap["counters"]["hedge_wins"],
        "brownout_peak": seen_stage,
        "stale_silence_s": round(silent, 2),
        "rolled": snap["counters"]["rolled_replicas"],
        "stream_mismatch": snap["counters"].get("stream_mismatch", 0),
    }
    assert headline["stream_mismatch"] == 0, headline
    print("SERVING_FLEET_DRILL_OK " + json.dumps(headline), flush=True)
    shutil.rmtree(work_root, ignore_errors=True)


if __name__ == "__main__":
    main()
