#!/usr/bin/env python
"""pd_top: pretty-print a paddle_tpu observability snapshot, live or dumped.

The `top(1)` of the telemetry hub (docs/observability.md):

    python tools/pd_top.py bench_artifacts/telemetry_warm_path.json
    python tools/pd_top.py --port 9100                # live /snapshot
    python tools/pd_top.py --port 9100 --watch 2      # refresh every 2s
    python tools/pd_top.py --port 9100 --json         # raw JSON passthrough
    python tools/pd_top.py --port 9100 --fleet        # fleet plane only

The live mode talks to the stdlib endpoint started by
``observability.serve(port)`` / ``PT_METRICS_PORT=<port>``. Rendering is
``observability.render_snapshot`` — the same tables ``report()`` prints —
so a dumped file and a live process look identical.

``--fleet`` filters to the fleet observability plane (the supervisor
process's ``fleet_telemetry`` + ``slo`` providers): per-replica rows
(state, pool, inflight, beat age, p95, KV headroom), the fleet totals
line, and the SLO burn table.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable from anywhere: the repo root (one up from tools/) wins over
# sys.path[0] being tools/ itself
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load(args) -> dict:
    if args.port is not None:
        import urllib.request

        url = f"http://{args.host}:{args.port}/snapshot"
        with urllib.request.urlopen(url, timeout=5) as r:
            return json.load(r)
    with open(args.path) as f:
        return json.load(f)


def _render(snap: dict) -> str:
    try:
        from paddle_tpu.observability import render_snapshot

        return render_snapshot(snap)
    except ImportError:  # render dumped files even without the package
        return json.dumps(snap, indent=1, default=str)


_FLEET_FAMS = ("fleet_telemetry", "slo", "fleet_trace", "serving_fleet",
               "kv_migration")


def _fleet_filter(snap: dict) -> dict:
    """Keep only the fleet-plane families (+ meta). An empty result
    means the snapshot is not from a fleet supervisor process."""
    out = {k: v for k, v in snap.items()
           if k in _FLEET_FAMS or k == "meta"}
    if not any(k in out for k in ("fleet_telemetry", "slo")):
        out["fleet_telemetry"] = {
            "error": "no fleet_telemetry/slo providers in this snapshot "
                     "(point pd_top at the fleet SUPERVISOR process)"}
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pd_top", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", nargs="?", default=None,
                    help="dumped observability.snapshot() JSON file")
    ap.add_argument("--port", type=int, default=None,
                    help="live mode: observability.serve() port")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="live mode: refresh every N seconds until ^C")
    ap.add_argument("--json", action="store_true",
                    help="print the raw snapshot JSON instead of tables")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet mode: only the merged fleet telemetry "
                         "(per-replica rows + totals) and SLO tables")
    args = ap.parse_args(argv)
    if (args.path is None) == (args.port is None):
        ap.error("give exactly one of: a snapshot file, or --port")
    try:
        while True:
            snap = _load(args)
            if args.fleet:
                snap = _fleet_filter(snap)
            out = json.dumps(snap, indent=1, default=str) if args.json \
                else _render(snap)
            if args.watch:
                print("\x1b[2J\x1b[H", end="")  # clear screen, home cursor
            print(out)
            if not args.watch:
                return 0
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        print(f"pd_top: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
