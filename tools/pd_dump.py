#!/usr/bin/env python
"""pd_dump: write a paddle_tpu diagnostic bundle (the flight-recorder
dump, on demand).

    python tools/pd_dump.py                      # bundle under ./flight_dumps
    python tools/pd_dump.py --out /tmp/diag      # custom root
    python tools/pd_dump.py --reason oncall      # tag the bundle

The bundle directory contains ``snapshot.json`` (the full observability
hub), ``flight_ring.json`` (recent step timelines + events, when a
recorder is live in this process), ``request_trace.json`` (serving
request/slot chrome-trace), ``device_trace.json`` (last XPlane
correlation), ``config.json`` (versions/backend/devices/PT_* env) and —
written LAST — ``MANIFEST.json``: a bundle with a manifest is complete.

The same bundle is written automatically by the flight recorder on
anomaly triggers, SIGQUIT, and preemption (docs/observability.md).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pd_dump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", default=None,
                    help="bundle root (default: $PT_FLIGHT_DIR or "
                         "./flight_dumps)")
    ap.add_argument("--reason", default="manual")
    ap.add_argument("--json", action="store_true",
                    help="print the manifest JSON instead of the path")
    args = ap.parse_args(argv)

    from paddle_tpu.observability.trace import flight
    ring = None
    if flight._RECORDER is not None:
        ring = flight._RECORDER.snapshot()
    path = flight.dump_bundle(args.out, args.reason, ring=ring)
    if args.json:
        with open(os.path.join(path, "MANIFEST.json")) as f:
            print(json.dumps({"path": path, "manifest": json.load(f)}))
    else:
        print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
