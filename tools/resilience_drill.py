#!/usr/bin/env python
"""Kill-and-resume drill for tools/ci.sh's resilience gate (ISSUE-6).

Orchestrates three subprocesses of the SAME deterministic ``Model.fit``:

  ref      the uninterrupted run                          (2 XLA devices)
  victim   ``checkpoint_every=2``, delivered a real
           ``SIGTERM`` by THIS process once >=1 async
           commit has landed on disk                      (2 XLA devices)
  resume   ``fit(resume=True)`` from the committed
           checkpoint, on a CHANGED device count          (4 XLA devices)

and asserts the ISSUE-6 acceptance: the victim exits 0 after a final
preempt-reason commit (>=1 ``preemptions`` counted, 0 torn checkpoints),
and victim+resume per-step losses concatenate to the uninterrupted run's
loss sequence (allclose) despite the device-count change.

The in-process halves (commit atomicity, crash-mid-save injection,
re-sharding) live in tests/test_resilience.py; this drill is the
cross-process SIGTERM half that a pytest process cannot deliver to itself
without also killing the test runner.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

EPOCHS = 2
BATCH = 8
N_SAMPLES = 128  # 16 steps/epoch, 32 total
SEED = 11
VICTIM_STEP_SLEEP_S = 0.12  # widen the SIGTERM window; math is unchanged


def _run_child(mode: str, ckpt: str, out: str) -> None:
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.resilience import metrics as rm

    class ToyDataset(paddle.io.Dataset):
        def __init__(self, n):
            rng = np.random.default_rng(0)
            self.x = rng.standard_normal((n, 8)).astype("float32")
            w = rng.standard_normal((8,)).astype("float32")
            self.y = (self.x @ w > 0).astype("int64")

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    losses = []

    class Recorder(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            losses.append(float(np.asarray(logs["loss"])))
            if mode == "victim":
                time.sleep(VICTIM_STEP_SLEEP_S)

    # resume gets a DIFFERENT seed: its fresh weights/optimizer must be
    # fully overwritten by the restore for the loss tail to line up
    paddle.seed(SEED if mode != "resume" else 99)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    fit_kw = dict(epochs=EPOCHS, batch_size=BATCH, shuffle=False, verbose=0,
                  callbacks=[Recorder()])
    if mode != "ref":
        fit_kw.update(checkpoint_every=2, checkpoint_dir=ckpt,
                      resume=(mode == "resume"))
    model.fit(ToyDataset(N_SAMPLES), **fit_kw)

    record = {"mode": mode, "devices": len(__import__("jax").devices()),
              "losses": losses,
              "preemptions": rm.get("preemptions"),
              "torn_checkpoints": rm.get("torn_checkpoints"),
              "saves": rm.get("saves"), "restores": rm.get("restores")}
    with open(out, "w") as f:
        json.dump(record, f)


def _spawn(mode: str, ckpt: str, out: str, devices: int) -> subprocess.Popen:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count={devices} "
                        + env.get("XLA_FLAGS", "")).strip()
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", mode,
         "--ckpt", ckpt, "--out", out],
        env=env, cwd=root)


def _read(out: str) -> dict:
    with open(out) as f:
        return json.load(f)


def main() -> int:
    import numpy as np

    work = tempfile.mkdtemp(prefix="pt_resilience_drill_")
    ckpt = os.path.join(work, "ckpt")
    outs = {m: os.path.join(work, f"{m}.json") for m in
            ("ref", "victim", "resume")}

    print("[drill] ref run (uninterrupted, 2 devices)")
    assert _spawn("ref", ckpt, outs["ref"], devices=2).wait() == 0, \
        "ref run failed"

    print("[drill] victim run (checkpoint_every=2, 2 devices) ...")
    victim = _spawn("victim", ckpt, outs["victim"], devices=2)
    latest = os.path.join(ckpt, "LATEST")
    t0 = time.time()
    while not os.path.exists(latest):
        if victim.poll() is not None:
            print("[drill] FAIL: victim finished before any commit "
                  f"(rc={victim.returncode})")
            return 1
        if time.time() - t0 > 120:
            victim.kill()
            print("[drill] FAIL: no committed checkpoint within 120s")
            return 1
        time.sleep(0.05)
    print(f"[drill] first commit landed after {time.time() - t0:.1f}s "
          "-> kill -TERM")
    victim.send_signal(signal.SIGTERM)
    rc = victim.wait(timeout=120)
    assert rc == 0, f"victim did not exit cleanly after SIGTERM (rc={rc})"

    ref, vic = _read(outs["ref"]), _read(outs["victim"])
    assert vic["preemptions"] >= 1, vic
    assert vic["torn_checkpoints"] == 0, vic
    assert 0 < len(vic["losses"]) < len(ref["losses"]), \
        f"SIGTERM did not cut the run mid-flight: {len(vic['losses'])} " \
        f"of {len(ref['losses'])} steps"
    # commit-protocol layout, read directly (the parent process does not
    # import jax): LATEST names the tag, tag/manifest.json carries meta
    with open(os.path.join(ckpt, "LATEST")) as f:
        tag = json.load(f)["tag"]
    with open(os.path.join(ckpt, tag, "manifest.json")) as f:
        meta = json.load(f)["meta"]
    assert meta["reason"] == "preempt", meta
    assert meta["step"] == len(vic["losses"]) - 1, \
        f"commit step {meta['step']} != last trained step " \
        f"{len(vic['losses']) - 1}"

    print("[drill] resume run (resume=True, CHANGED device count: 4)")
    assert _spawn("resume", ckpt, outs["resume"], devices=4).wait() == 0, \
        "resume run failed"
    res = _read(outs["resume"])
    assert res["devices"] == 4 and vic["devices"] == 2, (vic, res)
    assert res["restores"] >= 1, res
    assert res["torn_checkpoints"] == 0, res

    stitched = vic["losses"] + res["losses"]
    assert len(stitched) == len(ref["losses"]), \
        f"step count mismatch: {len(vic['losses'])}+{len(res['losses'])} " \
        f"!= {len(ref['losses'])}"
    np.testing.assert_allclose(stitched, ref["losses"], rtol=1e-6, atol=1e-8,
                               err_msg="resumed loss tail diverged from the "
                                       "uninterrupted run")
    bit_equal = stitched == ref["losses"]
    print(json.dumps({
        "resilience_drill": "OK", "steps": len(ref["losses"]),
        "victim_steps": len(vic["losses"]), "resume_steps": len(res["losses"]),
        "preempt_commit_step": meta["step"], "preemptions": vic["preemptions"],
        "torn_checkpoints": 0, "devices": [vic["devices"], res["devices"]],
        "losses_bit_equal": bool(bit_equal),
    }))
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", choices=("ref", "victim", "resume"))
    ap.add_argument("--ckpt")
    ap.add_argument("--out")
    args = ap.parse_args()
    if args.child:
        _run_child(args.child, args.ckpt, args.out)
        sys.exit(0)
    sys.exit(main())
