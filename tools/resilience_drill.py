#!/usr/bin/env python
"""Kill-and-resume drills for tools/ci.sh's resilience + elastic gates.

Single-process leg (default, ISSUE-6): SIGTERM a real training
subprocess mid-run, resume on a changed XLA device count, stitched
losses bit-equal.

Multi-process leg (``--fleet``, ISSUE-11): a REAL 4-process
``jax.distributed`` fleet under the ``ElasticFleet`` supervisor,
training data-parallel (fixed global batch, host-side grad allreduce
through the control-plane store) with async checkpointing. A scripted
``worker_crash@rank=2&step=6`` kills one worker mid-run; the supervisor
fences the generation, survivors drain and exit, the gang restarts at
world=3 with the PR-9 planner picking the new config (pure-dp over 3
chips), every rank resumes from the fleet-wide newest committed
checkpoint, and training completes. Asserted: exactly one bounded
restart, planner dp == new world, 0 torn checkpoints anywhere, the
fleet provider's membership timeline records the eviction + restart
(with the recovery wall-clock breakdown), and the stitched rank-0 loss
curve (gen0 up to the resume point + gen1 to the end) matches an
uninterrupted world-1 reference run of the same global batch
(allclose — the dp re-split changes fp summation order, not math).

The single-process leg orchestrates three subprocesses of the SAME
deterministic ``Model.fit``:

  ref      the uninterrupted run                          (2 XLA devices)
  victim   ``checkpoint_every=2``, delivered a real
           ``SIGTERM`` by THIS process once >=1 async
           commit has landed on disk                      (2 XLA devices)
  resume   ``fit(resume=True)`` from the committed
           checkpoint, on a CHANGED device count          (4 XLA devices)

and asserts the ISSUE-6 acceptance: the victim exits 0 after a final
preempt-reason commit (>=1 ``preemptions`` counted, 0 torn checkpoints),
and victim+resume per-step losses concatenate to the uninterrupted run's
loss sequence (allclose) despite the device-count change.

The in-process halves (commit atomicity, crash-mid-save injection,
re-sharding) live in tests/test_resilience.py; this drill is the
cross-process SIGTERM half that a pytest process cannot deliver to itself
without also killing the test runner.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

EPOCHS = 2
BATCH = 8
N_SAMPLES = 128  # 16 steps/epoch, 32 total
SEED = 11
VICTIM_STEP_SLEEP_S = 0.12  # widen the SIGTERM window; math is unchanged


def _run_child(mode: str, ckpt: str, out: str) -> None:
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.resilience import metrics as rm

    class ToyDataset(paddle.io.Dataset):
        def __init__(self, n):
            rng = np.random.default_rng(0)
            self.x = rng.standard_normal((n, 8)).astype("float32")
            w = rng.standard_normal((8,)).astype("float32")
            self.y = (self.x @ w > 0).astype("int64")

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    losses = []

    class Recorder(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            losses.append(float(np.asarray(logs["loss"])))
            if mode == "victim":
                time.sleep(VICTIM_STEP_SLEEP_S)

    # resume gets a DIFFERENT seed: its fresh weights/optimizer must be
    # fully overwritten by the restore for the loss tail to line up
    paddle.seed(SEED if mode != "resume" else 99)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    fit_kw = dict(epochs=EPOCHS, batch_size=BATCH, shuffle=False, verbose=0,
                  callbacks=[Recorder()])
    if mode != "ref":
        fit_kw.update(checkpoint_every=2, checkpoint_dir=ckpt,
                      resume=(mode == "resume"))
    model.fit(ToyDataset(N_SAMPLES), **fit_kw)

    record = {"mode": mode, "devices": len(__import__("jax").devices()),
              "losses": losses,
              "preemptions": rm.get("preemptions"),
              "torn_checkpoints": rm.get("torn_checkpoints"),
              "saves": rm.get("saves"), "restores": rm.get("restores")}
    with open(out, "w") as f:
        json.dump(record, f)
    _assert_lockdep(f"child:{mode}")


def _assert_lockdep(tag: str) -> None:
    """Armed re-run gate (ci.sh): the drill must finish with the witness
    live, locks actually witnessed, and a cycle-free order graph."""
    if os.environ.get("PT_LOCKDEP", "") in ("", "0", "false"):
        return
    from paddle_tpu.analysis import lockdep

    snap = lockdep.snapshot()
    assert snap["armed"] and snap["locks"], \
        f"[{tag}] PT_LOCKDEP=1 but the witness saw no locks"
    assert snap["cycles"] == [], \
        f"[{tag}] lock-order cycles: {snap['cycles']}"
    print(f"[{tag}] lockdep ok: {len(snap['locks'])} witnessed locks, "
          f"{len(snap['edges'])} order edges, zero cycles", flush=True)


def _spawn(mode: str, ckpt: str, out: str, devices: int) -> subprocess.Popen:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count={devices} "
                        + env.get("XLA_FLAGS", "")).strip()
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", mode,
         "--ckpt", ckpt, "--out", out],
        env=env, cwd=root)


def _read(out: str) -> dict:
    with open(out) as f:
        return json.load(f)


def main() -> int:
    import numpy as np

    work = tempfile.mkdtemp(prefix="pt_resilience_drill_")
    ckpt = os.path.join(work, "ckpt")
    outs = {m: os.path.join(work, f"{m}.json") for m in
            ("ref", "victim", "resume")}

    print("[drill] ref run (uninterrupted, 2 devices)")
    assert _spawn("ref", ckpt, outs["ref"], devices=2).wait() == 0, \
        "ref run failed"

    print("[drill] victim run (checkpoint_every=2, 2 devices) ...")
    victim = _spawn("victim", ckpt, outs["victim"], devices=2)
    latest = os.path.join(ckpt, "LATEST")
    t0 = time.time()
    while not os.path.exists(latest):
        if victim.poll() is not None:
            print("[drill] FAIL: victim finished before any commit "
                  f"(rc={victim.returncode})")
            return 1
        if time.time() - t0 > 120:
            victim.kill()
            print("[drill] FAIL: no committed checkpoint within 120s")
            return 1
        time.sleep(0.05)
    print(f"[drill] first commit landed after {time.time() - t0:.1f}s "
          "-> kill -TERM")
    victim.send_signal(signal.SIGTERM)
    rc = victim.wait(timeout=120)
    assert rc == 0, f"victim did not exit cleanly after SIGTERM (rc={rc})"

    ref, vic = _read(outs["ref"]), _read(outs["victim"])
    assert vic["preemptions"] >= 1, vic
    assert vic["torn_checkpoints"] == 0, vic
    assert 0 < len(vic["losses"]) < len(ref["losses"]), \
        f"SIGTERM did not cut the run mid-flight: {len(vic['losses'])} " \
        f"of {len(ref['losses'])} steps"
    # commit-protocol layout, read directly (the parent process does not
    # import jax): LATEST names the tag, tag/manifest.json carries meta
    with open(os.path.join(ckpt, "LATEST")) as f:
        tag = json.load(f)["tag"]
    with open(os.path.join(ckpt, tag, "manifest.json")) as f:
        meta = json.load(f)["meta"]
    assert meta["reason"] == "preempt", meta
    assert meta["step"] == len(vic["losses"]) - 1, \
        f"commit step {meta['step']} != last trained step " \
        f"{len(vic['losses']) - 1}"

    print("[drill] resume run (resume=True, CHANGED device count: 4)")
    assert _spawn("resume", ckpt, outs["resume"], devices=4).wait() == 0, \
        "resume run failed"
    res = _read(outs["resume"])
    assert res["devices"] == 4 and vic["devices"] == 2, (vic, res)
    assert res["restores"] >= 1, res
    assert res["torn_checkpoints"] == 0, res

    stitched = vic["losses"] + res["losses"]
    assert len(stitched) == len(ref["losses"]), \
        f"step count mismatch: {len(vic['losses'])}+{len(res['losses'])} " \
        f"!= {len(ref['losses'])}"
    np.testing.assert_allclose(stitched, ref["losses"], rtol=1e-6, atol=1e-8,
                               err_msg="resumed loss tail diverged from the "
                                       "uninterrupted run")
    bit_equal = stitched == ref["losses"]
    print(json.dumps({
        "resilience_drill": "OK", "steps": len(ref["losses"]),
        "victim_steps": len(vic["losses"]), "resume_steps": len(res["losses"]),
        "preempt_commit_step": meta["step"], "preemptions": vic["preemptions"],
        "torn_checkpoints": 0, "devices": [vic["devices"], res["devices"]],
        "losses_bit_equal": bool(bit_equal),
    }))
    return 0


# ---------------------------------------------------------------------------
# multi-process fleet leg (ISSUE-11)
# ---------------------------------------------------------------------------

FLEET_GLOBAL_BATCH = 12
FLEET_SAMPLES = 240          # 20 global steps, 1 epoch
FLEET_CRASH_STEP = 6
FLEET_CKPT_EVERY = 2


def _run_fleet_child(out_dir: str) -> None:
    """One fleet worker: rank/world/gen and the control plane all come
    from the supervisor's PT_FLEET_* env; world=1 + no endpoint is the
    standalone reference run."""
    # jax.distributed MUST initialize before any computation — and
    # importing paddle_tpu runs some (generator seeding, backend probes)
    # — so the coordinator handshake is the worker's first act
    world = int(os.environ.get("PT_FLEET_WORLD", "1"))
    coord = os.environ.get("PT_FLEET_COORDINATOR")
    if world > 1 and coord:
        import jax

        jax.distributed.initialize(
            coordinator_address=coord, num_processes=world,
            process_id=int(os.environ.get("PT_FLEET_RANK", "0")))
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.runtime import elastic_fit
    from paddle_tpu.distributed.resilience import metrics as rm

    class ToyDataset(paddle.io.Dataset):
        def __init__(self, n):
            rng = np.random.default_rng(3)
            self.x = rng.standard_normal((n, 8)).astype("float32")
            w = rng.standard_normal((8,)).astype("float32")
            self.y = (self.x @ w > 0).astype("int64")

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    def _write(res):
        res = dict(res)
        res["torn_checkpoints"] = rm.get("torn_checkpoints")
        res["restores"] = rm.get("restores")
        res["saves"] = rm.get("saves")
        path = os.path.join(out_dir, f"g{res['gen']}_r{res['rank']}.json")
        with open(path + ".tmp", "w") as f:
            json.dump(res, f)
        os.replace(path + ".tmp", path)

    def build(ctx):
        paddle.seed(7)  # identical init on every rank; resume overwrites
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        ds = ToyDataset(FLEET_SAMPLES)
        xb = np.stack([ds[i][0] for i in range(FLEET_GLOBAL_BATCH)])
        yb = np.stack([ds[i][1] for i in range(FLEET_GLOBAL_BATCH)])
        ce = nn.CrossEntropyLoss()
        return {"network": net, "optimizer": opt, "loss": ce,
                "dataset": ds, "sample_batch": (xb, yb),
                "loss_fn": lambda m, x, y: ce(m(x), y),
                "on_exit": _write}

    res = elastic_fit(build, global_batch=FLEET_GLOBAL_BATCH, epochs=1,
                      checkpoint_every=FLEET_CKPT_EVERY)
    _write(res)
    _assert_lockdep("fleet-child")


def fleet_main() -> int:
    import numpy as np

    # the parent imports the supervisor from the repo (python puts
    # tools/ on sys.path, not the repo root)
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.distributed.fleet.runtime import ElasticFleet, \
        FleetPolicy

    work = tempfile.mkdtemp(prefix="pt_fleet_drill_")
    out_dir = os.path.join(work, "out")
    ckpt_root = os.path.join(work, "ckpt")
    flight_root = os.path.join(work, "flight")
    for d in (out_dir, ckpt_root, flight_root):
        os.makedirs(d, exist_ok=True)
    here = os.path.abspath(__file__)
    root = os.path.dirname(os.path.dirname(here))
    base_env = {
        "PYTHONPATH": root + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
    }

    print("[fleet] reference run (standalone, world=1)")
    env = dict(os.environ, **base_env)
    env["PT_FLEET_WORLD"] = "1"
    rc = subprocess.call(
        [sys.executable, here, "--fleet-child", "--out", out_dir],
        env=env, cwd=root)
    assert rc == 0, f"reference run failed rc={rc}"
    ref = _read(os.path.join(out_dir, "g0_r0.json"))
    ref_losses = ref["losses"]
    os.rename(os.path.join(out_dir, "g0_r0.json"),
              os.path.join(out_dir, "ref.json"))
    assert len(ref_losses) == FLEET_SAMPLES // FLEET_GLOBAL_BATCH, ref

    print("[fleet] 4-worker jax.distributed fleet, "
          f"worker_crash@rank=2&step={FLEET_CRASH_STEP}&gen=0")
    fleet = ElasticFleet(
        [sys.executable, here, "--fleet-child", "--out", out_dir],
        np=4,
        policy=FleetPolicy(min_world=2, max_restarts=2,
                           heartbeat_timeout=8.0, backoff_base_s=0.2,
                           drain_timeout_s=30.0),
        log_dir=os.path.join(work, "logs"),
        ckpt_root=ckpt_root, flight_root=flight_root,
        extra_env=dict(
            base_env,
            PT_FAULTS=f"worker_crash@rank=2&step={FLEET_CRASH_STEP}&gen=0",
        ))
    try:
        report = fleet.run(timeout=600)
    finally:
        fleet.close()

    events = [e["event"] for e in report["timeline"]]
    print(f"[fleet] phase={report['phase']} restarts={report['restarts']} "
          f"events={events}")
    assert report["phase"] == "completed", report
    assert report["restarts"] == 1, report

    # membership timeline: the crash is recorded as an eviction, then the
    # fence and the bounded restart at the surviving world size
    evicts = [e for e in report["timeline"] if e["event"] == "evict"]
    assert any(e["rank"] == 2 and e["gen"] == 0 for e in evicts), evicts
    restarts = [e for e in report["timeline"] if e["event"] == "restart"]
    assert len(restarts) == 1 and restarts[0]["world"] == 3, restarts
    assert any(e["event"] == "complete" for e in report["timeline"])

    # recovery wall-clock breakdown (fence -> drain -> teardown ->
    # respawn; resume_ms lands once gen1's rank 0 trains its first step)
    rec = report["recoveries"][0]
    for k in ("drain_ms", "teardown_ms", "respawn_ms", "new_world"):
        assert k in rec, rec
    assert rec["new_world"] == 3, rec

    # the planner picked the new config: pure-dp over the surviving chips
    plan1 = report["plans"].get("1")
    assert plan1 is not None, report["plans"].keys()
    assert plan1["config"]["mesh"]["dp"] == 3, plan1

    # per-rank, per-generation results: gen0 rank0 drained at the fence;
    # gen1's three ranks resumed from the fleet-wide newest commit and
    # completed
    g0 = _read(os.path.join(out_dir, "g0_r0.json"))
    g1 = {r: _read(os.path.join(out_dir, f"g1_r{r}.json"))
          for r in range(3)}
    assert g0["world"] == 4 and all(v["world"] == 3 for v in g1.values())
    for v in list(g1.values()) + [g0]:
        assert v["torn_checkpoints"] == 0, v
    assert all(v["restores"] >= 1 for v in g1.values()), \
        {r: v["restores"] for r, v in g1.items()}
    resumed = {v["resumed_from"] for v in g1.values()}
    assert len(resumed) == 1 and None not in resumed, resumed

    # stitched rank-0 losses == the uninterrupted reference (the resumed
    # generation replays from the last commit, so trim gen0's overlap)
    start = g1[0]["start_step"]
    assert 0 < start <= FLEET_CRASH_STEP + 1, (start, g0)
    stitched = g0["losses"][:start] + g1[0]["losses"]
    assert len(stitched) == len(ref_losses), \
        f"{start}+{len(g1[0]['losses'])} != {len(ref_losses)}"
    np.testing.assert_allclose(
        stitched, ref_losses, rtol=2e-3, atol=1e-5,
        err_msg="fleet loss curve diverged from the world-1 reference")
    # every gen1 rank records the SAME allreduced loss sequence
    for r in (1, 2):
        np.testing.assert_allclose(g1[r]["losses"], g1[0]["losses"],
                                   rtol=0, atol=0)

    print(json.dumps({
        "fleet_drill": "OK", "steps": len(ref_losses),
        "gen0_steps": len(g0["losses"]), "resume_step": start,
        "restarts": report["restarts"], "new_world": rec["new_world"],
        "plan_dp": plan1["config"]["mesh"]["dp"],
        "torn_checkpoints": 0,
        "recovery_ms": {k: rec[k] for k in
                        ("drain_ms", "teardown_ms", "respawn_ms")
                        if k in rec},
        "resume_ms": rec.get("resume_ms"),
        "max_abs_loss_delta": float(np.max(np.abs(
            np.asarray(stitched) - np.asarray(ref_losses)))),
    }))
    _assert_lockdep("fleet-supervisor")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", choices=("ref", "victim", "resume"))
    ap.add_argument("--ckpt")
    ap.add_argument("--out")
    ap.add_argument("--fleet", action="store_true",
                    help="run the multi-process elastic fleet leg")
    ap.add_argument("--fleet-child", action="store_true")
    args = ap.parse_args()
    if args.fleet_child:
        _run_fleet_child(args.out)
        sys.exit(0)
    if args.child:
        _run_child(args.child, args.ckpt, args.out)
        sys.exit(0)
    sys.exit(fleet_main() if args.fleet else main())
