#!/usr/bin/env python
"""Post-training RL drill — the ISSUE-17 acceptance run.

A REAL 3-process CPU loop: 2 serving-replica processes (one
``GenerationEngine`` each, socket RPC under the ``ServingFleet``
supervisor) plus 1 trainer process running the RL objective under
``elastic_fit``, stitched together by the control-plane ``TCPStore``
and the streaming weight-distribution service:

    rollout (fleet) -> reward (replay buffer) -> train (trainer proc)
        -> publish (WeightPublisher) -> swap in place (subscribers)

and asserts, end to end:

1. learning: over ``ROUNDS`` rounds of rejection-sampling distillation
   on the cyclic-pattern task, mean rollout reward IMPROVES by a solid
   margin over the half-trained starting policy (seeded, greedy — the
   whole loop is deterministic modulo float scheduling);
2. exactly-once through chaos: ``r1`` hard-crashes mid-rollout
   (PT_FAULTS) ⇒ the fleet fences it, replays onto the survivor with
   the WEIGHT-VERSION PIN (a pinned request never stitches across
   versions), every rollout request still completes, zero
   lost/duplicated tokens, and the restarted replica re-subscribes and
   catches up to the latest published version;
3. push under load: long generations are IN FLIGHT when the final
   version lands ⇒ admission pauses, every request finishes
   bit-identically on a single version (verified against a reference
   engine fed the exact digest-verified states the subscribers
   applied), and the streamed tokens equal each result's tail;
4. the ``post_training`` hub provider (loop rounds/rewards, rollout
   and buffer counters, applied versions, push latency) lands in
   ``observability.snapshot()`` and the telemetry dump.

Exit code 0 only when every assertion holds.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_CACHE_DIR = os.environ.setdefault(
    "PT_PERSISTENT_CACHE_DIR",
    tempfile.mkdtemp(prefix="pt_rl_cache_"))  # replicas+trainer share it

import numpy as np  # noqa: E402

# the tuned recipe (see docs/post_training.md): a HALF-trained policy
# (30 pretrain steps -> greedy reward ~0.42 on random-phase prompts)
# improves through rejection-sampling distillation — keep only
# (near-)perfect trajectories, train prompt continuations as plain CE
# and generated tokens importance-weighted, 12 inner steps per round
PATTERN = list(range(8))
ROUNDS = 8
B = 16                  # rollouts per round == train batch rows
PROMPT_LEN = 6
MAX_NEW = 6
SEQ_LEN = 12
INNER_STEPS = 12
LR = 2e-3
PROMPT_WEIGHT = 2.0
SELECT_THRESH = 0.99
PREFIX = "ptq"
BASE_VERSION = 1        # v1 = the pretrained policy, pushed at start


def build_policy_model():
    """The shared policy recipe — replicas, trainer, and the reference
    engine all build bit-identical weights from the same seed. The
    pretrain rows cover every phase of the pattern (a single-phase
    corpus teaches a POSITION prior that never transfers to
    random-phase prompts), and 30 steps leaves reward headroom."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=64,
                    dtype="float32")
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-3,
                          parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y),
                         optimizer)
    rows = np.stack([(np.arange(32) + r) % len(PATTERN)
                     for r in range(len(PATTERN))])
    ids = paddle.to_tensor(rows.astype("int64"))
    for _ in range(30):
        step(ids, ids)
    return model


def build_replica():
    """Replica builder (runs INSIDE each worker process)."""
    from paddle_tpu import serving

    return serving.GenerationEngine(
        build_policy_model(),
        serving.GenerationConfig(max_slots=2, max_seq_len=32, page_len=8,
                                 prefill_buckets=(8, 16)))


def trainer_main(store_addr: str) -> int:
    """The trainer process: rebuild the policy, publish it as v1, then
    run ``rl_fit`` — each round blocks on the rollout process's batch
    key, trains INNER_STEPS on it, and streams the update as the next
    version. Afterwards it holds the publisher open for the drill's
    under-load push and verification."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.post_training import WeightPublisher, rl_fit, track
    from paddle_tpu.serving.generation import (_extract_gpt_params,
                                               flatten_gpt_params)

    host, port = store_addr.rsplit(":", 1)
    store = TCPStore(host=host, port=int(port), world_size=1,
                     timeout=600)
    model = build_policy_model()

    def snap():
        return flatten_gpt_params(_extract_gpt_params(model))

    pub = track(WeightPublisher(name="trainer", keep_versions=4).start())
    pub.publish(snap(), version=BASE_VERSION, meta={"init": True})
    store.set(f"{PREFIX}/pub", f"{pub.host}:{pub.port}")
    print(f"[trainer] publisher up at {pub.host}:{pub.port}, "
          f"v{BASE_VERSION} = pretrained policy", flush=True)

    def build(ctx):
        return {"network": model,
                "optimizer": opt.Adam(parameters=model.parameters(),
                                      learning_rate=LR)}

    out = rl_fit(build, store=store, publisher=pub, rounds=ROUNDS,
                 batch_size=B, seq_len=SEQ_LEN,
                 steps_per_round=INNER_STEPS, base_version=BASE_VERSION,
                 prefix=PREFIX)
    print(f"[trainer] rl_fit done: pushed versions {out['pushed']}",
          flush=True)
    store.set(f"{PREFIX}/done", json.dumps(out["pushed"]))

    # under-load phase: publish one more version ON COMMAND, while the
    # rollout process holds long generations in flight
    store.wait([f"{PREFIX}/push_now"])
    pub.publish(snap(), meta={"final": True})
    store.set(f"{PREFIX}/final_version", str(pub.latest_version()))
    print(f"[trainer] final under-load push: v{pub.latest_version()}",
          flush=True)
    store.wait([f"{PREFIX}/exit"])
    pub.close()
    return 0


def main():
    import paddle_tpu.observability as obs
    import paddle_tpu.post_training as pt
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.post_training import (ReplayBuffer, RolloutWorker,
                                          WeightSubscriber,
                                          cyclic_prompts, make_rl_batch,
                                          pattern_reward, put_batch)
    from paddle_tpu.serving import ServingFleet, ServingFleetPolicy
    from paddle_tpu.serving.router import RouterConfig

    work_root = tempfile.mkdtemp(prefix="pt_rl_drill_")
    store = TCPStore(is_master=True, port=0, world_size=1, timeout=900)

    trainer_log = open(os.path.join(work_root, "trainer.log"), "wb")
    trainer = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "trainer",
         f"127.0.0.1:{store.port}"],
        env=dict(os.environ), stdout=trainer_log, stderr=trainer_log)

    def wait_key(key, deadline_s=600):
        # short per-call wait timeouts so every blocking wait on a
        # trainer-produced key polls trainer liveness between attempts
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            if trainer.poll() is not None:
                trainer_log.flush()
                with open(trainer_log.name) as f:
                    tail = f.read()[-4000:]
                raise AssertionError(
                    f"trainer died (rc={trainer.returncode}) waiting "
                    f"for {key}:\n{tail}")
            try:
                store.wait([key], timeout=2)
                return store.get(key).decode()
            except TimeoutError:
                pass
        raise AssertionError(f"timed out waiting for store key {key}")

    try:
        _run(work_root, store, wait_key, obs, pt, ReplayBuffer,
             RolloutWorker, WeightSubscriber, cyclic_prompts,
             make_rl_batch, pattern_reward, put_batch, ServingFleet,
             ServingFleetPolicy, RouterConfig)
    finally:
        store.set(f"{PREFIX}/exit", "1")
        try:
            trainer.wait(timeout=30)
        except subprocess.TimeoutExpired:
            trainer.kill()
        trainer_log.close()
    shutil.rmtree(work_root, ignore_errors=True)


def _run(work_root, store, wait_key, obs, pt, ReplayBuffer,
         RolloutWorker, WeightSubscriber, cyclic_prompts, make_rl_batch,
         pattern_reward, put_batch, ServingFleet, ServingFleetPolicy,
         RouterConfig):
    # deterministic chaos: r1 hard-exits at its 20th submit — mid-way
    # through a rollout round (~8 submits/replica/round), with pinned
    # requests in flight. inc=0 pins the rule to the first incarnation
    # so the restarted r1 serves cleanly.
    os.environ["PT_FAULTS"] = "replica_crash@name=r1&seq=20&inc=0"
    policy = ServingFleetPolicy(
        heartbeat_interval=0.25, heartbeat_timeout=3.0,
        backoff_base_s=0.2, backoff_max_s=2.0, poll_interval=0.05,
        hedge_ms=None, replica_capacity=8, drain_timeout_s=30.0)
    fleet = ServingFleet(
        builder=os.path.abspath(__file__) + ":build_replica",
        n_replicas=2, names=["r1", "r2"], policy=policy,
        router_config=RouterConfig(),
        flight_root=os.path.join(work_root, "flight"),
        log_dir=os.path.join(work_root, "logs"))
    t0 = time.time()
    fleet.start(wait_ready=True, timeout=600)
    print(f"[drill] 2-process serving fleet ready in "
          f"{time.time() - t0:.1f}s", flush=True)

    # -- weight service hookup ------------------------------------------------
    pub_host, pub_port = wait_key(f"{PREFIX}/pub").rsplit(":", 1)
    pub_port = int(pub_port)
    fleet.subscribe_weights(pub_host, pub_port, poll_interval=0.05)
    # the drill's own subscriber mirrors every applied state — the
    # digest-verified bytes the replicas run become the REFERENCE
    states = {}
    ref_sub = pt.track(WeightSubscriber(
        pub_host, pub_port, name="ref", poll_interval=0.05,
        on_update=lambda st, ver, meta: states.__setitem__(ver, st)))
    ref_sub.start()

    def wait_versions(target, deadline_s=180, names=("r1", "r2")):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            vers = fleet.replica_weight_versions()
            if all(vers.get(n, -1) >= target for n in names):
                return vers
            time.sleep(0.05)
        raise AssertionError(
            f"replicas never reached v{target}: "
            f"{fleet.replica_weight_versions()} "
            f"{fleet.provider_snapshot()['replicas']}")

    wait_versions(BASE_VERSION)
    print(f"[drill] both replicas serving v{BASE_VERSION} "
          f"(pretrained policy)", flush=True)

    # -- the loop -------------------------------------------------------------
    buf = pt.track(ReplayBuffer(capacity=1024, seed=0, staleness_limit=4,
                                reward_fn=pattern_reward(PATTERN)))
    worker = pt.track(RolloutWorker(
        fleet, cyclic_prompts(PATTERN, PROMPT_LEN, seed=3),
        max_new_tokens=MAX_NEW, timeout=300))

    rewards, push_lat_ms, pool = [], [], []
    for k in range(ROUNDS):
        trajs = worker.rollout(B, on_trajectory=buf.add)
        # exactly-once: every rollout request completes — including the
        # round r1 dies under — with one behavior logprob per token
        assert len(trajs) == B, (k, worker.stats())
        assert all(len(t.tokens) == MAX_NEW and
                   len(t.logprobs) == MAX_NEW for t in trajs), trajs
        rewards.append(round(float(np.mean([t.reward for t in trajs])),
                             3))
        pool.extend(trajs)
        pool = pool[-4 * B:]
        # rejection sampling: train on (near-)perfect trajectories
        # only, replicated to fill the batch; before any exist, the
        # best of the pool
        good = sorted([t for t in pool if t.reward >= SELECT_THRESH],
                      key=lambda t: -t.id)
        best = good or sorted(pool, key=lambda t: -t.reward)
        best = (best * ((B - 1) // len(best) + 1))[:B]
        ids, y = make_rl_batch(best, SEQ_LEN, baseline=0.0,
                               prompt_weight=PROMPT_WEIGHT)
        t_put = time.time()
        put_batch(store, PREFIX, k, ids, y)
        vers = wait_versions(BASE_VERSION + k + 1)
        push_lat_ms.append(round((time.time() - t_put) * 1e3, 1))
        pt.loop_note(round=k + 1, rounds=ROUNDS, rewards=rewards,
                     replica_versions=vers,
                     train_and_push_ms=push_lat_ms,
                     selected_reward=round(float(np.mean(
                         [t.reward for t in best])), 3))
        print(f"[drill] round {k}: reward={rewards[-1]:.3f} "
              f"selected={np.mean([t.reward for t in best]):.3f} "
              f"versions={vers} "
              f"(train+push {push_lat_ms[-1]:.0f}ms)", flush=True)

    pushed = json.loads(wait_key(f"{PREFIX}/done"))
    assert pushed == list(range(BASE_VERSION + 1,
                                BASE_VERSION + ROUNDS + 1)), pushed

    # -- learning assert ------------------------------------------------------
    assert rewards[-1] >= rewards[0] + 0.10, rewards
    assert max(rewards) >= rewards[0] + 0.15, rewards
    assert float(np.mean(rewards[-2:])) > float(np.mean(rewards[:2])), \
        rewards
    print(f"[drill] learning ok: reward {rewards[0]:.3f} -> "
          f"{rewards[-1]:.3f} over {ROUNDS} rounds: {rewards}",
          flush=True)

    # -- crash recovery assert ------------------------------------------------
    snap = fleet.provider_snapshot()
    crash_recs = [r for r in snap["recoveries"]
                  if r["replica"] == "r1"
                  and r["cause"] in ("crash", "rpc_fault",
                                     "submit_fault")]
    assert crash_recs, snap["recoveries"]
    assert snap["counters"].get("fences", 0) >= 1, snap["counters"]
    assert snap["replicas"]["r1"]["incarnation"] >= 1, snap["replicas"]
    assert snap["replicas"]["r1"]["state"] == "ready", snap["replicas"]
    assert snap["counters"].get("stream_mismatch", 0) == 0, \
        snap["counters"]
    # the restarted r1 re-subscribed and caught up (wait_versions above
    # already proved it rejoined at the current version)
    assert snap["counters"].get("weight_subscribes", 0) >= 3, \
        snap["counters"]
    print(f"[drill] crash ok: r1 fenced+restarted+resubscribed "
          f"mid-rollout (cause={crash_recs[0]['cause']}), "
          f"zero token loss/dup", flush=True)

    # -- push under load: in-flight requests stay version-pure ----------------
    last_ver = BASE_VERSION + ROUNDS
    jobs = []
    for i in range(10):
        prompt = np.asarray([PATTERN[(i + j) % len(PATTERN)]
                             for j in range(PROMPT_LEN)], np.int64)
        streamed = []
        fut = fleet.submit(prompt, max_new_tokens=24,
                           on_token=streamed.append)
        jobs.append((prompt, streamed, fut))
    store.set(f"{PREFIX}/push_now", "1")
    final_ver = int(wait_key(f"{PREFIX}/final_version"))
    assert final_ver == last_ver + 1, (final_ver, last_ver)
    # the publish must LAND mid-flight: the reference subscriber
    # applies it while the long generations are still running
    deadline = time.time() + 60
    while final_ver not in states and time.time() < deadline:
        time.sleep(0.01)
    in_flight_at_push = sum(1 for _, _, f in jobs if not f.done())
    assert final_ver in states, (final_ver, sorted(states))
    assert in_flight_at_push >= 1, "push landed after all requests"

    results = []
    for prompt, streamed, fut in jobs:
        out = np.asarray(fut.result(timeout=300)).tolist()
        assert streamed == out[len(prompt):], \
            ("stream dup/loss under push", streamed, out[len(prompt):])
        ver = worker._request_version(fut)
        results.append((prompt.tolist(), out, ver))
    assert {v for _, _, v in results} == {last_ver}, results

    # bit-identical verification: a reference engine swaps in the SAME
    # digest-verified states the replicas applied; every under-load
    # output must match exactly one version's greedy decode — a
    # mid-request swap would produce a mixture matching neither
    ref_engine = build_replica()
    ref_engine.start()

    def ref_decode(version, prompt, mx):
        ref_engine.swap_weights(states[version], version=version)
        return np.asarray(ref_engine.submit(
            np.asarray(prompt, np.int64), mx).result(
                timeout=120)).tolist()

    matched = {last_ver: 0, final_ver: 0}
    for prompt, out, _ in results:
        if out == ref_decode(last_ver, prompt, 24):
            matched[last_ver] += 1
        else:
            assert out == ref_decode(final_ver, prompt, 24), \
                ("output matches NO single version", prompt, out)
            matched[final_ver] += 1
    assert matched[last_ver] >= 1, matched
    # after the in-flight work drains, the staged swap lands fleetwide
    wait_versions(final_ver)
    ref_engine.close()
    print(f"[drill] under-load push ok: {len(results)} long requests "
          f"bit-identical (v{last_ver}: {matched[last_ver]}, "
          f"v{final_ver}: {matched[final_ver]}), "
          f"{in_flight_at_push} in flight at publish, fleet now at "
          f"v{final_ver}", flush=True)

    # -- provider + telemetry -------------------------------------------------
    pt.loop_note(final_version=final_ver, matched=matched,
                 push_latency_ms=ref_sub.stats()["last"].get(
                     "push_latency_ms"))
    hub = obs.snapshot()["post_training"]
    assert hub["loop"]["round"] == ROUNDS, hub["loop"]
    assert hub["loop"]["rewards"] == rewards, hub["loop"]
    kinds = {r["kind"] for r in hub["components"]}
    assert {"ReplayBuffer", "RolloutWorker",
            "WeightSubscriber"} <= kinds, kinds
    b_row = next(r for r in hub["components"]
                 if r["kind"] == "ReplayBuffer")
    assert b_row["depth"] > 0 and b_row["added"] == ROUNDS * B, b_row
    s_row = next(r for r in hub["components"]
                 if r["kind"] == "WeightSubscriber")
    assert s_row["applied_version"] == final_ver, s_row
    assert s_row["last"]["push_latency_ms"] is not None, s_row

    dump_path = os.path.join(work_root, "telemetry.json")
    obs.dump(dump_path)
    with open(dump_path) as f:
        tele = json.load(f)
    assert tele["post_training"]["loop"]["rewards"] == rewards, \
        "post_training provider missing from the telemetry dump"
    print("[drill] telemetry ok: post_training provider in dump",
          flush=True)
    if os.environ.get("PT_LOCKDEP", "") not in ("", "0", "false"):
        ld = tele.get("lockdep")
        assert ld and ld.get("armed"), \
            "PT_LOCKDEP=1 but the lockdep provider is missing/disarmed"
        assert ld["cycles"] == [], f"lock-order cycles: {ld['cycles']}"
        assert any("post_training" in name for name in ld["locks"]), \
            "lockdep witnessed no post_training locks"
        print(f"[drill] lockdep ok: {len(ld['locks'])} witnessed locks, "
              f"zero cycles", flush=True)

    ref_sub.stop()
    fleet.close()
    headline = {
        "rounds": ROUNDS,
        "reward_first": rewards[0], "reward_last": rewards[-1],
        "rewards": rewards,
        "trajectories": worker.stats()["completed"],
        "versions_pushed": len(pushed) + 2,  # + init + under-load
        "fences": snap["counters"].get("fences", 0),
        "stream_mismatch": snap["counters"].get("stream_mismatch", 0),
        "version_reprefill": snap["counters"].get("version_reprefill",
                                                  0),
        "version_restitch": snap["counters"].get("version_restitch", 0),
        "inflight_at_final_push": in_flight_at_push,
        "underload_matched": {str(k): v for k, v in matched.items()},
        "push_latency_ms": ref_sub.stats()["last"].get(
            "push_latency_ms"),
    }
    print("RL_DRILL_OK " + json.dumps(headline), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "trainer":
        sys.exit(trainer_main(sys.argv[2]))
    main()
