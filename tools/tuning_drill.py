#!/usr/bin/env python
"""Online auto-tuner drill — the ISSUE-20 acceptance run.

Three legs, all against REAL multi-process fleets:

serving        a 2-replica ``ServingFleet`` boots on hand-declared
               prefill buckets sized for long prompts; the live
               workload is short (a shift).  The ``OnlineTuner`` +
               ``ServingShapePolicy`` derive tighter buckets/slots from
               the merged prompt/slot histograms (quantile-cover),
               actuate them through ``apply_serving_shape`` (a rolling
               restart in which every replica AOT-warms the NEW shape
               BEFORE re-admitting traffic), and the post-apply
               measurement window confirms the predicted padding-waste
               win (keep).  The SAME request set replayed across the
               cutover must produce BIT-IDENTICAL token streams.  The
               ``tuner`` hub provider (proposals/applies/keeps/active
               digests + the decision ledger) is asserted from the
               telemetry dump, and the ``PT_ONLINE_TUNING=0``
               kill-switch is exercised.

plan-keep      a 2-worker ``ElasticFleet`` trains under the planner's
               best pure-dp plan while rank 0 runs ``ElasticPlanTuner``
               from a fit callback.  A fault keyed to the ACTIVE plan
               digest slows every step (sustained — the windowed
               detector never fires on one spike); the tuner re-scores
               the cached candidates with the degraded measurement
               anchored, publishes the winner as ``fleet/plan_override``
               and raises a ``retune:plan`` fence.  The gang drains at
               the checkpoint boundary, restarts PLANNED (report
               ``restarts == 0`` — no crash budget spent), the next
               generation adopts the override, the slowdown vanishes
               (it was keyed to the old digest) and the cross-
               generation measurement window confirms: keep.

plan-rollback  same fleet, but the slowdown is UNCONDITIONAL: the
               swapped-to plan measures just as slow, the tuner rolls
               back through a second planned fence (``retune:rollback``)
               onto the original plan, embargoes the refuted digest,
               and the run completes with no flapping.

With ``PT_LOCKDEP=1`` every leg re-runs under the runtime lock-order
witness and must stay cycle-free.  Exit 0 only when every assertion
holds.
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_CACHE_DIR = os.environ.setdefault(
    "PT_PERSISTENT_CACHE_DIR",
    tempfile.mkdtemp(prefix="pt_tuning_cache_"))

# -- serving leg constants ----------------------------------------------------
DECLARED_PREFILL = (32, 40)      # sized for long prompts; traffic is short
ROUND_REQUESTS = 24
WAVE = 8
MAX_NEW = 4

# -- elastic leg constants ----------------------------------------------------
ELASTIC_WORLD = 2
ELASTIC_GLOBAL_BATCH = 8
ELASTIC_SAMPLES = 240            # 30 global steps, 1 epoch
ELASTIC_CKPT_EVERY = 2
SLOW_AFTER_STEPS = 10            # fault arms after the baseline window
SLOW_SLEEP_S = 0.12


def _assert_lockdep(tag: str) -> None:
    if os.environ.get("PT_LOCKDEP", "") in ("", "0", "false"):
        return
    from paddle_tpu.analysis import lockdep

    snap = lockdep.snapshot()
    assert snap["armed"] and snap["locks"], \
        f"[{tag}] PT_LOCKDEP=1 but the witness saw no locks"
    assert snap["cycles"] == [], f"[{tag}] lock-order cycles: {snap['cycles']}"
    print(f"[{tag}] lockdep ok: {len(snap['locks'])} witnessed locks, "
          f"{len(snap['edges'])} order edges, zero cycles", flush=True)


# ---------------------------------------------------------------------------
# serving leg
# ---------------------------------------------------------------------------

def build_replica():
    """Replica builder (runs INSIDE each serving worker): the tiny
    pattern-trained GPT every serving drill uses, on DELIBERATELY coarse
    declared prefill buckets — the shape the tuner will beat."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit, serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=64,
                    dtype="float32")
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-3,
                          parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y), optimizer)
    ids = paddle.to_tensor(
        np.tile(np.arange(8), 8)[None, :].astype("int64"))
    for _ in range(80):
        step(ids, ids)
    return serving.GenerationEngine(
        model, serving.GenerationConfig(
            max_slots=2, max_seq_len=48, page_len=8, num_pages=48,
            prefill_buckets=DECLARED_PREFILL))


def _round_prompts():
    import numpy as np

    pattern = np.tile(np.arange(8), 8)
    prompts = []
    for i in range(ROUND_REQUESTS):
        plen = 8 if i % 2 else 16
        start = (i * 3) % 8
        prompts.append(pattern[start:start + plen].astype(np.int64))
    return prompts


def _run_round(fleet):
    """Submit the deterministic request set (in capacity-sized waves)
    and return every full output token list, stream-checked."""
    outs = []
    prompts = _round_prompts()
    for base in range(0, len(prompts), WAVE):
        futs = []
        for prompt in prompts[base:base + WAVE]:
            streamed = []
            futs.append((len(prompt), streamed,
                         fleet.submit(prompt, max_new_tokens=MAX_NEW,
                                      on_token=streamed.append)))
        for plen, streamed, fut in futs:
            out = fut.result(timeout=300).tolist()
            assert len(out) == plen + MAX_NEW, (plen, out)
            assert streamed == out[plen:], "stream dup/loss"
            outs.append(out)
    return outs


def serving_leg(work_root: str) -> dict:
    import paddle_tpu.observability as obs
    from paddle_tpu.serving import ServingFleet, ServingFleetPolicy
    from paddle_tpu.serving.router import RouterConfig
    from paddle_tpu.tuning import OnlineTuner
    from paddle_tpu.tuning.serving_tuner import (DECLARED_DIGEST,
                                                 ServingShapePolicy)

    policy = ServingFleetPolicy(
        heartbeat_interval=0.25, heartbeat_timeout=3.0,
        backoff_base_s=0.2, backoff_max_s=2.0, poll_interval=0.05,
        hedge_ms=None, replica_capacity=WAVE, drain_timeout_s=30.0,
        telemetry_interval_s=0.5)
    fleet = ServingFleet(
        builder=os.path.abspath(__file__) + ":build_replica",
        n_replicas=2, names=["r0", "r1"], policy=policy,
        router_config=RouterConfig(),
        flight_root=os.path.join(work_root, "flight"),
        log_dir=os.path.join(work_root, "logs"))
    t0 = time.time()
    fleet.start(wait_ready=True, timeout=600)
    print(f"[serving] 2-replica fleet ready in {time.time() - t0:.1f}s "
          f"on declared prefill buckets {list(DECLARED_PREFILL)}",
          flush=True)

    shape_policy = ServingShapePolicy(
        fleet,
        declared={"prefill_buckets": list(DECLARED_PREFILL),
                  "max_slots": 2},
        window_s=600.0, min_count=10, q=0.99, max_waste=0.2,
        max_buckets=6, improve_margin=0.02, max_slots_cap=3,
        measure_count=12, measure_timeout_s=60.0, cooldown_s=0.5)
    tuner = OnlineTuner([shape_policy],
                        signal_sources={"fleet_telemetry":
                                        fleet.scrape_now},
                        provider_name="tuner")

    # -- kill-switch: a disabled tuner must not tick, propose or actuate
    os.environ["PT_ONLINE_TUNING"] = "0"
    tuner.tick()
    off = obs.snapshot()["tuner"]
    assert tuner.ticks == 0 and off["enabled"] is False, off
    assert off["policies"]["serving_shape"]["proposals"] == 0, off
    os.environ.pop("PT_ONLINE_TUNING", None)
    print("[serving] kill-switch ok: PT_ONLINE_TUNING=0 ticked nothing",
          flush=True)

    # -- pre-cutover traffic: shifted-short workload on coarse buckets
    tuner.tick()  # zero-baseline scrape before any traffic
    expected = None
    applies = 0
    for round_no in range(6):
        outs = _run_round(fleet)
        if expected is None:
            expected = outs
        else:
            assert outs == expected, "pre-cutover streams drifted"
        tuner.tick()
        applies = obs.snapshot()["tuner"]["policies"][
            "serving_shape"]["applies"]
        if applies:
            break
    assert applies == 1, \
        f"tuner never actuated a derived shape (applies={applies})"

    snap = obs.snapshot()["tuner"]
    pol = snap["policies"]["serving_shape"]
    shape = pol["active_shape"]
    assert pol["active"] != DECLARED_DIGEST, pol
    assert pol["phase"] == "measuring", pol
    derived = shape.get("prefill_buckets") or []
    assert derived and max(derived) < min(DECLARED_PREFILL), \
        f"derived buckets {derived} should be tighter than declared " \
        f"{DECLARED_PREFILL}"
    events = [d["event"] for d in snap["decisions"]]
    assert events[-2:] == ["propose", "apply"], events
    fl = fleet.provider_snapshot()
    assert fl["counters"].get("shape_applies", 0) == 1, fl["counters"]
    assert fl["counters"].get("rolling_restarts", 0) == 1, fl["counters"]
    print(f"[serving] respec ok: derived prefill={derived} "
          f"max_slots={shape.get('max_slots')} rolled across the fleet "
          f"(digest {pol['active']})", flush=True)

    # -- bit-identical streams across the cutover
    post = _run_round(fleet)
    assert post == expected, \
        "token streams changed across the shape cutover"
    print(f"[serving] cutover ok: {len(post)} replayed requests "
          f"produced bit-identical streams", flush=True)

    # -- the measurement window confirms the waste claim: keep
    keeps = 0
    for _ in range(8):
        tuner.tick()
        pol = obs.snapshot()["tuner"]["policies"]["serving_shape"]
        keeps = pol["keeps"]
        if keeps:
            break
        _run_round(fleet)
    assert keeps == 1 and pol["rollbacks"] == 0, pol
    live = pol["live_waste"].get("prefill_buckets_waste")
    assert live is not None and live <= 0.1, pol["live_waste"]
    ledger = [d["event"] for d in
              obs.snapshot()["tuner"]["decisions"]]
    assert ledger[-3:] == ["propose", "apply", "keep"], ledger
    print(f"[serving] keep ok: live prefill waste {live} under the "
          f"derived shape (ledger {ledger[-3:]})", flush=True)

    _assert_lockdep("serving-supervisor")
    fleet.close()
    return {"derived_prefill": derived,
            "max_slots": shape.get("max_slots"),
            "live_waste": live, "applies": 1, "keeps": keeps,
            "replayed": len(post)}


# ---------------------------------------------------------------------------
# elastic legs (plan re-rank: keep / rollback)
# ---------------------------------------------------------------------------

def _run_elastic_child(out_dir: str) -> None:
    """One elastic worker: rank 0 drives ``ElasticPlanTuner`` from a fit
    callback; the scripted slowdown is the regression under test."""
    world = int(os.environ.get("PT_FLEET_WORLD", "1"))
    coord = os.environ.get("PT_FLEET_COORDINATOR")
    if world > 1 and coord:
        import jax

        jax.distributed.initialize(
            coordinator_address=coord, num_processes=world,
            process_id=int(os.environ.get("PT_FLEET_RANK", "0")))
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.runtime import elastic_fit

    slow_mode = os.environ.get("PT_DRILL_SLOW", "")

    class ToyDataset(paddle.io.Dataset):
        def __init__(self, n):
            rng = np.random.default_rng(3)
            self.x = rng.standard_normal((n, 8)).astype("float32")
            w = rng.standard_normal((8,)).astype("float32")
            self.y = (self.x @ w > 0).astype("int64")

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    holder = {}

    def _write(res):
        res = dict(res)
        tuner = holder.get("tuner")
        if tuner is not None:
            try:
                res["tuner"] = tuner.snapshot()
            except Exception:
                pass
        path = os.path.join(out_dir, f"g{res['gen']}_r{res['rank']}.json")
        with open(path + ".tmp", "w") as f:
            json.dump(res, f)
        os.replace(path + ".tmp", path)

    class TunerStepCallback(paddle.callbacks.Callback):
        """Times every completed step into ``tuner.on_step`` and injects
        the scripted slowdown: ``first`` slows only while the INITIAL
        plan digest is active (the regression the swap escapes),
        ``always`` slows unconditionally (the swap cannot help — it
        must be refuted and rolled back)."""

        def __init__(self, tuner, initial_digest, gen):
            self.tuner = tuner
            self.initial = initial_digest
            self.gen = gen
            self.steps = 0
            self._last = None

        def on_train_batch_end(self, step, logs=None):
            self.steps += 1
            armed = self.gen > 0 or self.steps > SLOW_AFTER_STEPS
            if slow_mode == "first":
                armed = armed and \
                    self.tuner.active_digest() == self.initial
            elif slow_mode != "always":
                armed = False
            if armed:
                time.sleep(SLOW_SLEEP_S)
            now = time.perf_counter()
            if self._last is not None:
                self.tuner.on_step((now - self._last) * 1e3)
            self._last = now

    def build(ctx):
        paddle.seed(7)  # identical init on every rank; resume overwrites
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        ce = nn.CrossEntropyLoss()
        ds = ToyDataset(ELASTIC_SAMPLES)
        xb = np.stack([ds[i][0] for i in range(ELASTIC_GLOBAL_BATCH)])
        yb = np.stack([ds[i][1] for i in range(ELASTIC_GLOBAL_BATCH)])

        def loss_fn(m, x, y):
            return ce(m(x), y)

        cbs = []
        if ctx.rank == 0 and ctx.store is not None and ctx.world > 1:
            from paddle_tpu.distributed.auto_parallel import planner
            from paddle_tpu.distributed.fleet.runtime import \
                replan_for_world
            from paddle_tpu.tuning import (ElasticPlanTuner,
                                           RegressionDetector)

            prof = planner.profile_model(net, sample_batch=(xb, yb),
                                         loss_fn=loss_fn)
            cands = planner.plan(
                net, n_devices=ctx.world, hbm_bytes=64e9,
                batch=ELASTIC_GLOBAL_BATCH, sample_batch=(xb, yb),
                loss_fn=loss_fn, accumulate=(1,), remat=(False, True),
                levels=(None,), offload=(False,), cp_degrees=(1,))
            # only plans the CPU fleet can execute: pure-dp over world
            pure = [c for c in cands
                    if c.config["mesh"].get("dp", 1) == ctx.world
                    and all(v == 1 for k, v in c.config["mesh"].items()
                            if k != "dp")]
            assert len(pure) >= 2, \
                f"need >=2 pure-dp candidates to swap between, got " \
                f"{len(pure)}"
            base = replan_for_world(net, ctx.world,
                                    batch=ELASTIC_GLOBAL_BATCH,
                                    sample_batch=(xb, yb),
                                    loss_fn=loss_fn)
            initial = planner.plan_digest(base.config)
            tuner = ElasticPlanTuner(
                ctx, prof, pure, margin=0.2, measure_steps=5,
                skip_steps=2, cooldown_s=10.0, hbm_bytes=64e9,
                detector=RegressionDetector(
                    baseline_window=8, min_samples=4, sustain_n=3,
                    trigger_ratio=1.3, min_abs_ms=30.0))
            holder["tuner"] = tuner
            cbs.append(TunerStepCallback(tuner, initial, ctx.gen))
        return {"network": net, "optimizer": opt, "loss": ce,
                "dataset": ds, "sample_batch": (xb, yb),
                "loss_fn": loss_fn, "callbacks": cbs, "on_exit": _write}

    res = elastic_fit(build, global_batch=ELASTIC_GLOBAL_BATCH, epochs=1,
                      checkpoint_every=ELASTIC_CKPT_EVERY)
    _write(res)
    _assert_lockdep("elastic-child")


def _read(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def elastic_leg(mode: str) -> dict:
    """Run the 2-worker elastic fleet with the scripted slowdown and
    assert the keep (``mode='first'``) or rollback (``mode='always'``)
    path end to end."""
    from paddle_tpu.distributed.auto_parallel.planner import plan_digest
    from paddle_tpu.distributed.fleet.runtime import (ElasticFleet,
                                                      FleetPolicy,
                                                      _probe_json)
    from paddle_tpu.tuning.plan_tuner import PLAN_STATE_KEY

    leg = "plan-keep" if mode == "first" else "plan-rollback"
    work = tempfile.mkdtemp(prefix=f"pt_tuning_{leg}_")
    out_dir = os.path.join(work, "out")
    os.makedirs(out_dir, exist_ok=True)
    here = os.path.abspath(__file__)
    root = os.path.dirname(os.path.dirname(here))
    print(f"[{leg}] 2-worker elastic fleet, scripted slowdown "
          f"mode={mode!r} after step {SLOW_AFTER_STEPS}", flush=True)
    fleet = ElasticFleet(
        [sys.executable, here, "--elastic-child", "--out", out_dir],
        np=ELASTIC_WORLD,
        policy=FleetPolicy(min_world=ELASTIC_WORLD, max_restarts=2,
                           heartbeat_timeout=8.0, backoff_base_s=0.2,
                           drain_timeout_s=30.0),
        log_dir=os.path.join(work, "logs"),
        ckpt_root=os.path.join(work, "ckpt"),
        extra_env={
            "PYTHONPATH": root + os.pathsep +
            os.environ.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": "cpu",
            "PT_DRILL_SLOW": mode,
        })
    try:
        report = fleet.run(timeout=600)
        state = _probe_json(fleet.store, PLAN_STATE_KEY)
    finally:
        fleet.close()

    events = [e["event"] for e in report["timeline"]]
    print(f"[{leg}] phase={report['phase']} "
          f"restarts={report['restarts']} events={events}", flush=True)
    assert report["phase"] == "completed", report
    # PLANNED fences spend no crash budget
    assert report["restarts"] == 0, report
    recs = report["recoveries"]
    want_gens = 1 if mode == "first" else 2
    assert len(recs) == want_gens, recs
    assert all(r["planned"] for r in recs), recs
    assert recs[0]["reason"] == "retune:plan", recs
    if mode == "always":
        assert recs[1]["reason"] == "retune:rollback", recs

    plans = {str(k): v for k, v in report["plans"].items()}
    digests = {g: plan_digest(p["config"])
               for g, p in plans.items()}
    assert digests["1"] != digests["0"], \
        f"gen1 never adopted the override: {digests}"

    assert isinstance(state, dict), state
    counters = state["counters"]
    assert counters["proposals"] == 1 and counters["applies"] == 1, \
        counters
    verdict = state["last_verdict"]
    if mode == "first":
        assert counters["keeps"] == 1 and counters["rollbacks"] == 0, \
            counters
        assert verdict and verdict["kept"] is True, verdict
        assert state["active"] == digests["1"], (state["active"], digests)
    else:
        assert counters["keeps"] == 0 and counters["rollbacks"] == 1, \
            counters
        assert verdict and verdict["kept"] is False, verdict
        # rolled back onto the original plan, refuted digest embargoed
        assert state["active"] == digests["0"], (state["active"], digests)
        assert digests["2"] == digests["0"], digests
        assert state["rejected"] == [digests["1"]], state["rejected"]
        assert verdict["measured_ms"] > state["target_ms"] > 0, verdict

    # the worker-side ``tuner`` provider surface rode along in the final
    # generation's result dump
    final = _read(os.path.join(out_dir, f"g{want_gens}_r0.json"))
    tsnap = final.get("tuner")
    assert tsnap and tsnap["enabled"] is True, tsnap
    assert tsnap["counters"] == counters, (tsnap["counters"], counters)
    return {"restarts": report["restarts"],
            "recoveries": [r["reason"] for r in recs],
            "counters": counters,
            "verdict": verdict,
            "measured_ms": verdict.get("measured_ms"),
            "target_ms": state.get("target_ms")}


# ---------------------------------------------------------------------------

def main(legs) -> int:
    headline = {}
    if "serving" in legs:
        work_root = tempfile.mkdtemp(prefix="pt_tuning_serving_")
        headline["serving"] = serving_leg(work_root)
    if "plan-keep" in legs:
        headline["plan_keep"] = elastic_leg("first")
    if "plan-rollback" in legs:
        headline["plan_rollback"] = elastic_leg("always")
    _assert_lockdep("supervisor")
    print("TUNING_DRILL_OK " + json.dumps(headline), flush=True)
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--elastic-child", action="store_true")
    ap.add_argument("--out")
    ap.add_argument("--leg", action="append",
                    choices=("serving", "plan-keep", "plan-rollback"),
                    help="run only the named leg(s); default: all")
    args = ap.parse_args()
    if args.elastic_child:
        _run_elastic_child(args.out)
        sys.exit(0)
    sys.exit(main(args.leg or ("serving", "plan-keep", "plan-rollback")))
