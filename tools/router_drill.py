#!/usr/bin/env python
"""Router drill — the ISSUE-12 serving-gate acceptance run.

Two ``GenerationEngine`` replicas behind ``ReplicaRouter``, CPU-only:

1. replica A compiles its executable set under a fresh persistent cache;
   replica B (the "restarted" replica) then builds the SAME set and must
   warm entirely from the cache: **zero fresh XLA compiles** (the
   persistent-cache counter, same contract as the ISSUE-3 warm start);
2. shared-system-prompt traffic through the router: **prefix_hit_rate >
   0** and every continuation correct;
3. injected replica fault: A closes mid-run; the router fences it and the
   remaining traffic **drains through B** (queue depth returns to 0);
4. the paged decode path reports **zero retrace events** steady-state
   (``analysis.retrace`` counter with ``PT_RETRACE_AUDIT=1``).

Exit code 0 only when every assertion holds.
"""
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PT_RETRACE_AUDIT"] = "1"
_CACHE_DIR = tempfile.mkdtemp(prefix="pt_routerdrill_cache_")
os.environ["PT_PERSISTENT_CACHE_DIR"] = _CACHE_DIR  # read at import

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.analysis as A  # noqa: E402
import paddle_tpu.optimizer as opt  # noqa: E402
from paddle_tpu import jit, serving  # noqa: E402
from paddle_tpu.jit import persistent_cache as pcache  # noqa: E402
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM  # noqa: E402


def main():
    A.retrace.enable()
    assert pcache.is_enabled(), "persistent cache must be on for the drill"

    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=64,
                    dtype="float32")
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-3, parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y), optimizer)
    pattern = np.tile(np.arange(8), 8)
    ids = paddle.to_tensor(pattern[None, :].astype("int64"))
    for _ in range(80):
        loss = step(ids, ids)
    assert float(loss) < 0.1, float(loss)

    def mk(name):
        return serving.GenerationEngine(
            model, serving.GenerationConfig(max_slots=2, max_seq_len=32,
                                            page_len=8,
                                            prefill_buckets=(8, 16, 24)),
            name=name)

    # -- 1. warm-replica zero-compile contract --------------------------------
    rep_a = mk("replica_a").warmup()
    base = pcache.stats()
    assert base["compiles"] > 0, base  # A really compiled something
    rep_b = mk("replica_b").warmup()
    warm = pcache.stats()
    fresh_on_warm = warm["compiles"] - base["compiles"]
    warm_hits = warm["hits"] - base["hits"]
    assert fresh_on_warm == 0, \
        f"warm replica paid {fresh_on_warm} fresh XLA compiles"
    assert warm_hits > 0, warm

    # -- 2. shared-system-prompt traffic through the router -------------------
    router = serving.ReplicaRouter([rep_a, rep_b], name="drill_fleet")
    prompt = pattern[:17].astype("int64")  # two full 8-blocks shared
    with router:
        router.submit(prompt, max_new_tokens=4).result(timeout=300)
        futs = [router.submit(prompt, max_new_tokens=4) for _ in range(7)]
        for f in futs:
            out = f.result(timeout=300)
            want = [(17 + i) % 8 for i in range(len(out) - 17)]
            assert out[17:].tolist() == want, (out[17:].tolist(), want)
        st = router.stats()
        fleet_hit = max(r["prefix_hit_rate"] or 0.0
                        for r in st["replicas"].values())
        assert fleet_hit > 0, st
        assert st["affinity_hits"] > 0, st

        # -- 3. injected replica fault: fence + drain through B ---------------
        victim = max(st["replicas"],
                     key=lambda n: st["replicas"][n]["routed"])
        survivor = "replica_b" if victim == "replica_a" else "replica_a"
        dict(replica_a=rep_a, replica_b=rep_b)[victim].close(drain=False)
        futs = [router.submit(prompt, max_new_tokens=3) for _ in range(6)]
        for f in futs:
            out = f.result(timeout=300)
            want = [(17 + i) % 8 for i in range(len(out) - 17)]
            assert out[17:].tolist() == want
        st = router.stats()
        assert victim in st["down"], st
        assert router.queue_depth() == 0, "queue stuck after replica fault"
        assert st["replicas"][survivor]["responses"] >= 6, st

        # -- 4. zero retrace steady-state -------------------------------------
        for rep in (rep_a, rep_b):
            rt = rep.retrace_events()
            assert rt == 0, (rep.name, rt)

    print("router drill OK:", json.dumps({
        "warm_replica_fresh_compiles": fresh_on_warm,
        "warm_replica_cache_hits": warm_hits,
        "prefix_hit_rate": fleet_hit,
        "affinity_hits": st["affinity_hits"],
        "faulted": victim,
        "survivor_responses": st["replicas"][survivor]["responses"],
        "retrace_events": 0,
    }))


if __name__ == "__main__":
    try:
        main()
    finally:
        shutil.rmtree(_CACHE_DIR, ignore_errors=True)
