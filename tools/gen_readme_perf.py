"""Regenerate README.md's perf paragraph from a bench artifact.

VERDICT r4 weak #2: prose perf claims drifted from the measured JSON (a
stale "7% dispatch" survived a re-measure). This tool makes drift
impossible: the README section between the BENCH markers is GENERATED from
the newest BENCH_r*.json (or an explicit path), so every number in prose
is a number in the artifact.

Usage: python tools/gen_readme_perf.py [bench.json]
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BEGIN, END = "<!-- BENCH:begin", "<!-- BENCH:end -->"


def _round_of(p: str) -> int:
    m = re.search(r"r(\d+)", os.path.basename(p))
    return int(m.group(1)) if m else -1


def _load(path=None):
    if path is None:
        cands = glob.glob(os.path.join(ROOT, "BENCH_r*.json")) + \
            glob.glob(os.path.join(ROOT, "bench_artifacts", "*.json"))
        if not cands:
            raise SystemExit("no bench artifact found")
        # deterministic: highest round number wins (parsed from the name,
        # so fresh-clone mtimes don't matter); session artifacts beat the
        # driver artifact of the same round (they carry the later rows)
        path = max(cands, key=lambda p: (
            _round_of(p), "bench_artifacts" in p, os.path.basename(p)))
    with open(path) as f:
        data = json.load(f)
    if "detail" not in data and isinstance(data.get("parsed"), dict):
        data = data["parsed"]  # driver artifact with parsed result
    if "detail" not in data and "tail" in data:
        # driver artifact: the bench's printed JSON line lives in "tail"
        for line in str(data["tail"]).splitlines():
            line = line.strip()
            if line.startswith("{") and '"detail"' in line:
                data = json.loads(line)
                break
        else:
            raise SystemExit(
                f"{path}: no parseable bench line (parsed=null and the "
                f"tail window truncates the JSON) — pass a fresh artifact")
    return os.path.basename(path), data.get("detail", data)


def render(src_name, d) -> str:
    parts = []
    if "mfu" in d:
        parts.append(
            f"Llama 1.16B pretrain at **{d['mfu']}% MFU** (target ≥38%; "
            f"{round(d['mfu'] / 38.0, 2)}× baseline) with the full train "
            f"step — forward, backward, fused optimizer — compiled into a "
            f"single donated-buffer XLA executable")
    if "dit" in d:
        dit = d["dit"]
        parts.append(f"DiT-XL/2 diffusion training at "
                     f"**{dit['images_per_sec']} images/sec "
                     f"({dit['mfu']}% MFU)**")
    if "moe" in d:
        moe = d["moe"]
        s = (f"a {moe['params_total_m'] / 1e3:.2f}B-total/"
             f"{moe['params_activated_m'] / 1e3:.2f}B-active "
             f"DeepSeekMoE-style model at "
             f"**{moe['mfu_activated']}% activated-MFU** "
             f"({moe['mfu_executed']}% on executed FLOPs, cf="
             f"{moe['capacity_factor']}, '{moe.get('dispatch', '?')}' "
             f"dispatch")
        probe = moe.get("dispatch_probe")
        if probe and "dispatch_share" in probe:
            s += (f"; routing/dispatch measured at "
                  f"{probe['dispatch_share'] * 100:.1f}% of the MLP")
        parts.append(s + ")")
    if "long_seq_16k" in d:
        ls = d["long_seq_16k"]
        parts.append(f"16k-token long-context at **{ls['mfu']}% MFU**")
    if "adafactor_1p8b" in d:
        af = d["adafactor_1p8b"]
        parts.append(
            f"a {af['params_m'] / 1e3:.2f}B model trains *resident* at "
            f"**{af['mfu']}% MFU** via Adafactor")
    if "stream_capacity" in d:
        sc = d["stream_capacity"]
        parts.append(
            f"`jit.StreamedTrainStep` streams stacked decoder weights + "
            f"optimizer state through pinned host memory, training "
            f"**{sc['params_b']}B params** on the same chip")
    if "seg_capacity" in d:
        sg = d["seg_capacity"]
        parts.append(
            f"`jit.SegmentedTrainStep` (per-layer executables, no stacked "
            f"grad chain) lifts the ceiling to **{sg['params_b']}B**")
    if "llama7b_seg" in d:
        l7 = d["llama7b_seg"]
        parts.append(
            f"the segmented path trains the published **Llama-2-7B "
            f"architecture ({l7['params_b']}B params) on the single chip** "
            f"({l7['step_time_s']}s/step, {l7['gb_moved_per_step']}GB/step "
            f"over a {l7['effective_host_gbps']}GB/s effective host link)")
    if "resnet_cifar" in d:
        rc = d["resnet_cifar"]
        pr = rc.get("loss_parity", {})
        parts.append(
            f"ResNet-18 surrogate-CIFAR parity: TPU-vs-CPU loss curves "
            f"match within **{pr.get('max_abs_delta', '?')}** over "
            f"{pr.get('steps', '?')} steps at "
            f"**{rc['images_per_sec']} images/sec**")
    if "bert_finetune" in d:
        bf = d["bert_finetune"]
        parts.append(
            f"BERT-base SST-2-shaped finetune reaches "
            f"**{bf['heldout_accuracy'] * 100:.1f}% held-out accuracy** at "
            f"**{bf['sequences_per_sec']} sequences/sec**")
    if not parts:
        raise SystemExit(
            "bench artifact has no recognized rows — refusing to write an "
            "empty perf section (the drift this tool exists to prevent)")
    body = "; ".join(parts)
    return (f"{BEGIN} (generated by tools/gen_readme_perf.py from "
            f"{src_name} — edit the artifact, not this text) -->\n"
            f"**Current flagship benches** (one TPU v5e chip, `bench.py`): "
            f"{body}.\n{END}")


def main():
    src_name, d = _load(sys.argv[1] if len(sys.argv) > 1 else None)
    readme_path = os.path.join(ROOT, "README.md")
    with open(readme_path) as f:
        readme = f.read()
    block = render(src_name, d)
    pat = re.compile(re.escape(BEGIN) + r".*?" + re.escape(END), re.S)
    if pat.search(readme):
        readme = pat.sub(lambda _m: block, readme)
    else:
        raise SystemExit("README.md lacks the BENCH markers")
    with open(readme_path, "w") as f:
        f.write(readme)
    print(f"README perf section regenerated from {src_name}")


if __name__ == "__main__":
    main()
