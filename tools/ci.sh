#!/usr/bin/env bash
# CI entrypoint: repo self-lint + the tier-1 test suite.
#
#   bash tools/ci.sh            # both gates
#   bash tools/ci.sh --lint     # self-lint only (fast)
#
# Mirrors the reference's hard CI gates (tools/ci_op_benchmark.sh role):
# a PR that trips the static checker or the tier-1 suite does not land.
set -o pipefail
cd "$(dirname "$0")/.."

echo "== pd_check --self (repo footgun lint) =="
JAX_PLATFORMS=cpu python tools/pd_check.py --self || exit 1

if [ "${1:-}" = "--lint" ]; then
    exit 0
fi

echo "== serving gate (engine tests + demo) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python examples/serve_gpt.py --clients 4 || exit 1

echo "== tier-1 test suite =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit $rc
