#!/usr/bin/env bash
# CI entrypoint: repo self-lint + the tier-1 test suite.
#
#   bash tools/ci.sh            # both gates
#   bash tools/ci.sh --lint     # self-lint only (fast)
#
# Mirrors the reference's hard CI gates (tools/ci_op_benchmark.sh role):
# a PR that trips the static checker or the tier-1 suite does not land.
set -o pipefail
cd "$(dirname "$0")/.."

echo "== pd_check --self (repo footgun lint) =="
JAX_PLATFORMS=cpu python tools/pd_check.py --self || exit 1

echo "== pd_check --concurrency (CC lint: threads & locks) =="
# repo-wide blocking-under-lock / signal-handler-lock / thread-leak /
# lock-order pass; any error-severity finding fails the build
JAX_PLATFORMS=cpu python tools/pd_check.py --concurrency || exit 1

if [ "${1:-}" = "--lint" ]; then
    exit 0
fi

echo "== serving gate (engine tests + demo) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python examples/serve_gpt.py --clients 4 || exit 1
# ISSUE-12 serving tier: the full paged-KV/speculative/router test file
# (slow legs included: spec greedy parity vs model.generate, zero-retrace
# audit, 2-replica fleet with injected fault), then the router drill —
# 2 replicas, shared-system-prompt traffic -> prefix hits, zero fresh XLA
# compiles on the warm replica (persistent-cache counter), queue drains
# after the injected replica fault, zero serving retrace events
JAX_PLATFORMS=cpu python -m pytest tests/test_paged_serving.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python tools/router_drill.py || exit 1

echo "== perf gate (warm path: bench headline + persistent-cache warm start) =="
# the full warm-path file, slow-marked legs included (tier-1 excludes
# them for wall clock): a fresh process must warm previously-compiled
# programs with ZERO fresh XLA compiles (the ISSUE-3 acceptance counter)
JAX_PLATFORMS=cpu python -m pytest tests/test_warm_path.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1

echo "== streaming-offload gate (executor tests, slow legs included) =="
# overlapped-vs-serialized bit parity, pipelined group schedule (also
# under accumulate(k)), stream_wait/offload_stream telemetry, and the
# Llama-scale A/B (slow-marked for tier-1 wall clock, run here)
JAX_PLATFORMS=cpu python -m pytest tests/test_offload_executor.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
# the CPU bench smoke must emit a parseable non-null headline as its last
# line (first line is the parseable stub) within its own budget
rm -f /tmp/_bench_smoke.log
# stale telemetry must not satisfy the observability gate below
rm -f bench_artifacts/telemetry_*.json
timeout -k 10 1000 env JAX_PLATFORMS=cpu BENCH_BUDGET_S=900 \
    python bench.py > /tmp/_bench_smoke.log 2>/tmp/_bench_smoke.err || {
        echo "bench smoke failed"; tail -20 /tmp/_bench_smoke.err; exit 1; }
python - <<'PY' || exit 1
import json
lines = [l for l in open("/tmp/_bench_smoke.log") if l.strip()]
# the LAST stdout line is the contract the harness parses (the r04/r05
# blackouts): it must be valid JSON and fit the driver's ~2KB tail window
assert len(lines[-1]) < 2000, f"headline too long: {len(lines[-1])}B"
first, last = json.loads(lines[0]), json.loads(lines[-1])
assert last["value"] is not None, "bench headline is null"
disk = json.loads(open("bench_artifacts/headline.json").read())
assert disk["detail"] == last["detail"], "on-disk headline out of step"
assert "warm_path" in last["detail"], "warm-path row missing"
assert "persistent_cache" in last["detail"], "cold/warm startup row missing"
pc = last["detail"]["persistent_cache"]
assert pc["warm_fresh_xla_compiles"] == 0, pc
sc = last["detail"]["stream_capacity"]
assert sc["overlap_efficiency"] > 0, sc       # transfers actually hidden
assert sc["losses_bit_equal"] is True, sc     # hiding changed no bits
cs = last["detail"]["checkpoint_stall"]       # ISSUE-6 acceptance: async
assert cs["stall_ratio"] is not None, cs      # save stall < 25% of the
assert cs["stall_ratio"] < 0.25, cs           # synchronous save time
ap = last["detail"]["autoplan"]               # ISSUE-10 acceptance: the
assert ap["top_is_feasible"] is True, ap      # planner's top pick runs,
assert ap["top_vs_best_ratio"] is not None and \
    ap["top_vs_best_ratio"] <= 1.25, ap       # is within 1.25x of the
assert ap["beats_median"] is True, ap         # best measured candidate,
                                              # and beats the median
print("perf gate OK:", {k: last["detail"][k]
                        for k in ("warm_path", "persistent_cache",
                                  "stream_capacity", "checkpoint_stall",
                                  "autoplan")})
# ISSUE-12 acceptance: the paged serving recipe (full rows live in
# bench_progress.json — the size-capped headline may slim them)
prog = json.loads(open("bench_artifacts/bench_progress.json").read())
pg = prog["serving"]["paged_gen"]
assert pg["prefix_hit_rate"] > 0.5, pg          # shared-prefix traffic hits
assert pg["speedup_vs_cold"] >= 1.5, pg         # >=1.5x vs no-reuse baseline
assert pg["spec_acceptance"] > 0.3, pg          # the draft earns its keep
assert pg["effective_tokens_per_step"] > 1.2, pg
assert pg["fleet"]["replicas"] == 2, pg
print("paged serving gate OK:", {k: pg[k] for k in
                                 ("prefix_hit_rate", "speedup_vs_cold",
                                  "spec_acceptance",
                                  "effective_tokens_per_step")})
PY

echo "== kernels gate (ISSUE-13: Pallas fused-op layer) =="
# interpret-vs-composed parity (fwd + grad) for fused MoE dispatch,
# RMSNorm+residual, RoPE and paged attention; registry/flag seam;
# retrace-audited attention threshold; planner fused cost entries
JAX_PLATFORMS=cpu python -m pytest tests/test_pallas_kernels.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
# the bench smoke's fused-vs-composed A/B rows (full rows in
# bench_progress.json; the size-capped headline keeps the scalars)
python - <<'PY' || exit 1
import json
last = json.loads([l for l in open("/tmp/_bench_smoke.log")
                   if l.strip()][-1])
assert "fused_kernels" in last["detail"], "fused_kernels headline row missing"
prog = json.loads(open("bench_artifacts/bench_progress.json").read())
fk = prog["fused_kernels"]
for op in ("rms_norm", "rope"):                 # per-op A/B rows
    row = fk[op]
    assert row["composed_us"] > 0 and row["fused_us"] > 0, (op, row)
# ISSUE-13 acceptance: fused MoE dispatch_share <= 0.08, parity pinned
assert fk["dispatch_share_fused"] <= 0.08, fk["dispatch_share_fused"]
assert fk["dispatch_parity_max_err"] < 1e-4, fk["dispatch_parity_max_err"]
# paged decode: the fused seam is no worse than the gather path on CPU
pd = fk.get("paged_decode")
assert pd and pd["ratio"] <= 1.25, pd
print("kernels gate OK:", {"dispatch_share_fused": fk["dispatch_share_fused"],
                           "dispatch_share_index": fk["dispatch_share_index"],
                           "parity_err": fk["dispatch_parity_max_err"],
                           "rms_speedup": fk["rms_norm"]["speedup"],
                           "rope_speedup": fk["rope"]["speedup"],
                           "paged_ratio": pd["ratio"]})
PY
# the planner must re-rank or record cost deltas when fused entries are on
JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

paddle.seed(0)
m = LlamaForCausalLM(LlamaConfig.tiny())
kw = dict(n_devices=8, hbm_bytes=9.5e9, batch=16, seq=64)
off = dist.plan(m, fused_kernels=False, **kw)
on = dist.plan(m, fused_kernels=True, **kw)
by = {str(c.config): c.predicted_step_s for c in off}
deltas = [by[str(c.config)] - c.predicted_step_s
          for c in on if str(c.config) in by]
assert sum(1 for d in deltas if d > 0) >= 1, "no fused cost delta recorded"
assert on[0].breakdown.get("fused_gain_s", 0) > 0, on[0].breakdown
reranked = [c.describe() for c in off[:10]] != [c.describe() for c in on[:10]]
print("planner fused entries OK:", {
    "configs_repriced": sum(1 for d in deltas if d > 0),
    "top_reranked": reranked,
    "top_gain_ms": round(on[0].breakdown["fused_gain_s"] * 1e3, 4)})
PY

echo "== sparse gate (ISSUE-14: streamed embedding tables) =="
# cache policy determinism, streamed-vs-resident bit parity (incl.
# accumulate(k) and early-prefetch staleness), OOV policy, hapi flush,
# PS shard source, serving zero-retrace, planner term, lane row API
JAX_PLATFORMS=cpu python -m pytest tests/test_sparse_embedding.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
# the bench smoke's sparse_embed acceptance row: a table 4x the
# configured device cap trains through the hot-row cache with >= 0.8
# hit rate, losses BIT-equal to the all-resident twin, the lane hides
# some of the miss-fetch time, and the warmed serving lookup path ran
# with zero retraces / zero fresh executables
python - <<'PY' || exit 1
import json
last = json.loads([l for l in open("/tmp/_bench_smoke.log")
                   if l.strip()][-1])
assert "sparse_embed" in last["detail"], "sparse_embed headline row missing"
prog = json.loads(open("bench_artifacts/bench_progress.json").read())
se = prog["sparse_embed"]
assert se["hit_rate"] >= 0.8, se["hit_rate"]
assert se["losses_bit_equal"] is True, se
assert se["serve_zero_retrace"] is True, se
assert se["overlap_hidden_ms"] > 0, se
assert se["table_over_cap"] >= 4.0, se
assert se["streamed_over_resident"] <= 1.3, se
print("sparse gate OK:", {k: se[k] for k in
                          ("hit_rate", "streamed_over_resident",
                           "overlap_hidden_ms", "losses_bit_equal",
                           "serve_zero_retrace")})
PY

echo "== observability gate (telemetry snapshot from the bench smoke) =="
# the smoke above ran with PT_METRICS_PORT off; its per-recipe telemetry
# dump must carry the unified-hub families, with real step-timeline and
# bench rows (ISSUE-4 acceptance: the warm path is visible from outside)
python - <<'PY' || exit 1
import json
snap = json.load(open("bench_artifacts/telemetry_warm_path.json"))
for fam in ("persistent_cache", "retrace_events", "step_timeline",
            "trace_cache", "bench", "device_trace", "request_trace"):
    assert fam in snap, f"{fam} family missing from telemetry snapshot"
tl = snap["step_timeline"]
assert tl["steps"] > 0, tl
assert tl["phases"].get("compile", {}).get("count", 0) >= 1, tl["phases"]
assert tl["phases"].get("host_dispatch", {}).get("count", 0) >= 1, tl["phases"]
assert "warm_path" in snap["bench"], snap["bench"].keys()
probe = snap["bench"]["warm_path"].get("telemetry_overhead_us", {})
assert probe.get("timeline_step", 1e9) < 500, probe  # off-path overhead bound
# ISSUE-7: the warm-path capture probe must deliver XPlane device truth —
# correlated steps, >= 1 device-attributed op, real device_compute_us
dt = snap["device_trace"]
assert dt.get("steps_correlated", 0) >= 1, dt
assert dt.get("op_table"), dt
assert tl.get("device_source") == "xplane", tl.get("device_source")
assert tl.get("device_compute_us", {}).get("count", 0) >= 1, tl
# native Prometheus histogram families (ISSUE-7 satellite)
for h in ("step_time_ms", "request_latency_ms", "queue_wait_ms"):
    assert snap.get(h, {}).get("type") == "histogram", h
assert snap["step_time_ms"]["count"] > 0, snap["step_time_ms"]
print("observability gate OK:", {"steps": tl["steps"],
                                 "phases": sorted(tl["phases"]),
                                 "device_source": tl.get("device_source"),
                                 "top_op": dt["op_table"][0]["op"],
                                 "overhead_us": probe})
PY

echo "== memory-truth gate (ISSUE-8: memory family + drift bound + OOM drill) =="
# the bench smoke's telemetry dump must carry the `memory` family (per-
# device watermarks, host RSS) and a populated `memory_drift` provider
# whose predicted-vs-XLA ratio sits inside the CI bound — the estimator
# validation that makes it a trusted planner input
python - <<'PY' || exit 1
import json
snap = json.load(open("bench_artifacts/telemetry_warm_path.json"))
mem = snap["memory"]
assert mem["devices"], mem
for key, row in mem["devices"].items():
    assert row.get("watermark_bytes", 0) > 0, (key, row)
    assert "bytes_in_use" in row, (key, row)
assert mem["host"]["rss_bytes"] > 0, mem["host"]
drift = snap["memory_drift"]
assert drift["count"] >= 1, drift
assert drift.get("within_bound") is True, drift
lo, hi = drift["bound"]
assert lo <= drift["last_ratio"] <= hi, drift
wp = snap["bench"]["warm_path"].get("memory") or {}
assert wp.get("drift_ratio") is not None, wp   # measured-vs-predicted row
print("memory gate OK:", {"devices": sorted(mem["devices"]),
                          "last_ratio": drift["last_ratio"],
                          "records": drift["count"],
                          "warm_path_memory": wp})
PY
# full memory-truth test file (slow legs included), then the injected-OOM
# forensics drill: PT_FAULTS="oom@step=N" must leave a complete parseable
# bundle whose memory report names the top live buffers
JAX_PLATFORMS=cpu python -m pytest tests/test_memory_truth.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python tools/mem_drill.py || exit 1

echo "== device-truth tracing gate (ISSUE-7: capture/serving-trace/flight drills + full test file) =="
# XPlane parse round-trips, trace-ID propagation, flight-recorder
# trigger->bundle — the heavy capture tests are slow-marked for tier-1
# wall clock but run IN FULL here
JAX_PLATFORMS=cpu python -m pytest tests/test_trace.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
# the three ISSUE-7 acceptance asserts: a CPU-traced step window reports
# XPlane-correlated device_compute_us + >=1 device-attributed op; one
# serving request's spans share a trace ID end to end; an injected
# slow-transfer regression trips the flight recorder into a complete
# parseable pd_dump bundle
JAX_PLATFORMS=cpu python tools/trace_drill.py || exit 1

echo "== planner gate (ISSUE-10: cost-model auto-parallel planner) =="
# the full planner test file (enumeration divisibility, HBM pruning,
# deterministic ranking, MULTICHIP_r05 round-trip, Engine auto_plan) plus
# the blackout-round-3 bench contract tests (SIGTERM'd smoke leaves a
# parseable last line; the budget watchdog self-emits)
JAX_PLATFORMS=cpu python -m pytest tests/test_planner.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python -m pytest tests/test_fixes_r6.py -q -k bench \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
# smoke plan() on the bench tiny-Llama shape: a non-empty ranked list
# whose top pick is feasible (the autoplan headline row is asserted by
# the perf gate above)
JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

paddle.seed(0)
cands = dist.plan(LlamaForCausalLM(LlamaConfig.tiny()), n_devices=8,
                  hbm_bytes=9.5e9, batch=16, seq=64)
assert cands, "plan() returned an empty ranked list"
assert cands[0].feasible, cands[0].to_dict()
assert cands[0].predicted_step_s > 0
print("planner gate OK:", {"candidates": len(cands),
                           "top": cands[0].describe(),
                           "predicted_ms": round(
                               cands[0].predicted_step_s * 1e3, 2)})
PY
# the smoke's telemetry dump must carry the ranking-fidelity provider
# (predicted-vs-measured rank correlation — the acceptance asks for it in
# the headline AND the telemetry dump)
python - <<'PY' || exit 1
import json
snap = json.load(open("bench_artifacts/telemetry_autoplan.json"))
fid = snap["autoplan"]["fidelity"]
assert fid["rank_corr"] is not None, fid
assert fid["top_vs_best_ratio"] is not None, fid
assert snap["autoplan"]["measured"], "per-candidate measurements missing"
print("autoplan telemetry OK:", fid)
PY

echo "== resilience gate (commit protocol + kill-and-resume drill) =="
# the full resilience file (crash-mid-save injection, torn-checkpoint
# detection, in-process preempt/resume), then the cross-process half:
# a REAL kill -TERM of a training subprocess mid-run, resumed on a
# CHANGED XLA device count — stitched losses must match the
# uninterrupted run (the ISSUE-6 kill-and-resume acceptance)
JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
python tools/resilience_drill.py || exit 1

echo "== elastic gate (ISSUE-11: multi-process fleet runtime) =="
# the recovery state machine + hardened heartbeats + sync_peers
# diagnostics + supervisor failure paths (slow process legs included),
# then the end-to-end drill: a REAL 4-process jax.distributed fleet
# survives an injected worker_crash — fence, bounded restart at
# world=3, planner-selected new config, checkpoint-resumed completion,
# 0 torn checkpoints, membership timeline records eviction + restart
JAX_PLATFORMS=cpu python -m pytest tests/test_fleet_runtime.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
python tools/resilience_drill.py --fleet || exit 1

echo "== serving-fleet gate (ISSUE-15: fault-tolerant multi-process serving) =="
# the reliability protocol in-process (classified fence errors, health
# re-admission, replay dedup ledger, hedging, brownout stages, rolling
# restart, retry jitter, replica fault kinds — slow legs included:
# real-engine stream/cancel + the 2-process crash-failover e2e), then
# the REAL 3-process chaos drill: replica_crash mid-stream fenced and
# replayed bit-identically (zero lost-or-duplicated tokens), a hung
# replica fenced within the heartbeat grace window, hedged re-prefill
# first-wins, brownout walk + decay, and a rolling restart under load
# with zero failed requests; counters + timeline land in the
# serving_fleet hub provider and the telemetry dump
JAX_PLATFORMS=cpu python -m pytest tests/test_serving_fleet.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python tools/serving_fleet_drill.py || exit 1

echo "== lockdep gate (ISSUE-16: armed drills, zero lock-order cycles) =="
# concurrency lint + witness unit drills (seeded AB/BA deadlock, CC
# true-positive fixtures), then the two heaviest multi-threaded drills
# re-run with the runtime lock-order witness ARMED: each must complete
# bit-identical with a populated lockdep provider and zero cycles
JAX_PLATFORMS=cpu python -m pytest tests/test_concurrency_lint.py \
    tests/test_lockdep.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
PT_LOCKDEP=1 python tools/resilience_drill.py || exit 1
JAX_PLATFORMS=cpu PT_LOCKDEP=1 python tools/serving_fleet_drill.py || exit 1

echo "== post-training gate (ISSUE-17: rollout -> reward -> train -> publish) =="
# the weight-distribution service (roundtrip bit-equality, per-chunk +
# whole-blob digest rejection, mid-transfer crash -> resumed transfer,
# backpressure, engine apply), behavior-logprob streams (crash-mid-
# stream parity), version-pinned replay (no cross-version stitch),
# buffer/reward/trainer units — then the REAL 3-process RL drill:
# 2 serving replicas + 1 elastic_fit trainer streaming weight versions;
# reward improves on the pattern task, r1 crashes mid-rollout with zero
# lost/duplicated tokens, the final push lands under load and every
# in-flight request finishes bit-identically on a single version; the
# lockdep-armed re-run must stay cycle-free
JAX_PLATFORMS=cpu python -m pytest tests/test_post_training.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python tools/rl_drill.py || exit 1
JAX_PLATFORMS=cpu PT_LOCKDEP=1 python tools/rl_drill.py || exit 1

echo "== kv migration gate (ISSUE-18: disaggregated prefill/decode) =="
# wire-format units (pack/unpack fp32 bit-exact, int8 <= 0.55x bytes,
# chunk digests, ghost-gated fleet cache, pool-aware routing, cost
# model), the slow engine loopback (export -> pack -> install on a
# second engine, continuation BIT-identical) and in-process pooled
# fleet — then the REAL 3-process drill: 1 prefill + 2 decode replicas,
# every request migrated over the wire with zero re-prefill fallbacks,
# a decode crash failed over by re-SHIPPING the retained pages, warm
# repeats served from the fleet-wide host-RAM tier; lockdep-armed
# re-run must stay cycle-free
JAX_PLATFORMS=cpu python -m pytest tests/test_kv_migration.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python tools/kv_migration_drill.py || exit 1
JAX_PLATFORMS=cpu PT_LOCKDEP=1 python tools/kv_migration_drill.py || exit 1

echo "== fleet observability gate (ISSUE-19: traces + merged telemetry + SLO) =="
# merge-API units (bucket-wise Histogram.merge exactness, label-aware
# CounterFamily.merge, quantile/burn math, tracer drain filters,
# collector dedup) and the in-process trace edge cases (hedge loser
# cancelled under the same fleet id, failover replay leg, ledger-
# complete with no re-dispatch, migrate_fallback reason) — then the
# REAL 3-process drill: one KV-migrated request renders as a single
# merged chrome trace with spans from >=3 distinct pids under one
# fleet trace id, the merged exposition carries per-replica labels
# with the fleet sum/count EXACTLY equal to the per-replica total, and
# the slo provider reports a finite burn rate; lockdep-armed re-run
# must stay cycle-free
JAX_PLATFORMS=cpu python -m pytest tests/test_fleet_observability.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python tools/fleet_trace_drill.py || exit 1
JAX_PLATFORMS=cpu PT_LOCKDEP=1 python tools/fleet_trace_drill.py || exit 1

echo "== tuning gate (ISSUE-20: online auto-tuner closed loop) =="
# detector matrix (single spike never triggers, sustained regression
# does), quantile-cover property tests, restart-safe histogram
# windows, BucketSpec validation on derived shapes, rescore/respec
# units, OnlineTuner ledger + kill-switch — then the REAL multi-
# process drill, three legs: (serving) a workload shift drives bucket
# re-derivation applied through a rolling restart with bit-identical
# replayed streams and a confirmed keep; (plan-keep) a scripted
# slowdown trips the detector, the fleet fences PLANNED at a
# checkpoint boundary (zero restart budget), swaps plans and keeps;
# (plan-rollback) a persistent slowdown fails the post-apply measure
# and rolls back to the original digest with the candidate embargoed;
# lockdep-armed re-run must stay cycle-free
JAX_PLATFORMS=cpu python -m pytest tests/test_tuning.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
JAX_PLATFORMS=cpu python tools/tuning_drill.py || exit 1
JAX_PLATFORMS=cpu PT_LOCKDEP=1 python tools/tuning_drill.py || exit 1

echo "== tier-1 test suite =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit $rc
