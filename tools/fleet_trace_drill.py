#!/usr/bin/env python
"""Fleet-observability drill — the ISSUE-19 acceptance run.

A REAL 3-process CPU fleet split into pools (1 prefill + 2 decode
replicas, socket RPC, heartbeats through the control-plane TCPStore)
driving the fleet observability plane end to end:

1. cross-process tracing: KV-migrated requests render as SINGLE merged
   chrome traces — the supervisor's routing + wire-transfer spans and
   the replica-side prefill/decode/kv spans all land under one
   ``fleet-<id>`` trace context, with spans from >=3 DISTINCT os pids
   (supervisor, prefill replica, decode replica) in one export;
2. telemetry scrape + merge: the supervisor's collector pulls every
   replica's hub snapshot over the ``telemetry`` RPC and merges
   histogram families bucket-wise — the fleet ``request_latency_ms``
   sum/count must equal the sum of the per-replica snapshots EXACTLY;
3. SLO signals: per-pool p95/p99 and a finite burn rate computed ONLY
   from the merged buckets (no supervisor-side latency sampling);
4. exposition: the on-disk Prometheus file carries per-replica
   ``replica``/``pool`` labeled series plus the fleet aggregate and
   ``pt_fleet_slo_*`` gauges.

With ``PT_LOCKDEP=1`` the whole drill re-runs under the runtime
lock-order witness and must stay cycle-free.  Exit code 0 only when
every assertion holds.
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_CACHE_DIR = os.environ.setdefault(
    "PT_PERSISTENT_CACHE_DIR",
    tempfile.mkdtemp(prefix="pt_fleettrace_cache_"))

import numpy as np  # noqa: E402


def build_replica():
    """The replica builder (runs INSIDE each worker process): the tiny
    pattern-trained GPT every serving drill uses — cheap to build,
    deterministic across processes."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit, serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=64,
                    dtype="float32")
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-3,
                          parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y),
                         optimizer)
    ids = paddle.to_tensor(
        np.tile(np.arange(8), 8)[None, :].astype("int64"))
    for _ in range(80):
        step(ids, ids)
    return serving.GenerationEngine(
        model, serving.GenerationConfig(
            max_slots=2, max_seq_len=48, page_len=8, num_pages=48,
            prefill_buckets=(8, 16, 24, 32, 40)))


def main():
    import paddle_tpu.observability as obs
    from paddle_tpu.serving import ServingFleet, ServingFleetPolicy
    from paddle_tpu.serving.router import RouterConfig

    pattern = np.tile(np.arange(8), 8)
    work_root = tempfile.mkdtemp(prefix="pt_fleettrace_drill_")
    prom_path = os.path.join(work_root, "fleet_metrics.prom")

    policy = ServingFleetPolicy(
        heartbeat_interval=0.25, heartbeat_timeout=3.0,
        backoff_base_s=0.2, backoff_max_s=2.0, poll_interval=0.05,
        hedge_ms=None, replica_capacity=8, drain_timeout_s=30.0,
        telemetry_interval_s=0.5, slo_target_ms=2000.0,
        slo_objective=0.99, slo_window_s=60.0)
    fleet = ServingFleet(
        builder=os.path.abspath(__file__) + ":build_replica",
        n_replicas=3, names=["p0", "d0", "d1"],
        pools={"prefill": ["p0"], "decode": ["d0", "d1"]},
        min_ship_tokens=8,
        policy=policy, router_config=RouterConfig(),
        flight_root=os.path.join(work_root, "flight"),
        log_dir=os.path.join(work_root, "logs"),
        prom_path=prom_path)
    t0 = time.time()
    fleet.start(wait_ready=True, timeout=600)
    print(f"[drill] 3-process pooled fleet ready in "
          f"{time.time() - t0:.1f}s", flush=True)

    # -- load: every request crosses prefill -> wire -> decode ----------------
    futs = []
    for i in range(6):
        plen = 16 + (i % 2) * 8
        mx = 4 + (i % 3)
        prompt = pattern[(i * 3) % 8:(i * 3) % 8 + plen].astype(np.int64)
        streamed = []
        futs.append((plen, mx, streamed,
                     fleet.submit(prompt, max_new_tokens=mx,
                                  on_token=streamed.append)))
    for plen, mx, streamed, fut in futs:
        out = fut.result(timeout=300).tolist()
        assert len(out) == plen + mx, (plen, mx, out)
        assert streamed == out[plen:], "stream dup/loss"
    n = len(futs)
    snap = fleet.provider_snapshot()
    assert snap["counters"].get("migrations", 0) >= 1, snap["counters"]
    print(f"[drill] load ok: {n} requests migrated prefill->decode",
          flush=True)

    # -- 1. one merged chrome trace spanning >=3 real processes ---------------
    trace_path = os.path.join(work_root, "fleet_trace.json")
    best_fid, best_pids = None, {}
    deadline = time.time() + 30
    while time.time() < deadline:
        fleet.export_fleet_trace(trace_path)
        for fid in fleet.traces.merged():
            pids = fleet.traces.span_pids(fid)
            if len(pids) > len(best_pids):
                best_fid, best_pids = fid, pids
        if len(best_pids) >= 3:
            break
        time.sleep(0.25)
    assert best_fid is not None and best_fid.startswith("fleet-"), best_fid
    assert len(best_pids) >= 3, \
        f"want spans from >=3 pids under one fleet trace, got {best_pids}"
    sup_pid = os.getpid()
    assert sup_pid in best_pids, (sup_pid, best_pids)
    assert "route" in best_pids[sup_pid], best_pids[sup_pid]
    assert "wire_transfer" in best_pids[sup_pid], \
        ("supervisor wire-transfer span missing", best_pids[sup_pid])
    # the export file itself carries the same >=3-pid trace
    with open(trace_path) as f:
        doc = json.load(f)
    ev_pids = {e["pid"] for e in doc["traceEvents"]
               if e.get("ph") == "X"
               and e.get("args", {}).get("fleet") == best_fid}
    assert len(ev_pids) >= 3, ev_pids
    col = fleet.traces.snapshot()
    assert col["fleet_traces"] >= n, col
    print(f"[drill] trace ok: fleet trace {best_fid} spans "
          f"{len(best_pids)} pids "
          f"({ {p: len(s) for p, s in best_pids.items()} } spans/pid); "
          f"export carries {col['traces']} traces from "
          f"{col['pids']} pids", flush=True)

    # -- 2. scrape + EXACT bucket-wise merge ----------------------------------
    merged = fleet.scrape_now()
    rows = merged["replicas"]
    assert set(rows) == {"p0", "d0", "d1"}, rows
    worker_pids = {r["pid"] for r in rows.values()}
    assert len(worker_pids) == 3 and sup_pid not in worker_pids, rows
    assert rows["p0"]["pool"] == "prefill", rows["p0"]
    assert merged["merge_errors"] == [], merged["merge_errors"]
    lat = merged["histograms"]["request_latency_ms"]
    per_rep = lat["per_replica"]
    assert lat["fleet"]["count"] == \
        sum(s["count"] for s in per_rep.values()), lat
    assert lat["fleet"]["sum_exact"] == \
        sum(s["sum_exact"] for s in per_rep.values()), \
        "fleet histogram sum must be the EXACT per-replica total"
    # every request produced one prefill-leg and one decode-leg latency
    assert lat["fleet"]["count"] >= 2 * n, lat["fleet"]["count"]
    assert set(lat["per_pool"]) == {"prefill", "decode"}, lat["per_pool"]
    print(f"[drill] merge ok: fleet request_latency_ms count="
          f"{lat['fleet']['count']} == sum of {len(per_rep)} replica "
          f"snapshots, sum_exact matches bit-for-bit", flush=True)

    # -- 3. SLO signals from merged buckets only ------------------------------
    slo = fleet.slo_snapshot()
    assert slo["target_ms"] == 2000.0, slo
    f = slo["fleet"]
    assert np.isfinite(f["burn_rate"]) and f["burn_rate"] >= 0.0, f
    assert np.isfinite(f["p95_ms"]) and f["p95_ms"] > 0.0, f
    assert f["count_total"] == lat["fleet"]["count"], \
        "slo counts must come from the merged histogram, nothing else"
    for pool in ("prefill", "decode"):
        pv = slo["pools"][pool]
        assert np.isfinite(pv["p95_ms"]) and pv["count_total"] >= n, pv
    print(f"[drill] slo ok: fleet p95={f['p95_ms']}ms "
          f"p99={f['p99_ms']}ms burn={f['burn_rate']} "
          f"(decode p95={slo['pools']['decode']['p95_ms']}ms)",
          flush=True)

    # -- 4. labeled exposition on disk ----------------------------------------
    assert os.path.exists(prom_path), prom_path
    with open(prom_path) as fh:
        text = fh.read()
    for rep in ("p0", "d0", "d1"):
        assert f'replica="{rep}"' in text, f"missing {rep} labels"
    assert 'pool="decode"' in text and 'pool="prefill"' in text, text[:400]
    assert "pt_request_latency_ms_count" in text
    assert "pt_fleet_slo_p95_ms" in text, "fleet p95 gauge missing"
    assert "pt_fleet_slo_burn_rate" in text
    print(f"[drill] exposition ok: {prom_path} carries per-replica "
          f"labels + fleet SLO gauges ({len(text.splitlines())} lines)",
          flush=True)

    # -- hub providers + lockdep ----------------------------------------------
    hub = obs.snapshot()
    assert hub["fleet_telemetry"]["totals"]["replicas"] == 3
    assert hub["slo"]["fleet"]["count_total"] >= 2 * n
    assert hub["fleet_trace"]["pids"] >= 3, hub["fleet_trace"]
    if os.environ.get("PT_LOCKDEP", "") not in ("", "0", "false"):
        ld = hub.get("lockdep")
        assert ld and ld.get("armed"), \
            "PT_LOCKDEP=1 but the lockdep provider is missing/disarmed"
        assert ld["cycles"] == [], f"lock-order cycles: {ld['cycles']}"
        assert ld["locks"], "lockdep witnessed no locks"
        print(f"[drill] lockdep ok: {len(ld['locks'])} witnessed locks, "
              f"{len(ld['edges'])} order edges, zero cycles", flush=True)

    fleet.close()
    headline = {
        "replicas": {"prefill": 1, "decode": 2},
        "completed": snap["counters"]["completed"],
        "fleet_traces": col["fleet_traces"],
        "trace_pids": sorted(best_pids),
        "merged_count": lat["fleet"]["count"],
        "fleet_p95_ms": f["p95_ms"],
        "burn_rate": f["burn_rate"],
        "scrapes": merged.get("scraped_at") is not None,
    }
    print("FLEET_TRACE_DRILL_OK " + json.dumps(headline), flush=True)
    shutil.rmtree(work_root, ignore_errors=True)


if __name__ == "__main__":
    main()
