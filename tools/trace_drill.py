#!/usr/bin/env python
"""trace_drill: the device-truth tracing acceptance drill (ISSUE-7).

Three asserts, run by tools/ci.sh's observability gate:

1. **XPlane correlation** — a CPU-run traced step window reports
   ``device_compute_us`` from XPlane correlation (not the host-block
   fallback), with step phases correlated and >= 1 device-attributed op
   in the op table.
2. **Request-scoped tracing** — a serving run exports a chrome trace in
   which one request's spans (admission -> queue -> batch_coalesce ->
   execute) share a single trace ID.
3. **Flight recorder** — an injected step-time regression
   (``PT_FAULTS="slow_transfer@..."`` slowing a streaming-lane transfer
   in a subprocess) trips the anomaly detector and produces a complete,
   parseable ``pd_dump`` bundle.

    python tools/trace_drill.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _drill_capture() -> dict:
    """Drill 1: XPlane-correlated step/op attribution on a real capture."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as popt
    from paddle_tpu import jit
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import trace

    obs.timeline().reset()
    net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 1))
    opt = popt.Adam(learning_rate=0.01, parameters=net.parameters())
    step = jit.TrainStep(net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
    x = paddle.to_tensor(np.ones((8, 16), np.float32))
    y = paddle.to_tensor(np.zeros((8, 1), np.float32))
    step(x, y)  # compile outside the capture window
    with trace.capture_steps() as cap:
        for _ in range(4):
            float(step(x, y))  # the loss read syncs each step
    assert cap.error is None, cap.error
    cor = cap.result
    assert cor.steps_correlated >= 3, cor.summary()
    assert cor.op_table, "no device-attributed ops"
    assert any(s["phases"] for s in cor.steps), "no correlated step phases"
    tl = obs.timeline().summary()
    assert tl["device_source"] == "xplane", tl["device_source"]
    assert tl["device_compute_us"]["count"] >= 3, tl["device_compute_us"]
    snap = obs.snapshot()["device_trace"]
    assert snap["op_table"], snap
    return {"steps_correlated": cor.steps_correlated,
            "top_op": cor.op_table[0]["op"],
            "device_us_avg": tl["device_compute_us"]["avg"],
            "overlap_efficiency": cor.overlap_efficiency()}


def _drill_serving() -> dict:
    """Drill 2: one request's spans share a trace ID, end to end."""
    import numpy as np

    from paddle_tpu import serving
    from paddle_tpu.observability.trace import tracer

    eng = serving.ServingEngine(
        lambda x: x * 2.0, buckets=serving.BucketSpec(batch_sizes=(1, 4)),
        input_specs=[((8,), "float32")], name="drill_eng")
    with eng:
        futs = [eng.submit([np.full(8, i, np.float32)]) for i in range(8)]
        for f in futs:
            f.result(timeout=60)
    path = os.path.join(tempfile.mkdtemp(prefix="pt_drill_"),
                        "requests.trace.json")
    tracer().export_chrome(path)
    with open(path) as f:
        events = [e for e in json.load(f)["traceEvents"]
                  if e.get("ph") == "X"]
    assert events, "empty request trace export"
    by_id: dict = {}
    for e in events:
        tid = e.get("args", {}).get("trace_id")
        assert tid, f"span without trace_id: {e}"
        by_id.setdefault(tid, set()).add(e["name"])
    want = {"admission", "queue", "batch_coalesce", "execute"}
    full = [t for t, names in by_id.items() if want <= names]
    assert full, f"no request carries the full span chain: {by_id}"
    return {"requests_traced": len(by_id), "full_chain": len(full),
            "export": path}


_CHILD_STEPS = 12
_SLOW_SEQ = 8


def _flight_child() -> None:
    """Subprocess body for drill 3 (PT_FAULTS armed by the parent): a
    streaming-lane transfer + ~10ms of deterministic host work per step
    (a sub-ms baseline would let scheduler jitter on a loaded CI box trip
    the detectors before the injected fault); the injected slow_transfer
    turns one step into a regression + stall spike."""
    import numpy as np

    from paddle_tpu.jit.offload_stream import StreamLane
    from paddle_tpu.observability import timeline
    from paddle_tpu.observability.trace import flight_recorder

    rec = flight_recorder(min_steps=4, regress_factor=3.0,
                          min_dump_interval_s=0.0)
    tl = timeline()
    lane = StreamLane(overlap=True)
    arr = np.ones((256, 256), np.float32)
    for _ in range(_CHILD_STEPS):
        with tl.step():
            h = lane.submit("h2d", [arr], [None])
            time.sleep(0.01)  # the step's "compute"
            with tl.phase("stream_wait"):
                h.wait()
    snap = rec.snapshot()
    print(json.dumps({
        "anomalies": [a["reason"] for a in snap["anomalies"]],
        "dumps": [{"path": d["path"], "reason": d["reason"]}
                  for d in snap["dumps"]],
        "ring_ms": [r["ms"] for r in snap["ring"]],
    }))


def _drill_flight() -> dict:
    """Drill 3: PT_FAULTS slow-transfer -> anomaly -> pd_dump bundle."""
    out = tempfile.mkdtemp(prefix="pt_flight_")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PT_FAULTS": f"slow_transfer@seq={_SLOW_SEQ}&ms=400",
        "PT_FLIGHT_DIR": out,
    })
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--flight-child"],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert any(r.startswith(("step_regression", "stall_spike"))
               for r in report["anomalies"]), report
    hits = [d for d in report["dumps"]
            if d["reason"].startswith(("step_regression", "stall_spike"))]
    assert hits, f"anomaly fired but no bundle: {report}"
    bundle = hits[0]["path"]
    with open(os.path.join(bundle, "MANIFEST.json")) as f:
        manifest = json.load(f)
    for name in ("snapshot.json", "flight_ring.json", "config.json"):
        assert name in manifest["files"], manifest
        assert "error" not in manifest["files"][name], manifest
        with open(os.path.join(bundle, name)) as fh:
            json.load(fh)  # parseable
    ring = json.load(open(os.path.join(bundle, "flight_ring.json")))
    spike = max(r["ms"] for r in ring["ring"])
    assert spike >= 400, f"ring missed the injected 400ms stall: {spike}"
    return {"anomalies": report["anomalies"][:2], "bundle": bundle,
            "spike_ms": round(spike, 1)}


def main() -> int:
    if "--flight-child" in sys.argv:
        _flight_child()
        return 0
    results = {}
    for name, fn in (("capture", _drill_capture),
                     ("serving", _drill_serving),
                     ("flight", _drill_flight)):
        results[name] = fn()
        print(f"trace_drill [{name}] OK: {results[name]}")
    print("trace_drill: all three acceptance drills passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
