#!/usr/bin/env python
"""mem_drill: the injected-OOM forensics acceptance drill (ISSUE-8).

Spawns a real training subprocess armed with ``PT_FAULTS="oom@step=N"``
(the deterministic RESOURCE_EXHAUSTED twin) and verifies the crash left a
complete, parseable diagnostic bundle behind:

- the child process died with the OOM (forensics must not eat the crash);
- the bundle honors the MANIFEST-last contract (a manifest present ==
  every section accounted for);
- ``memory_report.json`` names the top live buffers by
  shape/dtype/sharding, carries the failing step's static live-range
  estimate (drift record) and the watermark history;
- the flight ring's steps carry per-step memory stamps.

Run directly (``python tools/mem_drill.py``) or via tools/ci.sh's memory
gate.
"""
from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OOM_STEP = 2


def child() -> int:
    """Train a tiny model with hapi fit until the armed OOM fires."""
    import numpy as np

    import paddle_tpu as pd
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt_mod
    from paddle_tpu.hapi import Model

    rng = np.random.default_rng(0)
    xs = rng.standard_normal((16, 8)).astype("float32")
    ys = rng.standard_normal((16, 4)).astype("float32")
    data = [(xs[i:i + 2], ys[i:i + 2]) for i in range(0, 16, 2)]

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = Model(net)
    model.prepare(optimizer=opt_mod.Adam(parameters=net.parameters(),
                                         learning_rate=1e-3),
                  loss=lambda out, y: ((out - y) ** 2).mean())
    try:
        model.fit(data, epochs=2, verbose=0)
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            print(f"child: OOM fired as scripted: {e}", file=sys.stderr)
            return 17  # the expected death
        raise
    print("child: trained to completion — the oom rule never fired",
          file=sys.stderr)
    return 0


def main() -> int:
    if "--child" in sys.argv:
        return child()

    flight_dir = tempfile.mkdtemp(prefix="pt_mem_drill_")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PT_FAULTS": f"oom@step={OOM_STEP}",
        "PT_FLIGHT_DIR": flight_dir,
        "PT_MEMORY_DRIFT": "1",  # the bundle must carry the static estimate
    })
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 17, (
        f"child rc={proc.returncode} (wanted the scripted OOM death)\n"
        f"stderr:\n{proc.stderr[-2000:]}")

    bundles = sorted(glob.glob(os.path.join(flight_dir, "pd_dump_*")))
    assert bundles, f"no bundle under {flight_dir}"
    bundle = next((b for b in bundles
                   if json.load(open(os.path.join(b, "MANIFEST.json")))
                   ["reason"].startswith("oom:")), None)
    assert bundle is not None, f"no oom-reason bundle among {bundles}"

    # MANIFEST-last contract: manifest present == bundle complete, every
    # section it names exists on disk (or carries an explicit error row)
    manifest = json.load(open(os.path.join(bundle, "MANIFEST.json")))
    for name, meta in manifest["files"].items():
        assert "error" in meta or os.path.exists(os.path.join(bundle, name)), \
            f"manifest names {name} but it is missing"
    assert "memory_report.json" in manifest["files"], manifest["files"]

    report = json.load(open(os.path.join(bundle, "memory_report.json")))
    oom = report["oom"]
    assert oom["site"] == "fit" and oom["ids"].get("step") == str(OOM_STEP), oom
    top = oom["top_live_buffers"]["top"]
    assert top, "memory report names no live buffers"
    for row in top:
        assert {"shape", "dtype", "sharding", "count",
                "total_bytes"} <= set(row), row
    assert oom["top_live_buffers"]["live_bytes"] > 0
    # the failing run's static live-range estimate rode along (drift armed)
    drift = report["drift"]
    assert drift["count"] >= 0 and "bound" in drift, drift
    # monitor truth: per-device rows + host RSS + watermark history
    mon = report["monitor"]
    assert mon["devices"] and mon["host"]["rss_bytes"] > 0, mon
    assert any(r.get("watermark_bytes", 0) >= 0
               for r in mon["devices"].values())

    # flight ring steps carry memory stamps (the fit steps before the OOM)
    ring = json.load(open(os.path.join(bundle, "flight_ring.json")))
    stamped = [r for r in ring["ring"] if r.get("mem")]
    assert stamped, "no memory-stamped steps in the flight ring"
    assert all(k in stamped[-1]["mem"]
               for k in ("in_use", "watermark", "host_rss"))

    print(json.dumps({
        "mem_drill": "OK",
        "bundle": os.path.basename(bundle),
        "oom_site": oom["site"],
        "top_buffer": top[0],
        "ring_steps_stamped": len(stamped),
    }, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
