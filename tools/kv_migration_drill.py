#!/usr/bin/env python
"""KV-migration drill — the ISSUE-18 acceptance run.

A REAL 3-process CPU fleet split into pools (1 prefill + 2 decode
replicas, socket RPC, heartbeats through the control-plane TCPStore)
driving the disaggregated serving path end to end:

1. migration: every eligible request runs its prefill leg (exactly one
   token) on the prefill replica, its paged-KV pages are packed,
   chunked, SHA-verified and installed on a decode replica over the
   fleet wire protocol, and the decode leg continues the stream —
   every request BIT-IDENTICAL to the uninterrupted
   ``model.generate`` reference, with ZERO re-prefill fallbacks;
2. failover by page ship: a decode replica hard-crashes mid-decode ⇒
   the supervisor re-ships the retained pages to the surviving decode
   replica and replays there (counter-asserted ``failover_ship``, not
   re-prefill), streams still exact; the crashed replica restarts and
   is re-admitted;
3. warm tier: repeats of one prompt hit the fleet-wide host-RAM cache
   (ghost-gated admission: export twice, then serve from RAM) —
   ``warm_hits`` counted, streams still exact;
4. the ``kv_migration`` hub provider and the telemetry dump carry the
   ship/install/failover/warm counters and the pool map.

With ``PT_LOCKDEP=1`` the whole drill re-runs under the runtime
lock-order witness and must stay cycle-free.  Exit code 0 only when
every assertion holds.
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_CACHE_DIR = os.environ.setdefault(
    "PT_PERSISTENT_CACHE_DIR",
    tempfile.mkdtemp(prefix="pt_kvmig_cache_"))  # restarts warm from it

import numpy as np  # noqa: E402


def build_replica():
    """The replica builder (runs INSIDE each worker process): a tiny
    pattern-trained GPT — every process builds bit-identical weights
    from the same seeded recipe, which is what makes the shipped-pages
    continuation bit-identical under greedy decoding."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit, serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=64,
                    dtype="float32")
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-3,
                          parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y),
                         optimizer)
    ids = paddle.to_tensor(
        np.tile(np.arange(8), 8)[None, :].astype("int64"))
    for _ in range(80):
        step(ids, ids)
    # buckets reach 40: a decode leg re-prefilling prompt+progress after
    # a failover must still fit (16-token prompt + up to 20 generated)
    return serving.GenerationEngine(
        model, serving.GenerationConfig(
            max_slots=2, max_seq_len=48, page_len=8, num_pages=48,
            prefill_buckets=(8, 16, 24, 32, 40)))


def main():
    import paddle_tpu as paddle
    import paddle_tpu.observability as obs
    from paddle_tpu.serving import ServingFleet, ServingFleetPolicy
    from paddle_tpu.serving.router import RouterConfig

    pattern = np.tile(np.arange(8), 8)
    work_root = tempfile.mkdtemp(prefix="pt_kvmig_drill_")

    t0 = time.time()
    ref_model = build_replica().model
    print(f"[drill] reference model built in {time.time() - t0:.1f}s",
          flush=True)

    def expect(prompt, max_new):
        return np.asarray(ref_model.generate(
            paddle.to_tensor(np.asarray(prompt, np.int64)[None]),
            max_new_tokens=max_new, use_cache=True).numpy())[0].tolist()

    # deterministic chaos, armed by env so the WORKERS inherit it: d0
    # hard-exits at its 3rd submit (phase-2 decode legs land 3 in-flight
    # streams on it).  inc=0 pins the rule to the first incarnation so
    # the restarted worker serves instead of crash-looping.
    os.environ["PT_FAULTS"] = "replica_crash@name=d0&seq=3&inc=0"

    # hedging OFF: the failover must cross the SHIP path, not a hedge
    policy = ServingFleetPolicy(
        heartbeat_interval=0.25, heartbeat_timeout=3.0,
        backoff_base_s=0.2, backoff_max_s=2.0, poll_interval=0.05,
        hedge_ms=None, replica_capacity=8, drain_timeout_s=30.0)
    fleet = ServingFleet(
        builder=os.path.abspath(__file__) + ":build_replica",
        n_replicas=3, names=["p0", "d0", "d1"],
        pools={"prefill": ["p0"], "decode": ["d0", "d1"]},
        min_ship_tokens=8,
        policy=policy, router_config=RouterConfig(),
        flight_root=os.path.join(work_root, "flight"),
        log_dir=os.path.join(work_root, "logs"))
    t0 = time.time()
    fleet.start(wait_ready=True, timeout=600)
    print(f"[drill] 3-process pooled fleet ready in "
          f"{time.time() - t0:.1f}s", flush=True)

    def run_load(jobs, tag):
        """Submit, collect streams, assert EXACT sequences and an
        exactly-once stream per request."""
        futs = []
        for off, plen, mx in jobs:
            prompt = pattern[off:off + plen].astype(np.int64)
            streamed = []
            fut = fleet.submit(prompt, max_new_tokens=mx,
                               on_token=streamed.append)
            futs.append((prompt, mx, streamed, fut))
        for prompt, mx, streamed, fut in futs:
            out = fut.result(timeout=300).tolist()
            want = expect(prompt, mx)
            assert out == want, (tag, prompt.tolist(), out, want)
            assert streamed == out[len(prompt):], \
                (tag, "stream dup/loss", streamed, out[len(prompt):])
        return len(futs)

    # -- phase 1: migration, bit-identical, zero fallbacks --------------------
    # distinct >=2-page prompts; every one is prefill-pool eligible
    # (plen >= min_ship_tokens=8, max_new > 1)
    jobs = [((i * 3) % 8, 16 + (i % 2) * 8, 6 + (i % 3))
            for i in range(8)]
    n = run_load(jobs, "migrate_phase")
    snap = fleet.provider_snapshot()
    mig = fleet.kv_migration_snapshot()
    assert snap["counters"].get("prefill_handoffs", 0) >= n, \
        snap["counters"]
    assert snap["counters"].get("migrations", 0) >= n, snap["counters"]
    assert mig["migrate_fallback"] == 0, mig
    assert mig["ships"] >= n and mig["installs"] >= n, mig
    assert mig["pages_shipped"] >= 2 * n, mig
    assert mig["pools"] == {"p0": "prefill", "d0": "decode",
                            "d1": "decode"}, mig["pools"]
    print(f"[drill] phase 1 ok: {n} requests exact through "
          f"prefill->decode migration "
          f"(ships={mig['ships']}, pages={mig['pages_shipped']}, "
          f"wire={mig['wire_bytes']}B, fallbacks=0)", flush=True)

    # -- phase 2: decode crash -> failover by page SHIP, not re-prefill -------
    # 6 long decode legs spread over d0/d1; d0 dies at its 3rd submit
    # with in-flight streams that must replay on d1 from shipped pages
    n = run_load([((i * 5) % 8, 16, 18 + (i % 3)) for i in range(6)],
                 "failover_phase")
    mig = fleet.kv_migration_snapshot()
    assert mig["failover_ship"] >= 1, mig
    assert mig["failover_reprefill"] == 0, mig
    deadline = time.time() + 60
    while time.time() < deadline:
        snap = fleet.provider_snapshot()
        if snap["replicas"]["d0"]["state"] == "ready" and \
                snap["replicas"]["d0"]["incarnation"] >= 1:
            break
        time.sleep(0.2)
    snap = fleet.provider_snapshot()
    assert snap["replicas"]["d0"]["state"] == "ready", snap["replicas"]
    assert snap["counters"].get("fences", 0) >= 1, snap["counters"]
    print(f"[drill] phase 2 ok: {n} requests exact through a decode "
          f"crash; failover re-shipped pages "
          f"(failover_ship={mig['failover_ship']}, reprefill=0); "
          f"d0 fenced+restarted+re-admitted", flush=True)

    # -- phase 3: repeats hit the fleet-wide warm tier ------------------------
    # one fixed 4-page prompt, 4 sequential submits: export #1 feeds the
    # ghost counter, #2 admits the payload, #3/#4 serve from host RAM
    before = fleet.kv_migration_snapshot()
    for _ in range(4):
        run_load([(0, 32, 6)], "warm_phase")
    mig = fleet.kv_migration_snapshot()
    warm_delta = mig["warm_hits"] - before["warm_hits"]
    export_delta = mig["exports"] - before["exports"]
    assert warm_delta >= 1, (before, mig)
    assert export_delta <= 3, (before, mig)
    assert mig["warm_cache"]["entries"] >= 1, mig["warm_cache"]
    print(f"[drill] phase 3 ok: 4 repeat submits exact, "
          f"{warm_delta} warm hits, {export_delta} exports "
          f"(cache: {mig['warm_cache']['entries']} entries, "
          f"{mig['warm_cache']['bytes']}B)", flush=True)

    # -- provider + telemetry dump --------------------------------------------
    hub = obs.snapshot()["kv_migration"]
    assert hub["ships"] >= 1 and hub["transit"] == "fp32", hub
    dump_path = os.path.join(work_root, "telemetry.json")
    obs.dump(dump_path)
    with open(dump_path) as f:
        tele = json.load(f)
    km = tele["kv_migration"]
    assert km["ships"] >= 1 and km["pools"], \
        "kv_migration provider missing from the telemetry dump"
    print("[drill] telemetry ok: kv_migration provider in dump")
    if os.environ.get("PT_LOCKDEP", "") not in ("", "0", "false"):
        ld = tele.get("lockdep")
        assert ld and ld.get("armed"), \
            "PT_LOCKDEP=1 but the lockdep provider is missing/disarmed"
        assert ld["cycles"] == [], f"lock-order cycles: {ld['cycles']}"
        assert ld["locks"], "lockdep witnessed no locks"
        print(f"[drill] lockdep ok: {len(ld['locks'])} witnessed locks, "
              f"{len(ld['edges'])} order edges, zero cycles", flush=True)

    snap = fleet.provider_snapshot()
    fleet.close()
    headline = {
        "replicas": {"prefill": 1, "decode": 2},
        "completed": snap["counters"]["completed"],
        "prefill_handoffs": snap["counters"]["prefill_handoffs"],
        "migrations": snap["counters"]["migrations"],
        "ships": mig["ships"],
        "pages_shipped": mig["pages_shipped"],
        "wire_mb": round(mig["wire_bytes"] / 1e6, 3),
        "failover_ship": mig["failover_ship"],
        "failover_reprefill": mig["failover_reprefill"],
        "migrate_fallback": mig["migrate_fallback"],
        "warm_hits": mig["warm_hits"],
        "stream_mismatch": snap["counters"].get("stream_mismatch", 0),
    }
    assert headline["stream_mismatch"] == 0, headline
    print("KV_MIGRATION_DRILL_OK " + json.dumps(headline), flush=True)
    shutil.rmtree(work_root, ignore_errors=True)


if __name__ == "__main__":
    main()
