"""Latency-hiding streaming executor (ISSUE-5): the offload train path
streams params/optimizer state per GROUP through a double-buffered
host<->device lane instead of round-tripping the whole set serialized.
On the CPU test backend both "host" and "device" are the same chip, so
overlap buys no wall clock here — these tests pin NUMERICS (overlapped
bit-equal to serialized), the group SCHEDULE (pipelined submission
order, also under accumulate(k)), and the telemetry/analysis surfaces;
the latency story is bench.py's stream_capacity A/B."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.jit.offload_stream import StreamLane, plan_stream_groups

# group sizing that forces a multi-group walk on the tiny test net
_KNOBS = dict(segment_size=2048, buffer_max_size=4096)


# -- planner ------------------------------------------------------------------

def test_plan_stream_groups_coalesce_order_and_cap():
    # small params coalesce until segment_size, never growing past the cap
    groups = plan_stream_groups([2048, 128, 2048, 64], 2048, 4096)
    assert groups == [[0], [1, 2], [3]]
    # partition: every index exactly once, walk order preserved
    flat = [i for g in groups for i in g]
    assert flat == list(range(4))
    # one param larger than the cap still gets its own (unsplittable) group
    assert plan_stream_groups([10 ** 9, 64], 2048, 4096) == [[0], [1]]
    # everything fits one segment -> one group
    assert plan_stream_groups([10, 10, 10], 2 ** 20, 2 ** 23) == [[0, 1, 2]]


# -- lane ---------------------------------------------------------------------

@pytest.mark.parametrize("overlap", [True, False])
def test_stream_lane_counters(overlap):
    import jax

    cpu = jax.devices("cpu")[0]
    lane = StreamLane(overlap=overlap)
    try:
        a = np.ones((256,), np.float32)
        h = lane.submit("h2d", [a, a], cpu, tag=0)
        out = h.wait()
        assert len(out) == 2 and float(out[0][0]) == 1.0
        lane.submit("d2h", [out[0]], cpu, tag=0).wait()
        s = lane.stats()
        assert s["h2d_bytes"] == 2 * a.nbytes
        assert s["d2h_bytes"] == a.nbytes
        assert s["transfers"] == 2
        assert s["overlap"] is overlap
        assert 0.0 <= s["overlap_efficiency"] <= 1.0
        if not overlap:
            # inline transfers: the consumer waited for every ms
            assert s["overlap_efficiency"] == 0.0
        assert lane.events == [("h2d", 0), ("d2h", 0)]
    finally:
        lane.close()


def test_stream_lane_error_surfaces_at_wait():
    lane = StreamLane(overlap=True)
    try:
        bad = lane.submit("h2d", [object()], None, tag=9)
        with pytest.raises(Exception):
            bad.wait()
    finally:
        lane.close()


# -- the executor -------------------------------------------------------------

def _stream_run(overlap, accumulate=0, steps=4, level="os_g", clip=None,
                eager=True):
    """One offload training run with the lane forced (non-)overlapping;
    returns losses, final params, and the step object (mesh torn down)."""
    paddle.seed(7)
    dist.reset_mesh()
    dist.init_mesh(dp=2, sharding=4)
    net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    o = opt.AdamW(learning_rate=0.02, parameters=net.parameters(),
                  grad_clip=clip)
    model, o = dist.group_sharded_parallel(net, o, level=level, offload=True,
                                           **_KNOBS)
    step = dist.ShardedTrainStep(net, lambda m, x, y: F.mse_loss(m(x), y), o)
    step._stream_overlap = overlap
    step._stream_eager = eager
    if accumulate:
        step = step.accumulate(accumulate)
    x = paddle.to_tensor(np.random.RandomState(3).rand(8, 16).astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(4).rand(8, 16).astype("float32"))
    losses = [float(step(x, y)) for _ in range(steps)]
    params = [np.asarray(p.data) for p in net.parameters()]
    inner = step._step if accumulate else step
    dist.reset_mesh()
    return losses, params, inner


@pytest.mark.dist
def test_overlapped_bit_equal_to_serialized():
    """The acceptance parity: same executables, same dispatch order —
    hiding the transfers must not change a single bit. Includes a
    global-norm clip, which the executor hoists out of the per-group
    updates (clipping one group's grads alone would be wrong)."""
    clip = nn.ClipGradByGlobalNorm(0.5)
    ov_l, ov_p, ov_step = _stream_run(True, clip=clip)
    se_l, se_p, se_step = _stream_run(False, clip=clip)
    assert ov_l == se_l  # float-exact
    for a, b in zip(ov_p, se_p):
        np.testing.assert_array_equal(a, b)
    assert ov_l[-1] < ov_l[0]
    # multi-group walk actually happened, and only the overlapped lane hid
    # transfer time behind compute
    assert len(ov_step._stream[0]) >= 2
    assert ov_step.stream_stats()["overlap_efficiency"] > 0.0
    assert se_step.stream_stats()["overlap_efficiency"] == 0.0


@pytest.mark.dist
def test_group_schedule_is_pipelined():
    """While group i's update computes, group i+1's grads are already
    going down and group i-1's params up — pinned via the lane's
    submission log."""
    _, _, step = _stream_run(True, steps=2)
    groups = step._stream[0]
    g = len(groups)
    assert g >= 3, "knobs must force a multi-group walk"
    sched = step.stream_schedule()
    per_step = len(sched) // 2
    one = sched[:per_step]
    assert sched[per_step:] == one  # schedule is stable across steps
    downs = [tag for kind, tag in one if kind == "d2h"]
    ups = [tag for kind, tag in one if kind == "h2d"]
    assert downs == list(range(g)) and ups == list(range(g))
    for gi in range(g):
        # a group's grads go down before its params come back up
        assert one.index(("d2h", gi)) < one.index(("h2d", gi))
        if gi + 1 < g:
            # the NEXT group's download is in flight before this group's
            # upload — the double buffer, not a serial round-trip
            assert one.index(("d2h", gi + 1)) < one.index(("h2d", gi))


@pytest.mark.dist
def test_accumulate_composes_with_streaming_offload():
    """step.accumulate(k) on the offload path: one fused fwd+bwd window,
    then the SAME per-group streaming update — bit-equal overlapped vs
    serialized, same pipelined schedule, and allclose to the resident
    fused accumulate."""
    ov_l, ov_p, ov_step = _stream_run(True, accumulate=2)
    se_l, se_p, _ = _stream_run(False, accumulate=2)
    assert ov_l == se_l
    for a, b in zip(ov_p, se_p):
        np.testing.assert_array_equal(a, b)
    sched = ov_step.stream_schedule()
    g = len(ov_step._stream[0])
    one = sched[:len(sched) // 4]
    assert [t for k, t in one if k == "d2h"] == list(range(g))

    # resident twin (no offload) of the same window
    paddle.seed(7)
    dist.reset_mesh()
    dist.init_mesh(dp=2, sharding=4)
    net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    o = opt.AdamW(learning_rate=0.02, parameters=net.parameters())
    model, o = dist.group_sharded_parallel(net, o, level="os_g")
    step = dist.ShardedTrainStep(
        net, lambda m, x, y: F.mse_loss(m(x), y), o).accumulate(2)
    x = paddle.to_tensor(np.random.RandomState(3).rand(8, 16).astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(4).rand(8, 16).astype("float32"))
    res_l = [float(step(x, y)) for _ in range(4)]
    dist.reset_mesh()
    np.testing.assert_allclose(ov_l, res_l, rtol=2e-5)


@pytest.mark.dist
def test_offload_stream_observability():
    """The lane shows up from the outside: ``offload_stream`` counter
    family carries the bytes, the step timeline gains a ``stream_wait``
    phase, and both land in the one-JSON snapshot."""
    import paddle_tpu.observability as obs

    fam = obs.family("offload_stream")
    tl = obs.timeline()
    tl.reset()
    h2d0 = fam.get(("h2d_bytes",))
    _, _, step = _stream_run(True, steps=2)
    assert fam.get(("h2d_bytes",)) > h2d0
    assert fam.get(("transfers",)) > 0
    s = tl.summary()
    assert s["steps"] == 2
    assert s["phases"]["stream_wait"]["count"] >= 1, s["phases"]
    snap = obs.snapshot()
    assert "offload_stream" in snap
    # exposition renders the derived overlap line for pd_top
    text = obs.render_snapshot(snap)
    assert "offload_stream" in text and "overlap_efficiency" in text
    # per-step-object counters agree in kind
    st = step.stream_stats()
    assert st["h2d_bytes"] > 0 and st["d2h_bytes"] > 0


@pytest.mark.dist
def test_analysis_models_two_group_working_set():
    """The HBM estimator charges the streamed step the two-group staging
    working set, not the full master+state residency."""
    import paddle_tpu.analysis as analysis

    paddle.seed(7)
    dist.reset_mesh()
    dist.init_mesh(dp=2, sharding=4)
    net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    o = opt.AdamW(learning_rate=0.02, parameters=net.parameters())
    model, o = dist.group_sharded_parallel(net, o, level="os_g",
                                           offload=True, **_KNOBS)
    step = dist.ShardedTrainStep(net, lambda m, x, y: F.mse_loss(m(x), y), o)
    plan = analysis.offload_stream_plan(step)
    assert plan["groups"] >= 2
    assert plan["working_set_bytes"] == 2 * plan["max_group_staging_bytes"]
    assert plan["working_set_bytes"] < plan["full_residency_bytes"]
    x = paddle.to_tensor(np.zeros((8, 16), np.float32))
    y = paddle.to_tensor(np.zeros((8, 16), np.float32))
    est = analysis.estimate_offload_stream_hbm(step, x, y)
    assert est["peak_bytes"] == (est["device_program_peak_bytes"]
                                 + est["stream_working_set_bytes"])
    diags = analysis.stream_plan_check(step, x, y)
    assert [d.code for d in diags] == ["MM012"]  # tiny net fits
    dist.reset_mesh()


@pytest.mark.dist
@pytest.mark.slow
def test_llama_stream_ab_parity():
    """The bench recipe's exact A/B at test scale (run by tools/ci.sh;
    slow-marked for tier-1 wall clock): a tiny Llama through
    group_sharded_parallel(offload=True), overlapped vs serialized lane,
    losses bit-equal and transfer time measurably hidden."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    def run(overlap):
        paddle.seed(0)
        dist.reset_mesh()
        dist.init_mesh(dp=2, sharding=4)
        m = LlamaForCausalLM(LlamaConfig.tiny())
        o = opt.AdamW(learning_rate=3e-4, parameters=m.parameters())
        m2, o = dist.group_sharded_parallel(m, o, level="os", offload=True)
        step = dist.ShardedTrainStep(m, lambda mm, x, y: mm(x, labels=y), o)
        step._stream_overlap = overlap
        ids = paddle.randint(0, 128, [8, 16])
        losses = [float(step(ids, ids)) for _ in range(3)]
        eff = step.stream_stats()["overlap_efficiency"]
        dist.reset_mesh()
        return losses, eff

    ov_l, ov_eff = run(True)
    se_l, se_eff = run(False)
    assert ov_l == se_l
    assert ov_l[-1] < ov_l[0]
    assert ov_eff > 0.0 and se_eff == 0.0


# -- cross-step pipeline fill + pinned staging (ISSUE-10 PR-5 carried) --------

@pytest.mark.dist
def test_eager_fill_bit_equal_to_boundary_drain():
    """The cross-step fill (final uploads handed to the next dispatch as
    jax futures, so the next step's group-0 grad download overlaps the
    fwd+bwd window) changes SCHEDULING only: losses and params must stay
    bit-equal to the drain-at-boundary walk AND to the serialized lane."""
    clip = nn.ClipGradByGlobalNorm(0.5)
    ea_l, ea_p, ea_step = _stream_run(True, clip=clip, eager=True)
    dr_l, dr_p, _ = _stream_run(True, clip=clip, eager=False)
    se_l, se_p, _ = _stream_run(False, clip=clip)
    assert ea_l == dr_l == se_l  # float-exact
    for a, b in zip(ea_p, dr_p):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(ea_p, se_p):
        np.testing.assert_array_equal(a, b)
    # the walk really pipelined (multi-group) and hid transfer time
    assert len(ea_step._stream[0]) >= 2
    assert ea_step.stream_stats()["overlap_efficiency"] > 0.0


@pytest.mark.dist
def test_eager_fill_composes_with_accumulate():
    ea_l, ea_p, _ = _stream_run(True, accumulate=2, eager=True)
    dr_l, dr_p, _ = _stream_run(True, accumulate=2, eager=False)
    assert ea_l == dr_l
    for a, b in zip(ea_p, dr_p):
        np.testing.assert_array_equal(a, b)


def test_wait_dispatched_returns_usable_futures():
    """Lane-level contract of the fill: wait_dispatched() hands back the
    transfer's result arrays as soon as they are issued; consuming them
    (or waiting again) sees the same landed bytes wait() would."""
    import jax

    cpu = jax.devices("cpu")[0]
    lane = StreamLane(overlap=True)
    try:
        a = np.arange(512, dtype=np.float32)
        h = lane.submit("h2d", [a], cpu, tag=0)
        early = h.wait_dispatched()
        assert len(early) == 1
        np.testing.assert_array_equal(np.asarray(early[0]), a)
        landed = h.wait()
        assert landed[0] is early[0]
        # serialized lanes resolve at submit: both surfaces identical
        ser = StreamLane(overlap=False)
        try:
            h2 = ser.submit("h2d", [a], cpu, tag=1)
            assert h2.wait_dispatched()[0] is h2.wait()[0]
        finally:
            ser.close()
    finally:
        lane.close()


def test_wait_dispatched_surfaces_lane_failure():
    lane = StreamLane(overlap=True)
    try:
        bad = lane.submit("h2d", [object()], None, tag=3)
        with pytest.raises(Exception):
            bad.wait_dispatched()
    finally:
        lane.close()


def test_pinned_staging_probe_falls_back_on_cpu():
    """Satellite contract: the pinned-host memory_kind staging arms ONLY
    where the backend exposes a usable pinned_host space — the CPU tier-1
    backend must take the direct path untouched."""
    from paddle_tpu.jit.offload_stream import pinned_host_supported

    assert pinned_host_supported() is False  # CPU test backend
    lane = StreamLane(overlap=True, pinned_staging=True)  # explicit ask
    try:
        assert lane.pinned_staging is False  # probe fell back cleanly
        import jax

        cpu = jax.devices("cpu")[0]
        a = np.ones((64,), np.float32)
        out = lane.submit("h2d", [a], cpu, tag=0).wait()
        np.testing.assert_array_equal(np.asarray(out[0]), a)
        s = lane.stats()
        assert s["pinned_staging"] is False
        assert s["pinned_staged"] == 0
    finally:
        lane.close()
