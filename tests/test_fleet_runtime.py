"""Elastic multi-host fleet runtime (ISSUE-11): the recovery state
machine in isolation, the hardened heartbeat daemon, sync_peers barrier
diagnostics, per-rank flight dirs, and the supervisor's failure paths
(restart-budget exhaustion with a forensic bundle, coordinator-lost
clean worker exit). The end-to-end 4-process ``jax.distributed`` drill
lives in ``tools/resilience_drill.py --fleet`` (ci.sh elastic gate)."""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from paddle_tpu.distributed.fleet.runtime import (
    EXIT_COORD_LOST, EXIT_FENCED, BlockShardedDataset, ElasticFleet,
    FleetPhase, FleetPolicy, FleetStateMachine, pick_resume_dir)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _policy(**kw):
    base = dict(min_world=2, max_restarts=2, heartbeat_timeout=5.0,
                backoff_base_s=0.1, start_timeout_s=30.0)
    base.update(kw)
    return FleetPolicy(**base)


# ---------------------------------------------------------------------------
# pure state machine
# ---------------------------------------------------------------------------

class TestFleetStateMachine:
    def test_membership_join_and_hold(self):
        sm = FleetStateMachine(3, _policy(), now=0.0)
        assert sm.phase is FleetPhase.LAUNCHING
        for r in range(3):
            sm.heartbeat(r, 0.2)
        assert sm.phase is FleetPhase.RUNNING
        act = sm.observe(1.0, {r: None for r in range(3)})
        assert act.kind == "hold"
        assert sm.ranks_alive(1.0) == [0, 1, 2]
        joins = [e for e in sm.timeline if e["event"] == "join"]
        assert sorted(e["rank"] for e in joins) == [0, 1, 2]

    def test_stale_heartbeat_evicts_and_fences(self):
        sm = FleetStateMachine(2, _policy(), now=0.0)
        sm.heartbeat(0, 0.0)
        sm.heartbeat(1, 0.0)
        sm.heartbeat(0, 6.0)  # rank 1 silent past the 5s window
        act = sm.observe(6.0, {0: None, 1: None})
        assert act.kind == "fence" and act.dead == [1]
        ev = [e for e in sm.timeline if e["event"] == "evict"]
        assert ev and ev[0]["rank"] == 1 and ev[0]["cause"] == "stale"

    def test_stall_under_grace_never_evicts(self):
        """The no-false-evict contract: silence SHORTER than
        heartbeat_timeout holds, it does not fence."""
        sm = FleetStateMachine(2, _policy(heartbeat_timeout=5.0), now=0.0)
        sm.heartbeat(0, 0.0)
        sm.heartbeat(1, 0.0)
        act = sm.observe(4.9, {0: None, 1: None})  # 4.9s stall < 5s
        assert act.kind == "hold"
        assert sm.stale_ranks(4.9) == []
        # the stalled rank recovers: still no fence, no evict event
        sm.heartbeat(0, 4.95)
        sm.heartbeat(1, 4.95)
        act = sm.observe(6.0, {0: None, 1: None})
        assert act.kind == "hold"
        assert not [e for e in sm.timeline if e["event"] == "evict"]

    def test_flap_is_recorded_not_duplicated(self):
        sm = FleetStateMachine(2, _policy(), now=0.0)
        sm.heartbeat(0, 0.0)
        sm.heartbeat(1, 0.0)
        sm.heartbeat(0, 6.0)
        assert sm.observe(6.0, {0: None, 1: None}).kind == "fence"
        # re-reading the SAME old beat must not resurrect the rank
        sm.heartbeat(1, 0.0)
        assert 1 in sm._evicted
        assert not [e for e in sm.timeline if e["event"] == "flap"]
        # a genuinely fresh beat records one flap
        sm.heartbeat(1, 6.5)
        flaps = [e for e in sm.timeline if e["event"] == "flap"]
        assert len(flaps) == 1 and flaps[0]["rank"] == 1

    def test_crash_fence_drain_restart_cycle(self):
        sm = FleetStateMachine(4, _policy(), now=0.0)
        for r in range(4):
            sm.heartbeat(r, 0.1)
        act = sm.observe(1.0, {0: None, 1: None, 2: 43, 3: None})
        assert act.kind == "fence" and act.dead == [2]
        ev = [e for e in sm.timeline if e["event"] == "evict"]
        assert ev[0]["rank"] == 2 and ev[0]["cause"] == "crash"
        # drain: hold until every worker exited (survivors leave FENCED)
        act = sm.observe(2.0, {0: EXIT_FENCED, 1: EXIT_FENCED, 2: 43,
                               3: None})
        assert act.kind == "hold"
        act = sm.observe(3.0, {0: EXIT_FENCED, 1: EXIT_FENCED, 2: 43,
                               3: EXIT_FENCED})
        assert act.kind == "restart" and act.world == 3
        assert act.backoff_s == pytest.approx(0.1)
        sm.restarted(4.0, 3)
        assert sm.gen == 1 and sm.restarts == 1 and sm.world == 3
        for r in range(3):
            sm.heartbeat(r, 4.1)
        assert sm.observe(5.0, {0: 0, 1: 0, 2: 0}).kind == "complete"
        events = [e["event"] for e in sm.timeline]
        assert events.count("fence") == 1
        assert events.count("restart") == 1
        assert events[-1] == "complete"

    def test_worker_fence_adopts_planned_drain(self):
        """A worker-raised retune fence (online tuner): the supervisor
        adopts it with NO eviction, and a peer that dies MID-DRAIN
        (e.g. killed by the fenced rank-0 coordinator's fast exit) is
        drain mechanics, not a membership change — the gang restarts
        planned, full world, zero backoff, zero budget."""
        sm = FleetStateMachine(2, _policy(min_world=2, max_restarts=0),
                               now=0.0)
        sm.heartbeat(0, 0.1)
        sm.heartbeat(1, 0.1)
        sm.worker_fence(1.0, "retune:plan")
        assert sm.phase is FleetPhase.FENCED and sm.planned_fence
        sm.worker_fence(1.1, "retune:plan")  # idempotent while FENCED
        fences = [e for e in sm.timeline if e["event"] == "fence"]
        assert len(fences) == 1 and fences[0]["reason"] == "retune:plan"
        # rank 0 drains clean; rank 1 aborts under the coordinator loss
        assert sm.observe(2.0, {0: EXIT_FENCED, 1: None}).kind == "hold"
        act = sm.observe(3.0, {0: EXIT_FENCED, 1: -6})
        assert act.kind == "restart" and act.world == 2
        assert act.backoff_s == 0.0
        assert not [e for e in sm.timeline if e["event"] == "evict"]
        sm.restarted(4.0, 2)
        # max_restarts=0, yet the planned roll went through: no budget
        assert sm.restarts == 0 and sm.gen == 1
        assert not sm.planned_fence  # consumed, not sticky

    def test_backoff_grows_exponentially_and_caps(self):
        p = _policy(backoff_base_s=0.5, backoff_max_s=2.0)
        assert p.backoff_s(1) == pytest.approx(0.5)
        assert p.backoff_s(2) == pytest.approx(1.0)
        assert p.backoff_s(3) == pytest.approx(2.0)
        assert p.backoff_s(9) == pytest.approx(2.0)  # capped

    def test_restart_budget_exhaustion_fails(self):
        sm = FleetStateMachine(3, _policy(min_world=1, max_restarts=1),
                               now=0.0)
        for r in range(3):
            sm.heartbeat(r, 0.1)
        assert sm.observe(1.0, {0: None, 1: 9, 2: None}).kind == "fence"
        act = sm.observe(2.0, {0: EXIT_FENCED, 1: 9, 2: EXIT_FENCED})
        assert act.kind == "restart"
        sm.restarted(3.0, 2)
        for r in range(2):
            sm.heartbeat(r, 3.1)
        assert sm.observe(4.0, {0: 9, 1: None}).kind == "fence"
        act = sm.observe(5.0, {0: 9, 1: EXIT_FENCED})
        assert act.kind == "fail" and "budget" in act.reason
        assert sm.phase is FleetPhase.FAILED

    def test_below_min_world_fails(self):
        sm = FleetStateMachine(3, _policy(min_world=3), now=0.0)
        for r in range(3):
            sm.heartbeat(r, 0.1)
        assert sm.observe(1.0, {0: None, 1: 9, 2: None}).kind == "fence"
        act = sm.observe(2.0, {0: EXIT_FENCED, 1: 9, 2: EXIT_FENCED})
        assert act.kind == "fail" and "min_world" in act.reason

    def test_launch_timeout_fails_naming_missing_ranks(self):
        sm = FleetStateMachine(3, _policy(start_timeout_s=10.0), now=0.0)
        sm.heartbeat(0, 1.0)  # ranks 1, 2 never register
        act = sm.observe(11.0, {r: None for r in range(3)})
        assert act.kind == "fail"
        assert "[1, 2]" in act.reason

    def test_snapshot_shape_for_provider(self):
        sm = FleetStateMachine(2, _policy(), now=0.0)
        sm.heartbeat(0, 0.1)
        snap = sm.snapshot()
        assert snap["phase"] == "launching" and snap["world"] == 2
        assert snap["restarts"] == 0
        assert snap["timeline"][0]["event"] == "join"
        json.dumps(snap)  # provider output must be JSON-clean


# ---------------------------------------------------------------------------
# hardened heartbeat daemon (satellite 1)
# ---------------------------------------------------------------------------

class _FlakyStore:
    """set() fails the first N calls per key-write; everything is
    recorded so the test can assert the retry path ran."""

    def __init__(self, fail_first: int = 0, fail_forever: bool = False):
        self.fail_first = fail_first
        self.fail_forever = fail_forever
        self.sets = 0
        self.failures = 0
        self.values = {}
        self.counters = {}

    def set(self, key, value):
        self.sets += 1
        if self.fail_forever or self.failures < self.fail_first:
            self.failures += 1
            raise RuntimeError("injected transient store error")
        self.values[key] = value

    def add(self, key, amount=1):
        self.counters[key] = self.counters.get(key, 0) + amount
        return self.counters[key]

    def get(self, key):
        return self.values[key]


class TestHardenedHeartbeat:
    def test_transient_store_error_is_retried(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        store = _FlakyStore(fail_first=1)
        m = ElasticManager(store, rank=0, world_size=1,
                           heartbeat_interval=0.05)
        m._beat()  # first attempt fails, retry lands
        assert store.failures == 1
        assert "elastic/worker/0" in store.values
        assert m.beat_failures == 0 and m.last_beat_t is not None

    def test_daemon_survives_persistent_failure(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        store = _FlakyStore(fail_forever=True)
        m = ElasticManager(store, rank=0, world_size=1,
                           heartbeat_interval=0.02)
        with pytest.warns(RuntimeWarning, match="heartbeat"):
            m._thread = threading.Thread(target=m._loop, daemon=True)
            m._thread.start()
            deadline = time.time() + 5
            while m.beat_failures < 2 and time.time() < deadline:
                time.sleep(0.02)
        assert m.beat_failures >= 2, "daemon died instead of retrying"
        assert m._thread.is_alive()
        m.exit()

    def test_heartbeat_stall_under_grace_no_false_evict(self):
        """A stalled daemon (injected ``heartbeat_stall``) shorter than
        the eviction window keeps the worker in alive_workers."""
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        from paddle_tpu.distributed.resilience.faults import inject
        from paddle_tpu.distributed.store import TCPStore

        store = TCPStore(is_master=True, world_size=1)
        try:
            m = ElasticManager(store, rank=0, world_size=1,
                               heartbeat_interval=0.05, timeout=2.0)
            with inject("heartbeat_stall", rank=0, sleep_ms=300):
                m.register()
                time.sleep(0.5)  # the stall elapses inside the window
                assert 0 in m.alive_workers(), \
                    "stall under the grace window must not evict"
            m.exit()
        finally:
            store.close()

    def test_wait_per_call_timeout_override(self):
        """wait(keys, timeout=...) expires on its own deadline and leaves the
        connection re-armed with the store-level timeout (the post-training
        drill polls round keys this way while checking trainer liveness)."""
        from paddle_tpu.distributed.store import TCPStore

        store = TCPStore(is_master=True, world_size=1, timeout=900)
        try:
            t0 = time.time()
            with pytest.raises(TimeoutError, match="1s"):
                store.wait(["never-set"], timeout=1)
            assert time.time() - t0 < 10, "per-call timeout was ignored"
            store.set("present", b"1")
            store.wait(["present"], timeout=1)  # satisfied wait, no raise
            assert store.get("present") == b"1"  # connection still healthy
        finally:
            store.close()


# ---------------------------------------------------------------------------
# sync_peers barrier diagnostics (satellite 2)
# ---------------------------------------------------------------------------

class TestSyncPeersDiagnostics:
    def test_timeout_names_arrived_and_missing_ranks(self):
        from paddle_tpu.distributed.run.master import Master, \
            membership_table

        main = Master(endpoint=None, print_hint=False)
        peer = Master(endpoint=main.endpoint, print_hint=False)
        errs = {}

        def join(name, master, key):
            try:
                master.sync_peers("/job", name, size=3, timeout=2.0)
            except Exception as e:
                errs[key] = e

        ta = threading.Thread(target=join, args=("nodeA", main, "a"))
        tb = threading.Thread(target=join, args=("nodeB", peer, "b"))
        ta.start()
        tb.start()
        ta.join(timeout=30)
        tb.join(timeout=30)
        try:
            assert set(errs) == {"a", "b"}, errs
            for e in errs.values():
                assert isinstance(e, TimeoutError), e
                msg = str(e)
                assert "arrived 2/3" in msg, msg
                assert "nodeA" in msg and "nodeB" in msg, msg
                assert "missing ranks: [2]" in msg, msg
            rows = membership_table(main.store, "/job", 3)
            assert [r["present"] for r in rows] == [True, True, False]
            assert rows[0]["value"] == "nodeA"
            assert rows[1]["value"] == "nodeB"
            assert rows[0]["age_s"] is not None
        finally:
            peer.stop()
            main.stop()


# ---------------------------------------------------------------------------
# per-rank flight dirs (satellite 6)
# ---------------------------------------------------------------------------

def test_flight_bundles_land_in_per_rank_dirs(tmp_path, monkeypatch):
    from paddle_tpu.observability.trace.flight import dump_bundle

    monkeypatch.setenv("PT_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("PT_FLEET_RANK", "3")
    path = dump_bundle(reason="unit")
    assert path.startswith(str(tmp_path / "rank3")), path
    assert os.path.exists(os.path.join(path, "MANIFEST.json"))
    # an explicit out_dir wins over the env (tooling contract unchanged)
    explicit = dump_bundle(out_dir=str(tmp_path / "direct"), reason="unit")
    assert explicit.startswith(str(tmp_path / "direct")), explicit


# ---------------------------------------------------------------------------
# resume-dir election + dataset sharding
# ---------------------------------------------------------------------------

def test_pick_resume_dir_elects_max_step_then_lowest_rank(tmp_path):
    def commit(rank, step, latest=True):
        d = tmp_path / f"rank{rank}" / f"step_{step:08d}"
        d.mkdir(parents=True, exist_ok=True)
        (d / "manifest.json").write_text(
            json.dumps({"meta": {"step": step}, "entries": {}}))
        if latest:
            (tmp_path / f"rank{rank}" / "LATEST").write_text(
                json.dumps({"tag": f"step_{step:08d}"}))

    assert pick_resume_dir(str(tmp_path)) is None
    commit(0, 5)
    commit(1, 7)
    commit(2, 7)
    picked = pick_resume_dir(str(tmp_path))
    assert picked == str(tmp_path / "rank1"), picked  # max step, low rank
    # a dir with a broken LATEST and no committed step dir is skipped
    (tmp_path / "rank3").mkdir()
    (tmp_path / "rank3" / "LATEST").write_text("{broken")
    assert pick_resume_dir(str(tmp_path)) == str(tmp_path / "rank1")
    # a broken LATEST over an INTACT committed dir degrades to it (the
    # commit-protocol read_latest fallback): that rank still holds the
    # fleet-wide newest commit and must win the election
    commit(4, 9, latest=False)
    (tmp_path / "rank4" / "LATEST").write_text("{torn")
    assert pick_resume_dir(str(tmp_path)) == str(tmp_path / "rank4")


def test_block_sharded_dataset_reassembles_global_batch():
    data = list(range(48))
    world4 = [BlockShardedDataset(data, 12, r, 4) for r in range(4)]
    world1 = BlockShardedDataset(data, 12, 0, 1)
    for step in range(4):
        mine = [world1[step * 12 + i] for i in range(12)]
        theirs = []
        for r in range(4):
            theirs += [world4[r][step * 3 + i] for i in range(3)]
        assert mine == theirs == data[step * 12:(step + 1) * 12]
    with pytest.raises(ValueError, match="divide"):
        BlockShardedDataset(data, 10, 0, 4)


# ---------------------------------------------------------------------------
# supervisor failure paths (process-spawning: slow-marked for tier-1;
# the ci.sh elastic gate runs the full file)
# ---------------------------------------------------------------------------

_BUDGET_WORKER = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {repo!r})
    from paddle_tpu.distributed.fleet.runtime import FleetWorkerContext

    ctx = FleetWorkerContext.from_env()
    ctx.register()
    if ctx.rank == 0:
        time.sleep(0.5)
        ctx.exit(75, reason="drained")   # EXIT_FENCED-style exit
    sys.exit(9)                          # the repeat offender
""")


@pytest.mark.slow
def test_restart_budget_exhaustion_leaves_forensic_bundle(tmp_path):
    """A gang that keeps dying: the supervisor burns its bounded restart
    budget and FAILS LOUDLY — phase=failed plus a complete
    (manifest-last) fleet_forensics bundle naming the exits."""
    script = tmp_path / "worker.py"
    script.write_text(_BUDGET_WORKER.format(repo=REPO))
    fleet = ElasticFleet(
        [sys.executable, str(script)], np=2,
        policy=_policy(min_world=1, max_restarts=1, backoff_base_s=0.05,
                       drain_timeout_s=10.0),
        log_dir=str(tmp_path / "logs"),
        flight_root=str(tmp_path / "flight"),
        extra_env={"JAX_PLATFORMS": "cpu"})
    try:
        report = fleet.run(timeout=180)
    finally:
        fleet.close()
    assert report["phase"] == "failed", report
    assert report["restarts"] == 1
    assert "budget" in report["reason"]
    path = report.get("forensics")
    assert path and os.path.isdir(path), report
    manifest = json.load(open(os.path.join(path, "MANIFEST.json")))
    assert "fleet_report.json" in manifest["files"]
    assert "worker_log_tails.json" in manifest["files"]
    dumped = json.load(open(os.path.join(path, "fleet_report.json")))
    evs = [e["event"] for e in dumped["timeline"]]
    assert evs[-1] == "fail"
    assert evs.count("restart") == 1 and evs.count("fence") == 2


_COORD_LOST_WORKER = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, {repo!r})
    from paddle_tpu.distributed.fleet.runtime import FleetWorkerContext

    ctx = FleetWorkerContext.from_env()
    ctx.register()
    print("registered", flush=True)
    for _ in range(600):          # ~2 min upper bound, exit() cuts it
        ctx.fenced()              # store probes notice a dead coordinator
        time.sleep(0.2)
    sys.exit(5)                   # watchdog never fired: orphan — FAIL
""")


@pytest.mark.slow
def test_coordinator_lost_triggers_clean_worker_exit(tmp_path):
    """Kill the control-plane store under a live worker: the worker must
    notice within a few probes and exit with EXIT_COORD_LOST instead of
    orphaning itself under a dead coordinator."""
    from paddle_tpu.distributed.store import TCPStore

    script = tmp_path / "worker.py"
    script.write_text(_COORD_LOST_WORKER.format(repo=REPO))
    store = TCPStore(is_master=True, world_size=1)
    env = dict(os.environ)
    env.update({"PT_FLEET_ENDPOINT": f"127.0.0.1:{store.port}",
                "PT_FLEET_WORLD": "2", "PT_FLEET_RANK": "0",
                "PT_FLEET_GEN": "0", "JAX_PLATFORMS": "cpu"})
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        # wait for registration (first line), then yank the coordinator
        line = proc.stdout.readline()
        assert "registered" in line, line
        store.close()
        rc = proc.wait(timeout=60)
        assert rc == EXIT_COORD_LOST, \
            f"worker exited rc={rc}, wanted clean EXIT_COORD_LOST"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_fleet_provider_registered_in_hub():
    """Constructing a supervisor registers the ``fleet`` provider: the
    hub snapshot carries the membership timeline without a run."""
    from paddle_tpu import observability

    fleet = ElasticFleet([sys.executable, "-c", "pass"], np=2,
                         policy=_policy())
    try:
        snap = observability.snapshot()["fleet"]
        assert snap["phase"] == "launching"
        assert snap["policy"]["max_restarts"] == 2
        assert "timeline" in snap and "recoveries" in snap
        assert "worker_exits" in snap and "flight_bundles" in snap
    finally:
        fleet.close()
