"""Test harness config.

Tests run on a virtual 8-device CPU mesh (the reference tests distributed code
multi-process on one host, test_dist_base.py:783; we test multi-chip SPMD with
XLA's forced host device count instead). Must run before jax creates backends.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import threading
import time

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "thread_leak_ok: opt out of the non-daemon thread-leak guard "
        "(tests that intentionally leave a joinable thread behind)")


@pytest.fixture(autouse=True)
def _no_thread_leaks(request):
    """Every test must clean up its non-daemon threads: a leaked joinable
    thread holds the interpreter open at exit and poisons later tests'
    lockdep/leak accounting. Daemon threads (named pt-*) are the
    runtime's long-lived workers and are exempt by design."""
    before = set(threading.enumerate())
    yield
    if request.node.get_closest_marker("thread_leak_ok"):
        return
    # teardown grace: threads mid-join finish within a short window
    deadline = time.time() + 2.0
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and not t.daemon and t.is_alive()]
        if not leaked:
            return
        time.sleep(0.05)
    pytest.fail(
        f"test leaked non-daemon thread(s): "
        f"{[t.name for t in leaked]} — join them in teardown or mark "
        f"the test @pytest.mark.thread_leak_ok", pytrace=False)
