"""Test harness config.

Tests run on a virtual 8-device CPU mesh (the reference tests distributed code
multi-process on one host, test_dist_base.py:783; we test multi-chip SPMD with
XLA's forced host device count instead). Must run before jax creates backends.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
