"""Vision model long tail + Flowers dataset."""
import io
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets, models


def _np(t):
    return np.asarray(t.data)


@pytest.mark.parametrize("ctor,size", [
    (models.densenet121, 64),
    (models.squeezenet1_0, 64),
    (models.squeezenet1_1, 64),
    (models.shufflenet_v2_x0_5, 64),
    (models.shufflenet_v2_swish, 64),
])
def test_extra_models_forward(ctor, size):
    net = ctor(num_classes=10)
    net.eval()
    out = net(paddle.randn([2, 3, size, size]))
    assert out.shape == [2, 10]


def test_googlenet_aux_heads_and_grad():
    net = models.googlenet(num_classes=5)
    net.train()
    x = paddle.randn([2, 3, 96, 96])
    main, aux1, aux2 = net(x)
    assert main.shape == aux1.shape == aux2.shape == [2, 5]
    loss = main.sum() + 0.3 * (aux1.sum() + aux2.sum())
    loss.backward()
    assert net.fc.weight.grad is not None
    net.eval()
    out = net(x)
    assert out.shape == [2, 5]


def test_inception_v3_forward():
    net = models.inception_v3(num_classes=7)
    net.eval()
    out = net(paddle.randn([1, 3, 299, 299]))
    assert out.shape == [1, 7]


def test_densenet_variants_param_counts_increase():
    import numpy as _n

    def nparams(net):
        return sum(int(_n.prod(p.shape)) for p in net.parameters())

    n121 = nparams(models.densenet121(num_classes=0, with_pool=False))
    n169 = nparams(models.densenet169(num_classes=0, with_pool=False))
    assert n169 > n121


def test_adaptive_pool_non_divisible():
    import paddle_tpu.nn.functional as F

    x = paddle.randn([1, 2, 7, 5])
    out = F.adaptive_avg_pool2d(x, 3)
    assert out.shape == [1, 2, 3, 3]
    # parity with torch-style bin edges on a known input
    v = np.arange(7, dtype="float32").reshape(1, 1, 7, 1)
    got = _np(F.adaptive_avg_pool2d(paddle.to_tensor(np.broadcast_to(v, (1, 1, 7, 1)).copy()), (3, 1)))
    # bins: [0,3) [2,5) [4,7)  -> means 1, 3, 5
    np.testing.assert_allclose(got.ravel(), [1.0, 3.0, 5.0])


def test_flowers_dataset(tmp_path):
    from PIL import Image
    import scipy.io

    tar_path = os.path.join(str(tmp_path), "102flowers.tgz")
    with tarfile.open(tar_path, "w:gz") as tf:
        for i in range(1, 5):
            buf = io.BytesIO()
            Image.fromarray(
                np.full((8, 8, 3), i * 10, "uint8")).save(buf, format="JPEG")
            data = buf.getvalue()
            info = tarfile.TarInfo(f"jpg/image_{i:05d}.jpg")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    labels = os.path.join(str(tmp_path), "imagelabels.mat")
    scipy.io.savemat(labels, {"labels": np.asarray([[1, 2, 1, 2]])})
    setid = os.path.join(str(tmp_path), "setid.mat")
    scipy.io.savemat(setid, {"trnid": np.asarray([[1, 2, 3]]),
                             "valid": np.asarray([[4]]),
                             "tstid": np.asarray([[4]])})
    ds = datasets.Flowers(data_file=tar_path, label_file=labels,
                          setid_file=setid, mode="train")
    assert len(ds) == 3
    img, label = ds[0]
    assert img.shape == (8, 8, 3) and label in (0, 1)
    val = datasets.Flowers(data_file=tar_path, label_file=labels,
                           setid_file=setid, mode="valid")
    assert len(val) == 1


# -- incubate fused layers + optimizers ---------------------------------------

def test_fused_transformer_encoder_layer():
    import paddle_tpu.incubate as incubate

    paddle.seed(0)
    layer = incubate.nn.FusedTransformerEncoderLayer(
        d_model=32, nhead=4, dim_feedforward=64, dropout_rate=0.0)
    layer.eval()
    x = paddle.randn([2, 8, 32])
    out = layer(x)
    assert out.shape == [2, 8, 32]
    out.sum().backward()
    assert layer.fused_attn.qkv.weight.grad is not None


def test_fused_mha_pre_and_post_norm_differ():
    import paddle_tpu.incubate as incubate

    paddle.seed(1)
    x = paddle.randn([1, 4, 16])
    pre = incubate.nn.FusedMultiHeadAttention(16, 2, dropout_rate=0.0,
                                              attn_dropout_rate=0.0,
                                              normalize_before=True)
    post = incubate.nn.FusedMultiHeadAttention(16, 2, dropout_rate=0.0,
                                               attn_dropout_rate=0.0,
                                               normalize_before=False)
    post.set_state_dict(dict(pre.state_dict()))
    pre.eval(); post.eval()
    assert not np.allclose(_np(pre(x)), _np(post(x)))


def test_lookahead_optimizer():
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.incubate.optimizer import LookAhead

    paddle.seed(0)
    net = nn.Linear(4, 1)
    inner = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    opt = LookAhead(inner, alpha=0.5, k=2)
    x = paddle.randn([16, 4]); y = paddle.randn([16, 1])
    l0 = None
    for _ in range(10):
        loss = F.mse_loss(net(x), y)
        if l0 is None:
            l0 = float(loss)
        loss.backward(); opt.step(); opt.clear_grad()
    assert float(loss) < l0


def test_model_average_apply_restore():
    import paddle_tpu.nn as nn
    from paddle_tpu.incubate.optimizer import ModelAverage

    net = nn.Linear(2, 1)
    avg = ModelAverage(parameters=net.parameters(), min_average_window=1,
                       max_average_window=100)
    w0 = _np(net.weight).copy()
    avg.step()
    net.weight.set_value(w0 + 1.0)
    avg.step()
    cur = _np(net.weight).copy()
    with avg.apply():
        np.testing.assert_allclose(_np(net.weight), w0 + 0.5, rtol=1e-6)
    np.testing.assert_allclose(_np(net.weight), cur, rtol=1e-6)
