"""Regression tests for round-2 advisor findings (ADVICE.md) + p2p transport."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def _np(t):
    return np.asarray(t.data)


class TestBf16Checkpoint:
    def test_bf16_roundtrip(self, tmp_path):
        """ADVICE high: ml_dtypes arrays save with a void descr; load must
        reinterpret instead of failing with 'No cast function available'."""
        x = paddle.ones([4, 3], dtype="bfloat16") * 1.5
        path = os.path.join(str(tmp_path), "bf16")
        dist.save_state_dict({"x": x}, path)
        y = paddle.zeros([4, 3], dtype="bfloat16")
        dist.load_state_dict({"x": y}, path)
        assert str(y.dtype).endswith("bfloat16")
        np.testing.assert_array_equal(
            _np(y).astype(np.float32), np.full((4, 3), 1.5, np.float32))

    def test_bf16_into_f32_target(self, tmp_path):
        x = paddle.full([2, 2], 0.25, dtype="bfloat16")
        path = os.path.join(str(tmp_path), "bf16b")
        dist.save_state_dict({"x": x}, path)
        y = paddle.zeros([2, 2], dtype="float32")
        dist.load_state_dict({"x": y}, path)
        np.testing.assert_allclose(_np(y), 0.25)


class TestStoreDesync:
    def test_timeout_then_correct_reply(self):
        """ADVICE medium: after a client-side timeout the fd holds a stale
        in-flight reply; the store must drop + reconnect so the next request
        doesn't parse the stale reply as its own."""
        from paddle_tpu.distributed.store import TCPStore

        master = TCPStore(is_master=True, world_size=1, timeout=1)
        try:
            setter = TCPStore(host="127.0.0.1", port=master.port,
                              world_size=1)
            with pytest.raises(TimeoutError):
                master.wait(["never-set-key"])
            # unblock the stuck server worker; its reply goes to the dead fd
            setter.set("never-set-key", b"late")
            master.set("k2", b"v2")
            assert master.get("k2") == b"v2"
            # counter integrity after the desync event
            assert master.add("ctr", 5) == 5
            assert master.add("ctr", 1) == 6
        finally:
            master.close()


class TestRecvTimeout:
    def test_recv_timeout_parameter(self):
        """ADVICE low: recv's mailbox wait must honor a caller timeout."""
        import time

        t0 = time.time()
        with pytest.raises(RuntimeError, match="after 0.2s"):
            dist.recv(paddle.zeros([2]), src=0, tag=777, timeout=0.2)
        assert time.time() - t0 < 5.0


_P2P_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")  # env var is pinned by site cfg
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    out_dir = sys.argv[1]
    if rank == 0:
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        dist.send(x, dst=1, tag=3)
        y = paddle.zeros([4])
        dist.recv(y, src=1, tag=4)
        np.testing.assert_array_equal(np.asarray(y.data), [9., 9., 9., 9.])
        # ordered delivery: two messages, same tag
        dist.send(paddle.full([1], 1.0), dst=1, tag=5)
        dist.send(paddle.full([1], 2.0), dst=1, tag=5)
    else:
        y = paddle.zeros([2, 3])
        dist.recv(y, src=0, tag=3)
        np.testing.assert_array_equal(
            np.asarray(y.data), np.arange(6, dtype=np.float32).reshape(2, 3))
        dist.send(paddle.full([4], 9.0), dst=0, tag=4)
        a, b = paddle.zeros([1]), paddle.zeros([1])
        dist.recv(a, src=0, tag=5)
        dist.recv(b, src=0, tag=5)
        assert float(a.data[0]) == 1.0 and float(b.data[0]) == 2.0
    # irecv-then-send exchange must not deadlock (blocking wait rides its own
    # store connection, so the concurrent send can still reach the daemon)
    peer = 1 - rank
    buf = paddle.zeros([2])
    task = dist.irecv(buf, src=peer, tag=8)
    dist.send(paddle.full([2], float(rank)), dst=peer, tag=8)
    assert task.wait(60), "exchange deadlocked"
    np.testing.assert_array_equal(np.asarray(buf.data), [peer, peer])
    with open(os.path.join(out_dir, f"ok.{rank}"), "w") as f:
        f.write("ok")
""")


class TestCrossProcessP2P:
    def test_two_process_send_recv(self, tmp_path):
        """VERDICT #4: send/recv must round-trip across gang-spawned
        processes via the TCPStore channel, not the in-process mailbox."""
        from paddle_tpu.distributed.launch.process import ProcessContext

        script = tmp_path / "p2p_worker.py"
        script.write_text(_P2P_WORKER)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {"PADDLE_P2P_ENDPOINT": f"127.0.0.1:{port}",
               "PADDLE_TRAINERS_NUM": "2",
               "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
        ctx = ProcessContext.start(
            [sys.executable, str(script), str(tmp_path)], 2,
            base_env=env, log_dir=str(tmp_path / "logs"))
        rc = ctx.wait(timeout=120)
        if rc != 0:
            logs = ""
            for r in (0, 1):
                p = tmp_path / "logs" / f"workerlog.{r}"
                if p.exists():
                    logs += f"--- rank {r} ---\n" + p.read_text()[-2000:]
            pytest.fail(f"gang exited rc={rc}\n{logs}")
        assert (tmp_path / "ok.0").exists() and (tmp_path / "ok.1").exists()


class TestBuildRace:
    def test_concurrent_load_same_lib(self, tmp_path):
        """ADVICE low: concurrent first-use builds must not corrupt the .so."""
        src = tmp_path / "mini.cpp"
        src.write_text('extern "C" int forty_two() { return 42; }\n')
        code = textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})
            from paddle_tpu.utils import cpp_extension
            lib = cpp_extension.load("mini", [{str(src)!r}],
                                     build_directory={str(tmp_path)!r})
            assert lib.forty_two() == 42
        """)
        procs = [subprocess.Popen([sys.executable, "-c", code],
                                  stderr=subprocess.PIPE) for _ in range(4)]
        for p in procs:
            _, err = p.communicate(timeout=120)
            assert p.returncode == 0, err.decode()
