"""Warm-path pass tests: fused gradient accumulation, async device
prefetch, and the persistent executable cache (ISSUE 3).

Accumulation parity is the hard contract: ``TrainStep.accumulate(k)`` must
match k sequential eager micro-steps (loss scaled 1/k, one optimizer
update) to numerical noise, keep buffer donation, and never retrace.
The persistent-cache contract is cross-process: a second process warming
the same programs performs ZERO fresh XLA compiles (counter-asserted),
and corrupt/stale entries degrade to a miss, never an error.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu import analysis, io, jit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp_and_opt(seed=3, wd=0.01):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    o = opt.AdamW(learning_rate=1e-2, parameters=net.parameters(),
                  weight_decay=wd)
    return net, o


def _batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 8).astype("float32"),
            rng.randint(0, 4, n).astype("int64"))


class TestFusedAccumulation:
    def test_parity_with_sequential_microsteps(self):
        """accumulate(k) == the eager recipe: k micro-steps of
        backward(loss_i/k) then ONE optimizer update."""
        k = 4
        X, Y = _batch(8)

        net1, o1 = _mlp_and_opt()
        step = jit.TrainStep(net1, lambda m, x, y: F.cross_entropy(m(x), y),
                             o1)
        acc = step.accumulate(k)
        loss_fused = float(acc(paddle.to_tensor(X), paddle.to_tensor(Y)))
        assert o1._global_step == 1  # one applied update per window

        net2, o2 = _mlp_and_opt()
        mb = 8 // k
        losses = []
        for i in range(k):
            xb = paddle.to_tensor(X[i * mb:(i + 1) * mb])
            yb = paddle.to_tensor(Y[i * mb:(i + 1) * mb])
            loss = F.cross_entropy(net2(xb), yb)
            losses.append(float(loss))
            (loss * (1.0 / k)).backward()
        o2.step()
        o2.clear_grad()

        assert loss_fused == pytest.approx(sum(losses) / k, abs=1e-6)
        for p1, p2 in zip(net1.parameters(), net2.parameters()):
            np.testing.assert_allclose(np.asarray(p1.data),
                                       np.asarray(p2.data),
                                       rtol=1e-6, atol=1e-7)

    @pytest.mark.slow  # tier-1 wall clock is near budget; ci.sh covers it
    def test_remat_variant_matches(self):
        """remat changes memory, not math: same params either way."""
        X, Y = _batch(8)
        net1, o1 = _mlp_and_opt()
        jit.TrainStep(net1, lambda m, x, y: F.cross_entropy(m(x), y),
                      o1).accumulate(4)(paddle.to_tensor(X),
                                        paddle.to_tensor(Y))
        net2, o2 = _mlp_and_opt()
        jit.TrainStep(net2, lambda m, x, y: F.cross_entropy(m(x), y),
                      o2).accumulate(4, remat=True)(paddle.to_tensor(X),
                                                    paddle.to_tensor(Y))
        for p1, p2 in zip(net1.parameters(), net2.parameters()):
            np.testing.assert_allclose(np.asarray(p1.data),
                                       np.asarray(p2.data),
                                       rtol=1e-6, atol=1e-7)

    def test_donation_still_in_effect(self):
        """Params + optimizer state stay donated in the fused executable
        (asserted through the analysis capture the HBM estimator uses)."""
        net, o = _mlp_and_opt()
        step = jit.TrainStep(net, lambda m, x, y: F.cross_entropy(m(x), y),
                             o)
        acc = step.accumulate(2)
        X, Y = _batch(8)
        prog = analysis.capture(acc, paddle.to_tensor(X),
                                paddle.to_tensor(Y))
        import jax

        n_donated = len(jax.tree_util.tree_leaves(
            ([p.data for p in acc.train_params],
             [o._accumulators[id(p)] for p in acc.train_params])))
        assert sum(prog.donated_invars) == n_donated > 0
        # the estimator consumes the donation mask: peak must come in
        # UNDER the no-donation resident floor (params+states die at use)
        est = analysis.estimate_peak(prog)
        est_nodonate = analysis.memory.estimate_peak_jaxpr(
            prog.jaxpr, (False,) * len(prog.donated_invars), prog.label)
        assert est.peak_bytes <= est_nodonate.peak_bytes

    def test_zero_retrace_across_calls(self):
        net, o = _mlp_and_opt()
        acc = jit.TrainStep(net, lambda m, x, y: F.cross_entropy(m(x), y),
                            o).accumulate(2)
        X, Y = _batch(8)
        aud = analysis.retrace.enable()
        base = len(aud.events)
        try:
            for i in range(3):
                acc(paddle.to_tensor(X), paddle.to_tensor(Y))
            mine = [e for e in aud.events[base:]
                    if "accumulate" in str(e.label)]
            assert not mine, [e.why() for e in mine]
        finally:
            analysis.retrace.disable()

    def test_bad_steps_and_indivisible_batch_raise(self):
        net, o = _mlp_and_opt()
        step = jit.TrainStep(net, lambda m, x, y: F.cross_entropy(m(x), y),
                             o)
        with pytest.raises(ValueError):
            step.accumulate(0)
        acc = step.accumulate(3)
        X, Y = _batch(8)  # 8 % 3 != 0
        with pytest.raises(ValueError):
            acc(paddle.to_tensor(X), paddle.to_tensor(Y))

    @pytest.mark.slow  # tier-1 wall clock is near budget; ci.sh covers it
    def test_sharded_accumulate_parity(self):
        """ShardedTrainStep.accumulate on a 1-device mesh matches the
        unsharded fused step."""
        import jax

        import paddle_tpu.distributed as dist

        X, Y = _batch(8)
        net1, o1 = _mlp_and_opt()
        acc1 = jit.TrainStep(net1, lambda m, x, y: F.cross_entropy(m(x), y),
                             o1).accumulate(2)
        l1 = float(acc1(paddle.to_tensor(X), paddle.to_tensor(Y)))

        dist.reset_mesh()
        dist.init_mesh(devices=jax.devices()[:1])
        try:
            net2, o2 = _mlp_and_opt()
            acc2 = dist.ShardedTrainStep(
                net2, lambda m, x, y: F.cross_entropy(m(x), y),
                o2).accumulate(2)
            l2 = float(acc2(paddle.to_tensor(X), paddle.to_tensor(Y)))
            assert l1 == pytest.approx(l2, abs=1e-6)
            for p1, p2 in zip(net1.parameters(), net2.parameters()):
                np.testing.assert_allclose(np.asarray(p1.data),
                                           np.asarray(p2.data),
                                           rtol=1e-5, atol=1e-6)
        finally:
            dist.reset_mesh()


class TestPipelineConfigsHonored:
    def test_validation_at_assignment(self):
        import paddle_tpu.distributed.fleet as fleet

        s = fleet.DistributedStrategy()
        s.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
        with pytest.raises(ValueError, match="unknown key"):
            s.pipeline_configs = {"acumulate_steps": 4}  # the typo case
        with pytest.raises(ValueError, match="positive"):
            s.pipeline_configs = {"accumulate_steps": 0}
        with pytest.raises(ValueError, match="positive"):
            s.pipeline_configs = {"micro_batch_size": -1}
        with pytest.raises(ValueError, match="positive"):
            s.pipeline_configs["accumulate_steps"] = -2  # item assignment
        with pytest.raises(ValueError, match="unknown key"):
            s.pipeline_configs.update(bogus=1)
        s.pipeline_configs["accumulate_steps"] = 8
        assert s.pipeline_configs["accumulate_steps"] == 8

    def test_accumulate_steps_drives_fused_window(self):
        """pipeline_configs["accumulate_steps"] is CONSUMED: train_batch
        applies exactly one update per call through the fused executable,
        matching the unsharded accumulate numerics."""
        import jax

        import paddle_tpu.distributed as dist
        import paddle_tpu.distributed.fleet as fleet
        from paddle_tpu.distributed.meta_parallel import PipelineParallel

        X, Y = _batch(8)
        net1, o1 = _mlp_and_opt()
        acc = jit.TrainStep(net1, lambda m, x, y: F.cross_entropy(m(x), y),
                            o1).accumulate(2)
        ref_loss = float(acc(paddle.to_tensor(X), paddle.to_tensor(Y)))

        dist.reset_mesh()
        s = fleet.DistributedStrategy()
        s.pipeline = True
        s.pipeline_configs = {"accumulate_steps": 2}
        fleet.init(strategy=s)
        try:
            net2, o2 = _mlp_and_opt()

            class _XentPipe(nn.Layer):
                def __init__(self, inner):
                    super().__init__()
                    self.inner = inner

                def forward(self, x):
                    return self.inner(x)

                def compute_loss(self, x, y):
                    return F.cross_entropy(self.inner(x), y)

            pp = PipelineParallel(_XentPipe(net2),
                                  fleet.get_hybrid_communicate_group(),
                                  strategy=s)
            hopt = fleet.distributed_optimizer(o2, strategy=s)
            loss = pp.train_batch((paddle.to_tensor(X), paddle.to_tensor(Y)),
                                  hopt)
            assert any(k[0] == "pp_accum" for k in pp._steps)
            assert o2._global_step == 1
            assert float(loss) == pytest.approx(ref_loss, abs=1e-6)
        finally:
            dist.reset_mesh()

    @pytest.mark.slow  # tier-1 wall clock is near budget; ci.sh covers it
    def test_accumulate_steps_with_scaler_same_window_semantics(self):
        """Paths that can't host the fused scan (in-graph scaler) keep the
        SAME call contract — one call = the full batch = one update — via
        the eager microbatch split, not a silent per-call window."""
        import paddle_tpu.distributed as dist
        import paddle_tpu.distributed.fleet as fleet
        from paddle_tpu import amp
        from paddle_tpu.distributed.meta_parallel import PipelineParallel

        X, Y = _batch(8)
        net1, o1 = _mlp_and_opt()
        acc = jit.TrainStep(net1, lambda m, x, y: F.cross_entropy(m(x), y),
                            o1).accumulate(4)
        ref_loss = float(acc(paddle.to_tensor(X), paddle.to_tensor(Y)))

        dist.reset_mesh()
        s = fleet.DistributedStrategy()
        s.pipeline = True
        s.pipeline_configs = {"accumulate_steps": 4}
        fleet.init(strategy=s)
        try:
            net2, o2 = _mlp_and_opt()

            class _XentPipe(nn.Layer):
                def __init__(self, inner):
                    super().__init__()
                    self.inner = inner

                def forward(self, x):
                    return self.inner(x)

                def compute_loss(self, x, y):
                    return F.cross_entropy(self.inner(x), y)

            pp = PipelineParallel(_XentPipe(net2),
                                  fleet.get_hybrid_communicate_group(),
                                  strategy=s)
            hopt = fleet.distributed_optimizer(o2, strategy=s)
            loss = pp.train_batch((paddle.to_tensor(X), paddle.to_tensor(Y)),
                                  hopt,
                                  scaler=amp.GradScaler(
                                      init_loss_scaling=1024.0))
            assert o2._global_step == 1
            assert float(loss) == pytest.approx(ref_loss, abs=1e-5)
            for p1, p2 in zip(net1.parameters(), net2.parameters()):
                np.testing.assert_allclose(np.asarray(p1.data),
                                           np.asarray(p2.data),
                                           rtol=2e-4, atol=1e-5)
        finally:
            dist.reset_mesh()


class TestDevicePrefetch:
    def test_order_values_and_device_residency(self):
        import jax

        xs = np.random.RandomState(0).randn(12, 4).astype("float32")
        ys = np.arange(12).astype("int64")
        ds = io.TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
        loader = io.DataLoader(ds, batch_size=3, prefetch_to_device=True)
        assert len(loader) == 4
        got = list(loader)
        assert len(got) == 4
        for i, (xb, yb) in enumerate(got):
            assert isinstance(xb.data, jax.Array)
            np.testing.assert_array_equal(np.asarray(yb.data),
                                          ys[i * 3:(i + 1) * 3])

    def test_reiterable_and_error_propagation(self):
        pf = io.DevicePrefetcher([np.zeros(2), np.ones(2)])
        assert len(list(pf)) == 2
        assert len(list(pf)) == 2  # fresh thread per epoch

        def boom():
            yield np.zeros(2)
            raise RuntimeError("reader died")

        with pytest.raises(RuntimeError, match="reader died"):
            list(io.DevicePrefetcher(boom()))

    def test_sharding_callable_applied(self):
        import jax

        import paddle_tpu.distributed as dist

        dist.reset_mesh()
        dist.init_mesh(devices=jax.devices()[:1])
        try:
            net, o = _mlp_and_opt()
            step = dist.ShardedTrainStep(
                net, lambda m, x, y: F.cross_entropy(m(x), y), o)
            X, Y = _batch(4)
            (xb, yb), = list(io.DevicePrefetcher(
                [(paddle.to_tensor(X), paddle.to_tensor(Y))],
                sharding=step.batch_sharding))
            assert xb.data.sharding == step.batch_sharding(xb.data)
            # a prefetched batch feeds the compiled step unchanged
            float(step(xb, yb))
        finally:
            dist.reset_mesh()

    def test_fit_smoke_with_prefetch(self):
        from paddle_tpu.hapi import Model

        xs = np.random.RandomState(0).randn(16, 4).astype("float32")
        ys = np.random.RandomState(1).randint(0, 2, 16).astype("int64")
        ds = io.TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
        paddle.seed(5)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m = Model(net)
        m.prepare(opt.SGD(learning_rate=0.1, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss())
        m.fit(ds, batch_size=4, epochs=2, verbose=0, prefetch_to_device=True)


_CHILD = textwrap.dedent("""
    import json, sys
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import jit, serving
    from paddle_tpu.jit import persistent_cache as pc

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    net.eval()
    # a serving bucket warmup...
    eng = serving.ServingEngine(
        net, buckets=serving.BucketSpec(batch_sizes=(1, 2)),
        input_specs=[((4,), "float32")])
    eng.start()
    out = eng.submit([np.ones(4, "float32")]).result(timeout=60)
    stats = eng.stats()
    eng.close()
    # ...and a to_static function
    st = jit.to_static(net)
    y = st(paddle.to_tensor(np.ones((2, 4), "float32")))
    print("CHILD " + json.dumps({
        "pc": pc.stats(),
        "engine_pc": stats.get("persistent_cache"),
        "out0": float(np.asarray(y.data)[0, 0])}))
""")


def _run_child(cache_dir):
    env = dict(os.environ)
    env["PT_PERSISTENT_CACHE_DIR"] = cache_dir
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", _CHILD], capture_output=True,
                       text=True, env=env, timeout=300, cwd=REPO)
    for line in r.stdout.splitlines():
        if line.startswith("CHILD "):
            return json.loads(line[len("CHILD "):])
    raise AssertionError(f"child produced no result:\n{r.stderr[-2000:]}")


class TestPersistentCache:
    @pytest.mark.slow  # tier-1 wall-clock relief (ISSUE-5): run in full by tools/ci.sh's perf gate
    def test_warm_start_zero_fresh_compiles(self, tmp_path):
        """The acceptance contract, one cache dir, two processes: cold —
        THIS process compiles and serializes a serving bucket warmup and a
        to_static forward; warm — a fresh subprocess re-warms both with
        ZERO fresh XLA compiles (counter-asserted)."""
        from paddle_tpu import serving
        from paddle_tpu.jit import persistent_cache as pc

        d = str(tmp_path / "cache")
        old_dir, old_enabled = pc.cache_dir(), pc.is_enabled()
        pc.enable(d)
        pc.reset_stats()
        try:
            # the same programs _CHILD builds (lowered HLO must match)
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
            net.eval()
            eng = serving.ServingEngine(
                net, buckets=serving.BucketSpec(batch_sizes=(1, 2)),
                input_specs=[((4,), "float32")])
            eng.start()
            eng.submit([np.ones(4, "float32")]).result(timeout=60)
            eng.close()
            st = jit.to_static(net)
            y = st(paddle.to_tensor(np.ones((2, 4), "float32")))
            cold = pc.stats()
            out0 = float(np.asarray(y.data)[0, 0])
        finally:
            pc.disable()
            pc.reset_stats()
            if old_enabled and old_dir:
                pc.enable(old_dir)
        assert cold["misses"] > 0
        assert cold["compiles"] == cold["misses"]

        warm = _run_child(d)
        assert warm["pc"]["hits"] > 0
        assert warm["pc"]["misses"] == 0
        assert warm["pc"]["compiles"] == 0          # zero fresh XLA compiles
        assert warm["engine_pc"]["hits"] > 0
        assert warm["engine_pc"]["misses"] == 0
        assert warm["out0"] == pytest.approx(out0)
        # labels attribute the hits (surfaced via analysis.retrace summary)
        assert any(k.startswith("serving:") for k in warm["pc"]["by_label"])

    def test_corrupt_entries_ignored(self, tmp_path):
        """Garbage on disk degrades to miss + recompile + atomic rewrite,
        never an error (in-process: a fresh CachedJit instance re-consults
        the disk, so no subprocess is needed to exercise the load path)."""
        import pickle

        import jax.numpy as jnp

        from paddle_tpu.jit import persistent_cache as pc

        d = str(tmp_path / "cache")
        old_dir, old_enabled = pc.cache_dir(), pc.is_enabled()
        pc.enable(d)
        pc.reset_stats()
        try:
            fn = lambda x: (x * 3 - 1).sum()  # noqa: E731
            out0 = float(pc.cached_jit(fn, label="corrupt-probe")(
                jnp.ones((4,))))
            entries = [f for f in os.listdir(d) if f.endswith(".ptxc")]
            assert len(entries) == 1
            for f in entries:  # truncate/garbage the entry
                with open(os.path.join(d, f), "wb") as fh:
                    fh.write(b"garbage" * 3)
            pc.reset_stats()
            out1 = float(pc.cached_jit(fn, label="corrupt-probe")(
                jnp.ones((4,))))
            snap = pc.stats()
            assert out1 == pytest.approx(out0)
            assert snap["hits"] == 0 and snap["misses"] == 1
            assert snap["errors"] >= 1
            # the recompile healed the entry on disk
            for f in os.listdir(d):
                if f.endswith(".ptxc"):
                    with open(os.path.join(d, f), "rb") as fh:
                        blob = fh.read()
                    assert blob.startswith(pc._MAGIC)
                    pickle.loads(blob[len(pc._MAGIC):])
        finally:
            pc.disable()
            pc.reset_stats()
            if old_enabled and old_dir:
                pc.enable(old_dir)

    def test_stale_env_header_rejected_in_process(self, tmp_path):
        """A tampered entry whose header names another jax/platform is
        rejected at load (belt and braces over the key hash)."""
        import pickle

        from paddle_tpu.jit import persistent_cache as pc

        d = str(tmp_path / "cache3")
        old_dir, old_enabled = pc.cache_dir(), pc.is_enabled()
        pc.enable(d)
        pc.reset_stats()
        try:
            import jax.numpy as jnp

            cj = pc.cached_jit(lambda x: x * 2, label="stale-probe")
            cj(jnp.ones((3,)))
            assert pc.stats()["misses"] == 1
            entries = [f for f in os.listdir(d) if f.endswith(".ptxc")]
            assert len(entries) == 1
            path = os.path.join(d, entries[0])
            with open(path, "rb") as fh:
                blob = fh.read()
            header, payload = pickle.loads(blob[len(pc._MAGIC):])
            header["env"] = ("0.0.0", "0.0.0", "cpu", "1")
            with open(path, "wb") as fh:
                fh.write(pc._MAGIC + pickle.dumps((header, payload)))
            pc.reset_stats()
            cj2 = pc.cached_jit(lambda x: x * 2, label="stale-probe")
            out = cj2(jnp.ones((3,)))
            np.testing.assert_allclose(np.asarray(out), 2.0)
            snap = pc.stats()
            assert snap["hits"] == 0 and snap["misses"] == 1
            assert snap["errors"] >= 1
        finally:
            pc.disable()
            pc.reset_stats()
            if old_enabled and old_dir:
                pc.enable(old_dir)

    def test_disabled_cache_is_passthrough(self):
        from paddle_tpu.jit import persistent_cache as pc

        assert not pc.is_enabled()  # tier-1 runs with the cache off
        import jax.numpy as jnp

        cj = pc.cached_jit(lambda x: x + 1, label="off-probe")
        np.testing.assert_allclose(np.asarray(cj(jnp.zeros((2,)))), 1.0)
        assert pc.stats()["misses"] == 0  # nothing counted, nothing written
