"""ONNX export (reference python/paddle/onnx/export.py role): jaxpr -> .onnx
with a hand-rolled protobuf writer; validated by decoding the wire format."""
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


# -- minimal protobuf wire decoder for validation -----------------------------

def _read_varint(buf, i):
    v = s = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << s
        if not b & 0x80:
            return v, i
        s += 7


def _fields(buf):
    i = 0
    out = []
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        num, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            v = buf[i:i + 8]
            i += 8
        else:
            raise AssertionError(f"bad wire type {wire}")
        out.append((num, v))
    return out


def _group(fields):
    d = {}
    for num, v in fields:
        d.setdefault(num, []).append(v)
    return d


def _decode_model(raw):
    m = _group(_fields(raw))
    graph = _group(_fields(m[7][0]))
    nodes = [_group(_fields(n)) for n in graph.get(1, [])]
    inits = [_group(_fields(t)) for t in graph.get(5, [])]
    return {
        "ir_version": m[1][0],
        "producer": m[2][0].decode(),
        "opset": _group(_fields(m[8][0]))[2][0],
        "op_types": [n[4][0].decode() for n in nodes],
        "init_names": [t[8][0].decode() for t in inits],
        "init_raw": {t[8][0].decode(): t[9][0] for t in inits},
        "n_inputs": len(graph.get(11, [])),
        "n_outputs": len(graph.get(12, [])),
    }


def test_mlp_export_structure(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.Softmax())
    x = paddle.randn([2, 8])
    path = paddle.onnx.export(net, str(tmp_path / "mlp"), input_spec=[x])
    raw = open(path, "rb").read()
    model = _decode_model(raw)
    assert model["producer"] == "paddle_tpu"
    assert int(model["opset"]) == 17
    assert model["n_inputs"] == 1 and model["n_outputs"] == 1
    assert model["op_types"].count("MatMul") == 2
    assert "Exp" in model["op_types"] or "Softmax" in model["op_types"]
    # weights travel as initializers, bit-exact
    w0 = np.asarray(net[0].weight.data)
    raws = set(model["init_raw"].values())
    assert w0.tobytes() in raws
    assert len(model["init_names"]) >= 4  # 2 weights + 2 biases


def test_export_computes_same_function(tmp_path):
    """Decode the exported graph and re-execute it with numpy: the ONNX
    semantics of the emitted ops must reproduce the model's outputs."""
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    x = paddle.randn([5, 4])
    want = net(x).numpy()
    path = paddle.onnx.export(net, str(tmp_path / "m"), input_spec=[x])
    raw = open(path, "rb").read()
    m = _group(_fields(raw))
    graph = _group(_fields(m[7][0]))
    env = {}
    np_dt = {1: np.float32, 6: np.int32, 7: np.int64}
    for t in graph.get(5, []):
        tg = _group(_fields(t))
        dims = list(tg.get(1, []))
        env[tg[8][0].decode()] = np.frombuffer(
            tg[9][0], np_dt[tg[2][0]]).reshape(dims)
    inp = _group(_fields(graph[11][0]))[1][0].decode()
    env[inp] = x.numpy()
    out_name = _group(_fields(graph[12][0]))[1][0].decode()
    for nb in graph.get(1, []):
        n = _group(_fields(nb))
        op = n[4][0].decode()
        ins = [env[i.decode()] for i in n.get(1, [])]
        out = n[2][0].decode()
        if op == "MatMul":
            env[out] = ins[0] @ ins[1]
        elif op == "Add":
            env[out] = ins[0] + ins[1]
        elif op == "Max":
            env[out] = np.maximum(ins[0], ins[1])
        elif op in ("Identity",):
            env[out] = ins[0]
        elif op == "Reshape":
            env[out] = ins[0].reshape([int(d) for d in ins[1]])
        elif op == "Expand":
            env[out] = np.broadcast_to(ins[0], [int(d) for d in ins[1]])
        elif op == "Cast":
            env[out] = ins[0]
        else:
            pytest.fail(f"unexpected op {op} in simple MLP graph")
    np.testing.assert_allclose(env[out_name], want, rtol=1e-5)


def test_unmappable_primitive_raises_pointer(tmp_path):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny())
    ids = paddle.to_tensor(np.zeros((1, 8), "int64"))
    with pytest.raises(ValueError, match="StableHLO|no ONNX mapping"):
        paddle.onnx.export(model, str(tmp_path / "gpt"), input_spec=[ids])


def test_resnet18_export_conv_pool(tmp_path):
    """VERDICT r3 weak #5: vision export. Conv / MaxPool / Pad emit, and the
    decoded graph re-executes (jax.lax as the ONNX-semantics oracle for the
    conv/pool nodes) to the model's own outputs."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.vision import models

    paddle.seed(0)
    net = models.resnet18(num_classes=10)
    net.eval()
    x = paddle.randn([2, 3, 32, 32])
    want = net(x).numpy()
    path = paddle.onnx.export(net, str(tmp_path / "rn18"), input_spec=[x])
    raw = open(path, "rb").read()
    m = _group(_fields(raw))
    graph = _group(_fields(m[7][0]))
    env = {}
    np_dt = {1: np.float32, 6: np.int32, 7: np.int64}
    for t in graph.get(5, []):
        tg = _group(_fields(t))
        dims = list(tg.get(1, []))
        env[tg[8][0].decode()] = np.frombuffer(
            tg[9][0], np_dt[tg[2][0]]).reshape(dims)
    inp = _group(_fields(graph[11][0]))[1][0].decode()
    env[inp] = x.numpy()
    out_name = _group(_fields(graph[12][0]))[1][0].decode()

    def attrs_of(n):
        out = {}
        for ab in n.get(5, []):
            a = _group(_fields(ab))
            nm = a[1][0].decode()
            kind = a[20][0]
            if kind == 2:
                out[nm] = a[3][0]
            elif kind == 7:
                out[nm] = list(a.get(8, []))
            elif kind == 3:
                out[nm] = a[4][0].decode()
        return out

    seen_ops = set()
    for nb in graph.get(1, []):
        n = _group(_fields(nb))
        op = n[4][0].decode()
        seen_ops.add(op)
        ins = [env[i.decode()] for i in n.get(1, [])]
        out = n[2][0].decode()
        at = attrs_of(n)
        if op == "Conv":
            pads = at.get("pads", [0, 0, 0, 0])
            nsp = len(pads) // 2
            env[out] = np.asarray(jax.lax.conv_general_dilated(
                jnp.asarray(ins[0]), jnp.asarray(ins[1]),
                window_strides=at.get("strides", [1] * nsp),
                padding=list(zip(pads[:nsp], pads[nsp:])),
                rhs_dilation=at.get("dilations", [1] * nsp),
                feature_group_count=int(at.get("group", 1))))
        elif op == "MaxPool":
            k = at["kernel_shape"]
            s = at.get("strides", [1] * len(k))
            pads = at.get("pads", [0] * (2 * len(k)))
            nsp = len(k)
            env[out] = np.asarray(jax.lax.reduce_window(
                jnp.asarray(ins[0]), -jnp.inf, jax.lax.max,
                (1, 1) + tuple(k), (1, 1) + tuple(s),
                ((0, 0), (0, 0)) + tuple(zip(pads[:nsp], pads[nsp:]))))
        elif op == "AveragePool":
            k = at["kernel_shape"]
            s = at.get("strides", [1] * len(k))
            pads = at.get("pads", [0] * (2 * len(k)))
            nsp = len(k)
            ssum = jax.lax.reduce_window(
                jnp.asarray(ins[0]), 0.0, jax.lax.add,
                (1, 1) + tuple(k), (1, 1) + tuple(s),
                ((0, 0), (0, 0)) + tuple(zip(pads[:nsp], pads[nsp:])))
            cnt = 1
            for d in k:
                cnt *= int(d)
            env[out] = np.asarray(ssum) / cnt  # count_include_pad=1
        elif op == "MatMul":
            env[out] = ins[0] @ ins[1]
        elif op == "Add":
            env[out] = ins[0] + ins[1]
        elif op == "Sub":
            env[out] = ins[0] - ins[1]
        elif op == "Mul":
            env[out] = ins[0] * ins[1]
        elif op == "Div":
            env[out] = ins[0] / ins[1]
        elif op == "Max":
            env[out] = np.maximum(ins[0], ins[1])
        elif op == "Sqrt":
            env[out] = np.sqrt(ins[0])
        elif op == "Reciprocal":
            env[out] = 1.0 / ins[0]
        elif op in ("Identity", "Cast"):
            env[out] = ins[0]
        elif op == "Reshape":
            env[out] = ins[0].reshape([int(d) for d in ins[1]])
        elif op == "Expand":
            env[out] = np.broadcast_to(ins[0], [int(d) for d in ins[1]])
        elif op == "ReduceSum":
            env[out] = ins[0].sum(axis=tuple(int(a) for a in ins[1]))
        elif op == "Pad":
            pads = [int(v) for v in ins[1]]
            nd = len(pads) // 2
            env[out] = np.pad(ins[0],
                              list(zip(pads[:nd], pads[nd:])),
                              constant_values=float(ins[2]))
        else:
            pytest.fail(f"re-executor missing op {op}")
    assert "Conv" in seen_ops and "MaxPool" in seen_ops
    np.testing.assert_allclose(env[out_name], want, rtol=2e-4, atol=2e-5)
