"""The examples/ scripts must actually run (tiny variants, CPU)."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=420):
    r = subprocess.run([sys.executable] + args, capture_output=True,
                       text=True, timeout=timeout, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    return r.stdout


def test_train_llama_tiny():
    out = _run(["examples/train_llama_tpu.py", "--tiny", "--steps", "6"])
    assert "loss" in out


def test_finetune_bert_tiny():
    out = _run(["examples/finetune_bert.py", "--tiny"])
    assert "held-out accuracy" in out


def test_static_mode_example():
    out = _run(["examples/static_mode_train.py"])
    assert "served output shape" in out


def test_ps_recsys_example():
    out = _run(["examples/ps_recsys.py"])
    assert "epoch 2" in out


def test_train_moe_tiny():
    out = _run(["examples/train_moe.py", "--tiny", "--steps", "6"])
    assert "OK" in out


def test_generate_gpt_example():
    out = _run(["examples/generate_gpt.py"])
    assert "OK" in out


@pytest.mark.slow  # tier-1 wall clock is near its budget; tools/ci.sh runs
def test_serve_gpt_example():  # this demo directly in the serving gate
    out = _run(["examples/serve_gpt.py", "--clients", "4"])
    assert "OK" in out
    assert "stats:" in out


def test_distributed_example_virtual_mesh():
    out = _run(["examples/distributed_data_parallel.py", "--virtual", "4"])
    assert "OK" in out
