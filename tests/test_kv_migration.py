"""ISSUE 18: disaggregated prefill/decode serving with KV-page
migration and the fleet-wide tiered prefix cache.

Covers the acceptance surface without paying for processes where the
logic is pure or in-process: pack/unpack bit-exactness (fp32) and the
int8 parity/byte-ratio contract, the chunked wire discipline (per-chunk
SHA, whole-blob digest, corruption rejection), ghost-gated admission
and LRU residency in both warm tiers (``FleetKVCache``, the replica's
``HostPagePool``), pool-aware dispatch scoring, the cost model's
ship-vs-reprefill crossover, the fleet's prefill->decode handoff state
machine (in-process replicas, every failure mode falling back to
re-prefill), and engine-level export/install loopback bit-identity
(slow).  The real 3-process migration protocol is drilled end to end
by ``tools/kv_migration_drill.py`` (ci.sh kv-migration gate).
"""
import time
from concurrent.futures import Future

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.cost_model.comm import (
    LinkModel, kv_migration_crossover, kv_reprefill_seconds,
    kv_ship_seconds, link_model_for,
)
from paddle_tpu.serving import ServingFleet, ServingFleetPolicy
from paddle_tpu.serving.kv_transfer import (
    FleetKVCache, KVMigrationStats, assemble_chunks, chunk_blob,
    dequantize_page, pack_kv_pages, prompt_cache_key, quantize_page,
    unpack_kv_pages,
)
from paddle_tpu.serving.metrics import MetricsRegistry
from paddle_tpu.serving.paged_kv import HostPagePool
from paddle_tpu.serving.router import RouterConfig, score_candidates


def _pages(npages=3, layers=2, pl=8, heads=2, dim=4, seed=0):
    rng = np.random.RandomState(seed)
    k = [rng.randn(npages, pl, heads, dim).astype(np.float32)
         for _ in range(layers)]
    v = [rng.randn(npages, pl, heads, dim).astype(np.float32)
         for _ in range(layers)]
    return k, v


# -- pack / quantize / chunk (pure) -------------------------------------------

def test_pack_unpack_fp32_bit_exact():
    k, v = _pages()
    blob, manifest, meta = pack_kv_pages(k, v)
    assert meta["npages"] == 3 and meta["layers"] == 2
    assert not meta["quantized"]
    assert meta["wire_bytes"] == meta["fp32_bytes"] == len(blob)
    k2, v2 = unpack_kv_pages(blob, manifest)
    for a, b in zip(k + v, k2 + v2):
        np.testing.assert_array_equal(a, b)     # byte-exact, not close


def test_pack_unpack_int8_parity_and_wire_ratio():
    k, v = _pages(seed=1)
    blob, manifest, meta = pack_kv_pages(k, v, quantize=True)
    assert meta["quantized"]
    # the transit contract: int8 + per-page scales <= 0.55x fp32 bytes
    assert meta["wire_bytes"] <= 0.55 * meta["fp32_bytes"]
    k2, v2 = unpack_kv_pages(blob, manifest)
    for a, b in zip(k + v, k2 + v2):
        assert b.dtype == a.dtype
        # per-page symmetric int8: error bounded by scale/2 per element
        scale = np.abs(a).max(axis=(1, 2, 3), keepdims=True) / 127.0
        assert np.all(np.abs(a - b) <= scale / 2 + 1e-7)


def test_quantize_page_zero_and_roundtrip():
    q, s = quantize_page(np.zeros((4, 2, 2), np.float32))
    assert s > 0                                # never divides by zero
    np.testing.assert_array_equal(dequantize_page(q, s), 0.0)
    a = np.linspace(-3, 3, 16, dtype=np.float32).reshape(4, 2, 2)
    q, s = quantize_page(a)
    assert q.dtype == np.int8 and np.abs(dequantize_page(q, s) - a).max() \
        <= s / 2 + 1e-7


def test_chunk_assemble_digest_and_corruption():
    blob = bytes(range(256)) * 700              # several chunks
    chunks = chunk_blob(blob, chunk_bytes=50_000)
    assert len(chunks) == 4
    digest = None
    _b, _m, meta = pack_kv_pages(*_pages(npages=1, layers=1))
    digest = meta["digest"]                     # digest shape sanity
    assert len(digest) == 64
    import hashlib
    whole = hashlib.sha256(blob).hexdigest()
    # out-of-order delivery reassembles by idx
    got = assemble_chunks(list(reversed(chunks)), digest=whole)
    assert got == blob
    # a corrupted chunk is rejected by its per-chunk SHA
    bad = [dict(c) for c in chunks]
    import base64
    raw = bytearray(base64.b64decode(bad[2]["data"]))
    raw[0] ^= 0xFF
    bad[2]["data"] = base64.b64encode(bytes(raw)).decode("ascii")
    with pytest.raises(ValueError, match="SHA mismatch"):
        assemble_chunks(bad)
    # a missing chunk breaks the sequence
    with pytest.raises(ValueError, match="sequence broken"):
        assemble_chunks(chunks[:1] + chunks[2:])
    # whole-blob digest catches a consistent-but-wrong reassembly
    with pytest.raises(ValueError, match="digest mismatch"):
        assemble_chunks(chunks, digest="0" * 64)


def test_prompt_cache_key_full_page_identity():
    assert prompt_cache_key([1, 2, 3], 4) is None       # < 1 full page
    a = prompt_cache_key([1, 2, 3, 4, 5], 4)
    b = prompt_cache_key([1, 2, 3, 4, 9], 4)            # same full page
    assert a == b and a is not None
    assert prompt_cache_key([1, 2, 3, 5, 5], 4) != a    # differs in-page
    assert prompt_cache_key([1, 2, 3, 4], 2) != \
        prompt_cache_key([1, 2, 3, 4], 4)               # page_len keyed


# -- warm tiers (ghost-gated admission, LRU residency) ------------------------

def test_fleet_kv_cache_ghost_admission_lru_and_stats():
    c = FleetKVCache(capacity_bytes=300, admit_threshold=2)
    pay = lambda n: {"data": b"x" * n}
    # 1st put only feeds the ghost counter; 2nd is admitted
    assert not c.put("a", pay(100))
    assert c.get("a") is None
    assert c.put("a", pay(100))
    assert c.get("a") is not None
    # capacity eviction is LRU: admit b and c (2 puts each), then touch
    # a so b becomes LRU, then admit d -> b evicted
    for k in ("b", "c"):
        c.put(k, pay(100))
        assert c.put(k, pay(100))
    assert c.get("a") is not None
    c.put("d", pay(100))
    assert c.put("d", pay(100))
    st = c.stats()
    assert st["entries"] == 3 and st["bytes"] == 300
    assert st["evictions"] >= 1 and st["admits"] == 4
    assert c.get("b") is None                   # the LRU victim
    # an over-capacity payload is never admitted
    c.put("huge", pay(1000))
    assert not c.put("huge", pay(1000))
    assert c.get(None) is None and not c.put(None, pay(1))


def test_host_page_pool_ghost_gate_and_quantized_residency():
    hp = HostPagePool(capacity_bytes=1 << 20, admit_threshold=2)
    k = [np.random.RandomState(0).randn(8, 2, 4).astype(np.float32)]
    v = [np.random.RandomState(1).randn(8, 2, 4).astype(np.float32)]
    assert not hp.put(("x",), k, v)             # unseen: ghost-rejected
    hp.note_access(("x",))
    hp.note_access(("x",))
    assert hp.put(("x",), k, v)
    got = hp.get(("x",))
    assert got is not None
    k2, v2 = got
    assert np.abs(k2[0] - k[0]).max() < 0.02    # int8 parity bound
    assert np.abs(v2[0] - v[0]).max() < 0.02
    assert hp.stats()["bytes"] < k[0].nbytes + v[0].nbytes  # int8 resident


def test_kv_migration_stats_snapshot():
    s = KVMigrationStats()
    s.note_ship(4, 100, 400, quantized=True)
    s.note_ship(2, 200, 200, quantized=False)
    s.note_install(3.0)
    s.note_install(5.0)
    s.note_export()
    s.note_warm_hit()
    s.note_fallback()
    s.note_failover(ship=True)
    s.note_failover(ship=False)
    snap = s.snapshot()
    assert snap["ships"] == 2 and snap["pages_shipped"] == 6
    assert snap["wire_bytes"] == 300 and snap["fp32_bytes"] == 600
    assert snap["transit_quantized_fraction"] == 0.5
    assert snap["install_ms_avg"] == 4.0
    assert snap["failover_ship"] == 1 and snap["failover_reprefill"] == 1
    assert snap["migrate_fallback"] == 1 and snap["warm_hits"] == 1


# -- pool-aware dispatch scoring ----------------------------------------------

class _Cand:
    def __init__(self, name, depth=0, headroom=1.0, match=0):
        self.name = name
        self.metrics = MetricsRegistry()
        self._d, self._h, self._m = depth, headroom, match

    def queue_depth(self):
        return self._d

    def kv_headroom(self):
        return self._h

    def prefix_match_tokens(self, prompt, blocks=None):
        return self._m


def test_score_candidates_pool_weighting():
    cfg = RouterConfig()
    prompt = np.arange(16)
    deep = _Cand("deep", depth=10, headroom=0.9)
    tight = _Cand("tight", depth=1, headroom=0.05, match=16)
    # prefill pool: queue depth dominates, KV pressure barely matters ->
    # the shallow-queue replica wins even with no headroom
    s, _ = score_candidates(cfg, prompt, [deep, tight], pool="prefill")
    assert s[1] < s[0]
    # decode pool: headroom + affinity dominate; a page-holding replica
    # with moderate queue beats an empty cold one
    holder = _Cand("holder", depth=3, headroom=0.6, match=16)
    cold = _Cand("cold", depth=0, headroom=0.7)
    s, m = score_candidates(cfg, prompt, [cold, holder], pool="decode")
    assert s[1] < s[0] and m == [0, 16]
    # None keeps the fused weighting (back-compat with ReplicaRouter)
    s_none, _ = score_candidates(cfg, prompt, [cold, holder])
    s_dec, _ = score_candidates(cfg, prompt, [cold, holder], pool="decode")
    assert s_none != s_dec


# -- cost model: migration vs re-prefill crossover ----------------------------

def test_kv_ship_and_reprefill_pricing_monotone():
    lm = link_model_for("cpu-host")
    assert kv_ship_seconds(lm, 2 << 20) > kv_ship_seconds(lm, 1 << 20)
    assert kv_reprefill_seconds(lm, 512, 1e6) > \
        kv_reprefill_seconds(lm, 256, 1e6)
    assert kv_ship_seconds(lm, 0) > 0           # RPC overhead floor


def test_kv_migration_crossover_shape_and_quantize_shift():
    lm = link_model_for("cpu-host")
    out = kv_migration_crossover(lm, page_len=8, bytes_per_page=1 << 16,
                                 flops_per_token=5e7)
    assert set(out) >= {"crossover_pages", "ship_s", "reprefill_s"}
    n = out["crossover_pages"]
    assert n is not None and n >= 1
    # int8 halves the wire bytes: the crossover can only move EARLIER
    qout = kv_migration_crossover(lm, page_len=8, bytes_per_page=1 << 16,
                                  flops_per_token=5e7, quantized=True)
    assert qout["crossover_pages"] is not None
    assert qout["crossover_pages"] <= n
    # a link too slow to ever win reports None, not a bogus page count
    slow = LinkModel(name="slowlink", peak_flops=1e15,
                     host_bytes_per_s=1e3, dispatch_s=0.0)
    assert kv_migration_crossover(slow, page_len=8,
                                  bytes_per_page=1 << 20,
                                  flops_per_token=1.0,
                                  max_pages=64)["crossover_pages"] is None


# -- the fleet's handoff state machine (in-process replicas) ------------------

class _FakeReplica:
    """GenerationEngine-shaped stub (no export/install: every migration
    takes the re-prefill fallback, which is the path under test)."""

    def __init__(self, name):
        self.name = name
        self.metrics = MetricsRegistry()
        self.jobs = []            # (prompt, max_new, on_token, future)
        self.cancelled = []
        self.spec = True

    def start(self):
        return self

    def close(self, drain=True):
        pass

    def restart(self):
        pass

    def fence(self):
        pass

    def drain(self):
        pass

    def health(self):
        return True

    def queue_depth(self):
        return len(self.jobs)

    def stats(self):
        return self.metrics.snapshot()

    def kv_headroom(self):
        return 1.0

    def prefix_match_tokens(self, prompt, blocks=None):
        return 0

    def set_speculative(self, on):
        self.spec = on

    def cancel(self, fut):
        self.cancelled.append(fut)
        return False

    def submit(self, prompt, max_new_tokens=16, deadline_ms=None,
               on_token=None):
        fut = Future()
        self.jobs.append((np.asarray(prompt), int(max_new_tokens),
                          on_token, fut))
        return fut

    def finish_job(self, i=0):
        prompt, mx, cb, fut = self.jobs.pop(i)
        toks = [int(prompt[-1]) + 1 + j for j in range(mx)]
        for t in toks:
            if cb:
                cb(t)
        fut.set_result(np.asarray(list(prompt) + toks, np.int64))


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def _pooled_fleet(min_ship_tokens=4, **kw):
    pol = ServingFleetPolicy(poll_interval=0.02, hedge_ms=None)
    pre, d0, d1 = (_FakeReplica(n) for n in ("pre", "d0", "d1"))
    fleet = ServingFleet(
        replicas=[pre, d0, d1],
        pools={"prefill": ["pre"], "decode": ["d0", "d1"]},
        policy=pol, min_ship_tokens=min_ship_tokens, **kw).start()
    return fleet, pre, (d0, d1)


def test_fleet_pool_validation():
    reps = [_FakeReplica("a"), _FakeReplica("b")]
    with pytest.raises(ValueError, match="unknown replica"):
        ServingFleet(replicas=reps, pools={"prefill": ["zz"],
                                           "decode": ["b"]})
    with pytest.raises(ValueError, match="pool"):
        ServingFleet(replicas=reps, pools={"prefil": ["a"]})
    with pytest.raises(ValueError, match="two pools"):
        ServingFleet(replicas=reps, pools={"prefill": ["a"],
                                           "decode": ["a", "b"]})
    with pytest.raises(ValueError, match="kv_transit"):
        ServingFleet(replicas=reps, kv_transit="fp16")


def test_fleet_prefill_leg_caps_one_token_then_decode_continues():
    """The handoff contract: a fresh request lands on the prefill pool
    capped to ONE token; the decode leg carries prompt+that token and
    the REMAINING budget; the stream is exactly-once; stubs without an
    export surface take the re-prefill fallback (counted)."""
    fleet, pre, (d0, d1) = _pooled_fleet()
    try:
        streamed = []
        fut = fleet.submit([7, 8, 9, 10], max_new_tokens=4,
                           on_token=streamed.append)
        assert _wait(lambda: pre.jobs)
        p, mx, _cb, _f = pre.jobs[0]
        assert p.tolist() == [7, 8, 9, 10] and mx == 1
        assert not d0.jobs and not d1.jobs      # decode waits for handoff
        pre.finish_job()                        # emits token 11
        assert _wait(lambda: d0.jobs or d1.jobs)
        dec = d0 if d0.jobs else d1
        dp, dmx, _dc, _df = dec.jobs[0]
        assert dp.tolist() == [7, 8, 9, 10, 11]  # prompt + prefill token
        assert dmx == 3                          # remaining budget only
        dec.finish_job()
        out = fut.result(timeout=10)
        assert out.tolist() == [7, 8, 9, 10, 11, 12, 13, 14]
        assert streamed == [11, 12, 13, 14]      # exactly-once stream
        snap = fleet.provider_snapshot()
        assert snap["counters"]["prefill_handoffs"] == 1
        assert snap["counters"]["migrate_fallback"] == 1  # no export seam
        assert snap["replicas"]["pre"]["pool"] == "prefill"
        assert snap["replicas"]["d0"]["pool"] == "decode"
        mig = fleet.kv_migration_snapshot()
        assert mig["migrate_fallback"] == 1 and mig["ships"] == 0
        assert mig["pools"] == {"pre": "prefill", "d0": "decode",
                                "d1": "decode"}
    finally:
        fleet.close()


def test_fleet_short_prompt_and_single_token_skip_prefill_pool():
    fleet, pre, (d0, d1) = _pooled_fleet(min_ship_tokens=8)
    try:
        f1 = fleet.submit([1, 2, 3], max_new_tokens=4)   # short prompt
        f2 = fleet.submit([1, 2, 3, 4, 5, 6, 7, 8],
                          max_new_tokens=1)              # nothing to ship
        assert _wait(lambda: len(d0.jobs) + len(d1.jobs) == 2)
        assert not pre.jobs
        for r in (d0, d1):
            while r.jobs:
                r.finish_job()
        f1.result(timeout=10)
        f2.result(timeout=10)
        assert "prefill_handoffs" not in \
            fleet.provider_snapshot()["counters"]
    finally:
        fleet.close()


def test_fleet_empty_prefill_pool_degrades_to_fused_path():
    """A dead prefill tier must not strand traffic: requests fall back
    to direct decode-pool dispatch (counted), streams still complete."""
    pol = ServingFleetPolicy(poll_interval=0.02, hedge_ms=None)
    pre, d0 = _FakeReplica("pre"), _FakeReplica("d0")
    fleet = ServingFleet(replicas=[pre, d0],
                         pools={"prefill": ["pre"], "decode": ["d0"]},
                         policy=pol, min_ship_tokens=4).start()
    try:
        fleet.fence_replica("pre", cause="test_kill")
        fut = fleet.submit([5, 6, 7, 8], max_new_tokens=2)
        assert _wait(lambda: d0.jobs)
        p, mx, _cb, _f = d0.jobs[0]
        assert p.tolist() == [5, 6, 7, 8] and mx == 2    # fused leg
        d0.finish_job()
        assert fut.result(timeout=10).tolist() == [5, 6, 7, 8, 9, 10]
    finally:
        fleet.close()


def test_kv_migration_provider_on_hub():
    from paddle_tpu import observability as obs

    fleet, pre, (d0, d1) = _pooled_fleet()
    try:
        hub = obs.snapshot()["kv_migration"]
        assert hub["transit"] == "fp32"
        assert hub["warm_cache"]["entries"] == 0
        assert hub["pending_migrations"] == 0
        assert hub["pools"]["pre"] == "prefill"
    finally:
        fleet.close()


# -- real-engine integration (slow legs; the ci.sh gate runs them) ------------

@pytest.fixture(scope="module")
def tiny_lm():
    """1-layer GPT trained to continue the repeating 0..7 pattern."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=64,
                    dtype="float32")
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-3,
                          parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y),
                         optimizer)
    pattern = np.tile(np.arange(8), 8)
    ids = paddle.to_tensor(pattern[None, :].astype("int64"))
    for _ in range(80):
        loss = step(ids, ids)
    assert float(loss) < 0.1
    return model, pattern


def _mk_engine(model, name):
    return serving.GenerationEngine(
        model, serving.GenerationConfig(max_slots=2, max_seq_len=48,
                                        page_len=8,
                                        prefill_buckets=(8, 16, 24, 32,
                                                         40)),
        name=name)


@pytest.mark.slow  # real engine compiles; ci.sh kv-migration gate runs it
def test_engine_export_install_loopback_bit_identical(tiny_lm):
    """The page shipper's engine seam: export the prompt's pages from
    one engine, install into another, and the continuation stream is
    bit-identical to an uninterrupted single-engine decode — through
    BOTH transits (fp32 byte-exact install, int8 dequantized)."""
    model, pattern = tiny_lm
    src = _mk_engine(model, "kvm_src").start()
    dst = _mk_engine(model, "kvm_dst").start()
    ref_eng = _mk_engine(model, "kvm_ref").start()
    try:
        prompt = pattern[:32].astype("int64")   # 4 full pages
        ref = ref_eng.submit(prompt, max_new_tokens=9).result(
            timeout=300).tolist()
        first = src.submit(prompt, max_new_tokens=1).result(timeout=300)
        t0 = int(first[32])
        assert t0 == ref[32]
        with pytest.raises(KeyError):           # uncached prompt: no export
            src.export_kv_pages(np.arange(16, 32, dtype=np.int64))
        n, k_st, v_st = src.export_kv_pages(prompt)
        assert n == 4 and k_st[0].shape == (4, 8, 2, 16)
        # fp32 transit is byte-exact end to end
        blob, manifest, _meta = pack_kv_pages(k_st, v_st)
        k2, v2 = unpack_kv_pages(blob, manifest)
        assert dst.install_kv_pages(prompt, k2, v2) == 4
        cont = dst.submit(np.append(prompt, t0).astype("int64"),
                          max_new_tokens=8).result(timeout=300)
        assert cont.tolist() == ref             # bit-identical stream
        # the decode leg ran on a full prefix hit, not a re-prefill
        st = dst.stats()["kv_pages"]["prefix"]
        assert st["hits"] >= 1 and st["hit_tokens"] >= 32
        assert dst.metrics.counter("kv_installs") == 1
        assert src.metrics.counter("kv_exports") >= 1
        # installing the same prompt again adopts nothing (first writer
        # wins), and never leaks pages
        assert dst.install_kv_pages(prompt, k2, v2) == 0
        alloc = dst._pool.allocator
        alloc.check()
    finally:
        for e in (src, dst, ref_eng):
            e.close()


@pytest.mark.slow  # real engines behind an in-process pooled fleet
def test_inprocess_pooled_fleet_migration_bit_identical(tiny_lm):
    """A split fleet over REAL engines (in-process seam): the prefill
    replica fills pages, the supervisor ships them to a decode replica,
    and the stream equals the engine's own uninterrupted greedy decode.
    Repeats of the same prompt then hit the fleet-wide warm tier."""
    model, pattern = tiny_lm
    ref_eng = _mk_engine(model, "kvm_fref").start()
    pre = _mk_engine(model, "kvm_fpre")
    d0 = _mk_engine(model, "kvm_fd0")
    fleet = ServingFleet(
        replicas=[pre, d0],
        pools={"prefill": ["kvm_fpre"], "decode": ["kvm_fd0"]},
        policy=ServingFleetPolicy(poll_interval=0.02, hedge_ms=None),
        min_ship_tokens=8)
    fleet.start()
    try:
        prompt = pattern[:32].astype("int64")
        ref = ref_eng.submit(prompt, max_new_tokens=9).result(
            timeout=300).tolist()
        outs = [fleet.submit(prompt, max_new_tokens=9).result(
            timeout=300).tolist() for _ in range(3)]
        for out in outs:
            assert out == ref                    # bit-identical stream
        snap = fleet.provider_snapshot()
        assert snap["counters"]["prefill_handoffs"] == 3
        assert snap["counters"]["migrations"] == 3
        assert snap["counters"].get("migrate_fallback", 0) == 0
        mig = fleet.kv_migration_snapshot()
        assert mig["ships"] == 3 and mig["pages_shipped"] == 12
        assert mig["installs"] == 3
        # warm tier: put #1 ghost-rejected, #2 admitted, #3 a hit —
        # only the first two migrations export from the prefill replica
        assert mig["warm_hits"] == 1 and mig["exports"] == 2
        assert mig["warm_cache"]["entries"] == 1
    finally:
        fleet.close()
        ref_eng.close()
