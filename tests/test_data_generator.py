"""fleet.data_generator: slot text format emit/parse roundtrip + the
SlotDataset (InMemoryDataset role) feeding a DataLoader and the PS trainer
path. Reference: python/paddle/distributed/fleet/data_generator/
data_generator.py:21,239,283."""
import io
import sys

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.data_generator import (
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
    SlotDataset, parse_multi_slot)


class WordsLabel(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def local_iter():
            toks = [int(x) for x in line.split()]
            yield [("words", toks[:-1]), ("label", [toks[-1]])]
        return local_iter


def test_multi_slot_emit_format():
    gen = WordsLabel()
    out = gen.run_from_memory(["1926 8 17 1", "3 4 0"])
    # reference format: "len id id ... len id"
    assert out == ["3 1926 8 17 1 1\n", "2 3 4 1 0\n"]


def test_string_generator_passthrough():
    class G(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("words", ["1926", "08", "17"]), ("label", ["1"])]
            return it

    assert G().run_from_memory([None]) == ["3 1926 08 17 1 1\n"]


def test_proto_consistency_enforced():
    gen = WordsLabel()
    gen.run_from_memory(["1 2 3 0"])

    class Bad(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("other", [1])]
            return it

    bad = Bad()
    bad._proto_info = gen._proto_info  # simulate slot drift mid-stream
    with pytest.raises(ValueError, match="number of slots|must stay"):
        bad.run_from_memory([None])


def test_generate_batch_hook():
    class Doubler(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("x", [line.strip()])]
            return it

        def generate_batch(self, samples):
            def it():
                for s in samples:
                    name, vals = s[0]
                    yield [(name, vals + vals)]
            return it

    g = Doubler()
    g.set_batch(2)
    assert g.run_from_memory(["a", "b", "c"]) == \
        ["2 a a\n", "2 b b\n", "2 c c\n"]


def test_empty_slot_rejected_at_generation_time():
    """A 0-length slot would desync the len-prefixed reader one slot later;
    both generators must refuse to emit it (reference contract)."""

    class Empty(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("words", []), ("label", [1])]
            return it

    with pytest.raises(ValueError, match="can not be empty"):
        Empty().run_from_memory([None])

    class EmptyStr(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("words", ["a"]), ("label", [])]
            return it

    with pytest.raises(ValueError, match="can not be empty"):
        EmptyStr().run_from_memory([None])


def test_run_from_stdin_pipe(monkeypatch, capsys):
    gen = WordsLabel()
    monkeypatch.setattr(sys, "stdin", io.StringIO("5 6 1\n7 0\n"))
    gen.run_from_stdin()
    assert capsys.readouterr().out == "2 5 6 1 1\n1 7 1 0\n"


def test_parse_roundtrip_and_errors():
    slots = parse_multi_slot("3 1926 8 17 1 1", 2)
    assert slots == [[1926, 8, 17], [1]]
    assert parse_multi_slot("1 0.5 2 1 2", 2) == [[0.5], [1, 2]]
    with pytest.raises(ValueError, match="ended early"):
        parse_multi_slot("3 1 2 3", 2)
    with pytest.raises(ValueError, match="trailing"):
        parse_multi_slot("1 1 1 1 99", 2)


def test_slot_dataset_stable_slot_dtype():
    """A slot with mixed int/float lines keeps ONE dtype across samples."""
    ds = SlotDataset(["score"], pad_to=2)
    ds.load_lines(["1 1", "1 0.5"])
    a0, = ds[0]
    a1, = ds[1]
    assert a0.dtype == a1.dtype == np.float32
    ints = SlotDataset(["ids"]).load_lines(["2 7 8"])
    assert ints[0][0].dtype == np.int64


def test_slot_dataset_dataloader_to_ps_trainer():
    """End-to-end PS data path: generator lines -> SlotDataset (padded) ->
    io.DataLoader batches -> sparse pull/push through the PS tables."""
    import paddle_tpu.io as pio
    from paddle_tpu.distributed.ps import ParameterServer, PsTrainer
    from paddle_tpu.distributed.store import TCPStore

    gen = WordsLabel()
    lines = gen.run_from_memory(["1 2 3 1", "4 5 0", "6 1", "2 7 8 1"])
    ds = SlotDataset(["words", "label"], pad_to=4, pad_value=0)
    ds.load_lines(lines)
    assert len(ds) == 4
    words0, label0 = ds[0]
    assert words0.tolist() == [1, 2, 3, 0] and label0.tolist() == [1, 0, 0, 0]

    loader = pio.DataLoader(ds, batch_size=2, shuffle=False)
    batches = list(loader)
    assert len(batches) == 2
    assert tuple(batches[0][0].shape) == (2, 4)

    store = TCPStore(is_master=True)
    try:
        ps = ParameterServer(store)
        ps.create_table("emb", (16, 4), lr=0.5)
        ps.run()
        tr = PsTrainer(store)
        for words, label in batches:
            ids = np.asarray(words.numpy(), np.int64).reshape(-1)
            vecs = tr.pull("emb", ids)
            assert vecs.shape == (ids.size, 4)
            tr.push("emb", ids, np.ones_like(vecs), wait=True)
        after = tr.pull("emb", np.array([1], np.int64))
        assert after.shape == (1, 4)
        ps.stop()
    finally:
        store.close()
