"""Distributed checkpoint resharding + TCPStore + p2p + multiprocess loader."""
import multiprocessing
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from jax.sharding import PartitionSpec as P


def _np(t):
    return np.asarray(t.data)


# -- distributed checkpoint ---------------------------------------------------

def test_checkpoint_roundtrip_replicated(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    path = os.path.join(str(tmp_path), "ckpt")
    dist.save_state_dict(net.state_dict(), path)

    net2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    dist.load_state_dict(net2.state_dict(), path)
    for (k1, p1), (k2, p2) in zip(net.state_dict().items(),
                                  net2.state_dict().items()):
        np.testing.assert_array_equal(_np(p1), _np(p2))


def test_checkpoint_reshard_across_meshes(tmp_path):
    """Save with params sharded one way, load onto a different mesh layout."""
    import jax

    paddle.seed(1)
    path = os.path.join(str(tmp_path), "reshard")

    # save under an sdp=8 mesh with weights sharded over rows
    env1 = dist.init_mesh(sharding=8)
    w = paddle.randn([16, 8])
    w.data = jax.device_put(w.data, env1.sharding_for(P("sdp", None)))
    sd = {"w": w}
    dist.save_state_dict(sd, path)
    assert len([f for f in os.listdir(path) if f.endswith(".npy")]) >= 8
    w_ref = _np(w)
    dist.reset_mesh()

    # restore under mp2 x dp4, sharded over columns this time
    env2 = dist.init_mesh(mp=2, dp=4)
    w2 = paddle.zeros([16, 8])
    w2.data = jax.device_put(w2.data, env2.sharding_for(P(None, "mp")))
    dist.load_state_dict({"w": w2}, path)
    np.testing.assert_array_equal(_np(w2), w_ref)
    # target sharding preserved after load
    assert w2.data.sharding.spec == P(None, "mp")
    dist.reset_mesh()


def test_checkpoint_missing_key_raises(tmp_path):
    path = os.path.join(str(tmp_path), "ck")
    dist.save_state_dict({"a": paddle.ones([2])}, path)
    with pytest.raises(ValueError):
        dist.load_state_dict({"a": paddle.zeros([2]), "b": paddle.zeros([3])}, path)


def test_save_load_sharded_model_with_optimizer(tmp_path):
    from paddle_tpu.distributed.checkpoint import (save_sharded_model,
                                                   load_sharded_model)

    paddle.seed(2)
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(0.01, parameters=net.parameters())
    x = paddle.randn([8, 4])
    net(x).sum().backward()
    opt.step()
    opt.clear_grad()
    path = os.path.join(str(tmp_path), "m")
    save_sharded_model(net, opt, path)

    net2 = nn.Linear(4, 4)
    opt2 = paddle.optimizer.Adam(0.01, parameters=net2.parameters())
    load_sharded_model(net2, opt2, path)
    np.testing.assert_array_equal(_np(net.weight), _np(net2.weight))


# -- TCPStore (native C++ daemon) --------------------------------------------

def test_tcpstore_set_get_add():
    master = dist.TCPStore(is_master=True, world_size=1)
    try:
        master.set("alpha", b"hello")
        assert master.get("alpha") == b"hello"
        assert master.add("ctr", 5) == 5
        assert master.add("ctr", 2) == 7
        master.set("large", b"x" * 100_000)
        assert master.get("large") == b"x" * 100_000
        master.delete_key("alpha")
        master.set("alpha", b"new")
        assert master.get("alpha") == b"new"
    finally:
        master.close()


def _store_client(port, results):
    import paddle_tpu.distributed as dist

    client = dist.TCPStore(port=port, is_master=False, world_size=2)
    client.wait(["ready"])
    results.put(client.get("ready"))
    client.add("joined", 1)
    client.close()


def test_tcpstore_cross_process_rendezvous():
    master = dist.TCPStore(is_master=True, world_size=2)
    try:
        ctx = multiprocessing.get_context("fork")
        results = ctx.Queue()
        proc = ctx.Process(target=_store_client, args=(master.port, results))
        proc.start()
        time.sleep(0.2)
        master.set("ready", b"go")  # releases the client's blocking wait
        assert results.get(timeout=10) == b"go"
        deadline = time.time() + 10
        while master.add("joined", 0) < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert master.add("joined", 0) == 1
        proc.join(timeout=5)
    finally:
        master.close()


def test_tcpstore_blocking_get_waits():
    master = dist.TCPStore(is_master=True, world_size=1)
    try:
        import threading

        got = {}

        def getter():
            c = dist.TCPStore(port=master.port, is_master=False, world_size=1)
            got["v"] = c.get("later")
            c.close()

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(0.2)
        assert "v" not in got  # still blocked
        master.set("later", b"done")
        t.join(timeout=10)
        assert got.get("v") == b"done"
    finally:
        master.close()


# -- p2p send/recv ------------------------------------------------------------

def test_send_recv_roundtrip():
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    dist.send(x, dst=0)
    out = paddle.zeros([2, 3])
    dist.recv(out, src=0)
    np.testing.assert_array_equal(_np(out), _np(x))


def test_isend_irecv_tags():
    a = paddle.ones([2]) * 3
    b = paddle.ones([2]) * 7
    dist.isend(a, dst=0, tag=1)
    dist.isend(b, dst=0, tag=2)
    out2 = paddle.zeros([2])
    out1 = paddle.zeros([2])
    # irecv fills the buffer from a background thread: the task must be
    # waited before the buffer is read (asserting without wait() races)
    t2 = dist.irecv(out2, src=0, tag=2)
    t1 = dist.irecv(out1, src=0, tag=1)
    assert t1.wait(timeout=30) and t2.wait(timeout=30)
    np.testing.assert_array_equal(_np(out1), [3, 3])
    np.testing.assert_array_equal(_np(out2), [7, 7])


def test_recv_shape_mismatch_raises():
    dist.send(paddle.ones([4]), dst=0, tag=9)
    with pytest.raises(ValueError):
        dist.recv(paddle.zeros([2, 2]), src=0, tag=9)


# -- multiprocess DataLoader --------------------------------------------------

class _SlowDataset(paddle.io.Dataset):
    def __init__(self, n=32):
        self.n = n

    def __getitem__(self, i):
        time.sleep(0.002)
        return np.full((4,), i, "float32"), np.int64(i % 2)

    def __len__(self):
        return self.n


def test_multiprocess_loader_order_and_values():
    ds = _SlowDataset(32)
    loader = paddle.io.DataLoader(ds, batch_size=4, num_workers=2,
                                  shuffle=False)
    seen = []
    for xb, yb in loader:
        assert xb.shape == [4, 4]
        seen.extend(np.asarray(xb.data)[:, 0].astype(int).tolist())
    assert seen == list(range(32)), "multiprocess loader must preserve order"


def test_multiprocess_loader_matches_single_worker():
    ds = _SlowDataset(16)
    single = [np.asarray(x.data) for x, _ in
              paddle.io.DataLoader(ds, batch_size=8, num_workers=0, shuffle=False)]
    multi = [np.asarray(x.data) for x, _ in
             paddle.io.DataLoader(ds, batch_size=8, num_workers=2, shuffle=False)]
    for a, b in zip(single, multi):
        np.testing.assert_array_equal(a, b)


class _FailingDataset(paddle.io.Dataset):
    def __getitem__(self, i):
        if i == 5:
            raise RuntimeError("boom at 5")
        return np.zeros(2, "float32")

    def __len__(self):
        return 8


def test_multiprocess_loader_propagates_worker_error():
    loader = paddle.io.DataLoader(_FailingDataset(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom"):
        for _ in loader:
            pass


def test_worker_init_fn_and_info():
    calls = multiprocessing.get_context("fork").Queue()

    def init_fn(worker_id):
        from paddle_tpu.io import get_worker_info

        info = get_worker_info()
        calls.put((worker_id, info.num_workers))

    ds = _SlowDataset(8)
    loader = paddle.io.DataLoader(ds, batch_size=4, num_workers=2,
                                  worker_init_fn=init_fn)
    list(loader)
    got = sorted(calls.get(timeout=5) for _ in range(2))
    assert got == [(0, 2), (1, 2)]


def test_tcpstore_barrier_reusable():
    master = dist.TCPStore(is_master=True, world_size=1)
    try:
        for _ in range(3):  # same tag must re-arm each generation
            master.barrier("loop")
    finally:
        master.close()


def test_send_recv_emulated_ranks():
    x = paddle.ones([3]) * 5
    dist.send(x, dst=2, src=1)
    out = paddle.zeros([3])
    dist.recv(out, src=1, dst=2)
    np.testing.assert_array_equal(_np(out), [5, 5, 5])


def test_irecv_then_send_exchange():
    """The post-receive-then-send idiom must not deadlock."""
    mine = paddle.ones([2]) * 11
    buf = paddle.zeros([2])
    task = dist.irecv(buf, src=0, tag=42)
    assert not task.is_completed() or True  # receive posted, not yet matched
    dist.send(mine, dst=0, tag=42)
    assert task.wait(timeout=10)
    np.testing.assert_array_equal(_np(buf), [11, 11])


# -- shared-memory sample handoff ---------------------------------------------

class _BigDataset(paddle.io.Dataset):
    """Samples above the shm threshold (>=16KB)."""

    def __getitem__(self, i):
        return np.full((64, 64, 3), i, "float32"), np.int64(i)  # 48KB image

    def __len__(self):
        return 12


def test_shared_memory_loader_matches_plain():
    plain = [np.asarray(x.data) for x, _ in paddle.io.DataLoader(
        _BigDataset(), batch_size=4, num_workers=0, shuffle=False)]
    shm = [np.asarray(x.data) for x, _ in paddle.io.DataLoader(
        _BigDataset(), batch_size=4, num_workers=2, shuffle=False,
        use_shared_memory=True)]
    for a, b in zip(plain, shm):
        np.testing.assert_array_equal(a, b)


def test_shared_memory_roundtrip_unlinks():
    from multiprocessing import shared_memory
    from paddle_tpu.incubate.multiprocessing import (to_shared, from_shared,
                                                     share_sample_tree,
                                                     restore_sample_tree)

    arr = np.random.default_rng(0).standard_normal((128, 128)).astype("float32")
    desc = to_shared(arr)
    out = from_shared(desc)
    np.testing.assert_array_equal(out, arr)
    with pytest.raises(FileNotFoundError):  # segment freed after restore
        shared_memory.SharedMemory(name=desc.name)

    tree = {"img": arr, "label": np.int64(3), "small": np.zeros(4, "float32")}
    shared = share_sample_tree(tree)
    from paddle_tpu.incubate.multiprocessing import _ShmDescriptor

    assert isinstance(shared["img"], _ShmDescriptor)
    assert isinstance(shared["small"], np.ndarray)  # below threshold: inline
    back = restore_sample_tree(shared)
    np.testing.assert_array_equal(back["img"], arr)


def test_shared_memory_early_break_does_not_leak(tmp_path):
    import glob

    before = {f for f in glob.glob("/dev/shm/psm_*")}
    from paddle_tpu.io import _MultiprocessIterator

    loader = paddle.io.DataLoader(_BigDataset(), batch_size=2, num_workers=2,
                                  shuffle=False, use_shared_memory=True)
    it = _MultiprocessIterator(loader)
    next(it)  # consume one batch, abandon the rest mid-flight
    time.sleep(0.5)  # let in-flight worker results land in the queue
    it._shutdown()
    time.sleep(0.2)
    after = {f for f in glob.glob("/dev/shm/psm_*")}
    assert after - before == set(), f"leaked: {after - before}"


def test_shared_memory_structured_dtype_roundtrip():
    from paddle_tpu.incubate.multiprocessing import to_shared, from_shared

    dt = np.dtype([("a", "<i4"), ("b", "<f4", (4,))])
    arr = np.zeros(4096, dt)
    arr["a"] = np.arange(4096)
    out = from_shared(to_shared(arr))
    np.testing.assert_array_equal(out["a"], arr["a"])
    # object dtype refuses shared memory instead of crashing obscurely
    import pytest as _pt

    with _pt.raises(TypeError):
        to_shared(np.asarray([object()] * 10000))
