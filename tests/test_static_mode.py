"""Static-graph compat shim (VERDICT r3 next #6): reference-era static-mode
scripts — the test_fit_a_line.py shape — run unmodified through
enable_static / static.data / program_guard / Executor.run."""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture(autouse=True)
def _always_back_to_dygraph():
    yield
    paddle.disable_static()


def test_fit_a_line_static_training():
    """The canonical static regression script: build with placeholders,
    minimize, executor feed/fetch loop — loss must decrease."""
    paddle.enable_static()
    assert not paddle.in_dynamic_mode()

    main = paddle.static.default_main_program()
    startup = paddle.static.default_startup_program()

    paddle.seed(7)
    x = paddle.static.data(name="x", shape=[None, 13], dtype="float32")
    y = paddle.static.data(name="y", shape=[None, 1], dtype="float32")
    pred = paddle.static.nn.fc(x, size=1)
    loss = paddle.mean(paddle.nn.functional.square_error_cost(pred, y))
    opt = paddle.optimizer.SGD(learning_rate=0.05)
    opt.minimize(loss)

    exe = paddle.static.Executor(paddle.CPUPlace())
    exe.run(startup)

    rng = np.random.RandomState(0)
    true_w = rng.randn(13, 1).astype("float32")
    losses = []
    for _ in range(30):
        xb = rng.rand(16, 13).astype("float32")
        yb = xb @ true_w
        (lv,) = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        losses.append(float(lv))
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.25 * losses[0], losses[::10]


def test_program_guard_isolates_programs():
    paddle.enable_static()
    side = paddle.static.Program()
    with paddle.static.program_guard(side):
        a = paddle.static.data(name="a", shape=[None, 4], dtype="float32")
        out = a * 2.0 + 1.0
    assert "a" in side.feeds
    assert "a" not in paddle.static.default_main_program().feeds
    exe = paddle.static.Executor()
    av = np.ones((3, 4), "float32")
    (ov,) = exe.run(side, feed={"a": av}, fetch_list=[out])
    np.testing.assert_allclose(ov, av * 2.0 + 1.0)


def test_inference_program_feed_shape_respecializes():
    """None dims: build at dummy 1, run at any batch."""
    paddle.enable_static()
    x = paddle.static.data(name="x", shape=[None, 8], dtype="float32")
    h = paddle.static.nn.fc(x, size=4, activation="relu")
    exe = paddle.static.Executor()
    for b in (2, 5, 11):
        (hv,) = exe.run(feed={"x": np.ones((b, 8), "float32")},
                        fetch_list=[h])
        assert hv.shape == (b, 4)


def test_executor_missing_feed_raises():
    paddle.enable_static()
    x = paddle.static.data(name="x", shape=[None, 3], dtype="float32")
    out = x + 1.0
    exe = paddle.static.Executor()
    with pytest.raises(ValueError, match="missing feeds"):
        exe.run(feed={}, fetch_list=[out])


def test_dygraph_untouched_after_disable():
    paddle.enable_static()
    _ = paddle.static.data(name="x", shape=[2, 2], dtype="float32")
    paddle.disable_static()
    assert paddle.in_dynamic_mode()
    before = len(paddle.static.default_main_program().nodes)
    t = paddle.ones([2, 2]) * 3.0
    np.testing.assert_allclose(t.numpy(), 3.0)
    # nothing recorded once back in dygraph
    assert len(paddle.static.default_main_program().nodes) == before


def test_static_records_through_amp_autocast():
    """Feeds must stay connected when build-time ops run under amp
    auto_cast (the cast copy must not shadow the feed id)."""
    import paddle_tpu.amp as amp

    paddle.enable_static()
    x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
    with amp.auto_cast():
        out = x * 2.0 + 1.0
    exe = paddle.static.Executor()
    xv = np.full((3, 4), 2.0, "float32")
    (ov,) = exe.run(feed={"x": xv}, fetch_list=[out])
    assert ov.shape == (3, 4)
    np.testing.assert_allclose(np.asarray(ov, np.float32), 5.0)


def test_fc_flatten_semantics():
    """reference fc: trailing dims flatten into features; leading dims are
    restored (num_flatten_dims contract)."""
    paddle.enable_static()
    x = paddle.static.data(name="x", shape=[None, 3, 4], dtype="float32")
    flat = paddle.static.nn.fc(x, size=5)                   # [B, 5], W [12,5]
    keep = paddle.static.nn.fc(x, size=5, num_flatten_dims=2)  # [B, 3, 5]
    exe = paddle.static.Executor()
    xv = np.random.RandomState(0).rand(2, 3, 4).astype("float32")
    f, k = exe.run(feed={"x": xv}, fetch_list=[flat, keep])
    assert f.shape == (2, 5), f.shape
    assert k.shape == (2, 3, 5), k.shape


def test_infer_sees_updated_params_not_baked_constants():
    """The jit-cached replay must take parameters as ARGUMENTS: after a
    manual param update, a cached-shape run reflects the new values."""
    paddle.enable_static()
    x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
    out = paddle.static.nn.fc(x, size=2)
    exe = paddle.static.Executor()
    xv = np.ones((3, 4), "float32")
    (a,) = exe.run(feed={"x": xv}, fetch_list=[out])
    # mutate the fc weight and re-run the SAME shape (cached executable)
    prog = paddle.static.default_main_program()
    (w,) = [p for p in prog.param_tensors() if p.ndim == 2]
    import jax.numpy as jnp

    w.data = jnp.asarray(np.asarray(w.data) * 2.0)
    (b,) = exe.run(feed={"x": xv}, fetch_list=[out])
    assert not np.allclose(a, b), "cached replay baked stale params"


def test_save_load_inference_model_roundtrip(tmp_path):
    """VERDICT r4 next #6: a static script trains, saves a servable
    artifact, and BOTH load_inference_model and inference.create_predictor
    serve it with matching outputs."""
    paddle.enable_static()
    main = paddle.static.default_main_program()
    paddle.seed(3)
    x = paddle.static.data(name="x", shape=[None, 6], dtype="float32")
    y = paddle.static.data(name="y", shape=[None, 1], dtype="float32")
    pred = paddle.static.nn.fc(x, size=1)
    loss = paddle.mean(paddle.nn.functional.square_error_cost(pred, y))
    opt = paddle.optimizer.SGD(learning_rate=0.05)
    opt.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(paddle.static.default_startup_program())
    rng = np.random.RandomState(1)
    true_w = rng.randn(6, 1).astype("float32")
    for _ in range(20):
        xb = rng.rand(8, 6).astype("float32")
        exe.run(main, feed={"x": xb, "y": xb @ true_w}, fetch_list=[loss])

    prefix = str(tmp_path / "fit_line")
    paddle.static.save_inference_model(prefix, [x], [pred], exe)

    xq = rng.rand(5, 6).astype("float32")
    # direct replay = ground truth
    (want,) = exe.run(main, feed={"x": xq, "y": np.zeros((5, 1), "f4")},
                      fetch_list=[pred])

    prog, feed_names, fetch_targets = paddle.static.load_inference_model(
        prefix, exe)
    assert feed_names == ["x"]
    (got,) = exe.run(prog, feed={"x": xq}, fetch_list=fetch_targets)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # and the C-ABI-style predictor serves the same artifact
    from paddle_tpu import inference

    cfg = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
    predictor = inference.create_predictor(cfg)
    (served,) = predictor.run([xq])
    np.testing.assert_allclose(served, want, rtol=1e-5, atol=1e-6)


def test_program_freezes_after_first_run():
    """advisor r4: eager ops between Executor.run calls (metrics on fetched
    results) must not append nodes that later re-specializations replay."""
    paddle.enable_static()
    x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
    out = x * 3.0
    exe = paddle.static.Executor()
    prog = paddle.static.default_main_program()
    exe.run(feed={"x": np.ones((2, 4), "f4")}, fetch_list=[out])
    n_nodes = len(prog.nodes)
    (ov,) = exe.run(feed={"x": np.ones((2, 4), "f4")}, fetch_list=[out])
    _metric = paddle.to_tensor(ov).mean() * 2.0  # run-phase eager op
    assert len(prog.nodes) == n_nodes
    # re-specialization at a new batch still replays the clean program
    (ov3,) = exe.run(feed={"x": np.ones((3, 4), "f4")}, fetch_list=[out])
    assert ov3.shape == (3, 4)
    np.testing.assert_allclose(ov3, 3.0)


def test_fetch_of_fresh_tensor_is_loud():
    """advisor r4: fetching a tensor the build phase didn't produce must
    raise (the silent alternative is a per-step re-trace)."""
    paddle.enable_static()
    x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
    out = x + 1.0
    exe = paddle.static.Executor()
    exe.run(feed={"x": np.ones((2, 4), "f4")}, fetch_list=[out])
    fresh = paddle.to_tensor(np.ones((2, 4), "f4")) * 5.0
    with pytest.raises(ValueError, match="not produced by this program"):
        exe.run(feed={"x": np.ones((2, 4), "f4")}, fetch_list=[fresh])


def test_save_inference_model_uncovered_placeholder_is_loud():
    """A fetch whose cone reads a placeholder missing from feed_vars must
    raise, not bake the build-time dummy into the artifact."""
    paddle.enable_static()
    x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
    y = paddle.static.data(name="y", shape=[None, 4], dtype="float32")
    out = x * 2.0 + y
    with pytest.raises(ValueError, match="placeholder 'y'"):
        paddle.static.save_inference_model("/tmp/should_not_exist",
                                           [x], [out])
