"""Round-4 dy2static breadth: for loops, break/continue, bool-op predicates.

Patterns ported from the reference dygraph_to_static unittests
(test_loop.py, test_break_continue.py, test_logical_operator.py shapes);
each converted function must agree with its eager run.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def _check(f, *inputs, rtol=1e-6):
    st = paddle.jit.to_static(f)
    for args in inputs:
        args = [paddle.to_tensor(a) for a in args]
        want = f(*args)
        got = st(*args)
        np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=rtol)


class TestForLoops:
    def test_for_range_accumulate(self):
        def f(x):
            s = x * 0.0
            for i in range(5):
                s = s + x * float(i)
            return s

        _check(f, ([1.0, 2.0],), ([-3.0, 0.5],))

    def test_for_range_start_stop_step(self):
        def f(x):
            s = x * 0.0
            for i in range(1, 9, 2):
                s = s + i
            return s + x

        _check(f, ([1.0],))

    def test_for_range_tensor_bound(self):
        """`for i in range(t)` with a TRACED bound lowers to a while carry."""
        def f(x, n):
            s = x * 0.0
            for i in range(n):
                s = s + x
            return s

        st = paddle.jit.to_static(f)
        x = paddle.to_tensor([2.0, 3.0])
        out = st(x, paddle.to_tensor(4))
        np.testing.assert_allclose(out.numpy(), [8.0, 12.0])
        out = st(x, paddle.to_tensor(0))
        np.testing.assert_allclose(out.numpy(), [0.0, 0.0])

    def test_for_over_tensor_rows(self):
        def f(x):
            s = x[0] * 0.0
            for v in x:
                s = s + v * v
            return s

        _check(f, (np.arange(6, dtype="float32").reshape(3, 2),))

    def test_for_with_augassign(self):
        def f(x):
            s = x * 0.0
            for i in range(4):
                s += x
            return s

        _check(f, ([1.5, -2.0],))

    def test_for_containing_convertible_if(self):
        def f(x):
            s = x.sum() * 0.0
            for v in x:
                if v > 0:
                    s = s + v
                else:
                    s = s - v
            return s

        _check(f, ([1.0, -2.0, 3.0],), ([-1.0, -1.0, -1.0],))


class TestBreakContinue:
    def test_while_guarded_break(self):
        def f(x):
            i = x.sum() * 0 + 0.0
            s = x.sum() * 0.0
            while i < 10:
                if s > 20:
                    break
                s = s + i
                i = i + 1
            return s

        _check(f, ([1.0],))

    def test_for_guarded_break(self):
        def f(x):
            s = x * 0.0
            for i in range(10):
                if i >= 3:
                    break
                s = s + x
            return s

        _check(f, ([2.0, 4.0],))

    def test_for_guarded_continue(self):
        def f(x):
            s = x * 0.0
            for i in range(6):
                if i == 2:
                    continue
                s = s + x * float(1.0)
            return s

        _check(f, ([1.0, -1.0],))

    def test_for_tensor_guard_continue(self):
        """Guard on the loop DATA (traced even with concrete trip count)."""
        def f(x):
            s = x[0] * 0.0
            for v in x:
                if v.sum() < 0:
                    continue
                s = s + v
            return s

        _check(f, (np.array([[1.0], [-2.0], [3.0]], "float32"),))

    def test_bare_break_after_work(self):
        def f(x):
            s = x * 0.0
            for i in range(5):
                s = s + x
                break
            return s

        _check(f, ([7.0],))

    def test_while_break_on_tensor_state(self):
        def f(x):
            s = x.sum() * 0.0
            i = s * 0.0
            while i < 100:
                s = s + x.sum()
                i = i + 1.0
                if s > 5:
                    break
            return s

        _check(f, ([2.0],), ([0.5],))


class TestBoolOps:
    def test_if_and(self):
        def f(x, y):
            if x.sum() > 0 and y.sum() > 0:
                r = x + y
            else:
                r = x - y
            return r

        _check(f, ([1.0], [2.0]), ([1.0], [-2.0]), ([-1.0], [2.0]))

    def test_if_or_not(self):
        def f(x, y):
            if not (x.sum() > 0) or y.sum() > 0:
                r = x * 2.0
            else:
                r = y * 3.0
            return r

        _check(f, ([1.0], [2.0]), ([1.0], [-2.0]), ([-1.0], [-2.0]))

    def test_while_boolop_test(self):
        def f(x):
            s = x.sum() * 0.0
            i = s * 0.0
            while i < 10 and s < 6:
                s = s + x.sum()
                i = i + 1.0
            return s

        _check(f, ([2.0],), ([0.25],))

    def test_break_guard_with_boolop(self):
        def f(x):
            s = x.sum() * 0.0
            for i in range(8):
                if s > 3 and i > 1:
                    break
                s = s + x.sum()
            return s

        _check(f, ([1.0],), ([5.0],))


class TestConversionSafety:
    def test_for_else_not_converted(self):
        """for/else is out of scope: the loop must stay Python (call sites
        may still be wrapped for call-graph conversion, so identity is not
        guaranteed — assert no loop machinery and same result)."""
        def f(x):
            s = x * 0.0
            for i in range(3):
                s = s + x
            else:
                s = s + 1.0
            return s

        from paddle_tpu.jit.dy2static import convert_to_static

        f2 = convert_to_static(f)
        assert "__pt_for_range" not in f2.__code__.co_names
        x = paddle.to_tensor([1.0, 2.0])
        np.testing.assert_allclose(f2(x).numpy(), f(x).numpy())

    def test_guarded_fresh_name_not_converted(self):
        """An assignment after a guard whose target does NOT pre-exist can't
        be select-guarded — the loop must stay unconverted."""
        def f(x):
            s = x * 0.0
            for i in range(4):
                if i > 1:
                    continue
                fresh = x * 2.0
                s = s + fresh
            return s

        from paddle_tpu.jit.dy2static import convert_to_static

        f2 = convert_to_static(f)
        assert "__pt_for_range" not in f2.__code__.co_names
        x = paddle.to_tensor([1.0, 2.0])
        np.testing.assert_allclose(f2(x).numpy(), f(x).numpy())

    def test_loop_var_reassign_not_converted(self):
        def f(x):
            s = x * 0.0
            for i in range(4):
                i = i + 1
                s = s + i
            return s

        from paddle_tpu.jit.dy2static import convert_to_static

        f2 = convert_to_static(f)
        assert "__pt_for_range" not in f2.__code__.co_names
        x = paddle.to_tensor([1.0, 2.0])
        np.testing.assert_allclose(f2(x).numpy(), f(x).numpy())

    def test_converted_runs_inside_trace(self):
        """The converted loop must actually compile: run under jit tracing
        where Python control flow on tensors would raise."""
        import jax

        def f(x):
            s = x * 0.0
            for i in range(6):
                if s.sum() > 4:
                    break
                s = s + x
            return s

        st = paddle.jit.to_static(f)
        from paddle_tpu.core.tensor import Tensor

        def traced(a):
            return st(Tensor(a)).data

        out = jax.jit(traced)(np.array([1.0, 1.0], "float32"))
        np.testing.assert_allclose(np.asarray(out), [3.0, 3.0])
