"""Cost-model-driven auto-parallel planner (ISSUE-10 tentpole).

Reference: auto_parallel/planner.py + cost_model.py — plan(model, chips,
hbm) returns the predicted-fastest feasible config. These tests pin the
contract on the 1-device CPU tier-1 box (scoring is arithmetic over one
abstract capture; nothing needs 8 real devices):

- candidate enumeration respects head/kv/expert divisibility and batch
  divisibility over the data axes;
- HBM-infeasible configs are pruned (deliberately tiny hbm_bytes);
- ranking is deterministic call-to-call;
- every MULTICHIP_r05 matrix config round-trips through plan() scoring;
- Engine.prepare(auto_plan=True) applies the top pick end to end.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.auto_parallel import planner
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, LlamaMoEConfig

# the exact mesh configs the 8-device dryrun matrix executes
# (__graft_entry__._mesh_configs(8), MULTICHIP_r05 all green)
MULTICHIP_R05 = (
    {"dp": 2, "mp": 2, "cp": 2},
    {"sharding": 4, "dp": 2, "level": "os_g"},
    {"sharding": 2, "mp": 2, "dp": 2, "level": "p_g_os"},
    {"pp": 2, "dp": 4},
    {"ep": 2, "mp": 2, "dp": 2},
)


def _tiny_profile(batch=16, seq=64, moe=False):
    paddle.seed(0)
    cfg = LlamaMoEConfig.tiny() if moe else LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    return planner.profile_model(model, batch=batch, seq=seq), model


class TestProfile:
    def test_profile_measures_flops_and_acts(self):
        prof, model = _tiny_profile()
        n_params = sum(p.size for p in model.parameters()
                       if not p.stop_gradient)
        assert prof.param_elems == n_params
        assert prof.flops_per_step > 0 and prof.act_bytes > 0
        assert prof.num_heads == 4 and prof.num_kv_heads == 2
        assert prof.batch == 16 and prof.seq == 64

    def test_sample_batch_overrides_shape(self):
        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        ids = paddle.randint(0, 256, [4, 32])
        prof = planner.profile_model(model, sample_batch=(ids, ids))
        assert prof.batch == 4 and prof.seq == 32

    def test_non_lm_model_requires_sample_batch(self):
        net = nn.Linear(8, 8)
        with pytest.raises(ValueError, match="sample_batch"):
            planner.profile_model(net, batch=4, seq=8)


class TestEnumeration:
    def test_head_and_kv_divisibility(self):
        prof, _ = _tiny_profile()  # heads=4, kv=2
        cfgs = planner.enumerate_candidates(8, prof, batch=16)
        assert cfgs
        for c in cfgs:
            mp = c["mesh"]["mp"]
            assert prof.num_heads % mp == 0
            assert prof.num_kv_heads % mp == 0
            # kv=2 excludes mp=4 and mp=8 outright
            assert mp <= 2

    def test_expert_divisibility(self):
        prof, _ = _tiny_profile(moe=True)  # 4 experts
        cfgs = planner.enumerate_candidates(8, prof, batch=16)
        eps = {c["mesh"]["ep"] for c in cfgs}
        assert eps - {1}, "expert axis never proposed for a MoE model"
        for c in cfgs:
            assert prof.num_experts % c["mesh"]["ep"] == 0

    def test_no_expert_axis_for_dense_model(self):
        prof, _ = _tiny_profile()
        cfgs = planner.enumerate_candidates(8, prof, batch=16)
        assert all(c["mesh"]["ep"] == 1 for c in cfgs)

    def test_batch_divides_data_axes_and_microbatches(self):
        prof, _ = _tiny_profile(batch=16)
        for c in planner.enumerate_candidates(8, prof, batch=16):
            data = c["mesh"]["dp"] * c["mesh"]["sharding"]
            k = c["accumulate_steps"]
            assert 16 % data == 0
            assert 16 % k == 0 and (16 // k) % data == 0

    def test_mesh_product_always_matches_device_count(self):
        # odd leftover data degrees must not silently shrink the mesh
        # (the dp=2/sharding=data//2 split needs an even data degree)
        prof, _ = _tiny_profile(batch=40)
        for n in (6, 8, 10, 12):
            cfgs = planner.enumerate_candidates(n, prof, batch=40)
            for c in cfgs:
                total = 1
                for d in c["mesh"].values():
                    total *= d
                assert total == n, (n, c["mesh"])

    def test_offload_requires_zero_level(self):
        prof, _ = _tiny_profile()
        for c in planner.enumerate_candidates(8, prof, batch=16):
            if c["offload"]:
                assert c["level"] in ("os", "os_g", "p_g_os")


class TestScoringAndRanking:
    def test_infeasible_pruned_with_tiny_budget(self):
        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        # a budget smaller than one param replica: nothing fits
        with pytest.warns(UserWarning, match="no candidate fits"):
            cands = dist.plan(model, n_devices=8, hbm_bytes=1e4,
                              batch=16, seq=64)
        assert cands and all(not c.feasible for c in cands)
        # default return prunes them: a realistic budget returns ONLY
        # feasible candidates unless include_infeasible is passed
        ok = dist.plan(model, n_devices=8, hbm_bytes=9.5e9,
                       batch=16, seq=64)
        assert ok and all(c.feasible for c in ok)
        both = dist.plan(model, n_devices=8, hbm_bytes=2e6, batch=16,
                         seq=64, include_infeasible=True)
        assert any(not c.feasible for c in both)
        # feasible (if any) strictly precede infeasible in the ranking
        flags = [c.feasible for c in both]
        assert flags == sorted(flags, reverse=True)

    def test_ranking_deterministic(self):
        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        a = dist.plan(model, n_devices=8, hbm_bytes=9.5e9, batch=16, seq=64)
        b = dist.plan(model, n_devices=8, hbm_bytes=9.5e9, batch=16, seq=64)
        assert [c.describe() for c in a] == [c.describe() for c in b]
        assert [c.predicted_step_s for c in a] == \
            [c.predicted_step_s for c in b]

    def test_bigger_model_needs_more_memory(self):
        prof, _ = _tiny_profile()
        cand = planner.score_config(prof, {"dp": 8}, hbm_bytes=9.5e9,
                                    drift_ratio=1.0)
        # same config, 100x the params: peak must scale up
        import dataclasses

        prof_big = dataclasses.replace(
            prof, param_bytes=prof.param_bytes * 100,
            param_elems=prof.param_elems * 100)
        big = planner.score_config(prof_big, {"dp": 8}, hbm_bytes=9.5e9,
                                   drift_ratio=1.0)
        assert big.predicted_peak_bytes > 10 * cand.predicted_peak_bytes

    def test_offload_trades_state_residency_for_transfer_time(self):
        # at flagship scale the host-parked master/state dwarfs the lane's
        # two-group staging working set (tiny models go the OTHER way —
        # staging exceeds the saved state — which the model also captures)
        import dataclasses

        prof, _ = _tiny_profile()
        prof = dataclasses.replace(prof,
                                   param_bytes=prof.param_bytes * 200,
                                   param_elems=prof.param_elems * 200)
        base = planner.score_config(
            prof, {"sharding": 8, "level": "os_g"}, hbm_bytes=9.5e9,
            drift_ratio=1.0)
        off = planner.score_config(
            prof, {"sharding": 8, "level": "os_g", "offload": True},
            hbm_bytes=9.5e9, drift_ratio=1.0)
        assert off.predicted_peak_bytes < base.predicted_peak_bytes
        assert off.predicted_step_s > base.predicted_step_s

    def test_multichip_r05_matrix_roundtrips(self):
        """Every config the 8-device dryrun matrix executes must score
        without error and produce finite time + memory predictions."""
        prof_dense, _ = _tiny_profile()
        prof_moe, _ = _tiny_profile(moe=True)
        for raw in MULTICHIP_R05:
            prof = prof_moe if raw.get("ep", 1) > 1 else prof_dense
            cand = planner.score_config(prof, dict(raw), hbm_bytes=9.5e9)
            assert np.isfinite(cand.predicted_step_s) and \
                cand.predicted_step_s > 0, raw
            assert cand.predicted_peak_bytes > 0, raw
            assert cand.feasible, raw  # tiny model, real budget
            # the mesh degrees survive normalization exactly
            for ax, d in raw.items():
                if ax in planner.AXES:
                    assert cand.config["mesh"][ax] == d, (raw, cand.config)

    def test_plan_candidate_config_surfaces(self):
        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        cands = dist.plan(model, n_devices=8, hbm_bytes=9.5e9,
                          batch=16, seq=64)
        top = cands[0]
        mesh = top.mesh
        total = int(np.prod(list(mesh.values())))
        assert total == 8, mesh
        pc = top.pipeline_configs()
        assert pc["accumulate_steps"] >= 1
        assert pc["accumulate_steps"] * pc["micro_batch_size"] == 16
        # the dict feeds fleet's validated strategy directly
        from paddle_tpu.distributed import fleet

        strat = fleet.DistributedStrategy()
        strat.pipeline_configs = pc  # raises on malformed plans
        d = top.to_dict()
        assert d["feasible"] is True and "breakdown" in d

    def test_drift_ratio_scales_the_gate(self):
        prof, _ = _tiny_profile()
        under = planner.score_config(prof, {"dp": 8}, hbm_bytes=9.5e9,
                                     drift_ratio=0.5)
        over = planner.score_config(prof, {"dp": 8}, hbm_bytes=9.5e9,
                                    drift_ratio=2.0)
        # a ratio < 1 means the estimator under-predicts XLA: the
        # calibrated peak must be LARGER
        assert under.predicted_peak_bytes > over.predicted_peak_bytes


class TestEngineAutoPlan:
    def test_prepare_auto_plan_applies_top_pick_and_fits(self):
        dist.reset_mesh()
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
        o = opt.AdamW(learning_rate=1e-3, parameters=net.parameters())
        eng = dist.Engine(model=net, loss=lambda out, y: F.mse_loss(out, y),
                          optimizer=o)
        x = paddle.randn([8, 16])
        y = paddle.randn([8, 16])
        eng.prepare(sample_batch=(x, y), auto_plan=True)
        assert eng.applied_plan is not None
        assert eng.plan_candidates and eng.plan_candidates[0].feasible
        assert eng.applied_plan is eng.plan_candidates[0]

        rng = np.random.RandomState(0)

        class DS:
            def __len__(self):
                return 16

            def __getitem__(self, i):
                v = rng.rand(16).astype("float32")
                return v, v * 0.5

        hist = eng.fit(DS(), epochs=1, batch_size=8)
        assert len(hist) == 1 and np.isfinite(hist[0])
        dist.reset_mesh()

    def test_prepare_refuses_infeasible_plan(self):
        """An impossible HBM budget must fail at prepare() time with an
        actionable error, not install a config predicted to OOM."""
        dist.reset_mesh()
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
        o = opt.AdamW(learning_rate=1e-3, parameters=net.parameters())
        eng = dist.Engine(model=net, loss=lambda out, y: F.mse_loss(out, y),
                          optimizer=o)
        x = paddle.randn([8, 16])
        y = paddle.randn([8, 16])
        with pytest.warns(UserWarning, match="no candidate fits"):
            with pytest.raises(ValueError, match="no candidate fits"):
                eng.prepare(sample_batch=(x, y), auto_plan=True,
                            hbm_bytes=10.0)
        assert eng.applied_plan is None
        dist.reset_mesh()

    def test_cost_model_surface_delegates(self):
        from paddle_tpu.cost_model import CostModel

        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        cands = CostModel().plan_parallel(model, n_devices=8,
                                          hbm_bytes=9.5e9, batch=16, seq=64)
        assert cands and cands[0].feasible
