"""ISSUE 20: online auto-tuning — the runtime that retunes itself.

Pure-layer coverage for the tuning stack: the regression detector's
trigger/no-trigger matrix (a single spike never fences a fleet), the
quantile-cover derivation (property-style over seeded workloads), the
restart-safe telemetry windows (``SloTracker`` monotonic rebase +
``HistogramWindow``), ``BucketSpec`` validation shared by hand-declared
and derived specs, live planner re-scoring with measured anchors, the
``ServingEngine.respec`` zero-retrace cutover, the policy driver
(``OnlineTuner``: ledger, embargo, kill-switch), and the elastic plan
tuner's full keep/rollback protocol over a fake control-plane store.
The real multi-process loop is drilled end to end by
``tools/tuning_drill.py`` (ci.sh gate).
"""
import json
import math
import random
import time
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.tuning import (
    OnlineTuner, Proposal, RegressionDetector, TuningPolicy,
    derive_buckets_from_histogram, derive_slots_from_histogram,
    padding_waste, quantile_cover, shape_digest, sizes_from_histogram,
    weighted_quantile,
)


# -- regression detector (satellite 3: unit matrix) ---------------------------

def _warm(det, ms=100.0, n=12):
    for _ in range(n):
        det.update(ms)
    return det


class TestRegressionDetector:
    def test_warming_then_ok(self):
        det = RegressionDetector(min_samples=8)
        for i in range(7):
            assert det.update(100.0) == "warming"
        assert det.update(100.0) == "ok"
        assert det.baseline_ms() == pytest.approx(100.0)

    def test_single_spike_never_triggers(self):
        det = _warm(RegressionDetector(sustain_n=5))
        assert det.update(1000.0) == "ok"     # one spike: GC, scrape, ...
        for _ in range(20):
            assert det.update(100.0) == "ok"
        assert det.triggers == 0

    def test_noise_below_threshold_never_triggers(self):
        det = RegressionDetector(trigger_ratio=1.3, min_abs_ms=5.0)
        rng = random.Random(0)
        for _ in range(300):
            det.update(100.0 + rng.uniform(-8, 8))  # +-8% jitter
        assert det.triggers == 0
        assert det.state == "ok"

    def test_sustained_regression_triggers_and_anchors(self):
        det = _warm(RegressionDetector(sustain_n=5))
        states = [det.update(200.0) for _ in range(5)]
        assert states[:4] == ["ok"] * 4 and states[4] == "regressed"
        assert det.triggers == 1
        # the anchor is the measured degraded level, not the baseline
        assert det.regressed_ms() == pytest.approx(200.0)
        assert det.baseline_ms() == pytest.approx(100.0)  # frozen

    def test_baseline_frozen_during_elevated_run(self):
        det = _warm(RegressionDetector(sustain_n=5, baseline_window=8))
        for _ in range(30):
            det.update(300.0)
        # 30 elevated samples did NOT drag the baseline up to 300
        assert det.baseline_ms() == pytest.approx(100.0)

    def test_hysteresis_recovery(self):
        det = _warm(RegressionDetector(sustain_n=3, recover_n=4,
                                       trigger_ratio=1.3,
                                       recover_ratio=1.1))
        for _ in range(3):
            det.update(200.0)
        assert det.state == "regressed"
        # sitting between recover and trigger thresholds: still regressed
        for _ in range(10):
            assert det.update(125.0) == "regressed"
        # recovery needs recover_n CONSECUTIVE healthy samples
        for _ in range(3):
            det.update(100.0)
        det.update(150.0)  # breaks the run
        for _ in range(3):
            assert det.update(100.0) == "regressed"
        assert det.update(100.0) == "ok"

    def test_validation(self):
        with pytest.raises(ValueError):
            RegressionDetector(recover_ratio=1.5, trigger_ratio=1.3)
        with pytest.raises(ValueError):
            RegressionDetector(sustain_n=1)


# -- quantile-cover (satellite 3: property-style) -----------------------------

class TestQuantileCover:
    def test_covers_quantile_and_bounds_waste_or_exhausts_budget(self):
        rng = random.Random(42)
        for trial in range(25):
            n = rng.randint(20, 400)
            dist_kind = trial % 3
            if dist_kind == 0:
                sizes = [rng.randint(1, 512) for _ in range(n)]
            elif dist_kind == 1:  # zipf-ish head-heavy
                sizes = [min(512, int(rng.paretovariate(1.2)))
                         for _ in range(n)]
            else:  # bimodal
                sizes = [rng.choice((8, 9, 10, 300, 310))
                         for _ in range(n)]
            q, max_waste, max_buckets = 0.99, 0.25, 6
            buckets = quantile_cover(sizes, q=q, max_waste=max_waste,
                                     max_buckets=max_buckets)
            assert buckets == tuple(sorted(set(buckets)))  # strict asc
            pq = weighted_quantile(sizes, q)
            assert buckets[-1] >= pq, "p99 must be covered"
            covered = [s for s in sizes if s <= pq]
            w = padding_waste(covered, buckets)
            # the waste bound holds UNLESS the bucket budget ran out
            assert w <= max_waste + 1e-9 or len(buckets) == max_buckets, \
                (trial, w, buckets)

    def test_deterministic(self):
        rng = random.Random(7)
        sizes = [rng.randint(1, 200) for _ in range(150)]
        a = quantile_cover(sizes, q=0.95, max_waste=0.2)
        b = quantile_cover(list(sizes), q=0.95, max_waste=0.2)
        assert a == b

    def test_align_and_min_bucket(self):
        buckets = quantile_cover([3, 5, 17, 40], q=1.0, max_waste=0.0,
                                 align=8, max_buckets=8)
        assert all(b % 8 == 0 for b in buckets)
        buckets = quantile_cover([1, 2, 3, 100], q=1.0, max_waste=0.0,
                                 min_bucket=16, max_buckets=8)
        assert min(buckets) >= 16

    def test_max_size_drops_over_limit_sizes_and_clamps_cover(self):
        # sizes past the engine hard limit are REJECTED, not padded —
        # they leave the derivation; the cover clamps to the limit
        buckets = quantile_cover([10, 20, 90], q=1.0, max_size=64,
                                 align=64)
        assert buckets == (64,)
        # but a clamp never un-covers an in-range quantile
        buckets = quantile_cover([10, 20, 60], q=1.0, max_size=48)
        assert buckets[-1] >= 48 or buckets[-1] == 20

    def test_single_size_single_bucket(self):
        assert quantile_cover([32] * 50, q=0.99, max_waste=0.1) == (32,)
        assert padding_waste([32] * 50, (32,)) == 0.0

    def test_empty_and_validation(self):
        assert quantile_cover([], q=0.99) == ()
        with pytest.raises(ValueError):
            quantile_cover([1], q=0.0)
        with pytest.raises(ValueError):
            quantile_cover([1], max_waste=1.0)

    def test_weighted_pairs_match_expanded(self):
        expanded = [4] * 30 + [16] * 10
        pairs = [(4, 30.0), (16, 10.0)]
        assert quantile_cover(expanded, q=0.99) == \
            quantile_cover(pairs, q=0.99)


# -- histogram adapters -------------------------------------------------------

class TestHistogramAdapters:
    def test_sizes_collapse_to_upper_bound_and_inf_clamps(self):
        bounds = (4.0, 16.0, 64.0, float("inf"))
        counts = (10, 5, 0, 2)
        sizes = sizes_from_histogram(bounds, counts)
        assert sizes == [(4, 10.0), (16, 5.0), (64, 2.0)]

    def test_derive_buckets_and_slots(self):
        bounds = (4.0, 16.0, 64.0, float("inf"))
        buckets = derive_buckets_from_histogram(bounds, (80, 15, 5, 0),
                                                q=0.99, max_waste=0.3)
        assert buckets and buckets[-1] >= 64
        assert 4 in buckets  # the dominant mass gets its own bucket
        slots = derive_slots_from_histogram((1.0, 2.0, 4.0, 8.0),
                                            (5, 10, 40, 2), q=0.99,
                                            headroom=1)
        assert slots == 9  # p99 occupancy 8 + 1 headroom
        assert derive_slots_from_histogram((1.0,), (0, 0)) is None

    def test_shape_digest_stable_and_order_free(self):
        a = shape_digest({"prefill_buckets": [4, 8], "max_slots": 3})
        b = shape_digest({"max_slots": 3, "prefill_buckets": [4, 8]})
        assert a == b and len(a) == 12
        assert a != shape_digest({"prefill_buckets": [4, 8],
                                  "max_slots": 4})


# -- restart-safe windows (satellite 1) ---------------------------------------

class TestRestartSafety:
    def test_slo_tracker_restart_mid_window_counts_new_traffic(self):
        """A replica restart must read as a PAUSE: the window neither
        goes negative nor spikes, and post-restart traffic keeps
        counting inside the same window (no muted remainder)."""
        from paddle_tpu.observability.fleet import SloPolicy, SloTracker
        from paddle_tpu.observability.registry import Histogram

        trk = SloTracker(SloPolicy(target_ms=10.0, objective=0.9,
                                   window_s=100.0))
        h = Histogram("lat", buckets=(10.0, 100.0))
        trk.update(0.0, per_pool={}, fleet=h.snapshot())
        for _ in range(10):
            h.observe(1.0)
        v = trk.update(10.0, per_pool={}, fleet=h.snapshot())
        assert v["fleet"]["requests_window"] == 10

        # restart mid-window: cumulative counts step backward
        fresh = Histogram("lat", buckets=(10.0, 100.0))
        v = trk.update(20.0, per_pool={}, fleet=fresh.snapshot())
        f = v["fleet"]
        assert f["requests_window"] >= 0 and f["errors_window"] >= 0
        assert f["requests_window"] <= 10  # never a phantom spike

        # post-restart traffic lands in the SAME window immediately
        for _ in range(6):
            fresh.observe(1.0)
        for _ in range(2):
            fresh.observe(50.0)
        v = trk.update(30.0, per_pool={}, fleet=fresh.snapshot())
        f = v["fleet"]
        assert f["requests_window"] == 18  # 10 pre + 8 post restart
        assert f["errors_window"] == 2
        assert f["burn_rate"] > 0

    def test_histogram_window_delta_and_restart_rebase(self):
        from paddle_tpu.observability.fleet import HistogramWindow
        from paddle_tpu.observability.registry import Histogram

        win = HistogramWindow(window_s=100.0)
        h = Histogram("sz", buckets=(4.0, 16.0))
        for v in (1, 2, 10):
            h.observe(v)
        win.update(0.0, h.snapshot())
        for v in (1, 1, 20):
            h.observe(v)
        win.update(10.0, h.snapshot())
        bounds, counts = win.delta(10.0)
        assert bounds == (4.0, 16.0, float("inf"))
        assert counts == [2, 0, 1]  # only the second batch is in-delta
        assert win.total(10.0) == 3

        # restart: a fresh histogram's lower counts must not go negative
        fresh = Histogram("sz", buckets=(4.0, 16.0))
        fresh.observe(3)
        win.update(20.0, fresh.snapshot())
        _b, counts = win.delta(20.0)
        assert all(c >= 0 for c in counts)
        assert win.rebases == 1
        fresh.observe(3)
        fresh.observe(3)
        win.update(30.0, fresh.snapshot())
        _b, counts = win.delta(30.0)
        assert counts[0] >= 2  # post-restart traffic visible in-window

    def test_histogram_window_layout_change_resets(self):
        from paddle_tpu.observability.fleet import HistogramWindow
        from paddle_tpu.observability.registry import Histogram

        win = HistogramWindow(window_s=100.0)
        a = Histogram("sz", buckets=(4.0, 16.0))
        a.observe(1)
        win.update(0.0, a.snapshot())
        b = Histogram("sz", buckets=(8.0, 32.0))  # respec'd layout
        b.observe(1)
        win.update(1.0, b.snapshot())
        bounds, counts = win.delta(1.0)
        assert bounds == (8.0, 32.0, float("inf"))
        assert sum(counts) == 0  # no cross-layout delta is invented


# -- BucketSpec validation (satellite 2) --------------------------------------

class TestBucketSpecValidation:
    def test_duplicates_rejected(self):
        from paddle_tpu.serving import BucketSpec

        with pytest.raises(ValueError, match="duplicate"):
            BucketSpec(batch_sizes=(1, 2, 2, 4))
        with pytest.raises(ValueError, match="duplicate"):
            BucketSpec(batch_sizes=(1,), seq_lens=(8, 8))

    def test_non_positive_and_non_int_rejected(self):
        from paddle_tpu.serving import BucketSpec

        with pytest.raises(ValueError, match="positive"):
            BucketSpec(batch_sizes=(0, 1))
        with pytest.raises(ValueError, match="positive"):
            BucketSpec(batch_sizes=(1,), seq_lens=(8, -16))
        with pytest.raises(ValueError, match="positive"):
            BucketSpec(batch_sizes=(1.5, 2))

    def test_order_insensitive_canonicalized(self):
        from paddle_tpu.serving import BucketSpec

        spec = BucketSpec(batch_sizes=(8, 1, 4, 2), seq_lens=(64, 16))
        assert spec.batch_sizes == (1, 2, 4, 8)
        assert spec.seq_lens == (16, 64)

    def test_observed_floor_rejects_dead_buckets(self):
        from paddle_tpu.serving import BucketSpec

        with pytest.raises(ValueError, match="observed"):
            BucketSpec(batch_sizes=(1,), seq_lens=(8, 64),
                       observed_floor=16)
        ok = BucketSpec(batch_sizes=(1,), seq_lens=(16, 64),
                        observed_floor=16)
        assert ok.observed_floor == 16

    def test_derived_specs_share_the_validation_path(self):
        """A tuner-derived shape validates through the same code as a
        hand-declared one — a bad derivation fails BEFORE any warmup."""
        from paddle_tpu.serving import BucketSpec
        from paddle_tpu.tuning.serving_tuner import _validate_shape

        buckets = quantile_cover([17, 33, 129], q=1.0, align=16)
        spec = BucketSpec(batch_sizes=(1, 2), seq_lens=buckets,
                          observed_floor=17)
        assert spec.seq_lens == buckets
        with pytest.raises(ValueError, match="duplicate"):
            _validate_shape({"prefill_buckets": [8, 8]})
        with pytest.raises(ValueError, match="observed"):
            _validate_shape({"seq_buckets": [8, 64],
                             "observed_floor": 16})
        with pytest.raises(ValueError, match="max_slots"):
            _validate_shape({"max_slots": 0})


# -- planner re-scoring -------------------------------------------------------

class TestRescore:
    @pytest.fixture(scope="class")
    def profile_and_cands(self):
        from paddle_tpu.distributed.auto_parallel import planner
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        prof = planner.profile_model(model, batch=16, seq=64)
        cands = planner.plan(model, n_devices=1, hbm_bytes=64e9,
                             batch=16, remat=(False, True),
                             accumulate=(1,), levels=(None,),
                             offload=(False,), cp_degrees=(1,))
        assert len(cands) >= 2
        return prof, cands

    def test_plan_digest_stable_and_distinct(self, profile_and_cands):
        from paddle_tpu.distributed.auto_parallel.planner import plan_digest

        _prof, cands = profile_and_cands
        digests = [plan_digest(c.config) for c in cands]
        assert len(set(digests)) == len(digests)
        assert plan_digest(cands[0].config) == \
            plan_digest(dict(cands[0].config))

    def test_rescore_matches_plan_ranking_unanchored(self,
                                                     profile_and_cands):
        from paddle_tpu.distributed.auto_parallel.planner import (
            rescore_candidates)

        prof, cands = profile_and_cands
        ranked = rescore_candidates(prof, cands, hbm_bytes=64e9)
        assert [c.config for c in ranked] == [c.config for c in cands]

    def test_measured_anchor_demotes_the_regressed_active(
            self, profile_and_cands):
        from paddle_tpu.distributed.auto_parallel.planner import (
            plan_digest, rescore_candidates)

        prof, cands = profile_and_cands
        active = plan_digest(cands[0].config)
        # the active plan measures 100x its model prediction: anchored
        reg_s = cands[0].predicted_step_s * 100
        ranked = rescore_candidates(prof, cands, hbm_bytes=64e9,
                                    measured={active: reg_s})
        assert plan_digest(ranked[0].config) != active
        anchored = [c for c in ranked
                    if plan_digest(c.config) == active][0]
        assert anchored.predicted_step_s == pytest.approx(reg_s)
        assert anchored.breakdown["measured_anchor_s"] == \
            pytest.approx(reg_s)

    def test_rescore_accepts_published_descriptors(self,
                                                   profile_and_cands):
        from paddle_tpu.distributed.auto_parallel.planner import (
            rescore_candidates)

        prof, cands = profile_and_cands
        descs = [json.loads(json.dumps(c.to_dict())) for c in cands]
        ranked = rescore_candidates(prof, descs, hbm_bytes=64e9)
        assert [c.config["mesh"] for c in ranked] == \
            [c.config["mesh"] for c in cands]


# -- respec: live bucket swap keeps the zero-retrace invariant ----------------

class TestRespec:
    def test_respec_prewarms_before_swap_and_serves_without_compiles(self):
        from paddle_tpu import serving

        eng = serving.ServingEngine(
            lambda x: x * 2.0,
            buckets=serving.BucketSpec(batch_sizes=(2,),
                                       seq_lens=(8, 16)),
            input_specs=[((None,), "float32")],
            config=serving.ServingConfig(max_batch_wait_ms=5.0))
        with eng:
            f = eng.submit([np.ones(5, np.float32)])
            np.testing.assert_array_equal(
                f.result(timeout=60)[0][:5], np.full(5, 2.0, np.float32))
            compiled_before = dict(eng._compiled)
            new = serving.BucketSpec(batch_sizes=(1, 2),
                                     seq_lens=(4, 8, 16))
            eng.respec(new)
            assert eng.buckets is new
            # old runners retained, new family warmed
            assert set(compiled_before) <= set(eng._compiled)
            stats = eng.stats()
            assert stats["counters"]["respecs"] == 1
            assert stats["counters"]["respec_compiles"] > 0
            misses0 = stats["counters"].get("compile_cache_misses", 0)
            # a request landing in a NEW bucket (seq 3 -> 4, batch 1)
            # must execute on the pre-warmed runner: no fresh compile
            f = eng.submit([np.ones(3, np.float32)])
            np.testing.assert_array_equal(
                f.result(timeout=60)[0][:3], np.full(3, 2.0, np.float32))
            assert eng.stats()["counters"].get(
                "compile_cache_misses", 0) == misses0

    def test_respec_rejects_invalid_spec(self):
        from paddle_tpu import serving

        with pytest.raises(ValueError, match="duplicate"):
            serving.BucketSpec(batch_sizes=(2, 2))


# -- apply_tuned_shape (replica-side respec) ----------------------------------

class TestApplyTunedShape:
    def test_generation_engine_rebuilt_with_derived_shape(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving.generation import (GenerationConfig,
                                                   GenerationEngine)
        from paddle_tpu.tuning.serving_tuner import apply_tuned_shape

        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=32, hidden_size=16, num_hidden_layers=1,
            num_attention_heads=2, max_position_embeddings=64,
            dtype="float32"))
        eng = GenerationEngine(model, GenerationConfig(
            max_slots=2, prefill_buckets=(16, 32)))
        tuned = apply_tuned_shape(eng, {"prefill_buckets": [8, 16],
                                        "max_slots": 3})
        assert tuned is not eng
        assert tuned.config.prefill_buckets == (8, 16)
        assert tuned.config.max_slots == 3
        # the original engine's declared knobs are untouched
        assert eng.config.prefill_buckets == (16, 32)

    def test_invalid_shape_fails_before_any_rebuild(self):
        from paddle_tpu.tuning.serving_tuner import apply_tuned_shape

        with pytest.raises(ValueError):
            apply_tuned_shape(object(), {"prefill_buckets": [4, 4]})

    def test_unknown_engine_passes_through(self):
        from paddle_tpu.tuning.serving_tuner import apply_tuned_shape

        sentinel = object()
        assert apply_tuned_shape(sentinel, {"max_slots": 2}) is sentinel


# -- OnlineTuner driver -------------------------------------------------------

class _ScriptedPolicy(TuningPolicy):
    name = "scripted"
    cooldown_s = 0.0

    def __init__(self, verdicts):
        self.verdicts = list(verdicts)  # measure() results, per apply
        self.log = []
        self.applied = None

    def observe(self, signals):
        self.log.append(("observe", dict(signals)))

    def propose(self):
        return Proposal(policy=self.name, kind="test", from_digest="a",
                        to_digest="b", payload={"x": 1},
                        predicted={"win": 1.0})

    def apply(self, proposal):
        self.log.append(("apply", proposal.to_digest))
        self.applied = proposal.to_digest
        return True

    def measure(self, proposal):
        return self.verdicts.pop(0) if self.verdicts else True

    def rollback(self, proposal):
        self.log.append(("rollback", proposal.to_digest))
        self.applied = None


class TestOnlineTuner:
    def test_kill_switch_disables_everything(self, monkeypatch):
        monkeypatch.setenv("PT_ONLINE_TUNING", "0")
        pol = _ScriptedPolicy([True])
        tuner = OnlineTuner([pol], provider_name=None)
        tuner.tick()
        assert tuner.ticks == 0 and pol.log == []
        snap = tuner.snapshot()
        assert snap["enabled"] is False  # visibly off, not silently stuck

    def test_keep_path_counts_and_ledger(self):
        pol = _ScriptedPolicy([None, True])  # window fills, then keep
        tuner = OnlineTuner([pol], signal_sources={"k": lambda: 7},
                            provider_name=None)
        tuner.tick()   # propose + apply
        tuner.tick()   # measure -> None (filling)
        tuner.tick()   # measure -> True (keep)
        snap = tuner.snapshot()["policies"]["scripted"]
        assert snap["proposals"] == 1 and snap["applies"] == 1
        assert snap["keeps"] == 1 and snap["rollbacks"] == 0
        events = [d["event"] for d in tuner.snapshot()["decisions"]]
        assert events == ["propose", "apply", "keep"]
        # signals reached the policy as one assembled view
        assert pol.log[0] == ("observe", {"k": 7})

    def test_rollback_embargoes_the_digest(self):
        pol = _ScriptedPolicy([False])  # refuted on first measure
        tuner = OnlineTuner([pol], provider_name=None)
        tuner.tick()   # propose+apply
        tuner.tick()   # measure -> False -> rollback
        snap = tuner.snapshot()["policies"]["scripted"]
        assert snap["rollbacks"] == 1 and snap["rejected"] == ["b"]
        assert pol.applied is None  # rollback() actually ran
        applies_before = snap["applies"]
        tuner.tick()   # same digest proposed again: embargoed
        snap = tuner.snapshot()["policies"]["scripted"]
        assert snap["applies"] == applies_before

    def test_dead_signal_source_does_not_stop_tuning(self):
        def boom():
            raise RuntimeError("scrape died")

        pol = _ScriptedPolicy([True])
        tuner = OnlineTuner([pol], signal_sources={"bad": boom},
                            provider_name=None)
        tuner.tick()
        assert "error" in pol.log[0][1]["bad"]
        assert tuner.snapshot()["policies"]["scripted"]["applies"] == 1


# -- elastic plan tuner over a fake control plane -----------------------------

class _FakeStore:
    def __init__(self):
        self.kv = {}
        self.counters = {}

    def set(self, k, v):
        self.kv[k] = v

    def get(self, k):
        return self.kv[k]

    def add(self, k, n):
        self.counters[k] = self.counters.get(k, 0) + int(n)
        return self.counters[k]


def _mk_plan_tuner(store, gen, prof, cands, **kw):
    from paddle_tpu.tuning.plan_tuner import ElasticPlanTuner

    ctx = SimpleNamespace(store=store, gen=gen, rank=0)
    kw.setdefault("detector",
                  RegressionDetector(min_samples=4, baseline_window=8,
                                     sustain_n=3))
    kw.setdefault("margin", 0.2)
    kw.setdefault("measure_steps", 3)
    kw.setdefault("skip_steps", 1)
    return ElasticPlanTuner(ctx, prof, cands, hbm_bytes=64e9,
                            register_provider_name=None, **kw)


class TestElasticPlanTuner:
    @pytest.fixture(scope="class")
    def profile_and_cands(self):
        from paddle_tpu.distributed.auto_parallel import planner
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        prof = planner.profile_model(model, batch=16, seq=64)
        cands = planner.plan(model, n_devices=1, hbm_bytes=64e9,
                             batch=16, remat=(False, True),
                             accumulate=(1,), levels=(None,),
                             offload=(False,), cp_degrees=(1,))
        return prof, cands

    def _publish_plan(self, store, gen, cand):
        from paddle_tpu.distributed.fleet.runtime import _publish

        _publish(store, f"fleet/{gen}/plan", cand.to_dict())

    def test_regression_raises_planned_fence_with_override(
            self, profile_and_cands):
        from paddle_tpu.distributed.auto_parallel.planner import plan_digest
        from paddle_tpu.distributed.fleet.runtime import _probe_json
        from paddle_tpu.tuning.plan_tuner import (PLAN_OVERRIDE_KEY,
                                                  PLAN_STATE_KEY)

        prof, cands = profile_and_cands
        store = _FakeStore()
        self._publish_plan(store, 0, cands[0])
        tuner = _mk_plan_tuner(store, 0, prof, cands)
        for _ in range(6):
            tuner.on_step(100.0)  # healthy baseline
        assert store.counters.get("fleet/0/fence", 0) == 0
        for _ in range(3):
            tuner.on_step(400.0)  # sustained regression
        # fence raised with the planned retune reason, override published
        assert store.counters["fleet/0/fence"] == 1
        assert json.loads(store.kv["fleet/0/fence_reason"]) == \
            "retune:plan"
        ov = _probe_json(store, PLAN_OVERRIDE_KEY)
        assert plan_digest(ov["config"]) != plan_digest(cands[0].config)
        st = _probe_json(store, PLAN_STATE_KEY)
        assert st["phase"] == "measure"
        assert st["counters"]["proposals"] == 1
        assert st["counters"]["applies"] == 1
        # further steps in the dying generation are inert
        tuner.on_step(400.0)
        assert store.counters["fleet/0/fence"] == 1

    def _regress_and_fence(self, prof, cands):
        store = _FakeStore()
        self._publish_plan(store, 0, cands[0])
        t0 = _mk_plan_tuner(store, 0, prof, cands)
        for _ in range(6):
            t0.on_step(100.0)
        for _ in range(3):
            t0.on_step(400.0)
        return store

    def test_next_generation_keeps_a_confirmed_win(self,
                                                   profile_and_cands):
        from paddle_tpu.distributed.auto_parallel.planner import plan_digest
        from paddle_tpu.distributed.fleet.runtime import _probe_json
        from paddle_tpu.tuning.plan_tuner import PLAN_STATE_KEY

        prof, cands = profile_and_cands
        store = self._regress_and_fence(prof, cands)
        # gen 1: the new plan is fast (the regression WAS plan-bound)
        t1 = _mk_plan_tuner(store, 1, prof, cands)
        for _ in range(4):  # skip 1 + 3 measure steps
            t1.on_step(100.0)
        st = _probe_json(store, PLAN_STATE_KEY)
        assert st["phase"] == "idle"
        assert st["counters"]["keeps"] == 1
        assert st["counters"]["rollbacks"] == 0
        assert st["last_verdict"]["kept"] is True
        assert st["active"] != plan_digest(cands[0].config)
        # no rollback fence was raised in gen 1
        assert store.counters.get("fleet/1/fence", 0) == 0

    def test_next_generation_rolls_back_a_refuted_win(
            self, profile_and_cands):
        from paddle_tpu.distributed.auto_parallel.planner import plan_digest
        from paddle_tpu.distributed.fleet.runtime import _probe_json
        from paddle_tpu.tuning.plan_tuner import (PLAN_OVERRIDE_KEY,
                                                  PLAN_STATE_KEY)

        prof, cands = profile_and_cands
        store = self._regress_and_fence(prof, cands)
        # gen 1: still slow — the regression was environmental
        t1 = _mk_plan_tuner(store, 1, prof, cands)
        for _ in range(4):
            t1.on_step(400.0)
        st = _probe_json(store, PLAN_STATE_KEY)
        assert st["counters"]["rollbacks"] == 1
        assert st["active"] == plan_digest(cands[0].config)
        assert st["last_verdict"]["kept"] is False
        # the override now restores the ORIGINAL plan, via a new fence
        ov = _probe_json(store, PLAN_OVERRIDE_KEY)
        assert plan_digest(ov["config"]) == plan_digest(cands[0].config)
        assert json.loads(store.kv["fleet/1/fence_reason"]) == \
            "retune:rollback"
        # gen 2: regression persists, but the loser is embargoed — the
        # tuner must NOT flap back onto it
        t2 = _mk_plan_tuner(store, 2, prof, cands)
        self._publish_plan(store, 2, cands[0])
        for _ in range(6):
            t2.on_step(100.0)
        time.sleep(0)  # cooldown from the rollback may still hold
        st = _probe_json(store, PLAN_STATE_KEY)
        rejected = st["rejected"]
        assert rejected and rejected[0] != plan_digest(cands[0].config)

    def test_kill_switch_freezes_the_plan_tuner(self, monkeypatch,
                                                profile_and_cands):
        prof, cands = profile_and_cands
        monkeypatch.setenv("PT_ONLINE_TUNING", "0")
        store = _FakeStore()
        self._publish_plan(store, 0, cands[0])
        tuner = _mk_plan_tuner(store, 0, prof, cands)
        for _ in range(6):
            tuner.on_step(100.0)
        for _ in range(10):
            tuner.on_step(500.0)
        assert store.counters.get("fleet/0/fence", 0) == 0
        assert "fleet/plan_override" not in store.kv


# -- worker replan honors the override ----------------------------------------

class TestReplanOverride:
    def test_override_wins_when_mesh_covers_world(self, monkeypatch):
        from paddle_tpu.distributed.fleet.runtime import (
            FleetWorkerContext, _probe_json, _publish)

        store = _FakeStore()
        ov = {"config": {"mesh": {"dp": 1, "mp": 1, "pp": 1, "cp": 1,
                                  "ep": 1, "sharding": 1},
               "accumulate_steps": 1, "remat": True}}
        _publish(store, "fleet/plan_override", ov)
        ctx = FleetWorkerContext(rank=0, world=1, gen=3, store=store)
        got = ctx.replan(None, batch=8)  # model unused: override wins
        assert got == ov
        # and it is republished as THIS generation's plan
        assert _probe_json(store, "fleet/3/plan") == ov

    def test_stale_override_for_wrong_world_is_ignored(self):
        from paddle_tpu.distributed.fleet.runtime import (
            FleetWorkerContext, _publish)
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        store = _FakeStore()
        ov = {"config": {"mesh": {"dp": 4, "mp": 1, "pp": 1, "cp": 1,
                                  "ep": 1, "sharding": 1}}}
        _publish(store, "fleet/plan_override", ov)
        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        ctx = FleetWorkerContext(rank=0, world=1, gen=0, store=store)
        got = ctx.replan(model, batch=16)
        assert got["config"]["mesh"]["dp"] == 1  # freshly planned
