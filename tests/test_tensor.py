"""Tensor basics: creation, metadata, conversion, indexing.

Models the reference's OpTest style (op_test.py:284): numpy is the oracle.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_roundtrip():
    a = np.random.rand(3, 4).astype("float32")
    t = paddle.to_tensor(a)
    assert t.shape == [3, 4]
    assert t.dtype == paddle.float32
    np.testing.assert_allclose(t.numpy(), a)


def test_scalar_tensor():
    t = paddle.to_tensor(3.5)
    assert t.item() == 3.5
    assert float(t) == 3.5
    assert t.shape == []


def test_creation_ops():
    np.testing.assert_array_equal(paddle.zeros([2, 3]).numpy(), np.zeros((2, 3)))
    np.testing.assert_array_equal(paddle.ones([2]).numpy(), np.ones(2))
    np.testing.assert_array_equal(paddle.full([2, 2], 7).numpy(), np.full((2, 2), 7))
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3))
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_array_equal(paddle.zeros_like(x).numpy(), np.zeros((2, 2)))
    np.testing.assert_array_equal(paddle.ones_like(x).numpy(), np.ones((2, 2)))
    np.testing.assert_allclose(
        paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6
    )
    np.testing.assert_array_equal(
        paddle.tril(paddle.ones([3, 3])).numpy(), np.tril(np.ones((3, 3)))
    )


def test_random_creation_shapes():
    assert paddle.rand([2, 3]).shape == [2, 3]
    assert paddle.randn([4]).shape == [4]
    r = paddle.randint(0, 10, [100])
    assert (r.numpy() >= 0).all() and (r.numpy() < 10).all()
    u = paddle.uniform([50], min=2.0, max=3.0)
    assert (u.numpy() >= 2.0).all() and (u.numpy() <= 3.0).all()
    p = paddle.randperm(10)
    assert sorted(p.numpy().tolist()) == list(range(10))


def test_seed_reproducibility():
    paddle.seed(42)
    a = paddle.randn([5]).numpy()
    paddle.seed(42)
    b = paddle.randn([5]).numpy()
    np.testing.assert_array_equal(a, b)


def test_getitem_static():
    a = np.arange(24).reshape(2, 3, 4).astype("float32")
    t = paddle.to_tensor(a)
    np.testing.assert_array_equal(t[0].numpy(), a[0])
    np.testing.assert_array_equal(t[1, 2].numpy(), a[1, 2])
    np.testing.assert_array_equal(t[:, 1:, ::2].numpy(), a[:, 1:, ::2])
    np.testing.assert_array_equal(t[..., -1].numpy(), a[..., -1])
    np.testing.assert_array_equal(t[None].numpy(), a[None])


def test_getitem_tensor_index():
    a = np.arange(20).reshape(4, 5).astype("float32")
    t = paddle.to_tensor(a)
    idx = paddle.to_tensor([0, 2, 3])
    np.testing.assert_array_equal(t[idx].numpy(), a[[0, 2, 3]])


def test_setitem():
    t = paddle.zeros([3, 3])
    t[1] = paddle.ones([3])
    assert t.numpy()[1].sum() == 3
    t[0, 0] = 5.0
    assert t.numpy()[0, 0] == 5.0


def test_cast():
    t = paddle.to_tensor([1.7, 2.3])
    i = t.cast("int32")
    assert i.dtype == paddle.int32
    np.testing.assert_array_equal(i.numpy(), [1, 2])


def test_inplace_ops():
    t = paddle.ones([3])
    t.add_(paddle.ones([3]))
    np.testing.assert_array_equal(t.numpy(), [2, 2, 2])
    t.zero_()
    np.testing.assert_array_equal(t.numpy(), [0, 0, 0])
    t.fill_(7)
    np.testing.assert_array_equal(t.numpy(), [7, 7, 7])


def test_comparison_dunders():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([2.0, 2.0, 2.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    np.testing.assert_array_equal((a >= 2).numpy(), [False, True, True])


def test_default_dtype():
    assert paddle.get_default_dtype() == paddle.float32
    paddle.set_default_dtype("bfloat16")
    try:
        assert paddle.ones([2]).dtype == paddle.bfloat16
    finally:
        paddle.set_default_dtype("float32")
