"""Concurrency lint (CC codes) drills.

True-positive proof: seeded fixture sources for every CC code are
detected. False-positive proof: the condition-variable idiom, timeouts,
suppressions, and the repo itself (post-fix) all lint clean. The real
findings this pass surfaced (fleet supervisor store probes under the
lock, embedding prefetch submitting to the bounded lane under the table
mutex, the SIGTERM handler taking the callback lock) are each
regression-pinned — by lint and, for the two runtime fixes, by a
thread-based behavioral pin.
"""
import os
import threading

import numpy as np
import pytest

from paddle_tpu.analysis import concurrency

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddle_tpu")


def lint(src, path="fixture.py"):
    return concurrency.lint_file(path, src)


def codes(diags):
    return [d.code for d in diags]


# -- CC001: blocking call under a held lock ---------------------------------
def test_cc001_sleep_under_with_lock():
    d = lint("""
import threading, time
lock = threading.Lock()
def f():
    with lock:
        time.sleep(1)
""")
    assert codes(d) == ["CC001"] and d[0].severity == "error"
    assert "time.sleep" in d[0].message


def test_cc001_untimed_queue_get_under_lock():
    d = lint("""
def f(self):
    with self._lock:
        item = self._q.get()
""")
    assert codes(d) == ["CC001"]


def test_cc001_between_acquire_release_only():
    d = lint("""
def f(self, sock, obj):
    self._lock.acquire()
    sock.sendall(obj)
    self._lock.release()
    sock.sendall(obj)
""")
    assert codes(d) == ["CC001"]
    assert d[0].location.endswith(":4")  # only the held-region send


def test_cc001_device_get_and_frame_io():
    d = lint("""
import jax
def f(self, x):
    with self._mu:
        y = jax.device_get(x)
def g(self, sock, obj):
    with self._send_lock:
        send_frame(sock, obj)
""")
    assert codes(d) == ["CC001", "CC001"]


def test_cc001_local_call_taint_chain():
    d = lint("""
class C:
    def _probe(self):
        return self.store.get("k")
    def _exits(self):
        return self._probe()
    def snapshot(self):
        with self._lock:
            return self._exits()
""")
    assert codes(d) == ["CC001"]
    assert "_exits" in d[0].message and "store.get" in d[0].message


def test_cc001_cond_wait_idiom_exempt():
    d = lint("""
def worker(self):
    with self._cond:
        while not self._queue:
            self._cond.wait()
""")
    assert d == []


def test_cc001_timeouts_exempt():
    d = lint("""
def f(self):
    with self._lock:
        self._q.get(timeout=1)
        self._q.put(1, timeout=0.5)
        fut.result(timeout=2)
        ev.wait(0.05)
        t.join(5)
""")
    assert d == []


def test_cc001_nested_def_does_not_inherit_held_context():
    d = lint("""
import time
def f(self):
    with self._lock:
        def later():
            time.sleep(1)   # runs later, lock not held then
        self.cb = later
""")
    assert d == []


def test_cc001_suppression_line_and_def():
    d = lint("""
import time
def f(self):
    with self._lock:
        time.sleep(1)  # pd-lint: disable=CC001
def g(self):  # pd-lint: disable=CC001
    with self._lock:
        time.sleep(1)
""")
    assert d == []


# -- CC002: lock in signal handler / __del__ --------------------------------
def test_cc002_signal_handler_lock_via_callee():
    d = lint("""
import signal, threading
_LOCK = threading.Lock()
def _fire():
    _LOCK.acquire()
    _LOCK.release()
def _handler(signum, frame):
    _fire()
signal.signal(signal.SIGTERM, _handler)
""")
    assert "CC002" in codes(d)


def test_cc002_flag_only_handler_clean():
    d = lint("""
import signal, threading
_FLAG = threading.Event()
def _handler(signum, frame):
    _FLAG.set()
signal.signal(signal.SIGTERM, _handler)
""")
    assert d == []


def test_cc002_del_with_lock():
    d = lint("""
class C:
    def __del__(self):
        with self._lock:
            self.closed = True
""")
    assert codes(d) == ["CC002"]


# -- CC003: non-daemon thread without join path -----------------------------
def test_cc003_leaky_thread_and_timer():
    d = lint("""
import threading
def go(fn):
    threading.Thread(target=fn).start()
    threading.Timer(1.0, fn).start()
""")
    assert codes(d) == ["CC003", "CC003"]
    assert all(x.severity == "warning" for x in d)


def test_cc003_daemon_or_joined_clean():
    d = lint("""
import threading
class C:
    def start(self):
        threading.Thread(target=self.run, daemon=True).start()
        self._t = threading.Thread(target=self.run)
        self._t.start()
    def close(self):
        self._t.join(timeout=5)
""")
    assert d == []


def test_cc003_daemonized_after_construction_clean():
    d = lint("""
import threading
def go(fn):
    t = threading.Timer(1.0, fn)
    t.daemon = True
    t.start()
""")
    assert d == []


# -- CC004: unguarded shared write in a thread target -----------------------
def test_cc004_augassign_in_thread_target():
    d = lint("""
import threading
class C:
    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
    def _loop(self):
        self.failures += 1
""")
    assert codes(d) == ["CC004"]


def test_cc004_locked_target_clean():
    d = lint("""
import threading
class C:
    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
    def _loop(self):
        with self._lock:
            self.failures += 1
""")
    assert d == []


# -- CC005: conflicting lock order ------------------------------------------
def test_cc005_ab_ba_conflict_same_file():
    d = lint("""
class C:
    def f(self):
        with self.lock_a:
            with self.lock_b:
                pass
    def g(self):
        with self.lock_b:
            with self.lock_a:
                pass
""")
    cc5 = [x for x in d if x.code == "CC005"]
    assert len(cc5) == 2 and all(x.severity == "error" for x in cc5)
    assert "opposite order" in cc5[0].message


def test_cc005_consistent_order_clean():
    d = lint("""
class C:
    def f(self):
        with self.lock_a:
            with self.lock_b:
                pass
    def g(self):
        with self.lock_a:
            with self.lock_b:
                pass
""")
    assert d == []


def test_cc005_cross_file_conflict(tmp_path):
    (tmp_path / "m1.py").write_text("""
class C:
    def f(self):
        with self.lock_a:
            with self.lock_b:
                pass
""")
    (tmp_path / "m2.py").write_text("""
class C:
    def g(self):
        with self.lock_b:
            with self.lock_a:
                pass
""")
    d = concurrency.lint_tree(str(tmp_path))
    cc5 = [x for x in d if x.code == "CC005"]
    assert len(cc5) == 2
    files = {os.path.basename(x.location.split(":")[0]) for x in cc5}
    assert files == {"m1.py", "m2.py"}


def test_cc005_suppressed():
    d = lint("""
class C:
    def f(self):
        with self.lock_a:
            with self.lock_b:  # pd-lint: disable=CC005
                pass
    def g(self):
        with self.lock_b:
            with self.lock_a:  # pd-lint: disable=CC005
                pass
""")
    assert d == []


def test_cc000_syntax_error():
    d = lint("def broken(:\n")
    assert codes(d) == ["CC000"]


# -- regression pins: the real findings stay fixed ---------------------------
@pytest.mark.parametrize("rel", [
    "distributed/fleet/runtime.py",      # supervisor probes under _lock
    "distributed/resilience/preempt.py",  # SIGTERM handler took _LOCK
    "sparse/embedding.py",               # lane submit under table _mu
    "distributed/collective.py",         # p2p dial retry under chan lock
    "serving/fleet.py",                  # unjoined non-daemon hedge Timer
])
def test_fixed_files_stay_clean(rel):
    diags = concurrency.lint_file(os.path.join(PKG, rel))
    errors = [d for d in diags if d.severity == "error"]
    assert errors == [], [d.render() for d in errors]


def test_repo_wide_zero_errors():
    diags = concurrency.run_concurrency()
    errors = [d for d in diags if d.severity == "error"]
    assert errors == [], [d.render() for d in errors]
    warnings = [d for d in diags if d.severity == "warning"]
    assert warnings == [], [d.render() for d in warnings]


def test_prefetch_releases_mutex_during_lane_submit():
    """Behavioral pin for the embedding CC001 fix: while prefetch() is
    parked in the (bounded, blockable) lane submit, another thread can
    still take the table mutex — pre-fix this times out."""
    from paddle_tpu.sparse.embedding import ShardedEmbeddingTable

    t = ShardedEmbeddingTable(256, 8, cache_rows=16, overlap=False,
                              name="ccpin")
    in_submit = threading.Event()
    release = threading.Event()

    def slow_submit(rows, **kw):
        in_submit.set()
        assert release.wait(10)

        class H:
            def rows_dispatched(self):
                raise AssertionError("not consumed in this test")
        return H()

    t.lane.submit_rows = slow_submit
    ids = np.arange(32, dtype=np.int64)
    worker = threading.Thread(target=t.prefetch, args=(ids,), daemon=True)
    worker.start()
    assert in_submit.wait(10), "prefetch never reached the lane submit"
    got = t._mu.acquire(timeout=2)
    assert got, "table mutex held across the blocking lane submit"
    t._mu.release()
    release.set()
    worker.join(timeout=10)
    assert not worker.is_alive()


def test_preempt_fire_callbacks_lock_free():
    """Behavioral pin for the CC002 fix: firing preemption callbacks
    while _LOCK is already held (exactly what a SIGTERM landing inside
    on_preemption() does) must not self-deadlock."""
    from paddle_tpu.distributed.resilience import preempt

    fired = []
    preempt.on_preemption(lambda: fired.append(1))
    try:
        done = threading.Event()

        def fire_while_locked():
            with preempt._LOCK:  # the interrupted frame's held lock
                preempt._fire_callbacks()
            done.set()

        th = threading.Thread(target=fire_while_locked, daemon=True)
        th.start()
        assert done.wait(5), "_fire_callbacks deadlocked on _LOCK"
        assert fired == [1]
    finally:
        preempt._CALLBACKS.clear()
