"""ISSUE 12: the production serving tier — paged KV cache with prefix
reuse, speculative decoding, and the multi-replica router.

Covers the acceptance surface: allocator/trie invariants (alloc, free,
ref-count, COW, fragmentation under churn, leaf-only LRU eviction),
prefix-hit parity (a shared-prefix request produces the same greedy
tokens as a cold prefill — its K/V pages ARE the cold request's pages),
speculative greedy parity vs ``model.generate``, rejection-sampling
distribution preservation, deadline-aware (EDF) slot joining with
queued-expiry shedding, paged admission bounds (pool capacity, not slot
length), router quota/backpressure/fault behavior, and the zero-retrace
steady-state contract for the paged decode path.
"""
import os
import time
from concurrent.futures import Future
from concurrent.futures import wait as fwait

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.serving.paged_kv import (
    PageAllocator, PagedKVPool, PoolExhausted, PrefixCache, token_blocks,
)


# -- allocator ----------------------------------------------------------------

def test_allocator_alloc_free_refcount_invariants():
    a = PageAllocator(8)                    # 1 scratch + 7 usable
    p = a.alloc(3)
    assert len(set(p)) == 3 and 0 not in p
    assert a.free_pages == 4 and a.live_pages == 3
    a.retain(p[0])
    assert a.ref(p[0]) == 2
    a.release(p[0])                         # still held once
    assert a.ref(p[0]) == 1 and a.free_pages == 4
    a.release(p[0])                         # now freed
    assert a.ref(p[0]) == 0 and a.free_pages == 5
    with pytest.raises(RuntimeError, match="double free"):
        a.release(p[0])
    with pytest.raises(RuntimeError, match="retain of free"):
        a.retain(p[0])
    with pytest.raises(PoolExhausted):
        a.alloc(8)
    assert a.free_pages == 5                # all-or-nothing: no leak
    a.check()


def test_allocator_cow_semantics():
    a = PageAllocator(4)
    (p,) = a.alloc(1)
    same, copied = a.cow(p)
    assert same == p and not copied         # exclusive: write in place
    a.retain(p)                             # now shared
    new, copied = a.cow(p)
    assert copied and new != p
    assert a.ref(new) == 1 and a.ref(p) == 1   # writer moved off the share
    assert a.cow_total == 1
    a.check()


def test_allocator_fragmentation_churn():
    """Random alloc/free churn: the free list and refcounts stay coherent
    (no double allocation, no lost pages) at every step."""
    rng = np.random.RandomState(0)
    a = PageAllocator(32)
    live = []
    for _ in range(400):
        if live and (rng.rand() < 0.5 or a.free_pages == 0):
            pages = live.pop(rng.randint(len(live)))
            for p in pages:
                a.release(p)
        else:
            n = rng.randint(1, 5)
            if n <= a.free_pages:
                live.append(a.alloc(n))
        a.check()
        held = [p for pages in live for p in pages]
        assert len(held) == len(set(held)), "page handed out twice"
        assert a.live_pages == len(held)
    for pages in live:
        for p in pages:
            a.release(p)
    a.check()
    assert a.free_pages == 31


# -- prefix trie --------------------------------------------------------------

def _chain(*blocks):
    return [tuple(b) for b in blocks]


def test_prefix_trie_match_insert_and_context_separation():
    a = PageAllocator(16)
    t = PrefixCache()
    pages = a.alloc(3)
    blocks = _chain([1, 2], [3, 4], [5, 6])
    assert t.insert(blocks, pages, a) == 3
    assert all(a.ref(p) == 2 for p in pages)       # ours + the trie's
    got = t.match(blocks, 2, a)
    assert got == pages
    assert all(a.ref(p) == 3 for p in pages)       # match retained for us
    # partial chains match their prefix only
    assert t.match(_chain([1, 2], [9, 9]), 2) == pages[:1]
    # the SAME block under a different prefix is a different node
    assert t.match(_chain([3, 4]), 2) == []
    assert t.match_len(blocks) == 3
    assert t.stats()["hit_tokens"] > 0


def test_prefix_trie_eviction_is_lru_leaf_only():
    a = PageAllocator(16)
    t = PrefixCache()
    p_ab = a.alloc(2)
    t.insert(_chain([1], [2]), p_ab, a)
    p_c = a.alloc(1)
    t.insert(_chain([3]), p_c, a)
    for p in p_ab + p_c:
        a.release(p)                       # trie is now the only holder
    t.match(_chain([3]), 1)                # bump [3]: chain a-b is LRU
    # evicting ONE page must take the a-b chain's LEAF, never its root
    assert t.evict(1, a) == 1
    assert t.match_len(_chain([1], [2])) == 1      # root [1] survives
    assert t.match_len(_chain([3])) == 1
    # a held page is never evicted: retain [3]'s page, ask for everything
    a.retain(p_c[0])
    freed = t.evict(10, a)
    assert freed == 1                      # [1] goes; held [3] survives
    assert t.match_len(_chain([3])) == 1 and len(t) == 1
    a.release(p_c[0])
    assert t.evict(10, a) == 1 and len(t) == 0
    a.check()
    assert a.free_pages == 15


def test_token_blocks_full_blocks_only():
    assert token_blocks(np.arange(10), 4) == [(0, 1, 2, 3), (4, 5, 6, 7)]
    assert token_blocks(np.arange(10), 4, limit=1) == [(0, 1, 2, 3)]
    assert token_blocks(np.arange(3), 4) == []


def test_pool_cow_copies_device_contents():
    pool = PagedKVPool(num_layers=1, num_pages=4, page_len=2, num_heads=1,
                       head_dim=2, dtype="float32")
    (p,) = pool.allocate(1)
    pool.k[0] = pool.k[0].at[p].set(1.5)
    pool.allocator.retain(p)               # shared: a writer must COW
    new, copied = pool.ensure_writable(p)
    assert copied and new != p
    np.testing.assert_array_equal(np.asarray(pool.k[0][new]),
                                  np.asarray(pool.k[0][p]))


# -- rejection sampling (sampled speculative correctness) ---------------------

def test_rejection_sample_preserves_target_distribution():
    """Empirical check of the published property: whatever the draft
    proposes, the FIRST emitted token is distributed as the target."""
    rng = np.random.RandomState(0)
    V, k, n = 4, 1, 20000
    draft = np.array([[0.7, 0.1, 0.1, 0.1]])
    target = np.array([[0.1, 0.4, 0.3, 0.2], [0.25, 0.25, 0.25, 0.25]])
    counts = np.zeros(V)
    for _ in range(n):
        d_tok = np.array([rng.choice(V, p=draft[0])])
        out, acc = serving.rejection_sample(draft, target, d_tok, rng)
        assert len(out) == acc + 1
        counts[out[0]] += 1
    emp = counts / n
    np.testing.assert_allclose(emp, target[0], atol=0.015)


def test_rejection_sample_identical_distributions_accept_all():
    rng = np.random.RandomState(1)
    probs = np.array([[0.5, 0.5], [0.5, 0.5], [0.5, 0.5]])
    for _ in range(50):
        d = np.array([rng.choice(2, p=probs[0]), rng.choice(2, p=probs[1])])
        out, acc = serving.rejection_sample(probs[:2], probs, d, rng)
        assert acc == 2 and list(out[:2]) == list(d)
    assert serving.greedy_accept([3, 5, 7], [3, 5, 9]) == 2
    assert serving.greedy_accept([4], [4]) == 1
    assert serving.greedy_accept([1], [2]) == 0


# -- engine: paged decode, prefix reuse, deadlines ----------------------------

@pytest.fixture(scope="module")
def tiny_lm():
    """1-layer GPT trained to continue the repeating 0..7 pattern:
    confident logits make greedy decode stable (the serving recipe)."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=64,
                    dtype="float32")
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=3e-3, parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: m(x, labels=y), optimizer)
    pattern = np.tile(np.arange(8), 8)[None, :]
    ids = paddle.to_tensor(pattern.astype("int64"))
    for _ in range(80):
        loss = step(ids, ids)
    assert float(loss) < 0.1
    return model, pattern[0]


@pytest.fixture(scope="module")
def paged_engine(tiny_lm):
    """ONE shared paged engine (compiles are the expensive part); tests
    assert on counter DELTAS so they stay order-independent."""
    model, pattern = tiny_lm
    eng = serving.GenerationEngine(
        model, serving.GenerationConfig(max_slots=2, max_seq_len=32,
                                        page_len=8,
                                        prefill_buckets=(8, 16, 24)))
    eng.start()
    yield eng, model, pattern
    eng.close()


def _counters(eng):
    snap = eng.metrics.snapshot()["counters"]
    return lambda name: snap.get(name, 0)


def test_prefix_hit_parity_with_cold_prefill(paged_engine):
    """A request sharing a cached prefix must produce the SAME tokens as
    the cold path — its prefix K/V pages ARE the cold request's pages, so
    the logits feeding every argmax are bit-identical by construction."""
    eng, model, pattern = paged_engine
    before = _counters(eng)
    prompt = pattern[:19].astype("int64")          # two full 8-blocks
    ref = np.asarray(model.generate(paddle.to_tensor(prompt[None]),
                                    max_new_tokens=6,
                                    use_cache=True).numpy())[0]
    cold = eng.submit(prompt, max_new_tokens=6).result(timeout=300)
    warm = eng.submit(prompt, max_new_tokens=6).result(timeout=300)
    assert cold.tolist() == ref.tolist()
    assert warm.tolist() == ref.tolist()
    after = _counters(eng)
    assert after("prefix_hits") - before("prefix_hits") >= 1
    assert after("prefix_hit_tokens") - before("prefix_hit_tokens") >= 16
    assert eng.prefix_match_tokens(prompt) == 16
    pool = eng.stats()["kv_pages"]
    assert pool["prefix"]["nodes"] >= 2
    assert pool["pages_free"] > 0


def test_pages_release_on_completion(paged_engine):
    """Finished requests return their private pages; only trie-adopted
    prefix pages stay live."""
    eng, _model, pattern = paged_engine
    eng.submit(pattern[:9].astype("int64"), max_new_tokens=3).result(
        timeout=300)
    t0 = time.monotonic()
    while eng.stats()["active_slots"] and time.monotonic() - t0 < 30:
        time.sleep(0.01)
    a = eng._pool.allocator
    trie_pages = len(eng._pool.trie)
    assert a.live_pages == trie_pages, (a.live_pages, trie_pages)


def test_deadline_edf_join_order_and_shedding(paged_engine):
    """Queued requests join freed slots earliest-deadline-first, and a
    request whose deadline expires while queued is shed before prefill."""
    from paddle_tpu.observability.trace import tracer

    eng, _model, pattern = paged_engine
    # occupy BOTH slots with long decodes so submissions below queue up
    # (must be in-slot, not queued: EDF would sort the doomed request
    # ahead of queued work and admit it before its deadline passes)
    busy = [eng.submit(pattern[:12].astype("int64"), max_new_tokens=20)
            for _ in range(2)]
    t0 = time.monotonic()
    while len(eng._active()) < 2 and time.monotonic() - t0 < 60:
        time.sleep(0.0005)
    assert len(eng._active()) == 2
    # distinct prompt lengths tag each request's trace
    no_dl = eng.submit(pattern[:10].astype("int64"), max_new_tokens=2)
    late = eng.submit(pattern[:11].astype("int64"), max_new_tokens=2,
                      deadline_ms=60_000)
    soon = eng.submit(pattern[:13].astype("int64"), max_new_tokens=2,
                      deadline_ms=30_000)
    doomed = eng.submit(pattern[:14].astype("int64"), max_new_tokens=2,
                        deadline_ms=0.5)
    with pytest.raises(serving.DeadlineExceeded):
        doomed.result(timeout=60)
    for f in busy + [no_dl, late, soon]:
        f.result(timeout=300)
    assert eng.metrics.counter("shed_total") >= 1
    # EDF: prefill order soon < late < no-deadline (from the trace spans)
    t_pf = {}
    for t in tracer().traces(engine=eng.name):
        pl = t["meta"].get("prompt_len")
        pf = next((s for s in t["spans"] if s["name"] == "prefill"), None)
        if pf is not None and t["ok"] and pl in (10, 11, 13):
            t_pf[pl] = pf["t0"]
    assert t_pf[13] < t_pf[11] < t_pf[10]


def test_paged_admission_pool_capacity_bounds(tiny_lm):
    """Under paged KV the admission bound is POOL capacity: a request that
    can never hold enough pages is a clean BadRequest; one that merely
    oversubscribes the pool queues and completes. The position table stays
    its own (max_seq_len) bound."""
    model, pattern = tiny_lm
    eng = serving.GenerationEngine(
        model, serving.GenerationConfig(max_slots=2, max_seq_len=32,
                                        page_len=8, num_pages=4,
                                        prefill_buckets=(8, 16)),
        name="tinypool")
    with eng:
        p = pattern[:9].astype("int64")
        with pytest.raises(serving.BadRequest, match="max_seq_len"):
            eng.submit(p, max_new_tokens=32).result(timeout=60)
        # needs ceil(25/8)=4 pages > the pool's 3 usable: impossible at
        # ANY load -> clean BadRequest
        with pytest.raises(serving.BadRequest, match="KV pages"):
            eng.submit(p, max_new_tokens=16).result(timeout=60)
        # two 2-page requests oversubscribe the 3-page pool: the second
        # WAITS for pages instead of failing
        a = eng.submit(p, max_new_tokens=7)
        b = eng.submit(p, max_new_tokens=7)
        for f in (a, b):
            out = f.result(timeout=300)
            assert out[9:].tolist() == [(9 + i) % 8
                                        for i in range(len(out) - 9)]
        alloc = eng._pool.allocator
        assert alloc.live_pages == len(eng._pool.trie)  # only trie-held
        alloc.check()


# -- speculative decoding -----------------------------------------------------

@pytest.fixture(scope="module")
def spec_engine(tiny_lm):
    """Target + 1-layer draft, both pattern-trained; spec_tokens=3."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    model, pattern = tiny_lm
    dcfg = GPTConfig(vocab_size=32, hidden_size=16, num_hidden_layers=1,
                     num_attention_heads=2, max_position_embeddings=64,
                     dtype="float32")
    paddle.seed(1)
    draft = GPTForCausalLM(dcfg)
    optimizer = opt.AdamW(learning_rate=3e-3, parameters=draft.parameters())
    step = jit.TrainStep(draft, lambda m, x, y: m(x, labels=y), optimizer)
    ids = paddle.to_tensor(np.tile(np.arange(8), 8)[None, :].astype("int64"))
    for _ in range(80):
        step(ids, ids)
    eng = serving.GenerationEngine(
        model, serving.GenerationConfig(max_slots=2, max_seq_len=32,
                                        page_len=8,
                                        prefill_buckets=(8, 16, 24),
                                        draft_model=draft, spec_tokens=3),
        name="specgen")
    eng.start()
    yield eng, model, pattern
    eng.close()


@pytest.mark.slow  # extra verify-window compile; ci.sh serving gate runs it
def test_speculative_greedy_parity_vs_generate(spec_engine):
    """Speculative greedy decode must be token-for-token equal to the
    model's own KV-cached greedy path — for EVERY request, whatever the
    draft proposed (acceptance only changes speed)."""
    eng, model, pattern = spec_engine
    before = _counters(eng)
    jobs = [(9, 8), (13, 6), (11, 10), (17, 8)]
    futs = [(p, m, eng.submit(pattern[:p].astype("int64"), max_new_tokens=m))
            for p, m in jobs]
    for p, m, f in futs:
        ref = np.asarray(model.generate(
            paddle.to_tensor(pattern[:p].astype("int64")[None]),
            max_new_tokens=m, use_cache=True).numpy())[0]
        got = f.result(timeout=300)
        assert got.tolist() == ref.tolist(), (p, m)
    after = _counters(eng)
    assert after("spec_rounds") > before("spec_rounds")
    assert after("spec_accepted") > before("spec_accepted")
    snap = eng.stats()
    assert snap["spec_acceptance"] > 0.3          # pattern-trained draft
    assert snap["effective_tokens_per_step"] > 1.2
    # speculation emitted MORE tokens than verify rounds: the whole point
    rounds = after("decode_steps") - before("decode_steps")
    tokens = after("tokens_total") - before("tokens_total")
    assert tokens > rounds


# -- zero retrace steady state ------------------------------------------------

@pytest.mark.slow  # shares the spec engine; ci.sh serving gate runs it
def test_paged_decode_zero_retrace_steady_state(tiny_lm):
    """PT_RETRACE_AUDIT machinery: after first-use compiles (the per-label
    baselines), mixed paged traffic — cold prefills, prefix hits, decode —
    must record ZERO serving-labeled retrace events."""
    model, pattern = tiny_lm
    os.environ["PT_RETRACE_AUDIT"] = "1"
    import paddle_tpu.analysis as A

    A.retrace.enable()
    try:
        eng = serving.GenerationEngine(
            model, serving.GenerationConfig(max_slots=2, max_seq_len=32,
                                            page_len=8,
                                            prefill_buckets=(8, 16, 24)),
            name="auditgen")
        with eng:
            futs = [eng.submit(pattern[o:o + 9 + (i % 3)].astype("int64"),
                               max_new_tokens=3 + (i % 4))
                    for i, o in enumerate([0, 0, 8, 0, 8, 1, 0, 2])]
            fwait(futs, timeout=300)
            stats = eng.stats()
        assert stats["retrace_events"] == 0, stats
    finally:
        A.retrace.disable()
        A.retrace.reset()
        os.environ.pop("PT_RETRACE_AUDIT", None)


# -- router -------------------------------------------------------------------

class _FakeReplica:
    """GenerationEngine-shaped stub: deterministic router-policy tests
    without device compiles."""

    def __init__(self, name, depth=0, headroom=1.0, match=0, closed=False,
                 full=False):
        from paddle_tpu.serving.metrics import MetricsRegistry

        self.name = name
        self.metrics = MetricsRegistry()
        self.depth, self.headroom, self.match = depth, headroom, match
        self.closed, self.full = closed, full
        self.submitted = []

    def start(self):
        return self

    def close(self, drain=True):
        self.closed = True

    def queue_depth(self):
        return self.depth

    def stats(self):
        return self.metrics.snapshot()

    def kv_headroom(self):
        return self.headroom

    def prefix_match_tokens(self, prompt):
        return self.match

    def submit(self, prompt, max_new_tokens=16, deadline_ms=None):
        if self.closed:
            raise serving.EngineClosed("down")
        if self.full:
            raise serving.QueueFull("full")
        fut = Future()
        self.submitted.append(np.asarray(prompt))
        return fut


def test_router_tenant_quota_and_fleet_backpressure():
    r1 = _FakeReplica("a")
    router = serving.ReplicaRouter(
        [r1], serving.RouterConfig(max_inflight=3, default_quota=2,
                                   tenant_quotas={"vip": 3}))
    p = np.arange(4)
    f1 = router.submit(p, tenant="free")
    router.submit(p, tenant="free")
    with pytest.raises(serving.TenantQuotaExceeded):
        router.submit(p, tenant="free")
    router.submit(p, tenant="vip")                 # own quota
    with pytest.raises(serving.QueueFull):         # fleet-wide bound
        router.submit(p, tenant="vip")
    f1.set_result(np.arange(5))                    # completion frees quota
    router.submit(p, tenant="free")
    st = router.stats()
    assert st["rejected"] == {"quota": 1, "capacity": 1}
    assert st["inflight"]["free"] == 2


def test_router_load_aware_and_prefix_affinity_dispatch():
    idle = _FakeReplica("idle", depth=0, headroom=1.0)
    busy = _FakeReplica("busy", depth=50, headroom=0.1)
    router = serving.ReplicaRouter([busy, idle])
    router.submit(np.arange(8))
    assert len(idle.submitted) == 1 and not busy.submitted
    # affinity overrides moderate load: the replica holding the prefix wins
    holder = _FakeReplica("holder", depth=2, match=8)
    cold = _FakeReplica("cold", depth=0)
    router2 = serving.ReplicaRouter([cold, holder])
    router2.submit(np.arange(8))
    assert len(holder.submitted) == 1 and not cold.submitted
    assert router2.stats()["affinity_hits"] == 1


def test_router_fault_marks_down_and_reroutes():
    dead = _FakeReplica("dead", closed=True)
    live = _FakeReplica("live")
    router = serving.ReplicaRouter([dead, live])
    router.submit(np.arange(4))
    assert len(live.submitted) == 1
    assert router.stats()["down"] == ["dead"]
    full = _FakeReplica("full2", full=True)
    router2 = serving.ReplicaRouter([full])
    with pytest.raises(serving.QueueFull):
        router2.submit(np.arange(4))
    router2._replicas[0].full = False
    router2.submit(np.arange(4))                   # recovers


@pytest.mark.slow  # two real replicas; ci.sh serving gate runs it
def test_router_end_to_end_fleet_with_replica_fault(tiny_lm):
    """Two real replicas behind the router: shared-prefix traffic routes
    with affinity, a replica fault mid-run fences it, and the surviving
    replica drains the rest — every surviving future resolves correctly."""
    model, pattern = tiny_lm

    def mk(name):
        return serving.GenerationEngine(
            model, serving.GenerationConfig(max_slots=2, max_seq_len=32,
                                            page_len=8,
                                            prefill_buckets=(8, 16, 24)),
            name=name)

    ra, rb = mk("fleet_a"), mk("fleet_b")
    router = serving.ReplicaRouter([ra, rb], name="fleet")
    prompt = pattern[:17].astype("int64")
    with router:
        # cold landing first: its replica becomes the prefix holder
        router.submit(prompt, max_new_tokens=4).result(timeout=300)
        futs = [router.submit(prompt, max_new_tokens=4) for _ in range(5)]
        outs = [f.result(timeout=300) for f in futs]
        for out in outs:
            assert out[17:].tolist() == [(17 + i) % 8
                                         for i in range(len(out) - 17)]
        st = router.stats()
        # same-prefix traffic concentrated on the replica holding the pages
        assert sum(r["routed"] for r in st["replicas"].values()) == 6
        assert st["affinity_hits"] >= 4
        assert max(r["routed"] for r in st["replicas"].values()) >= 5
        # replica fault: close A; traffic keeps draining through B
        ra.close(drain=False)
        futs2 = [router.submit(prompt, max_new_tokens=3) for _ in range(4)]
        for f in futs2:
            out = f.result(timeout=300)
            assert out[17:].tolist() == [(17 + i) % 8
                                         for i in range(len(out) - 17)]
        st = router.stats()
        assert "fleet_a" in st["down"]
        assert router.queue_depth() == 0           # drained, not stuck
        assert st["replicas"]["fleet_b"]["responses"] >= 4


# -- property-style invariants (ISSUE 18 satellite) ---------------------------

def test_pool_exhausted_carries_allocator_state():
    """The exception IS the diagnostic: need/free/live/usable and the
    lifetime alloc/free totals, so an OOM log line is actionable without
    a debugger attached."""
    a = PageAllocator(8)
    held = a.alloc(5)
    with pytest.raises(PoolExhausted) as ei:
        a.alloc(4)
    msg = str(ei.value)
    assert "need 4 pages" in msg and "2 free" in msg
    assert "(5 live) of 7 usable" in msg
    assert "pool=8 incl. scratch" in msg and "alloc_total=5" in msg
    for p in held:
        a.release(p)
    a.check()


def test_token_blocks_roundtrip_property():
    """For random prompts and page sizes: blocks tile the prompt's full
    pages exactly, in order, each of length page_len — concatenating
    them reconstructs the prompt's full-page prefix."""
    rng = np.random.RandomState(7)
    for _ in range(50):
        n = int(rng.randint(0, 65))
        pl = int(rng.randint(1, 17))
        prompt = rng.randint(0, 1000, size=n)
        blocks = token_blocks(prompt, pl)
        assert len(blocks) == n // pl
        assert all(len(b) == pl for b in blocks)
        flat = [t for b in blocks for t in b]
        assert flat == prompt[: (n // pl) * pl].tolist()
        lim = int(rng.randint(0, len(blocks) + 1))
        assert token_blocks(prompt, pl, limit=lim) == blocks[:lim]


def test_allocator_random_ops_invariants_property():
    """Seeded random walks over the FULL allocator surface — alloc,
    release, retain, cow — keep every invariant ``check()`` audits:
    free/live partition the pool, refcounts match holders, no page is
    handed out twice, exhaustion never leaks."""
    for seed in (0, 1, 2, 3):
        rng = np.random.RandomState(seed)
        a = PageAllocator(16)
        refs = {}                     # page -> refs WE hold
        for _ in range(300):
            op = rng.rand()
            held = [p for p, c in refs.items() if c > 0]
            if op < 0.35:
                n = int(rng.randint(1, 5))
                try:
                    for p in a.alloc(n):
                        assert refs.get(p, 0) == 0, "page reissued"
                        refs[p] = 1
                except PoolExhausted:
                    assert n > a.free_pages       # only a true OOM
            elif op < 0.65 and held:
                p = held[int(rng.randint(len(held)))]
                a.release(p)
                refs[p] -= 1
            elif op < 0.85 and held:
                p = held[int(rng.randint(len(held)))]
                a.retain(p)
                refs[p] += 1
            elif held:
                p = held[int(rng.randint(len(held)))]
                try:
                    new, copied = a.cow(p)
                except PoolExhausted:
                    continue
                assert copied == (refs[p] > 1)
                if copied:            # writer moved off the share
                    refs[p] -= 1
                    assert refs.get(new, 0) == 0
                    refs[new] = 1
            a.check()
            live = sum(1 for c in refs.values() if c > 0)
            assert a.live_pages == live
            assert a.free_pages == a.usable_pages - live
            for p, c in refs.items():
                assert a.ref(p) == c
        for p, c in refs.items():
            for _ in range(c):
                a.release(p)
        a.check()
        assert a.free_pages == a.usable_pages and a.live_pages == 0
