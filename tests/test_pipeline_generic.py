"""Generic compiled-PP: any LayerDesc model pipelines via the fleet API.

Reference contract: fleet/meta_parallel/pipeline_parallel.py:80,152 — 1F1B
runs for ANY PipelineLayer through PipelineParallel.train_batch, tied weights
(SharedLayerDesc) included. Here the compiled ppermute pipeline must deliver
that for a GPT built from LayerDescs, matching the pp=1 run exactly.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import fleet


def _gpt_pipe(seed=11):
    from paddle_tpu.models import GPTConfig, GPTForCausalLMPipe

    paddle.seed(seed)
    cfg = GPTConfig.tiny(num_hidden_layers=4, hidden_size=64,
                         num_attention_heads=4, vocab_size=128,
                         max_position_embeddings=64)
    return GPTForCausalLMPipe(cfg), cfg


def _run_gpt(pp, steps=3, seed=11):
    dist.reset_mesh()
    if pp > 1:
        dist.init_mesh(pp=pp, dp=8 // pp)
    model, cfg = _gpt_pipe(seed)
    rng = np.random.RandomState(3)
    ids = rng.randint(0, cfg.vocab_size, (8, 32)).astype("int64")
    losses = []
    if pp > 1:
        fleet.init(is_collective=True)
        wrapped = fleet.distributed_model(model)
        optimizer = fleet.distributed_optimizer(
            opt.AdamW(learning_rate=1e-3, parameters=model.parameters()))
        for _ in range(steps):
            loss = wrapped.train_batch(
                (paddle.to_tensor(ids), paddle.to_tensor(ids)), optimizer)
            losses.append(float(loss))
    else:  # eager sequential baseline
        optimizer = opt.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        for _ in range(steps):
            loss = model.compute_loss(paddle.to_tensor(ids),
                                      paddle.to_tensor(ids))
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            losses.append(float(loss))
    dist.reset_mesh()
    return losses


@pytest.mark.dist
def test_gpt_pipe_parity_pp2_vs_pp1():
    """GPT LayerDesc model: compiled pp2 pipeline == pp1 sequential."""
    base = _run_gpt(pp=1)
    piped = _run_gpt(pp=2)
    np.testing.assert_allclose(piped, base, rtol=2e-4)
    assert base[-1] < base[0], "training must reduce loss"


@pytest.mark.dist
def test_gpt_pipe_uses_compiled_pipeline():
    """The wrapper must actually engage the stacked ppermute run, and tied
    embeddings must remain one parameter."""
    from paddle_tpu.distributed.meta_parallel import PipelineParallel
    from paddle_tpu.distributed.meta_parallel.stage_stack import StackedStageRun

    dist.reset_mesh()
    dist.init_mesh(pp=2, dp=4)
    model, cfg = _gpt_pipe()
    fleet.init(is_collective=True)
    wrapped = fleet.distributed_model(model)
    assert isinstance(wrapped, PipelineParallel)
    stacks = [l for l in model._exec if isinstance(l, StackedStageRun)]
    assert len(stacks) == 1 and stacks[0].depth == cfg.num_hidden_layers
    # stacked params carry the pp spec on the stage dim
    for _, p in stacks[0].named_parameters():
        assert p.dist_spec is not None and p.dist_spec[0] == "pp"
    # embedding appears twice in descs but registers one weight
    names = [n for n, _ in model.named_parameters()
             if "embed_tokens" in n]
    assert len(names) == 1
    dist.reset_mesh()


@pytest.mark.dist
def test_heterogeneous_pipeline_warns_and_falls_back():
    dist.reset_mesh()
    dist.init_mesh(pp=2, dp=4)
    from paddle_tpu.distributed.meta_parallel import PipelineLayer

    with pytest.warns(UserWarning, match="no homogeneous layer run"):
        pipe = PipelineLayer(layers=[nn.Linear(8, 16), nn.Linear(16, 4),
                                     nn.Linear(4, 2)], num_stages=2)
    out = pipe(paddle.randn([4, 8]))
    assert out.shape == [4, 2]
    dist.reset_mesh()


def test_stacked_run_matches_sequential_no_mesh():
    """StackedStageRun without a pp mesh is a plain scan — must equal calling
    the layers one by one."""
    from paddle_tpu.distributed.meta_parallel.stage_stack import StackedStageRun

    dist.reset_mesh()
    paddle.seed(5)
    layers = [nn.Linear(16, 16) for _ in range(4)]
    ref_weights = [(l.weight.numpy().copy(), l.bias.numpy().copy())
                   for l in layers]
    x = paddle.randn([4, 16])
    expect = x
    for w, b in ref_weights:
        expect = expect.matmul(paddle.to_tensor(w)) + paddle.to_tensor(b)
    run = StackedStageRun(layers)
    got = run(x)
    np.testing.assert_allclose(got.numpy(), expect.numpy(), rtol=1e-5)
    # gradients flow into the stacked params
    got.sum().backward()
    for _, p in run.named_parameters():
        assert p.grad is not None


def test_bubble_fraction_formula():
    from paddle_tpu.distributed.meta_parallel.pipeline import bubble_fraction

    assert bubble_fraction(4, 2) == pytest.approx(1 / 5)
    assert bubble_fraction(2, 2) == pytest.approx(1 / 3)
    assert bubble_fraction(8, 1) == 0.0


@pytest.mark.dist
def test_microbatches_kept_when_batch_feasible():
    """batch >= M*d must keep the configured M with NO clamp warning; the
    dryrun pp2-dp4 config uses batch 16 for exactly this reason."""
    import warnings

    from paddle_tpu.distributed.meta_parallel.pipeline import (
        bubble_fraction, choose_microbatches)

    dist.reset_mesh()
    dist.init_mesh(pp=2, dp=4)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any clamp warning -> failure
            m = choose_microbatches(16, 4)
        assert m == 4
        assert bubble_fraction(m, 2) == pytest.approx(1 / 5)
        # infeasible batch still clamps, loudly, with the minimal batch named
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            m2 = choose_microbatches(8, 4)
        assert m2 == 2
        assert any("multiple of 16" in str(x.message) for x in w)
    finally:
        dist.reset_mesh()


def test_seg_method_pattern_balances_matching_layers():
    """VERDICT r3 weak #7: 'layer:Pattern' must balance only MATCHING layers
    so a heavy embedding rides along instead of skewing the split (reference
    pp_layers.py _segment_network:282)."""
    from paddle_tpu.distributed.meta_parallel.pp_layers import PipelineLayer

    class Emb:  # stand-in classes: only type names matter to the pattern
        pass

    class Block:
        pass

    class Head:
        pass

    layers = [Emb()] + [Block() for _ in range(8)] + [Head()]
    parts = PipelineLayer._segment(10, 2, "layer:Block", layers=layers)
    # stage 0: Emb + 4 Blocks (indices 0..4), stage 1: 4 Blocks + Head
    assert parts == [0, 5, 10]
    n_blocks = [sum(isinstance(layers[i], Block) for i in range(lo, hi))
                for lo, hi in zip(parts, parts[1:])]
    assert n_blocks == [4, 4]
    # uniform would have given [0,5,10] here too — use a skewed case: 3 front
    # non-matching layers must NOT count toward the balance
    layers2 = [Emb(), Emb(), Emb()] + [Block() for _ in range(4)]
    parts2 = PipelineLayer._segment(7, 2, "layer:Block", layers=layers2)
    n_blocks2 = [sum(isinstance(layers2[i], Block) for i in range(lo, hi))
                 for lo, hi in zip(parts2, parts2[1:])]
    assert n_blocks2 == [2, 2], (parts2, n_blocks2)

    # too few matches: loud fallback to uniform
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        parts3 = PipelineLayer._segment(4, 4, "layer:Nope",
                                        layers=[Block()] * 4)
    assert parts3 == [0, 1, 2, 3, 4]
    assert any("falling back" in str(x.message) for x in w)
