"""DiT diffusion transformer (BASELINE config 4 family)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import DiT, DiTConfig, GaussianDiffusion


def _np(t):
    return np.asarray(t.data)


def test_dit_zero_init_outputs_zero():
    """adaLN-Zero: the network is the zero map at init (final_proj zeroed)."""
    paddle.seed(0)
    model = DiT(DiTConfig.tiny())
    model.eval()
    out = model(paddle.randn([2, 3, 8, 8]),
                paddle.to_tensor(np.asarray([1, 50], "int32")),
                paddle.randint(0, 10, [2]))
    assert out.shape == [2, 3, 8, 8]
    np.testing.assert_allclose(_np(out), 0.0, atol=1e-6)


def test_dit_training_reduces_loss():
    paddle.seed(1)
    model = DiT(DiTConfig.tiny())
    diff = GaussianDiffusion(num_timesteps=100)
    opt = paddle.optimizer.AdamW(2e-3, parameters=model.parameters())
    rng = np.random.default_rng(0)
    x0 = paddle.to_tensor(rng.standard_normal((8, 3, 8, 8)).astype("float32"))
    y = paddle.to_tensor(rng.integers(0, 10, (8,)).astype("int64"))
    # fixed t/noise so the objective is deterministic and must fit
    t = paddle.to_tensor(np.full((8,), 50, "int32"))
    noise = paddle.to_tensor(rng.standard_normal((8, 3, 8, 8)).astype("float32"))
    losses = []
    for _ in range(30):
        loss = diff.training_loss(model, x0, y, t=t, noise=noise)
        losses.append(float(loss))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_dit_train_step_compiles():
    from paddle_tpu import jit

    paddle.seed(2)
    model = DiT(DiTConfig.tiny())
    diff = GaussianDiffusion(num_timesteps=50)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    step = jit.TrainStep(model, lambda m, x, y: diff.training_loss(m, x, y),
                         opt)
    x = paddle.randn([4, 3, 8, 8])
    y = paddle.randint(0, 10, [4])
    l1 = float(step(x, y))
    l2 = float(step(x, y))
    assert np.isfinite(l1) and np.isfinite(l2)


def test_ddim_sampler_shapes_and_determinism():
    paddle.seed(3)
    model = DiT(DiTConfig.tiny())
    model.eval()
    diff = GaussianDiffusion(num_timesteps=100)
    y = paddle.to_tensor(np.asarray([3, 7], "int64"))
    a = _np(diff.ddim_sample(model, (2, 3, 8, 8), y, steps=4, seed=5))
    b = _np(diff.ddim_sample(model, (2, 3, 8, 8), y, steps=4, seed=5))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 3, 8, 8)


def test_dit_tensor_parallel_matches_single():
    paddle.seed(4)
    x = paddle.randn([2, 3, 8, 8])
    t = paddle.to_tensor(np.asarray([10, 20], "int32"))
    y = paddle.to_tensor(np.asarray([1, 2], "int64"))

    paddle.seed(7)
    ref = DiT(DiTConfig.tiny())
    ref.eval()
    # perturb final_proj away from zero so outputs are informative
    ref.final_proj.weight.set_value(
        np.random.default_rng(0).standard_normal(
            tuple(ref.final_proj.weight.shape)).astype("float32") * 0.02)
    out_ref = _np(ref(x, t, y))

    env = dist.init_mesh(mp=4, dp=2)
    try:
        paddle.seed(7)
        par = DiT(DiTConfig.tiny())
        par.eval()
        par.final_proj.weight.set_value(
            np.random.default_rng(0).standard_normal(
                tuple(par.final_proj.weight.shape)).astype("float32") * 0.02)
        from paddle_tpu.distributed.parallel import place_model

        place_model(par)
        out_par = _np(par(x, t, y))
    finally:
        dist.reset_mesh()
    np.testing.assert_allclose(out_par, out_ref, rtol=1e-4, atol=1e-5)


def test_ddim_eta_nonzero_differs_and_learn_sigma_raises():
    paddle.seed(5)
    model = DiT(DiTConfig.tiny())
    model.eval()
    diff = GaussianDiffusion(num_timesteps=50)
    y = paddle.to_tensor(np.asarray([0, 1], "int64"))
    det = _np(diff.ddim_sample(model, (2, 3, 8, 8), y, steps=4, seed=9))
    stoch = _np(diff.ddim_sample(model, (2, 3, 8, 8), y, steps=4, seed=9,
                                 eta=1.0))
    assert not np.allclose(det, stoch)
    # same seed + same eta stays deterministic
    stoch2 = _np(diff.ddim_sample(model, (2, 3, 8, 8), y, steps=4, seed=9,
                                  eta=1.0))
    np.testing.assert_array_equal(stoch, stoch2)
    with pytest.raises(NotImplementedError):
        DiT(DiTConfig.tiny(learn_sigma=True))


def test_ddim_sample_in_training_mode_is_deterministic():
    paddle.seed(6)
    model = DiT(DiTConfig.tiny())
    model.train()  # sampler must force eval internally (CFG dropout off)
    diff = GaussianDiffusion(num_timesteps=50)
    y = paddle.to_tensor(np.asarray([2], "int64"))
    a = _np(diff.ddim_sample(model, (1, 3, 8, 8), y, steps=3, seed=1))
    b = _np(diff.ddim_sample(model, (1, 3, 8, 8), y, steps=3, seed=1))
    np.testing.assert_array_equal(a, b)
    assert model.training  # restored afterwards
