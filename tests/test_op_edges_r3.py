"""Round-3 op edge-case burndown (VERDICT #9): each formerly-raising path now
works, checked against numpy oracles."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestMathEdges:
    def test_diff_prepend_append(self):
        x = np.array([1.0, 3.0, 6.0, 10.0], "float32")
        pre = np.array([0.0], "float32")
        app = np.array([15.0, 21.0], "float32")
        got = paddle.diff(_t(x), prepend=_t(pre), append=_t(app))
        np.testing.assert_allclose(
            got.numpy(), np.diff(x, prepend=pre, append=app))

    def test_diag_padding_value(self):
        x = np.array([1.0, 2.0, 3.0], "float32")
        got = paddle.diag(_t(x), padding_value=9.0)
        ref = np.full((3, 3), 9.0, "float32")
        np.fill_diagonal(ref, x)
        np.testing.assert_allclose(got.numpy(), ref)
        # offset case
        got2 = paddle.diag(_t(x), offset=1, padding_value=-1.0)
        ref2 = np.full((4, 4), -1.0, "float32")
        for i in range(3):
            ref2[i, i + 1] = x[i]
        np.testing.assert_allclose(got2.numpy(), ref2)
        # 2-D extract ignores padding_value
        m = np.arange(9, dtype="float32").reshape(3, 3)
        np.testing.assert_allclose(
            paddle.diag(_t(m), padding_value=5.0).numpy(), np.diag(m))

    def test_bincount_weights(self):
        x = np.array([0, 1, 1, 3, 3, 3], "int64")
        w = np.array([0.5, 1.0, 2.0, 0.1, 0.2, 0.3], "float32")
        got = paddle.bincount(_t(x), weights=_t(w))
        np.testing.assert_allclose(got.numpy(), np.bincount(x, w),
                                   rtol=1e-6)
        got2 = paddle.bincount(_t(x), weights=_t(w), minlength=8)
        np.testing.assert_allclose(got2.numpy(),
                                   np.bincount(x, w, minlength=8), rtol=1e-6)

    @pytest.mark.parametrize("reduce", ["mul", "amin", "amax", "mean"])
    def test_put_along_axis_reduce_modes(self, reduce):
        x = np.arange(12, dtype="float32").reshape(3, 4) + 1.0
        idx = np.array([[0], [1], [2]], "int64")
        val = np.full((3, 1), 2.0, "float32")
        got = paddle.put_along_axis(_t(x), _t(idx), _t(val), axis=1,
                                    reduce=reduce).numpy()
        ref = x.copy()
        for r in range(3):
            c = idx[r, 0]
            if reduce == "mul":
                ref[r, c] *= 2.0
            elif reduce == "amin":
                ref[r, c] = min(ref[r, c], 2.0)
            elif reduce == "amax":
                ref[r, c] = max(ref[r, c], 2.0)
            else:  # mean, include_self
                ref[r, c] = (ref[r, c] + 2.0) / 2.0
        np.testing.assert_allclose(got, ref)


class TestNNEdges:
    def test_conv2d_transpose_nhwc(self):
        paddle.seed(0)
        x = np.random.RandomState(0).rand(2, 5, 5, 3).astype("float32")
        w = np.random.RandomState(1).rand(3, 4, 3, 3).astype("float32")
        nhwc = F.conv2d_transpose(_t(x), _t(w), stride=2, output_padding=1,
                                  data_format="NHWC")
        nchw = F.conv2d_transpose(_t(x.transpose(0, 3, 1, 2)), _t(w),
                                  stride=2, output_padding=1,
                                  data_format="NCHW")
        np.testing.assert_allclose(nhwc.numpy(),
                                   nchw.numpy().transpose(0, 2, 3, 1),
                                   rtol=1e-5)

    def test_interpolate_bicubic_and_area(self):
        x = np.random.RandomState(2).rand(1, 2, 8, 8).astype("float32")
        up = F.interpolate(_t(x), size=(16, 16), mode="bicubic")
        assert up.shape == [1, 2, 16, 16]
        area = F.interpolate(_t(x), size=(4, 4), mode="area")
        ref = x.reshape(1, 2, 4, 2, 4, 2).mean((3, 5))
        np.testing.assert_allclose(area.numpy(), ref, rtol=1e-5)

    def test_bce_with_logits_weight_pos_weight(self):
        logit = np.array([[0.5, -1.0], [2.0, 0.0]], "float32")
        label = np.array([[1.0, 0.0], [0.0, 1.0]], "float32")
        w = np.array([[1.0, 2.0], [0.5, 1.0]], "float32")
        pw = np.array([[3.0, 3.0], [3.0, 3.0]], "float32")

        def sig(v):
            return 1 / (1 + np.exp(-v))

        ref = -(pw * label * np.log(sig(logit))
                + (1 - label) * np.log(1 - sig(logit))) * w
        got = F.binary_cross_entropy_with_logits(
            _t(logit), _t(label), weight=_t(w), pos_weight=_t(pw),
            reduction="none")
        np.testing.assert_allclose(got.numpy(), ref, rtol=1e-5)
        got_m = F.binary_cross_entropy_with_logits(
            _t(logit), _t(label), weight=_t(w), reduction="mean")
        ref_m = (-(label * np.log(sig(logit))
                   + (1 - label) * np.log(1 - sig(logit))) * w).mean()
        np.testing.assert_allclose(float(got_m), ref_m, rtol=1e-5)

    def test_pixel_unshuffle_channel_shuffle_nhwc(self):
        x = np.random.RandomState(3).rand(2, 4, 4, 4).astype("float32")
        pu = F.pixel_unshuffle(_t(x), 2, data_format="NHWC")
        pu_ref = F.pixel_unshuffle(_t(x.transpose(0, 3, 1, 2)), 2)
        np.testing.assert_allclose(pu.numpy(),
                                   pu_ref.numpy().transpose(0, 2, 3, 1))
        cs = F.channel_shuffle(_t(x), 2, data_format="NHWC")
        cs_ref = F.channel_shuffle(_t(x.transpose(0, 3, 1, 2)), 2)
        np.testing.assert_allclose(cs.numpy(),
                                   cs_ref.numpy().transpose(0, 2, 3, 1))

    def test_adaptive_max_pool2d_return_mask(self):
        x = np.random.RandomState(4).rand(1, 1, 4, 6).astype("float32")
        out, mask = F.adaptive_max_pool2d(_t(x), (2, 3), return_mask=True)
        np.testing.assert_allclose(
            out.numpy(), x.reshape(1, 1, 2, 2, 3, 2).max((3, 5)), rtol=1e-6)
        flat = x[0, 0].ravel()
        for oh in range(2):
            for ow in range(3):
                np.testing.assert_allclose(
                    flat[int(mask.numpy()[0, 0, oh, ow])],
                    out.numpy()[0, 0, oh, ow])


class TestCaptureEdges:
    def test_to_static_with_kwargs(self):
        def f(x, y=None, scale=1.0):
            out = x * scale
            if y is not None:
                out = out + y
            return out

        st = paddle.jit.to_static(f)
        x = _t(np.ones(3, "float32"))
        y = _t(np.full(3, 2.0, "float32"))
        np.testing.assert_allclose(st(x, y=y, scale=3.0).numpy(),
                                   [5.0, 5.0, 5.0])
        np.testing.assert_allclose(st(x, scale=2.0).numpy(), [2.0, 2.0, 2.0])

    def test_recompute_with_kwargs(self):
        import paddle_tpu.distributed as dist

        def f(x, scale=1.0):
            return (x * scale).sum()

        x = paddle.to_tensor(np.ones(4, "float32"))
        x.stop_gradient = False
        out = dist.recompute(f, x, scale=3.0)
        out.backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0] * 4)
