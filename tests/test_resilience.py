"""Fault-tolerant training runtime (ISSUE-6): crash-consistent commit
protocol, async checkpointing, preemption-safe resume, deterministic fault
injection, retry policy, NaN-step skipping, and the checkpoint-story lint.

The cross-process halves of the acceptance — SIGTERM-killing a real
training subprocess and resuming on a DIFFERENT XLA device count — run in
tools/ci.sh's resilience gate; here the same machinery is exercised
in-process (request_preemption is the same flag the SIGTERM handler sets).
"""
import os
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from jax.sharding import PartitionSpec as P
from paddle_tpu.distributed import resilience as rz
from paddle_tpu.distributed.resilience import commit as cm
from paddle_tpu.distributed.resilience import metrics as rm
from paddle_tpu.distributed.resilience.faults import FaultInjector, _parse_env


def _np(t):
    return np.asarray(t.data)


def _params(net):
    return {k: np.asarray(_np(v)).copy() for k, v in net.state_dict().items()}


@pytest.fixture(autouse=True)
def _clean_faults():
    """No armed rule or preemption flag may leak across tests."""
    yield
    rz.injector().clear()
    rz.clear_preemption()
    rz.uninstall_preemption_handler()


# -- commit protocol ----------------------------------------------------------

@pytest.mark.slow
def test_async_save_commit_and_verify(tmp_path):
    paddle.seed(0)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    net(paddle.randn([2, 8])).sum().backward()
    opt.step()
    opt.clear_grad()
    with rz.AsyncCheckpointer(str(tmp_path), model=net, optimizer=opt,
                              keep=3) as ck:
        h = ck.save_async(step=0, epoch=0, sync=True)
        assert h.done() and h.error is None
        mani = cm.verify(h.path)  # re-hash every file against the manifest
    assert mani["format"] == 2
    assert mani["meta"]["step"] == 0
    assert set(mani["checksums"])  # HashingWriter checksums present
    assert cm.read_latest(str(tmp_path)) == "step_00000000"
    # no staging leftovers after a clean commit
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".staging")]


@pytest.mark.parametrize("phase", ["shards", "pre_manifest", "pre_rename",
                                   "pre_latest"])
def test_crash_mid_save_never_clobbers_latest(tmp_path, phase):
    """The headline atomicity guarantee: a save that dies at ANY phase of
    the protocol leaves LATEST on the previous complete checkpoint."""
    paddle.seed(1)
    net = nn.Linear(4, 4)
    ck = rz.AsyncCheckpointer(str(tmp_path), model=net, keep=3)
    ck.save_async(step=0, sync=True)
    before = cm.verify(os.path.join(str(tmp_path), "step_00000000"))
    with rz.inject("crash_mid_save", phase=phase):
        h = ck.save_async(step=1)
        with pytest.raises(rz.InjectedFault):
            h.wait()
        with pytest.raises(rz.InjectedFault):
            h.wait()  # sticky: EVERY later wait re-raises
    ck.close()
    assert cm.read_latest(str(tmp_path)) == "step_00000000"
    meta = rz.resume(str(tmp_path), model=net)
    assert meta["step"] == 0 and meta["tag"] == "step_00000000"
    # the survivor is byte-identical to its pre-crash self
    after = cm.verify(os.path.join(str(tmp_path), "step_00000000"))
    assert after["checksums"] == before["checksums"]


def test_failed_save_does_not_wedge_the_writer(tmp_path):
    """After a mid-save crash the SAME checkpointer commits the next save
    (its stale staging dir is recycled, the writer thread survives)."""
    net = nn.Linear(4, 4)
    ck = rz.AsyncCheckpointer(str(tmp_path), model=net, keep=3)
    with rz.inject("crash_mid_save", phase="pre_manifest"):
        with pytest.raises(rz.InjectedFault):
            ck.save_async(step=0, sync=True)
    h = ck.save_async(step=1, sync=True)
    assert h.error is None
    assert cm.read_latest(str(tmp_path)) == "step_00000001"
    ck.close()


@pytest.mark.slow
def test_torn_checkpoint_skipped_on_resume(tmp_path):
    """Checksum-failing newest checkpoint (bit rot / torn rename) is
    counted and skipped; resume lands on the previous complete one."""
    paddle.seed(2)
    net = nn.Linear(4, 4)
    ck = rz.AsyncCheckpointer(str(tmp_path), model=net, keep=3)
    ck.save_async(step=0, sync=True)
    w0 = _params(net)
    net.weight.data = net.weight.data + 1.0
    h = ck.save_async(step=1, sync=True)
    ck.close()
    # flip bytes in one shard of the newest checkpoint
    victim = next(f for f in sorted(os.listdir(h.path))
                  if f.endswith(".npy"))
    with open(os.path.join(h.path, victim), "r+b") as f:
        f.seek(90)
        f.write(b"\xff\xff\xff\xff")
    torn0 = rm.get("torn_checkpoints")
    with pytest.warns(UserWarning, match="skipping step_00000001"):
        meta = rz.resume(str(tmp_path), model=net)
    assert meta["tag"] == "step_00000000"
    assert rm.get("torn_checkpoints") == torn0 + 1
    np.testing.assert_array_equal(_np(net.weight), w0["weight"])


@pytest.mark.slow
def test_retention_keeps_last_k(tmp_path):
    net = nn.Linear(2, 2)
    ck = rz.AsyncCheckpointer(str(tmp_path), model=net, keep=2)
    for s in range(4):
        ck.save_async(step=s, sync=True)
    ck.close()
    assert cm.list_checkpoints(str(tmp_path)) == ["step_00000002",
                                                  "step_00000003"]
    assert cm.read_latest(str(tmp_path)) == "step_00000003"


def test_gc_staging_removes_foreign_leftovers(tmp_path):
    """A crashed OTHER process's staging dir is garbage on the next
    launch; the live process's own in-flight staging survives."""
    foreign = os.path.join(str(tmp_path), ".staging-step_00000009-99999")
    mine = os.path.join(str(tmp_path),
                        f".staging-step_00000008-{os.getpid()}")
    os.makedirs(foreign)
    os.makedirs(mine)
    assert cm.gc_staging(str(tmp_path)) == 1
    assert not os.path.isdir(foreign)
    assert os.path.isdir(mine)


# -- save/resume state round-trip ---------------------------------------------

def test_resume_restores_model_optimizer_rng(tmp_path):
    paddle.seed(3)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    for _ in range(3):
        net(paddle.randn([4, 8])).sum().backward()
        opt.step()
        opt.clear_grad()
    ck = rz.AsyncCheckpointer(str(tmp_path), model=net, optimizer=opt)
    ck.save_async(step=2, epoch=1, extra={"note": "hi"}, sync=True)
    ck.close()
    saved_w = _params(net)
    from paddle_tpu.framework import random as random_mod

    saved_rng = random_mod.get_rng_state()
    saved_acc = {k: np.asarray(v).copy()
                 for k, v in opt._accumulators[id(opt._parameter_list[0])]
                 .items()}

    paddle.seed(99)  # scramble everything the resume must restore
    net2 = nn.Linear(8, 4)
    opt2 = paddle.optimizer.AdamW(learning_rate=1e-3,
                                  parameters=net2.parameters())
    meta = rz.resume(str(tmp_path), model=net2, optimizer=opt2)
    assert meta["step"] == 2 and meta["epoch"] == 1
    assert meta["extra"]["note"] == "hi"
    for k, v in _params(net2).items():
        np.testing.assert_array_equal(v, saved_w[k])
    assert opt2._global_step == opt._global_step
    acc2 = opt2._accumulators[id(opt2._parameter_list[0])]
    for k, v in saved_acc.items():
        np.testing.assert_array_equal(np.asarray(acc2[k]), v)
    assert random_mod.get_rng_state() == saved_rng


def test_resume_onto_different_sharding(tmp_path):
    """The changed-device-count path: save with weights sharded sdp=8,
    resume into a replicated target — same manifest reassembly as a
    different device count (ci.sh proves the cross-process version)."""
    import jax

    paddle.seed(4)
    env1 = dist.init_mesh(sharding=8)
    net = nn.Linear(16, 8)
    net.weight.data = jax.device_put(net.weight.data,
                                     env1.sharding_for(P("sdp", None)))
    ck = rz.AsyncCheckpointer(str(tmp_path), model=net)
    ck.save_async(step=0, sync=True)
    ck.close()
    ref = _params(net)
    dist.reset_mesh()

    paddle.seed(5)
    net2 = nn.Linear(16, 8)  # replicated single-device layout
    meta = rz.resume(str(tmp_path), model=net2)
    assert meta is not None and meta["devices"] == 8
    for k, v in _params(net2).items():
        np.testing.assert_array_equal(v, ref[k])


def test_resume_empty_root_returns_none(tmp_path):
    assert rz.resume(str(tmp_path), model=nn.Linear(2, 2)) is None
    assert rz.latest_checkpoint(str(tmp_path)) is None


def test_backpressure_single_save_in_flight(tmp_path):
    net = nn.Linear(64, 64)
    ck = rz.AsyncCheckpointer(str(tmp_path), model=net, keep=4)
    h0 = ck.save_async(step=0)
    h1 = ck.save_async(step=1)  # must first wait out save 0
    ck.wait()
    assert h0.done() and h1.done() and h1.error is None
    ck.close()
    assert cm.list_checkpoints(str(tmp_path)) == ["step_00000000",
                                                  "step_00000001"]


# -- fault injector + retry ---------------------------------------------------

def test_injector_env_spec_matching_and_times():
    inj = FaultInjector()
    _parse_env("transfer@seq=3&times=2,slow_transfer@seq=1&ms=5,"
               "nan_step@step=7", inj)
    assert inj.check("transfer", seq=1) is None  # no match, no fire
    with pytest.raises(rz.InjectedFault):
        inj.check("transfer", seq=3)
    with pytest.raises(rz.InjectedFault):
        inj.check("transfer", seq=3)
    inj.check("transfer", seq=3)  # times=2 exhausted: no-op now
    assert inj.fired("transfer") == 2
    t0 = time.perf_counter()
    inj.check("slow_transfer", seq=1)  # sleeps, does not raise
    assert (time.perf_counter() - t0) >= 0.004
    assert not inj.peek("nan_step", step=6)
    assert inj.peek("nan_step", step=7)
    assert not inj.peek("nan_step", step=7)  # consumed


def test_injector_malformed_env_rule_skipped():
    inj = FaultInjector()
    with pytest.warns(UserWarning, match="malformed"):
        _parse_env("transfer@times=notanint,ok_kind@x=1", inj)
    with pytest.raises(rz.InjectedFault):
        inj.check("ok_kind", x=1)  # the well-formed rule still armed


def test_with_retries_bounded_backoff():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise rz.InjectedFault("transfer", {}, transient=True)
        return "ok"

    r0 = rm.get("retries")
    assert rz.with_retries(flaky, retries=2, backoff_ms=1) == "ok"
    assert calls["n"] == 3
    assert rm.get("retries") == r0 + 2
    # a non-transient error is never retried
    calls["n"] = 0

    def hard():
        calls["n"] += 1
        raise ValueError("programming error")

    with pytest.raises(ValueError):
        rz.with_retries(hard, retries=5, backoff_ms=1)
    assert calls["n"] == 1


def test_stream_lane_retries_transient_transfer(monkeypatch):
    import jax
    from paddle_tpu.jit.offload_stream import StreamLane

    monkeypatch.setenv("PT_TRANSFER_RETRIES", "2")
    monkeypatch.setenv("PT_TRANSFER_BACKOFF_MS", "1")
    lane = StreamLane(overlap=True)
    arrs = [np.ones((4, 4), np.float32)]
    dev = jax.devices()[0]
    with rz.inject("transfer", times=1):  # one failure, then clean
        h = lane.submit("h2d", arrs, dev, tag="g0", names=("w",))
        out = h.wait()
    assert np.asarray(out[0]).sum() == 16
    assert lane.stats()["retries"] >= 1
    lane.close()


def test_stream_lane_failure_named_and_sticky(monkeypatch):
    import jax
    from paddle_tpu.jit.offload_stream import StreamLane, StreamTransferError

    monkeypatch.setenv("PT_TRANSFER_RETRIES", "0")
    lane = StreamLane(overlap=True)
    dev = jax.devices()[0]
    with rz.inject("transfer", times=-1):
        h = lane.submit("h2d", [np.ones(3, np.float32)], dev,
                        tag="layer7", names=("w7", "b7"))
        with pytest.raises(StreamTransferError) as ei:
            h.wait()
        msg = str(ei.value)
        assert "layer7" in msg and "w7" in msg and "kind=h2d" in msg
        assert isinstance(ei.value.__cause__, rz.InjectedFault)
        with pytest.raises(StreamTransferError):
            h.wait()  # raises on EVERY subsequent call, not only the first
        with pytest.raises(StreamTransferError):
            lane.submit("h2d", [np.ones(3, np.float32)], dev)  # poisoned
    lane.close()


# -- NaN-step skip ------------------------------------------------------------

def _toy_fit_model(lr=0.01):
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=lr,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    return model


class _ToyDataset(paddle.io.Dataset):
    def __init__(self, n=32):
        rng = np.random.default_rng(0)
        self.x = rng.standard_normal((n, 8)).astype("float32")
        w = rng.standard_normal((8,)).astype("float32")
        self.y = (self.x @ w > 0).astype("int64")

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def test_nan_inf_skip_action_raises_nan_step_skipped():
    from paddle_tpu.core.tensor import NanStepSkipped, _check_nan_inf

    paddle.set_flags({"FLAGS_check_nan_inf_action": "skip"})
    try:
        bad = paddle.to_tensor(np.array([1.0, np.nan], np.float32))
        with pytest.raises(NanStepSkipped):
            _check_nan_inf("toy_op", [bad.data])
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf_action": "raise"})


def test_fit_skips_injected_nan_step_and_continues():
    """nan_step fault under action='skip': the poisoned step is dropped
    whole (no update), counted, and the epoch finishes."""
    paddle.seed(7)
    model = _toy_fit_model()
    ds = _ToyDataset(32)
    paddle.set_flags({"FLAGS_check_nan_inf_action": "skip"})
    skipped0 = rm.get("skipped_steps")
    try:
        with rz.inject("nan_step", step=1), warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            model.fit(ds, epochs=1, batch_size=8, shuffle=False, verbose=0)
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf_action": "raise"})
    assert rm.get("skipped_steps") == skipped0 + 1


# -- preemption-safe fit + resume --------------------------------------------

class _PreemptAt(paddle.callbacks.Callback):
    """Raise the preemption flag after global step N — in-process twin of
    the SIGTERM the ci.sh gate delivers to a real subprocess."""

    def __init__(self, at):
        self.at = at
        self.seen = 0

    def on_train_batch_end(self, step, logs=None):
        if self.seen == self.at:
            rz.request_preemption()
        self.seen += 1


@pytest.mark.slow
def test_fit_preempt_commit_resume_bit_equal(tmp_path):
    """The kill-and-resume acceptance, in-process: preempt mid-epoch,
    final sync commit, resume=True replays the remaining batches — final
    weights BIT-equal to the uninterrupted run, >=1 preemption committed,
    0 torn checkpoints."""
    ds = _ToyDataset(48)
    fit_kw = dict(epochs=1, batch_size=8, shuffle=False, verbose=0)

    paddle.seed(11)
    ref = _toy_fit_model()
    ref.fit(ds, **fit_kw)
    ref_w = _params(ref.network)

    root = str(tmp_path / "ck")
    pre0, torn0 = rm.get("preemptions"), rm.get("torn_checkpoints")
    paddle.seed(11)
    m2 = _toy_fit_model()
    m2.fit(ds, callbacks=[_PreemptAt(2)], checkpoint_every=2,
           checkpoint_dir=root, **fit_kw)
    assert rm.get("preemptions") == pre0 + 1
    meta = cm.load_manifest(os.path.join(root, cm.read_latest(root)))["meta"]
    assert meta["reason"] == "preempt" and meta["step"] == 2
    interrupted_w = _params(m2.network)

    # fresh model+optimizer (a relaunch), resume from the committed step
    rz.clear_preemption()
    paddle.seed(99)
    m3 = _toy_fit_model()
    m3.fit(ds, resume=True, checkpoint_every=2, checkpoint_dir=root,
           **fit_kw)
    final_w = _params(m3.network)
    assert any(not np.array_equal(interrupted_w[k], ref_w[k])
               for k in ref_w), "preemption did not actually cut the run"
    for k in ref_w:
        np.testing.assert_array_equal(final_w[k], ref_w[k])
    assert rm.get("torn_checkpoints") == torn0


@pytest.mark.slow
def test_fit_preempt_resume_bit_equal_shuffled(tmp_path):
    """Resume with shuffle=True: the resumed epoch redraws the ORIGINAL
    epoch's permutation (saves carry the epoch-begin rng state), so the
    stitched run is still bit-equal — not a run over duplicate/missed
    batches from a fresh permutation."""
    ds = _ToyDataset(48)
    fit_kw = dict(epochs=1, batch_size=8, shuffle=True, verbose=0)

    paddle.seed(21)
    ref = _toy_fit_model()
    ref.fit(ds, **fit_kw)
    ref_w = _params(ref.network)

    root = str(tmp_path / "ck")
    paddle.seed(21)
    m2 = _toy_fit_model()
    m2.fit(ds, callbacks=[_PreemptAt(2)], checkpoint_every=2,
           checkpoint_dir=root, **fit_kw)

    rz.clear_preemption()
    paddle.seed(99)  # a relaunch: different init rng, state comes from disk
    m3 = _toy_fit_model()
    m3.fit(ds, resume=True, checkpoint_every=2, checkpoint_dir=root,
           **fit_kw)
    final_w = _params(m3.network)
    for k in ref_w:
        np.testing.assert_array_equal(final_w[k], ref_w[k])


def test_preemption_flag_consumed_by_fit(tmp_path):
    """fit consumes the preemption it commits: a LATER fit in the same
    process runs to completion instead of stopping after its first step."""
    ds = _ToyDataset(32)
    paddle.seed(3)
    m = _toy_fit_model()
    m.fit(ds, callbacks=[_PreemptAt(1)], checkpoint_every=2,
          checkpoint_dir=str(tmp_path / "a"), epochs=1, batch_size=8,
          shuffle=False, verbose=0)
    assert not rz.preempted()  # consumed by the preempt commit
    root2 = str(tmp_path / "b")
    m2 = _toy_fit_model()
    m2.fit(ds, checkpoint_every=2, checkpoint_dir=root2, epochs=1,
           batch_size=8, shuffle=False, verbose=0)
    meta = cm.load_manifest(os.path.join(root2, cm.read_latest(root2)))["meta"]
    assert meta.get("reason") != "preempt"
    assert meta["step"] == 3  # all 4 steps ran; last periodic save at gs=3


def test_nan_skip_drops_whole_accumulation_window():
    """A NaN-skip mid-accumulation-window drops the WINDOW: no optimizer
    update is built from the partial, mis-scaled remainder. Bit-equal to
    training on the unpoisoned window only."""
    ds = _ToyDataset(32)  # 4 steps of 8 -> two accumulate(2) windows

    class _Tail(paddle.io.Dataset):  # window 2's batches only
        def __getitem__(self, i):
            return ds[16 + i]

        def __len__(self):
            return 16

    tail = _Tail()
    paddle.set_flags({"FLAGS_check_nan_inf_action": "skip"})
    try:
        paddle.seed(5)
        poisoned = _toy_fit_model()
        with rz.inject("nan_step", step=0), warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            poisoned.fit(ds, epochs=1, batch_size=8, shuffle=False,
                         verbose=0, accumulate_grad_batches=2)
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf_action": "raise"})
    paddle.seed(5)
    ref = _toy_fit_model()
    ref.fit(tail, epochs=1, batch_size=8, shuffle=False, verbose=0,
            accumulate_grad_batches=2)
    pw, rw = _params(poisoned.network), _params(ref.network)
    for k in rw:
        np.testing.assert_array_equal(pw[k], rw[k])


def test_preemption_handler_install_flag_clear():
    import signal

    assert rz.install_preemption_handler()
    assert not rz.preempted()
    os.kill(os.getpid(), signal.SIGTERM)  # handled: sets the flag only
    t0 = time.monotonic()
    while not rz.preempted() and time.monotonic() - t0 < 5:
        time.sleep(0.01)
    assert rz.preempted()
    rz.clear_preemption()
    assert not rz.preempted()
    rz.uninstall_preemption_handler()


# -- plain distributed.checkpoint satellite -----------------------------------

def test_save_state_dict_atomic_with_checksums(tmp_path):
    from paddle_tpu.distributed.checkpoint import CheckpointCorrupt

    paddle.seed(6)
    path = os.path.join(str(tmp_path), "ck")
    net = nn.Linear(4, 4)
    dist.save_state_dict(net.state_dict(), path)
    import json

    mani = json.load(open(os.path.join(path, "manifest.r0.json")))
    assert mani["format"] == 2
    for entry in mani["entries"].values():
        assert all(sh.get("sha256") for sh in entry["shards"])
    # no tmp leftovers: every file landed via os.replace
    assert not [f for f in os.listdir(path) if ".tmp-" in f]
    # torn shard detected at load...
    victim = next(f for f in sorted(os.listdir(path)) if f.endswith(".npy"))
    with open(os.path.join(path, victim), "r+b") as f:
        f.seek(-4, os.SEEK_END)  # flip DATA bytes (header must stay valid)
        f.write(b"\x5a\x5a\x5a\x5a")
    net2 = nn.Linear(4, 4)
    with pytest.raises(CheckpointCorrupt):
        dist.load_state_dict(net2.state_dict(), path)
    # ...and verify=False remains the escape hatch
    dist.load_state_dict(net2.state_dict(), path, verify=False)


# -- lint + observability -----------------------------------------------------

def test_checkpoint_story_lint(tmp_path):
    from paddle_tpu import analysis

    class _OffloadStep:
        offload = True

    class _ResidentStep:
        offload = False

    (d,) = analysis.checkpoint_story_check(_OffloadStep())
    assert d.code == "RS002" and d.severity == "warning"
    (d,) = analysis.checkpoint_story_check(_ResidentStep())
    assert d.code == "RS003" and d.severity == "info"
    step = _OffloadStep()
    rz.AsyncCheckpointer(str(tmp_path)).attach(step)
    (d,) = analysis.checkpoint_story_check(step)
    assert d.code == "RS001" and d.severity == "info"


def test_resilience_family_in_observability_snapshot(tmp_path):
    from paddle_tpu import observability as obs

    net = nn.Linear(2, 2)
    with rz.AsyncCheckpointer(str(tmp_path), model=net) as ck:
        ck.save_async(step=0, sync=True)
    snap = obs.snapshot()
    vals = snap["resilience"]["values"]
    assert vals["saves"] >= 1
    assert vals["hidden_save_ms"] + vals["save_stall_ms"] > 0
    assert rm.get("saves") >= 1
