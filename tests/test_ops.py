"""Op corpus vs numpy oracle (OpTest-style, reference op_test.py:284)."""
import numpy as np
import pytest

import paddle_tpu as paddle

rng = np.random.RandomState(7)


def t(a, sg=True):
    return paddle.to_tensor(a, stop_gradient=sg)


def test_binary_math():
    a = rng.rand(3, 4).astype("float32") + 0.5
    b = rng.rand(3, 4).astype("float32") + 0.5
    np.testing.assert_allclose(paddle.add(t(a), t(b)).numpy(), a + b, rtol=1e-6)
    np.testing.assert_allclose(paddle.subtract(t(a), t(b)).numpy(), a - b, rtol=1e-6)
    np.testing.assert_allclose(paddle.multiply(t(a), t(b)).numpy(), a * b, rtol=1e-6)
    np.testing.assert_allclose(paddle.divide(t(a), t(b)).numpy(), a / b, rtol=1e-6)
    np.testing.assert_allclose(paddle.maximum(t(a), t(b)).numpy(), np.maximum(a, b))
    np.testing.assert_allclose(paddle.pow(t(a), 2.0).numpy(), a**2, rtol=1e-5)
    np.testing.assert_allclose(paddle.atan2(t(a), t(b)).numpy(), np.arctan2(a, b), rtol=1e-5)


def test_scalar_promotion_keeps_dtype():
    x = t(np.ones((2, 2), "float32"))
    assert (x + 1).dtype == paddle.float32
    assert (x * 2.5).dtype == paddle.float32
    xb = x.cast("bfloat16")
    assert (xb + 1.5).dtype == paddle.bfloat16
    xi = t(np.ones((2,), "int32"))
    assert (xi + 1).dtype == paddle.int32


def test_unary_math():
    a = rng.rand(4, 3).astype("float32") + 0.1
    np.testing.assert_allclose(paddle.sqrt(t(a)).numpy(), np.sqrt(a), rtol=1e-6)
    np.testing.assert_allclose(paddle.rsqrt(t(a)).numpy(), 1 / np.sqrt(a), rtol=1e-5)
    np.testing.assert_allclose(paddle.log(t(a)).numpy(), np.log(a), rtol=1e-5)
    np.testing.assert_allclose(paddle.floor(t(a * 10)).numpy(), np.floor(a * 10))
    np.testing.assert_allclose(paddle.erf(t(a)).numpy(), np.vectorize(_erf)(a), rtol=1e-5)
    np.testing.assert_allclose(paddle.square(t(a)).numpy(), a * a, rtol=1e-6)


def _erf(x):
    import math

    return math.erf(x)


def test_reductions():
    a = rng.rand(3, 4, 5).astype("float32")
    np.testing.assert_allclose(paddle.sum(t(a)).numpy(), a.sum(), rtol=1e-5)
    np.testing.assert_allclose(paddle.sum(t(a), axis=1).numpy(), a.sum(1), rtol=1e-5)
    np.testing.assert_allclose(
        paddle.mean(t(a), axis=[0, 2], keepdim=True).numpy(),
        a.mean((0, 2), keepdims=True),
        rtol=1e-5,
    )
    np.testing.assert_allclose(paddle.max(t(a), axis=2).numpy(), a.max(2))
    np.testing.assert_allclose(paddle.prod(t(a), axis=0).numpy(), a.prod(0), rtol=1e-5)
    np.testing.assert_allclose(paddle.std(t(a)).numpy(), a.std(ddof=1), rtol=1e-5)
    np.testing.assert_allclose(paddle.var(t(a)).numpy(), a.var(ddof=1), rtol=1e-5)
    np.testing.assert_allclose(paddle.logsumexp(t(a), axis=1).numpy(),
                               np.log(np.exp(a).sum(1)), rtol=1e-5)
    assert paddle.argmax(t(a)).item() == a.argmax()
    np.testing.assert_array_equal(paddle.argmax(t(a), axis=1).numpy(), a.argmax(1))


def test_manipulation():
    a = np.arange(24).reshape(2, 3, 4).astype("float32")
    np.testing.assert_array_equal(paddle.reshape(t(a), [4, 6]).numpy(), a.reshape(4, 6))
    np.testing.assert_array_equal(
        paddle.transpose(t(a), [2, 0, 1]).numpy(), a.transpose(2, 0, 1)
    )
    np.testing.assert_array_equal(paddle.flatten(t(a), 1).numpy(), a.reshape(2, 12))
    np.testing.assert_array_equal(
        paddle.squeeze(t(a.reshape(2, 1, 3, 4)), axis=1).numpy(), a.reshape(2, 3, 4)
    )
    np.testing.assert_array_equal(paddle.unsqueeze(t(a), 0).numpy(), a[None])
    np.testing.assert_array_equal(
        paddle.concat([t(a), t(a)], axis=1).numpy(), np.concatenate([a, a], 1)
    )
    np.testing.assert_array_equal(
        paddle.stack([t(a), t(a)], axis=0).numpy(), np.stack([a, a])
    )
    parts = paddle.split(t(a), 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts2 = paddle.split(t(a), [1, -1], axis=1)
    assert parts2[1].shape == [2, 2, 4]
    np.testing.assert_array_equal(paddle.tile(t(a[0]), [2, 1]).numpy(), np.tile(a[0], (2, 1)))
    np.testing.assert_array_equal(
        paddle.expand(t(np.ones((1, 4), "float32")), [3, 4]).numpy(), np.ones((3, 4))
    )
    np.testing.assert_array_equal(paddle.flip(t(a), [0]).numpy(), a[::-1])
    np.testing.assert_array_equal(paddle.roll(t(a), 1, 0).numpy(), np.roll(a, 1, 0))


def test_gather_scatter():
    a = rng.rand(5, 3).astype("float32")
    idx = np.array([0, 3], "int32")
    np.testing.assert_array_equal(paddle.gather(t(a), t(idx)).numpy(), a[idx])
    nd_idx = np.array([[0, 1], [2, 2]], "int32")
    np.testing.assert_array_equal(
        paddle.gather_nd(t(a), t(nd_idx)).numpy(), a[[0, 2], [1, 2]]
    )
    base = np.zeros((5, 3), "float32")
    upd = np.ones((2, 3), "float32")
    out = paddle.scatter(t(base), t(idx), t(upd))
    expect = base.copy()
    expect[idx] = 1
    np.testing.assert_array_equal(out.numpy(), expect)


def test_where_sort_topk():
    a = rng.rand(4, 5).astype("float32")
    cond = a > 0.5
    np.testing.assert_array_equal(
        paddle.where(t(cond), t(a), t(-a)).numpy(), np.where(cond, a, -a)
    )
    np.testing.assert_array_equal(paddle.sort(t(a), axis=1).numpy(), np.sort(a, 1))
    np.testing.assert_array_equal(paddle.argsort(t(a), axis=1).numpy(), np.argsort(a, 1))
    v, i = paddle.topk(t(a), 2, axis=1)
    np.testing.assert_allclose(v.numpy(), np.sort(a, 1)[:, ::-1][:, :2])


def test_linalg():
    a = rng.rand(3, 4).astype("float32")
    b = rng.rand(4, 5).astype("float32")
    np.testing.assert_allclose(paddle.matmul(t(a), t(b)).numpy(), a @ b, rtol=1e-5)
    np.testing.assert_allclose(
        paddle.matmul(t(a), t(b.T), transpose_y=True).numpy(), a @ b, rtol=1e-5
    )
    batch = rng.rand(2, 3, 4).astype("float32")
    batch2 = rng.rand(2, 4, 5).astype("float32")
    np.testing.assert_allclose(paddle.bmm(t(batch), t(batch2)).numpy(), batch @ batch2, rtol=1e-5)
    np.testing.assert_allclose(paddle.t(t(a)).numpy(), a.T)
    np.testing.assert_allclose(paddle.norm(t(a)).numpy(), np.linalg.norm(a), rtol=1e-5)
    np.testing.assert_allclose(
        paddle.einsum("ij,jk->ik", t(a), t(b)).numpy(), a @ b, rtol=1e-5
    )
    sym = a @ a.T + 3 * np.eye(3, dtype="float32")
    np.testing.assert_allclose(
        paddle.cholesky(t(sym)).numpy(), np.linalg.cholesky(sym), rtol=1e-4
    )
    np.testing.assert_allclose(
        paddle.inverse(t(sym)).numpy(), np.linalg.inv(sym), rtol=1e-3, atol=1e-5
    )


def test_comparison_and_logic():
    a = np.array([1.0, 2.0, 3.0], "float32")
    b = np.array([2.0, 2.0, 2.0], "float32")
    np.testing.assert_array_equal(paddle.equal(t(a), t(b)).numpy(), a == b)
    np.testing.assert_array_equal(paddle.greater_than(t(a), t(b)).numpy(), a > b)
    assert paddle.allclose(t(a), t(a)).item()
    assert not paddle.equal_all(t(a), t(b)).item()
    x = np.array([True, False])
    y = np.array([True, True])
    np.testing.assert_array_equal(paddle.logical_and(t(x), t(y)).numpy(), x & y)
    np.testing.assert_array_equal(paddle.logical_not(t(x)).numpy(), ~x)


def test_cumsum_clip_lerp():
    a = rng.rand(3, 4).astype("float32")
    np.testing.assert_allclose(paddle.cumsum(t(a), axis=1).numpy(), np.cumsum(a, 1), rtol=1e-5)
    np.testing.assert_allclose(
        paddle.clip(t(a), 0.2, 0.8).numpy(), np.clip(a, 0.2, 0.8)
    )
    np.testing.assert_allclose(
        paddle.lerp(t(a), t(a * 2), 0.5).numpy(), a * 1.5, rtol=1e-6
    )
    np.testing.assert_allclose(paddle.add_n([t(a), t(a), t(a)]).numpy(), 3 * a, rtol=1e-6)


def test_one_hot_pad():
    labels = np.array([0, 2, 1], "int32")
    oh = paddle.one_hot(t(labels), 3)
    np.testing.assert_array_equal(oh.numpy(), np.eye(3, dtype="float32")[labels])
    a = np.ones((1, 1, 2, 2), "float32")
    padded = paddle.pad(t(a), [1, 1, 1, 1])
    assert padded.shape == [1, 1, 4, 4]


def test_host_dynamic_ops():
    a = np.array([[0.0, 1.0], [2.0, 0.0]], "float32")
    nz = paddle.nonzero(t(a))
    np.testing.assert_array_equal(nz.numpy(), np.stack(np.nonzero(a), 1))
    m = paddle.masked_select(t(a), t(a > 0))
    np.testing.assert_array_equal(m.numpy(), a[a > 0])
    u = paddle.unique(t(np.array([3, 1, 3, 2], "int32")))
    np.testing.assert_array_equal(u.numpy(), [1, 2, 3])
