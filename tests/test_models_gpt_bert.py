"""GPT + BERT model-family tests, incl. TP-sharded parity on the 8-CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import (BertConfig, BertForPretraining,
                               BertForSequenceClassification, GPTConfig,
                               GPTForCausalLM)


def _np(t):
    return np.asarray(t.data)


def _ids(shape, vocab=256, seed=0):
    return paddle.to_tensor(
        np.random.default_rng(seed).integers(0, vocab, shape).astype("int64"))


def test_gpt_loss_and_grads():
    paddle.seed(0)
    gpt = GPTForCausalLM(GPTConfig.tiny())
    ids = _ids((2, 16))
    loss = gpt(ids, labels=ids)
    assert np.isfinite(float(loss))
    loss.backward()
    assert all(p.grad is not None for p in gpt.parameters())


def test_gpt_train_step_converges():
    import paddle_tpu.optimizer as opt
    from paddle_tpu import jit

    paddle.seed(1)
    gpt = GPTForCausalLM(GPTConfig.tiny())
    o = opt.AdamW(learning_rate=1e-3, parameters=gpt.parameters())
    step = jit.TrainStep(gpt, lambda m, x: m(x, labels=x), o)
    ids = _ids((4, 32), seed=3)
    losses = [float(step(ids)) for _ in range(12)]
    assert losses[-1] < losses[0], losses


def test_gpt_generate_extends_sequence():
    paddle.seed(2)
    gpt = GPTForCausalLM(GPTConfig.tiny())
    gpt.eval()
    ids = _ids((1, 5))
    out = gpt.generate(ids, max_new_tokens=4)
    assert out.shape == [1, 9]
    np.testing.assert_array_equal(_np(out)[:, :5], _np(ids))


def test_bert_pretraining_and_classification():
    paddle.seed(3)
    cfg = BertConfig.tiny()
    bert = BertForPretraining(cfg)
    ids = _ids((2, 16))
    mlm = _ids((2, 16), seed=5)
    nsp = paddle.to_tensor(np.asarray([0, 1], "int64"))
    loss = bert(ids, masked_lm_labels=mlm, next_sentence_labels=nsp)
    assert np.isfinite(float(loss))
    loss.backward()

    clf = BertForSequenceClassification(cfg, num_classes=3)
    logits = clf(ids)
    assert logits.shape == [2, 3]


def test_bert_attention_mask_changes_output():
    paddle.seed(4)
    cfg = BertConfig.tiny()
    bert = BertForPretraining(cfg)
    bert.eval()
    ids = _ids((1, 8))
    full = paddle.to_tensor(np.ones((1, 8), "int64"))
    half = paddle.to_tensor(np.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], "int64"))
    out_full, _ = bert(ids, attention_mask=full)
    out_half, _ = bert(ids, attention_mask=half)
    assert not np.allclose(_np(out_full), _np(out_half))


def test_gpt_tensor_parallel_matches_single():
    """mp=4 sharded loss equals the unsharded loss (GSPMD parity)."""
    paddle.seed(5)
    ids = _ids((2, 16), seed=7)
    ref = GPTForCausalLM(GPTConfig.tiny())
    loss_ref = float(ref(ids, labels=ids))

    env = dist.init_mesh(dp=2, mp=4)
    try:
        paddle.seed(5)
        par = GPTForCausalLM(GPTConfig.tiny())
        from paddle_tpu.distributed.parallel import place_model

        place_model(par)
        loss_par = float(par(ids, labels=ids))
    finally:
        dist.reset_mesh()
    np.testing.assert_allclose(loss_par, loss_ref, rtol=2e-4)


def test_bert_tensor_parallel_matches_single():
    paddle.seed(6)
    ids = _ids((2, 16), seed=9)
    mlm = _ids((2, 16), seed=11)
    ref = BertForPretraining(BertConfig.tiny())
    ref.eval()
    loss_ref = float(ref(ids, masked_lm_labels=mlm))

    env = dist.init_mesh(mp=4, dp=2)
    try:
        paddle.seed(6)
        par = BertForPretraining(BertConfig.tiny())
        par.eval()
        from paddle_tpu.distributed.parallel import place_model

        place_model(par)
        loss_par = float(par(ids, masked_lm_labels=mlm))
    finally:
        dist.reset_mesh()
    np.testing.assert_allclose(loss_par, loss_ref, rtol=2e-4)


# -- parameter-server mode ----------------------------------------------------

def test_parameter_server_pull_push_sgd():
    from paddle_tpu.distributed.ps import ParameterServer, PsTrainer

    store = dist.TCPStore(is_master=True, world_size=1)
    try:
        ps = ParameterServer(store).create_table("emb", (100, 8), lr=0.5).run()
        trainer = PsTrainer(store)
        ids = np.asarray([3, 7, 3], "int64")
        rows = trainer.pull("emb", np.unique(ids))
        assert rows.shape == (2, 8)
        grads = np.ones((2, 8), "float32")
        trainer.push("emb", np.unique(ids), grads, wait=True)
        rows2 = trainer.pull("emb", np.unique(ids))
        np.testing.assert_allclose(rows2, rows - 0.5 * grads, rtol=1e-6)
        ps.stop()
    finally:
        store.close()


def test_sparse_embedding_learns():
    from paddle_tpu.distributed.ps import (ParameterServer, PsTrainer,
                                           SparseEmbedding)
    import paddle_tpu.nn.functional as F

    store = dist.TCPStore(is_master=True, world_size=1)
    try:
        ps = ParameterServer(store).create_table("tbl", (50, 4), lr=0.3).run()
        emb = SparseEmbedding(PsTrainer(store), "tbl", 4)
        ids = paddle.to_tensor(np.asarray([[1, 2], [2, 3]], "int64"))
        target = paddle.ones([2, 2, 4])
        losses = []
        for _ in range(25):
            out = emb(ids)
            loss = F.mse_loss(out, target)
            losses.append(float(loss))
            loss.backward()
            emb.push_grad(out.grad, wait=True)
        assert losses[-1] < losses[0] * 0.1, losses[::6]
        ps.stop()
    finally:
        store.close()


def test_gpt_cached_generate_matches_uncached():
    paddle.seed(7)
    gpt = GPTForCausalLM(GPTConfig.tiny())
    gpt.eval()
    ids = _ids((2, 6), seed=13)
    fast = gpt.generate(ids, max_new_tokens=5, use_cache=True)
    slow = gpt.generate(ids, max_new_tokens=5, use_cache=False)
    np.testing.assert_array_equal(_np(fast), _np(slow))


def test_gpt_param_count_exact():
    from paddle_tpu.models import gpt_param_count

    gpt = GPTForCausalLM(GPTConfig.tiny())
    actual = sum(int(np.prod(p.shape)) for p in gpt.parameters())
    assert gpt_param_count(gpt.config) == actual


# -- launcher process management ----------------------------------------------

def test_process_context_gang_success(tmp_path):
    import sys
    from paddle_tpu.distributed.launch.process import ProcessContext

    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "rank = os.environ['PADDLE_TRAINER_ID']\n"
        "print(f'hello from rank {rank} of', os.environ['PADDLE_TRAINERS_NUM'])\n")
    ctx = ProcessContext.start([sys.executable, str(script)], nprocs=3,
                               log_dir=str(tmp_path / "logs"))
    assert ctx.wait(timeout=60) == 0
    logs = ctx.logs()
    assert len(logs) == 3
    for r in range(3):
        assert f"hello from rank {r} of 3" in logs[r]


def test_process_context_kills_gang_on_failure(tmp_path):
    import sys
    from paddle_tpu.distributed.launch.process import ProcessContext

    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "if os.environ['PADDLE_TRAINER_ID'] == '1':\n"
        "    sys.exit(7)\n"
        "time.sleep(60)\n")
    ctx = ProcessContext.start([sys.executable, str(script)], nprocs=3,
                               log_dir=str(tmp_path / "logs"))
    t0 = __import__('time').time()
    rc = ctx.wait(timeout=60)
    assert rc == 7
    assert __import__('time').time() - t0 < 30  # gang killed, not waited out
    assert all(e.proc.poll() is not None for e in ctx.entries)


def test_fused_ce_counts_every_token():
    """Odd token counts must not silently drop the tail from the loss."""
    import jax.numpy as jnp
    from paddle_tpu.models.llama import _fused_linear_ce

    rng = np.random.default_rng(0)
    # n=9 with chunk=4 -> n_chunks=2, c=5, pad=1: exercises the padding path
    h = rng.standard_normal((9, 8)).astype("float32")
    w = rng.standard_normal((8, 11)).astype("float32")
    lab = rng.integers(0, 11, (9,)).astype("int32")
    fused = float(np.asarray(_fused_linear_ce(
        paddle.to_tensor(h), paddle.to_tensor(w), paddle.to_tensor(lab),
        chunk=4, ignore_index=-100).data))
    logits = h @ w
    logp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) - logits.max(-1, keepdims=True)
    ref = -np.mean([logp[i, lab[i]] for i in range(9)])
    np.testing.assert_allclose(fused, ref, rtol=1e-4)


def test_bert_bfloat16_config_applies():
    bert = __import__("paddle_tpu.models", fromlist=["BertForPretraining"]) \
        .BertForPretraining(BertConfig.tiny(dtype="bfloat16"))
    assert str(bert.bert.embeddings.word_embeddings.weight.dtype).endswith("bfloat16")
