"""RNN layers (fused scan vs per-step cells) + hapi Model tests."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _np(t):
    return np.asarray(t.data)


# -- cells vs numpy reference -------------------------------------------------

def test_lstm_cell_matches_numpy():
    paddle.seed(0)
    cell = nn.LSTMCell(4, 6)
    x = paddle.randn([3, 4])
    h0 = paddle.randn([3, 6])
    c0 = paddle.randn([3, 6])
    out, (h, c) = cell(x, (h0, c0))

    W_ih, W_hh = _np(cell.weight_ih), _np(cell.weight_hh)
    b_ih, b_hh = _np(cell.bias_ih), _np(cell.bias_hh)
    gates = _np(x) @ W_ih.T + b_ih + _np(h0) @ W_hh.T + b_hh
    i, f, g, o = np.split(gates, 4, axis=-1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    c_ref = sig(f) * _np(c0) + sig(i) * np.tanh(g)
    h_ref = sig(o) * np.tanh(c_ref)
    np.testing.assert_allclose(_np(h), h_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_np(c), c_ref, rtol=1e-5, atol=1e-5)


def test_gru_cell_matches_numpy():
    paddle.seed(1)
    cell = nn.GRUCell(5, 7)
    x = paddle.randn([2, 5])
    h0 = paddle.randn([2, 7])
    out, h = cell(x, h0)
    sig = lambda v: 1 / (1 + np.exp(-v))
    xg = _np(x) @ _np(cell.weight_ih).T + _np(cell.bias_ih)
    hg = _np(h0) @ _np(cell.weight_hh).T + _np(cell.bias_hh)
    x_r, x_z, x_c = np.split(xg, 3, -1)
    h_r, h_z, h_c = np.split(hg, 3, -1)
    r, z = sig(x_r + h_r), sig(x_z + h_z)
    c = np.tanh(x_c + r * h_c)
    h_ref = (_np(h0) - c) * z + c
    np.testing.assert_allclose(_np(h), h_ref, rtol=1e-5, atol=1e-5)


# -- fused multi-layer scan vs per-step RNN wrapper ---------------------------

def test_lstm_fused_matches_stepwise():
    paddle.seed(2)
    lstm = nn.LSTM(4, 8, num_layers=1)
    x = paddle.randn([2, 5, 4])
    y, (h, c) = lstm(x)
    assert y.shape == [2, 5, 8] and h.shape == [1, 2, 8] and c.shape == [1, 2, 8]
    # stepwise: same weights through the eager cell
    stepper = nn.RNN(lstm._cell(0, 0))
    y2, (h2, c2) = stepper(x)
    np.testing.assert_allclose(_np(y), _np(y2), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_np(h[0]), _np(h2), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_np(c[0]), _np(c2), rtol=1e-5, atol=1e-5)


def test_bidirectional_gru_shapes_and_grad():
    paddle.seed(3)
    gru = nn.GRU(4, 6, num_layers=2, direction="bidirect")
    x = paddle.randn([3, 7, 4])
    y, h = gru(x)
    assert y.shape == [3, 7, 12]
    assert h.shape == [4, 3, 6]  # num_layers * num_directions
    y.sum().backward()
    for p in gru.parameters():
        assert p.grad is not None, "missing grad for an RNN weight"


def test_simple_rnn_sequence_length_masking():
    paddle.seed(4)
    srnn = nn.SimpleRNN(3, 5)
    x = paddle.randn([2, 6, 3])
    seq = paddle.to_tensor(np.asarray([4, 6], "int32"))
    y, h = srnn(x, sequence_length=seq)
    # outputs past each row's length are zero
    np.testing.assert_allclose(_np(y)[0, 4:], 0.0, atol=1e-7)
    assert np.abs(_np(y)[1, 4:]).sum() > 0
    # final state equals the output at the last valid step
    np.testing.assert_allclose(_np(h)[0, 0], _np(y)[0, 3], rtol=1e-5, atol=1e-6)


def test_lstm_time_major_and_initial_state():
    paddle.seed(5)
    lstm = nn.LSTM(4, 4, time_major=True)
    x = paddle.randn([5, 2, 4])
    h0 = paddle.zeros([1, 2, 4])
    c0 = paddle.zeros([1, 2, 4])
    y, (h, c) = lstm(x, (h0, c0))
    assert y.shape == [5, 2, 4]
    y2, _ = lstm(x)
    np.testing.assert_allclose(_np(y), _np(y2), rtol=1e-5, atol=1e-6)


def test_birnn_wrapper():
    paddle.seed(6)
    birnn = nn.BiRNN(nn.GRUCell(3, 4), nn.GRUCell(3, 4))
    x = paddle.randn([2, 5, 3])
    y, (st_f, st_b) = birnn(x)
    assert y.shape == [2, 5, 8]


# -- hapi Model ---------------------------------------------------------------

class _ToyDataset(paddle.io.Dataset):
    def __init__(self, n=32):
        rng = np.random.default_rng(0)
        self.x = rng.standard_normal((n, 8)).astype("float32")
        w = rng.standard_normal((8,)).astype("float32")
        self.y = (self.x @ w > 0).astype("int64")

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _toy_model():
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy(),
    )
    return model


def test_model_fit_reduces_loss_and_evaluates(capsys):
    model = _toy_model()
    ds = _ToyDataset(64)
    first = model.train_batch([ds.x[:16]], [ds.y[:16]])
    model.fit(ds, epochs=4, batch_size=16, verbose=0)
    last = model.train_batch([ds.x[:16]], [ds.y[:16]], update=False)
    assert last[0][0] < first[0][0], "fit() did not reduce the loss"
    res = model.evaluate(ds, batch_size=16, verbose=0)
    assert "loss" in res and "acc" in res
    assert res["acc"] > 0.5


def test_model_predict_and_stack():
    model = _toy_model()
    ds = _ToyDataset(20)
    outs = model.predict(ds, batch_size=8, stack_outputs=True, verbose=0)
    assert outs[0].shape == (20, 2)


def test_model_save_load_roundtrip(tmp_path):
    model = _toy_model()
    ds = _ToyDataset(16)
    model.fit(ds, epochs=1, batch_size=8, verbose=0)
    path = os.path.join(str(tmp_path), "ckpt", "m")
    model.save(path)
    pred_before = model.predict_batch([ds.x[:4]])[0]
    model2 = _toy_model()
    model2.load(path)
    pred_after = model2.predict_batch([ds.x[:4]])[0]
    np.testing.assert_allclose(pred_before, pred_after, rtol=1e-6)


def test_model_callbacks_early_stopping():
    model = _toy_model()
    ds = _ToyDataset(32)
    es = paddle.callbacks.EarlyStopping(monitor="acc", mode="max", patience=0,
                                        verbose=0, save_best_model=False)
    model.fit(ds, eval_data=ds, epochs=6, batch_size=16, verbose=0, callbacks=[es])
    # with patience 0 and a quickly-saturating metric, training stops early
    assert model.stop_training or True  # fit completes without error


def test_summary_counts_params(capsys):
    net = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2))
    info = paddle.summary(net, (1, 8))
    assert info["total_params"] == 8 * 4 + 4 + 4 * 2 + 2
    assert info["trainable_params"] == info["total_params"]


def test_lstm_model_fit():
    paddle.seed(7)

    class SeqNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lstm = nn.LSTM(4, 8)
            self.head = nn.Linear(8, 2)

        def forward(self, x):
            _, (h, _) = self.lstm(x)
            return self.head(h[0])

    net = SeqNet()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.01, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    rng = np.random.default_rng(1)
    x = rng.standard_normal((16, 5, 4)).astype("float32")
    y = (x.sum((1, 2)) > 0).astype("int64")
    ds = paddle.io.TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    model.fit(ds, epochs=2, batch_size=8, verbose=0)
