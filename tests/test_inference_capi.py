"""C inference API (reference capi_exp PD_* surface): build the native .so,
drive it through ctypes the way a C host would."""
import ctypes
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("capi_model")
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    net.eval()
    x = paddle.randn([2, 8])
    prefix = str(d / "model")
    paddle.jit.save(net, prefix, input_spec=[x])
    return prefix, net, x


def test_capi_roundtrip(saved_model):
    prefix, net, x = saved_model
    from paddle_tpu.inference.capi_bridge import load_capi_lib

    lib = load_capi_lib()
    lib.PD_PredictorCreate.restype = ctypes.c_void_p
    lib.PD_PredictorCreate.argtypes = [ctypes.c_char_p]
    lib.PD_PredictorRunFloat.restype = ctypes.c_int64
    lib.PD_PredictorRunFloat.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int)]
    lib.PD_GetLastError.restype = ctypes.c_char_p
    lib.PD_PredictorGetInputNum.argtypes = [ctypes.c_void_p]
    lib.PD_PredictorDestroy.argtypes = [ctypes.c_void_p]

    h = lib.PD_PredictorCreate(prefix.encode())
    assert h, lib.PD_GetLastError()
    assert lib.PD_PredictorGetInputNum(h) == 1

    data = np.asarray(x.numpy(), np.float32)
    shape = (ctypes.c_int64 * 2)(*data.shape)
    out = np.zeros(2 * 4, np.float32)
    out_shape = (ctypes.c_int64 * 8)()
    out_ndim = ctypes.c_int(0)
    n = lib.PD_PredictorRunFloat(
        h, data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape, 2,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size,
        out_shape, 8, ctypes.byref(out_ndim))
    assert n == 8, lib.PD_GetLastError()
    assert out_ndim.value == 2 and list(out_shape[:2]) == [2, 4]
    np.testing.assert_allclose(out.reshape(2, 4), net(x).numpy(),
                               rtol=1e-4, atol=1e-5)
    lib.PD_PredictorDestroy(h)


def test_capi_error_reporting(saved_model):
    from paddle_tpu.inference.capi_bridge import load_capi_lib

    lib = load_capi_lib()
    lib.PD_PredictorCreate.restype = ctypes.c_void_p
    lib.PD_PredictorCreate.argtypes = [ctypes.c_char_p]
    lib.PD_GetLastError.restype = ctypes.c_char_p
    h = lib.PD_PredictorCreate(b"/nonexistent/model")
    assert not h
    assert lib.PD_GetLastError()
