"""PyLayer custom autograd (VERDICT item 8; reference:
python/paddle/autograd/py_layer.py:202)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


class CusTanh(PyLayer):
    @staticmethod
    def forward(ctx, x):
        y = paddle.tanh(x)
        ctx.save_for_backward(y)
        return y

    @staticmethod
    def backward(ctx, dy):
        (y,) = ctx.saved_tensor()
        return dy * (1 - y * y)


def test_pylayer_matches_builtin_grad():
    x_np = np.linspace(-2, 2, 12).astype(np.float32).reshape(3, 4)

    x1 = paddle.to_tensor(x_np, stop_gradient=False)
    y1 = CusTanh.apply(x1)
    y1.sum().backward()

    x2 = paddle.to_tensor(x_np, stop_gradient=False)
    y2 = paddle.tanh(x2)
    y2.sum().backward()

    np.testing.assert_allclose(y1.numpy(), y2.numpy(), rtol=1e-6)
    np.testing.assert_allclose(x1.grad.numpy(), x2.grad.numpy(), rtol=1e-5)


def test_pylayer_scale_ten():
    class ScaleBwd(PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 1.0

        @staticmethod
        def backward(ctx, dy):
            return dy * 10.0

    x = paddle.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
    ScaleBwd.apply(x).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((4,), 10.0, np.float32))


def test_pylayer_multi_input_nontensor_attr():
    class AXPlusB(PyLayer):
        @staticmethod
        def forward(ctx, x, y, alpha):
            ctx.alpha = alpha
            return x * alpha + y

        @staticmethod
        def backward(ctx, dz):
            return dz * ctx.alpha, dz

    x = paddle.to_tensor(np.ones((3,), np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones((3,), np.float32), stop_gradient=False)
    z = AXPlusB.apply(x, y, 3.0)
    z.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((3,), 3.0, np.float32))
    np.testing.assert_allclose(y.grad.numpy(), np.ones((3,), np.float32))


def test_pylayer_multi_output_chain():
    class SplitSq(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x, x + 1

        @staticmethod
        def backward(ctx, d_sq, d_lin):
            (x,) = ctx.saved_tensor()
            return d_sq * 2 * x + d_lin

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32), stop_gradient=False)
    a, b = SplitSq.apply(x)
    # chain through further framework ops
    loss = (a * 2).sum() + b.sum()
    loss.backward()
    # d/dx [2x^2 + x + 1] = 4x + 1
    np.testing.assert_allclose(x.grad.numpy(), 4 * np.array([1, 2, 3], np.float32) + 1)


def test_pylayer_stop_gradient_input():
    x = paddle.to_tensor(np.ones((2,), np.float32))  # stop_gradient=True
    y = CusTanh.apply(x)
    assert y.stop_gradient


def test_autograd_backward_multiroot():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    a = x * 3
    b = x * x
    paddle.autograd.backward([a, b])
    # d(3x)/dx + d(x^2)/dx = 3 + 2x = 7
    np.testing.assert_allclose(x.grad.numpy(), np.array([7.0], np.float32))
