"""AST-lite dy2static (reference program_translator.py:775): tensor-dependent
Python if/while agree between eager and to_static."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_return_if_matches_eager():
    def f(x):
        if x.sum() > 0:
            return x * 2
        else:
            return x - 1

    st = paddle.jit.to_static(f)
    for v in ([1.0, 2.0], [-5.0, 1.0]):
        x = paddle.to_tensor(v)
        np.testing.assert_allclose(st(x).numpy(), f(x).numpy())


def test_assign_if_matches_eager():
    def f(x):
        y = x * 0.5
        if x.mean() > 0:
            y = y + 10.0
            z = y * 2.0
        else:
            z = y - 3.0
        return z + x

    st = paddle.jit.to_static(f)
    for v in ([2.0, 4.0], [-2.0, -4.0]):
        x = paddle.to_tensor(v)
        np.testing.assert_allclose(st(x).numpy(), f(x).numpy(), rtol=1e-6)


def test_augassign_branch():
    def f(x):
        acc = x * 1.0
        if x.sum() > 0:
            acc += 5.0
        else:
            acc -= 5.0
        return acc

    st = paddle.jit.to_static(f)
    for v in ([3.0], [-3.0]):
        x = paddle.to_tensor(v)
        np.testing.assert_allclose(st(x).numpy(), f(x).numpy())


def test_tensor_while_matches_eager():
    def f(x):
        s = x * 1.0
        while s.sum() < 100.0:
            s = s * 2.0
        return s

    st = paddle.jit.to_static(f)
    x = paddle.to_tensor([1.5, 2.0])
    np.testing.assert_allclose(st(x).numpy(), f(x).numpy())


def test_layer_forward_converted():
    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.mean() > 0:
                return h * 2.0
            else:
                return h * -1.0

    paddle.seed(0)
    net = Gate()
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    eager = net(x).numpy()
    st = paddle.jit.to_static(net)
    np.testing.assert_allclose(st(x).numpy(), eager, rtol=1e-6)


def test_python_if_still_python_when_concrete():
    """Concrete (non-traced) predicates keep plain Python semantics."""
    def f(x, flag):
        if flag:
            return x + 1
        else:
            return x - 1

    st = paddle.jit.to_static(f)
    x = paddle.to_tensor([1.0])
    np.testing.assert_allclose(st(x, True).numpy(), [2.0])
    np.testing.assert_allclose(st(x, False).numpy(), [0.0])


def test_unconvertible_branch_raises_pointer():
    def f(x):
        if x.sum() > 0:  # branch body does IO-ish work: not convertible
            print("positive")
            return x
        return x * -1.0

    st = paddle.jit.to_static(f)
    with pytest.raises(TypeError, match="static.nn.cond"):
        st(paddle.to_tensor([1.0]))


def test_return_if_fallthrough():
    """`if t: return A` + bare `return B` (no else) converts too."""
    def f(x):
        if x.sum() > 0:
            return x * 3.0
        return x * -2.0

    st = paddle.jit.to_static(f)
    for v in ([1.0], [-1.0]):
        x = paddle.to_tensor(v)
        np.testing.assert_allclose(st(x).numpy(), f(x).numpy())


def test_dead_store_branch_not_converted():
    """A target assigned in only one arm with no prior read (dead store)
    must NOT convert — converted code would unbind it; eager runs fine."""
    def f(x, flag):
        y = x * 0.5
        if flag:
            y = y + 1.0
            extra = y * 2.0  # dead store, true-arm only
        else:
            y = y - 1.0
        return y

    st = paddle.jit.to_static(f)
    x = paddle.to_tensor([2.0])
    np.testing.assert_allclose(st(x, True).numpy(), f(x, True).numpy())
    np.testing.assert_allclose(st(x, False).numpy(), f(x, False).numpy())


def test_loop_local_temp_not_converted():
    def f(x, n):
        s = x * 1.0
        i = 0
        while i < n:  # concrete loop with a loop-local temp
            tmp = s * 2.0
            s = tmp + 1.0
            i = i + 1
        return s

    st = paddle.jit.to_static(f)
    x = paddle.to_tensor([1.0])
    np.testing.assert_allclose(st(x, 3).numpy(), f(x, 3).numpy())


def test_to_static_does_not_mutate_layer():
    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.mean() > 0:
                return h * 2.0
            return h * -1.0

    import paddle_tpu.nn as nn_mod

    paddle.seed(0)
    net = Gate()
    original_forward = net.forward
    paddle.jit.to_static(net)
    # the instance must keep its eager forward (no persistent rebinding)
    assert net.forward.__func__ is original_forward.__func__
