"""Epoch-level auto-checkpoint: save-per-epoch, crash, resume.

Reference role: fluid/incubate/checkpoint/auto_checkpoint.py:71
(train_epoch_range fast-forwards a relaunched job past completed epochs
and restores train state)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate.checkpoint import train_epoch_range


def _new_net():
    paddle.seed(7)
    net = nn.Linear(4, 4)
    o = opt.SGD(learning_rate=0.1, parameters=net.parameters())
    return net, o


def _train_one_epoch(net, o, epoch):
    x = paddle.to_tensor(np.full((2, 4), float(epoch + 1), "float32"))
    loss = (net(x) ** 2).mean()
    loss.backward()
    o.step()
    o.clear_grad()


def test_resume_skips_completed_epochs(tmp_path):
    ckpt = str(tmp_path)

    # "job 1" crashes after epoch 1 completes
    net, o = _new_net()
    seen = []
    for epoch in train_epoch_range(5, name="j", checkpoint_dir=ckpt,
                                   state={"model": net, "opt": o}):
        _train_one_epoch(net, o, epoch)
        seen.append(epoch)
        if epoch == 1:
            break  # simulated crash AFTER epoch-1 work, BEFORE its save?
    # the generator saves on resumption of the loop body boundary; epoch 1's
    # save happens when the loop advances — a break skips it, so epoch 1
    # must be REPLAYED on resume (at-least-once semantics)
    assert seen == [0, 1]
    w_at_crash = net.weight.numpy().copy()

    # "job 2": fresh process state, same checkpoint dir
    net2, o2 = _new_net()
    seen2 = []
    rng = train_epoch_range(5, name="j", checkpoint_dir=ckpt,
                            state={"model": net2, "opt": o2})
    for epoch in rng:
        if not seen2:
            # restored exactly the epoch-0 checkpoint, not the crashed work
            assert rng.restored_from == 0
            assert not np.allclose(net2.weight.numpy(), w_at_crash)
        _train_one_epoch(net2, o2, epoch)
        seen2.append(epoch)
    assert seen2 == [1, 2, 3, 4]

    # "job 3": everything done -> zero epochs replayed
    net3, o3 = _new_net()
    seen3 = list(train_epoch_range(5, name="j", checkpoint_dir=ckpt,
                                   state={"model": net3, "opt": o3}))
    assert seen3 == []


def test_deterministic_replay_matches_uninterrupted(tmp_path):
    """Crash + resume must land on the same weights as a straight run."""
    straight, so = _new_net()
    for epoch in range(4):
        _train_one_epoch(straight, so, epoch)

    net, o = _new_net()
    for epoch in train_epoch_range(4, name="d",
                                   checkpoint_dir=str(tmp_path / "a"),
                                   state={"m": net, "o": o}):
        _train_one_epoch(net, o, epoch)
        if epoch == 2:
            break
    net2, o2 = _new_net()
    for epoch in train_epoch_range(4, name="d",
                                   checkpoint_dir=str(tmp_path / "a"),
                                   state={"m": net2, "o": o2}):
        _train_one_epoch(net2, o2, epoch)
    np.testing.assert_allclose(net2.weight.numpy(),
                               straight.weight.numpy(), rtol=1e-6)


def test_stateful_optimizer_resume_matches_uninterrupted(tmp_path):
    """AdamW moments + LR scheduler must survive the crash/resume cycle —
    a fresh process's optimizer has NO accumulator keys yet, so restore
    must come from the manifest, not the fresh state_dict."""
    import paddle_tpu.optimizer.lr as lr_mod

    def new():
        paddle.seed(11)
        net = nn.Linear(4, 4)
        sched = lr_mod.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
        o = opt.AdamW(learning_rate=sched, parameters=net.parameters())
        return net, o, sched

    def epoch_work(net, o, sched, epoch):
        _train_one_epoch(net, o, epoch)
        sched.step()

    straight, so_, ss = new()
    for epoch in range(5):
        epoch_work(straight, so_, ss, epoch)

    net, o, sched = new()
    for epoch in train_epoch_range(5, name="adam",
                                   checkpoint_dir=str(tmp_path),
                                   state={"m": net, "o": o}):
        epoch_work(net, o, sched, epoch)
        if epoch == 2:
            break
    net2, o2, sched2 = new()
    rng = train_epoch_range(5, name="adam", checkpoint_dir=str(tmp_path),
                            state={"m": net2, "o": o2})
    for epoch in rng:
        epoch_work(net2, o2, sched2, epoch)
    assert rng.restored_from == 1  # epoch-2 work crashed before its save
    # moments + scheduler state came back through the optimizer, so the
    # resumed trajectory must match the uninterrupted run exactly
    np.testing.assert_allclose(net2.weight.numpy(),
                               straight.weight.numpy(), rtol=1e-5)


def test_lambda_decay_scheduler_state_roundtrips(tmp_path):
    """Callable-holding scheduler state (LambdaDecay.lr_lambda) must not
    crash the epoch save — pickle fallback covers it."""
    import paddle_tpu.optimizer.lr as lr_mod

    def new():
        paddle.seed(3)
        net = nn.Linear(4, 4)
        sched = lr_mod.LambdaDecay(learning_rate=0.1,
                                   lr_lambda=lambda e: 0.9 ** e)
        o = opt.AdamW(learning_rate=sched, parameters=net.parameters())
        return net, o, sched

    net, o, sched = new()
    for epoch in train_epoch_range(4, name="lam",
                                   checkpoint_dir=str(tmp_path),
                                   state={"m": net, "o": o}):
        _train_one_epoch(net, o, epoch)
        sched.step()
        if epoch == 1:
            break
    net2, o2, sched2 = new()
    rng = train_epoch_range(4, name="lam", checkpoint_dir=str(tmp_path),
                            state={"m": net2, "o": o2})
    seen = []
    for epoch in rng:
        _train_one_epoch(net2, o2, epoch)
        sched2.step()
        seen.append(epoch)
    assert rng.restored_from == 0 and seen == [1, 2, 3]


def test_restore_missing_model_keys_raises(tmp_path):
    net, o = _new_net()
    for epoch in train_epoch_range(2, name="miss",
                                   checkpoint_dir=str(tmp_path),
                                   state={"m": net}):
        _train_one_epoch(net, o, epoch)
        break  # epoch 0 saved... no — break skips the save
    # complete one epoch so a checkpoint exists
    for epoch in train_epoch_range(2, name="miss",
                                   checkpoint_dir=str(tmp_path),
                                   state={"m": net}):
        _train_one_epoch(net, o, epoch)
    # resume a BIGGER model against the small checkpoint: must raise
    paddle.seed(9)
    big = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4))
    with pytest.raises(KeyError, match="lacks"):
        list(train_epoch_range(4, name="miss",
                               checkpoint_dir=str(tmp_path),
                               state={"m": big}))


def test_save_interval_cleanup_keeps_two_saved(tmp_path):
    import os

    net, o = _new_net()
    for epoch in train_epoch_range(9, name="s", checkpoint_dir=str(tmp_path),
                                   state={"m": net}, save_interval=3):
        _train_one_epoch(net, o, epoch)
    d = str(tmp_path / "s")
    dirs = sorted(x for x in os.listdir(d) if x.startswith("e"))
    # saves at e0, e3, e6, e8 (final); keep-two leaves e6 + e8
    assert dirs == ["e6", "e8"], dirs


def test_marker_only_then_stateful_resume_warns(tmp_path):
    list(train_epoch_range(3, name="x", checkpoint_dir=str(tmp_path)))
    net, o = _new_net()
    with pytest.warns(UserWarning, match="no saved state"):
        rng = train_epoch_range(5, name="x", checkpoint_dir=str(tmp_path),
                                state={"m": net})
        seen = list(rng)
    assert seen == [3, 4]  # fast-forwarded, no crash
    assert rng.restored_from is None


def test_marker_only_mode(tmp_path):
    seen = []
    for epoch in train_epoch_range(3, name="m",
                                   checkpoint_dir=str(tmp_path)):
        seen.append(epoch)
    assert seen == [0, 1, 2]
    again = list(train_epoch_range(3, name="m",
                                   checkpoint_dir=str(tmp_path)))
    assert again == []
