"""Round-6 satellite fixes: bench headline contract, master rendezvous
diagnostics, port reservations, checkpoint accumulator resharding."""
import json
import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu import jit
from paddle_tpu.distributed.run.master import (
    Master, free_port, release_reserved_ports, reserve_port)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- bench.py headline contract ----------------------------------------------

@pytest.mark.slow  # tier-1 wall-clock relief (ISSUE-5): the full CPU bench
# smoke runs minutes; tools/ci.sh's perf gate runs it and asserts MORE
# (first+last line parse, size cap, stream_capacity/persistent_cache rows)
def test_bench_prints_compact_parseable_headline():
    """The driver contract: bench.py emits a compact parseable headline
    JSON line on stdout (CPU smoke path) well within budget."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line on stdout: {r.stdout[-500:]}"
    parsed = json.loads(lines[-1])
    assert parsed["metric"] == "llama_pretrain_mfu"
    assert "value" in parsed and "vs_baseline" in parsed
    # r4's failure mode was an oversized line; keep every printed line small
    assert all(len(ln) < 8192 for ln in lines)


def test_bench_compact_strips_heavy_keys():
    import bench

    detail = {"mfu": 50.0,
              "device_op_table": {"rows": list(range(1000))},
              "losses_tpu": list(range(500)),
              "nested": {"op_table": [1] * 500, "keep": 1}}
    out = bench._compact(detail)
    assert "device_op_table" not in out
    assert "losses_tpu" not in out
    assert "op_table" not in out["nested"]
    assert out["nested"]["keep"] == 1
    line = bench._headline({"mfu": 50.0}, detail)
    assert len(line) < 8000 and json.loads(line)["value"] == 50.0


@pytest.mark.slow  # spawns a real bench smoke and kills it mid-run; the
# ci.sh planner gate runs it (tier-1 wall-clock relief)
def test_bench_sigterm_leaves_parseable_last_line():
    """Blackout round-3 regression (ISSUE-10 satellite): a bench process
    SIGTERM'd mid-run — with the `timeout -k 10`-style SIGKILL follow-up —
    must still leave a parseable headline as its LAST stdout line. The
    watchdog/handler pair guarantees it even when the main thread is
    pinned inside a native XLA call where a Python signal handler cannot
    run."""
    import signal
    import tempfile
    import time as _time

    with tempfile.TemporaryFile("w+") as out:
        p = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "bench.py")],
            stdout=out, stderr=subprocess.DEVNULL, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "BENCH_BUDGET_S": "600"})
        killed = False
        try:
            _time.sleep(8)  # past the first stub emit, mid-measure
            p.send_signal(signal.SIGTERM)
            try:
                # generous window: the Python handler needs the main
                # thread to surface from native code (an XLA compile on a
                # loaded host can exceed the driver's literal 10s — THAT
                # path is the budget watchdog's job, tested separately);
                # what this test pins is the stdout contract either way
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                killed = True
                p.kill()
                p.wait(timeout=30)
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        out.seek(0)
        lines = [ln for ln in out.read().splitlines() if ln.strip()]
    assert lines, "SIGTERM'd bench left nothing on stdout"
    parsed = json.loads(lines[-1])  # the driver's contract — ALWAYS holds
    assert parsed["metric"] == "llama_pretrain_mfu"
    assert len(lines[-1]) < 2000
    assert not killed, "SIGTERM handler never ran within 30s"


@pytest.mark.slow  # ~25s of wall clock by design; the ci.sh planner gate
# runs it
def test_bench_watchdog_emits_before_tiny_budget_expires():
    """The budget watchdog is the SIGKILL-proof half: with a budget far
    smaller than the smoke, the process must exit 0 BY ITSELF with the
    headline re-printed last — no external signal needed."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "BENCH_BUDGET_S": "25"})
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert lines, r.stderr[-1000:]
    parsed = json.loads(lines[-1])  # ALWAYS parseable — the contract
    assert parsed["metric"] == "llama_pretrain_mfu"
    # rc mirrors whether the flagship value landed before truncation
    assert r.returncode == (0 if parsed["value"] is not None else 1), \
        (r.returncode, parsed["value"])


def test_bench_reads_back_prior_headline(tmp_path, monkeypatch):
    """Startup read-back: an interrupted prior round's on-disk headline
    surfaces in the next round's starting stub."""
    import bench

    monkeypatch.chdir(tmp_path)
    os.makedirs("bench_artifacts", exist_ok=True)
    row = {"metric": "llama_pretrain_mfu", "value": 55.9,
           "vs_baseline": 1.471, "detail": {"status": "interrupted"}}
    with open(os.path.join("bench_artifacts", "headline.json"), "w") as f:
        f.write(json.dumps(row))
    prior = bench._prior_headline()
    assert prior == {"value": 55.9, "vs_baseline": 1.471}
    # a stub/None-valued prior (this round's own startup write) is ignored
    with open(os.path.join("bench_artifacts", "headline.json"), "w") as f:
        f.write(json.dumps(dict(row, value=None)))
    assert bench._prior_headline() is None
    # and a missing/corrupt artifact never raises
    with open(os.path.join("bench_artifacts", "headline.json"), "w") as f:
        f.write("{not json")
    assert bench._prior_headline() is None


# -- master.py: mixed-rank gang diagnostics ----------------------------------

def test_sync_peers_mixed_explicit_auto_ranks():
    """An explicit-rank MAIN + auto participants used to hang forever on
    main_taken; the explicit node now publishes the arrival marker."""
    port = free_port()
    main = Master(f"127.0.0.1:{port}")
    assert main.role == Master.MAIN
    out = {}

    def auto_participant():
        m = Master(f"127.0.0.1:{port}")
        out["auto"] = m.sync_peers("/t/mixed", "b", 2, rank=-1,
                                   main_timeout=20.0)

    t = threading.Thread(target=auto_participant)
    t.start()
    # MAIN joins with an EXPLICIT rank (the mixed-gang configuration)
    peers, rank = main.sync_peers("/t/mixed", "a", 2, rank=0)
    t.join(timeout=30)
    assert not t.is_alive(), "auto participant hung in mixed-rank gang"
    assert rank == 0 and peers == ["a", "b"]
    assert out["auto"][1] == 1
    main.stop()


def test_sync_peers_auto_skips_explicitly_claimed_ranks():
    """Mixed gang with explicit ranks {0,1} + one auto node: the auto node
    must land on rank 2, not collide with the explicit rank 1."""
    port = free_port()
    main = Master(f"127.0.0.1:{port}")
    out = {}

    def explicit_r1():
        m = Master(f"127.0.0.1:{port}")
        out["r1"] = m.sync_peers("/t/skip", "b", 3, rank=1)

    def auto():
        m = Master(f"127.0.0.1:{port}")
        out["auto"] = m.sync_peers("/t/skip", "c", 3, rank=-1,
                                   main_timeout=20.0)

    t1 = threading.Thread(target=explicit_r1)
    t1.start()
    import time as _time

    _time.sleep(0.3)  # explicit nodes first (the documented mixed layout)
    t2 = threading.Thread(target=auto)
    t2.start()
    peers, rank = main.sync_peers("/t/skip", "a", 3, rank=0)
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert not t1.is_alive() and not t2.is_alive()
    assert rank == 0 and peers == ["a", "b", "c"]
    assert out["r1"][1] == 1
    assert out["auto"][1] == 2  # skipped the claimed rank 1
    main.stop()


def test_sync_peers_duplicate_rank_raises_instead_of_hanging():
    """Two nodes claiming one rank slot (duplicate explicit --rank, or a
    mixed-gang arrival/explicit collision) must raise, not silently
    overwrite one payload and hang the gang on the missing slot."""
    port = free_port()
    main = Master(f"127.0.0.1:{port}")
    result = {}

    def dup():
        m = Master(f"127.0.0.1:{port}")
        try:
            m.sync_peers("/t/dup", "b", 3, rank=1)
        except RuntimeError as e:
            result["err"] = str(e)

    main.store.add("/t/dup/main_present", 1)  # avoid the main wait
    t = threading.Thread(target=dup)
    # first claimant of rank 1 wins silently
    main.store.add("/t/dup/claim/1", 1)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive()
    assert "claimed twice" in result.get("err", "")
    main.stop()


def test_sync_peers_no_main_raises_diagnosis_quickly():
    port = free_port()
    main = Master(f"127.0.0.1:{port}")   # hosts the store only
    m = Master(f"127.0.0.1:{port}")
    assert m.role == Master.PARTICIPANT
    with pytest.raises(RuntimeError, match="misconfiguration"):
        # nobody ever joins as MAIN/explicit: must raise fast, not hang
        m.sync_peers("/t/nomain", "x", 2, rank=-1, main_timeout=1.0)
    main.stop()


# -- master.py: free_port TOCTOU ---------------------------------------------

def test_reserved_port_stays_bound_until_release():
    port = reserve_port()
    probe = socket.socket()
    try:
        with pytest.raises(OSError):
            probe.bind(("", port))   # held: a thief cannot take it
    finally:
        probe.close()
    release_reserved_ports()
    probe2 = socket.socket()
    try:
        probe2.bind(("", port))      # released: the real server binds
    finally:
        probe2.close()


def test_node_payload_ports_are_reserved():
    from paddle_tpu.distributed.run.master import _HELD_PORTS, node_payload

    release_reserved_ports()
    payload = json.loads(node_payload(2))
    held = {r.port for r in _HELD_PORTS}
    assert payload["coord_port"] in held
    assert payload["ps_port"] in held
    release_reserved_ports()


# -- incubate/checkpoint: accumulator resharding on restore ------------------

def test_auto_checkpoint_restores_accumulators_to_param_sharding(tmp_path):
    from paddle_tpu.incubate.checkpoint import train_epoch_range

    def build():
        paddle.seed(7)
        net = paddle.nn.Linear(8, 4)
        o = opt.AdamW(learning_rate=1e-3, parameters=net.parameters())
        step = jit.TrainStep(
            net, lambda m, x, y: ((m(x) - y) ** 2).mean(), o)
        return net, o, step

    x = paddle.randn([4, 8])
    y = paddle.randn([4, 4])

    from paddle_tpu.incubate.checkpoint import _EpochRange

    net, o, step = build()
    for epoch in train_epoch_range(2, name="accs", state={"opt": o},
                                   checkpoint_dir=str(tmp_path)):
        step(x, y)
    to_pos, _ = _EpochRange._pos_key_maps(o)
    moments = {to_pos(k): np.asarray(v.data if hasattr(v, "data") else v)
               for k, v in o.state_dict().items() if hasattr(v, "shape")}
    assert moments, "optimizer saved no accumulator state"

    # fresh process equivalent: new objects (param names DIFFER — the
    # global tensor counter advanced), resumed range restores state
    net2, o2, _ = build()
    r = train_epoch_range(2, name="accs", state={"opt": o2},
                          checkpoint_dir=str(tmp_path))
    for _ in r:
        pass  # both epochs completed: fast-forward, restore only
    assert r.restored_from == 1
    to_pos2, _ = _EpochRange._pos_key_maps(o2)
    restored = {to_pos2(k): v for k, v in o2.state_dict().items()
                if hasattr(v, "shape")}
    for k, v in moments.items():
        got = restored.get(k)
        assert got is not None, \
            f"accumulator {k} missing after restore ({sorted(restored)})"
        arr = got.data if hasattr(got, "data") else got
        np.testing.assert_allclose(np.asarray(arr, np.float32),
                                   v.astype(np.float32), rtol=1e-6)
        if hasattr(arr, "sharding") and k.startswith("__p"):
            # the resharding contract: moment-shaped state lands on its
            # parameter's sharding, not the default device placement
            idx = int(k[3:].split("__", 1)[0])
            owner = o2._parameter_list[idx]
            if tuple(arr.shape) == tuple(owner.shape):
                assert arr.sharding.is_equivalent_to(
                    owner.data.sharding, len(arr.shape))
