"""Distributed stack tests on the virtual 8-device CPU mesh.

Reference strategy: collective_*_api.py 2-proc tests + hybrid-parallel parity
tests (test_parallel_dygraph_tensor_parallel.py). Here SPMD single-controller:
numerics of sharded compiled steps must match single-device eager exactly.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)


@pytest.fixture(autouse=True)
def clean_mesh():
    yield
    dist.reset_mesh()
    import paddle_tpu.distributed.collective as coll

    coll._DEFAULT_GROUP = None
    import paddle_tpu.distributed.fleet.base as fb

    fb._STATE.initialized = False
    fb._STATE.hcg = None


def test_mesh_degrees_check():
    with pytest.raises(ValueError):
        dist.init_mesh(dp=3, mp=4)  # 12 != 8
    env = dist.init_mesh(dp=2, mp=2, pp=2)
    assert env.nranks == 8
    assert env.get_dim("mp") == 2


def test_all_reduce_sum_and_avg():
    dist.init_mesh(dp=4, mp=2)
    g = dist.new_group(axis="dp")
    t = paddle.to_tensor(np.arange(8, dtype="float32").reshape(4, 2))
    out = dist.all_reduce(t, group=g)
    col_sums = np.arange(8, dtype="float32").reshape(4, 2).sum(0)
    np.testing.assert_allclose(out.numpy(), np.tile(col_sums, (4, 1)))
    t2 = paddle.to_tensor(np.arange(8, dtype="float32").reshape(4, 2))
    out2 = dist.all_reduce(t2, op=dist.ReduceOp.AVG, group=g)
    np.testing.assert_allclose(out2.numpy(), np.tile(col_sums / 4, (4, 1)))


def test_all_gather_broadcast():
    dist.init_mesh(dp=2, mp=4)
    g = dist.new_group(axis="dp")
    t = paddle.to_tensor(np.arange(4, dtype="float32").reshape(2, 2))
    shards = []
    dist.all_gather(shards, t, group=g)
    assert len(shards) == 2
    np.testing.assert_array_equal(shards[1].numpy(), [[2, 3]])
    b = dist.broadcast(paddle.to_tensor(np.array([[1.0], [2.0]])), src=0, group=g)
    np.testing.assert_allclose(b.numpy(), [[1.0], [1.0]])


def test_reduce_scatter_alltoall():
    dist.init_mesh(dp=1, mp=8)
    g = dist.new_group(axis="mp")
    rs = dist.reduce_scatter(paddle.to_tensor(np.ones((64,), "float32")), group=g)
    assert rs.shape == [8]
    np.testing.assert_allclose(rs.numpy(), 8.0)
    a2a = dist.alltoall(paddle.to_tensor(np.arange(64, dtype="float32")), group=g)
    blocks = np.arange(64, dtype="float32").reshape(8, 8)
    np.testing.assert_allclose(a2a.numpy().reshape(8, 8), blocks.T)


def test_fleet_init_and_topology():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                               "sharding_degree": 1, "cp_degree": 1, "ep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert hcg.get_data_parallel_world_size() == 2
    topo = hcg.topology()
    assert topo.world_size() == 8
    comm = topo.get_comm_list("model")
    assert len(comm) == 4 and all(len(c) == 2 for c in comm)


def test_fleet_auto_dp_fill():
    fleet.init(is_collective=True)  # no strategy: all 8 devices on dp
    env = dist.get_mesh_env()
    assert env.get_dim("dp") == 8


def _tp_mlp():
    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.up = ColumnParallelLinear(8, 16, gather_output=False)
            self.down = RowParallelLinear(16, 8, input_is_parallel=True)

        def forward(self, x):
            return self.down(F.gelu(self.up(x)))

    return MLP()


@pytest.mark.dist
def test_tp_sharded_step_matches_eager():
    paddle.seed(3)
    dist.init_mesh(dp=2, mp=4)
    net = _tp_mlp()
    snap = {k: v.numpy().copy() for k, v in net.state_dict().items()}
    o = opt.Adam(learning_rate=0.05, parameters=net.parameters())
    step = dist.ShardedTrainStep(net, lambda m, x, y: F.mse_loss(m(x), y), o)
    x = np.random.RandomState(0).rand(8, 8).astype("float32")
    y = np.random.RandomState(1).rand(8, 8).astype("float32")
    sharded = [float(step(paddle.to_tensor(x), paddle.to_tensor(y))) for _ in range(4)]

    dist.reset_mesh()
    net2 = _tp_mlp()
    net2.set_state_dict(snap)
    o2 = opt.Adam(learning_rate=0.05, parameters=net2.parameters())
    eager = []
    for _ in range(4):
        loss = F.mse_loss(net2(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        o2.step()
        o2.clear_grad()
        eager.append(float(loss))
    np.testing.assert_allclose(sharded, eager, rtol=2e-4)


@pytest.mark.dist
def test_zero_sharding_matches_eager():
    paddle.seed(11)
    dist.init_mesh(sharding=8)
    net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    snap = {k: v.numpy().copy() for k, v in net.state_dict().items()}
    o = opt.AdamW(learning_rate=0.02, parameters=net.parameters())
    model, o = dist.group_sharded_parallel(net, o, level="p_g_os")
    # params got sdp specs
    specs = [p.dist_spec for p in net.parameters()]
    assert any(s is not None for s in specs)
    step = dist.ShardedTrainStep(net, lambda m, x, y: F.mse_loss(m(x), y), o)
    x = np.random.RandomState(2).rand(8, 16).astype("float32")
    y = np.random.RandomState(3).rand(8, 16).astype("float32")
    sharded = [float(step(paddle.to_tensor(x), paddle.to_tensor(y))) for _ in range(4)]

    dist.reset_mesh()
    net2 = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    net2.set_state_dict(snap)
    o2 = opt.AdamW(learning_rate=0.02, parameters=net2.parameters())
    eager = []
    for _ in range(4):
        loss = F.mse_loss(net2(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        o2.step()
        o2.clear_grad()
        eager.append(float(loss))
    np.testing.assert_allclose(sharded, eager, rtol=2e-4)


@pytest.mark.dist
def test_vocab_parallel_embedding():
    paddle.seed(0)
    dist.init_mesh(mp=8)
    emb = VocabParallelEmbedding(64, 16)
    ids = paddle.to_tensor(np.array([[1, 5, 63], [0, 2, 8]], "int32"))
    out = emb(ids)
    assert out.shape == [2, 3, 16]
    ref = emb.weight.numpy()[ids.numpy()]
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)


def test_data_parallel_wrapper():
    dist.init_mesh(dp=8)
    net = nn.Linear(4, 4)
    dp = dist.DataParallel(net)
    x = paddle.randn([8, 4])
    out = dp(x)
    assert out.shape == [8, 4]
    with dp.no_sync():  # semantic no-op under GSPMD; must not raise
        dp(x)
    assert len(dp.parameters()) == 2


def test_distributed_model_dispatch():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
                               "sharding_degree": 1, "cp_degree": 1, "ep_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    net = _tp_mlp()
    wrapped = fleet.distributed_model(net)
    from paddle_tpu.distributed.meta_parallel import TensorParallel

    assert isinstance(wrapped, TensorParallel)
    o = fleet.distributed_optimizer(opt.Adam(learning_rate=0.01,
                                             parameters=net.parameters()))
    out = wrapped(paddle.randn([4, 8]))
    out.mean().backward()
    o.step()
    o.clear_grad()


def test_pipeline_layer_segmentation():
    from paddle_tpu.distributed.meta_parallel import LayerDesc, PipelineLayer

    descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(7)]
    pipe = PipelineLayer(layers=descs, num_stages=4)
    parts = pipe.segment_parts
    assert parts == [0, 2, 4, 6, 7]
    assert len(pipe.get_stage_layers(0)) == 2
    assert len(pipe.get_stage_layers(3)) == 1
    out = pipe(paddle.randn([2, 8]))
    assert out.shape == [2, 8]


def test_shared_layer_desc_ties_weights():
    from paddle_tpu.distributed.meta_parallel import SharedLayerDesc, PipelineLayer

    descs = [
        SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
        nn.ReLU(),
        SharedLayerDesc("emb", nn.Linear, None, "weight", 8, 8),
    ]
    pipe = PipelineLayer(layers=descs, num_stages=1)
    params = pipe.parameters()
    # shared layer counted once: 1 weight + 1 bias (+0 from relu)
    assert len(params) == 2


def test_recompute_matches_direct():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 8))
    x = paddle.randn([4, 8])
    x.stop_gradient = False
    direct = net(x)
    direct.sum().backward()
    g_direct = x.grad.numpy().copy()
    w_direct = net[0].weight.grad.numpy().copy()
    net.clear_gradients()
    x2 = paddle.to_tensor(x.numpy())
    x2.stop_gradient = False
    out = dist.recompute(net, x2)
    np.testing.assert_allclose(out.numpy(), direct.numpy(), rtol=1e-5)
    out.sum().backward()
    np.testing.assert_allclose(x2.grad.numpy(), g_direct, rtol=1e-4)
    np.testing.assert_allclose(net[0].weight.grad.numpy(), w_direct, rtol=1e-4)


def _zero_stage_run(level, seed=21):
    """Run 4 sharded steps at the given ZeRO level; return (losses, step)."""
    paddle.seed(seed)
    dist.reset_mesh()
    dist.init_mesh(dp=2, sharding=4)
    net = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    snap = {k: v.numpy().copy() for k, v in net.state_dict().items()}
    o = opt.AdamW(learning_rate=0.02, parameters=net.parameters())
    model, o = dist.group_sharded_parallel(net, o, level=level)
    step = dist.ShardedTrainStep(net, lambda m, x, y: F.mse_loss(m(x), y), o)
    x = np.random.RandomState(4).rand(8, 16).astype("float32")
    y = np.random.RandomState(5).rand(8, 16).astype("float32")
    losses = [float(step(paddle.to_tensor(x), paddle.to_tensor(y))) for _ in range(4)]

    dist.reset_mesh()
    paddle.seed(seed)
    net2 = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    net2.set_state_dict(snap)
    o2 = opt.AdamW(learning_rate=0.02, parameters=net2.parameters())
    eager = []
    for _ in range(4):
        loss = F.mse_loss(net2(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        o2.step()
        o2.clear_grad()
        eager.append(float(loss))
    return losses, eager, step


@pytest.mark.dist
@pytest.mark.parametrize("level", ["os", "os_g"])
def test_zero_stage12_parity_and_state_sharding(level):
    losses, eager, step = _zero_stage_run(level)
    np.testing.assert_allclose(losses, eager, rtol=2e-4)
    # params stay replicated in stages 1/2
    for p in step.train_params:
        assert p.dist_spec is None
        shard = p.data.addressable_shards[0].data
        assert shard.shape == p.data.shape
    # optimizer moment state is sharded over sdp (4x smaller per device)
    opt_ = step.optimizer
    sharded_any = False
    for p in step.train_params:
        for k, v in opt_._accumulators[id(p)].items():
            if v.shape == tuple(p.shape):
                frac = v.addressable_shards[0].data.size / v.size
                if frac <= 0.25 + 1e-6:
                    sharded_any = True
    assert sharded_any, "no optimizer state was sharded over sdp"


@pytest.mark.dist
def test_pp_pipeline_matches_sequential():
    """The compiled ppermute pipeline (pp=2) must match the pp=1 sequential
    scan bit-for-bit (same math, different schedule)."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    def run(pp):
        dist.reset_mesh()
        dist.init_mesh(pp=pp, dp=8 // pp)
        paddle.seed(7)
        cfg = LlamaConfig.tiny(num_hidden_layers=4, hidden_size=64,
                               intermediate_size=128, num_attention_heads=4,
                               num_key_value_heads=4, vocab_size=128)
        model = LlamaForCausalLM(cfg)
        snap = {k: v.numpy().copy() for k, v in model.state_dict().items()}
        o = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = dist.ShardedTrainStep(model, lambda m, x, y: m(x, labels=y), o)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 128, (8, 16)).astype("int32"))
        losses = [float(step(ids, ids)) for _ in range(3)]
        return snap, losses

    snap1, seq_losses = run(1)
    snap2, pp_losses = run(2)
    # identical init (same seed) => identical training trajectory
    for k in snap1:
        np.testing.assert_allclose(snap1[k], snap2[k], rtol=0, atol=0)
    np.testing.assert_allclose(pp_losses, seq_losses, rtol=2e-5)


@pytest.mark.dist
def test_moe_ep_sharded_training():
    """MoE Llama on an ep2·mp2·dp2 mesh: expert weights sharded over ep, loss
    decreases, aux load-balance loss flows gradients to the router."""
    from paddle_tpu.models import LlamaMoEConfig, LlamaForCausalLM

    dist.reset_mesh()
    dist.init_mesh(ep=2, mp=2, dp=2)
    paddle.seed(0)
    cfg = LlamaMoEConfig.tiny(num_hidden_layers=2, hidden_size=64,
                              intermediate_size=128, num_attention_heads=4,
                              num_key_value_heads=4, vocab_size=128,
                              num_experts=4, top_k=2)
    model = LlamaForCausalLM(cfg)
    o = opt.AdamW(learning_rate=5e-3, parameters=model.parameters())
    step = dist.ShardedTrainStep(model, lambda m, x, y: m(x, labels=y), o)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (4, 16)).astype("int32"))
    losses = [float(step(ids, ids)) for _ in range(8)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # expert weights sharded over ep: per-device shard is half the expert dim
    stack = model.llama.layers
    for safe, orig in stack._names:
        if orig.endswith("experts.gate"):
            p = stack._parameters[safe]
            shard = p.data.addressable_shards[0].data
            assert shard.shape[1] == p.shape[1] // 2  # E dim split over ep2
            break
    else:
        raise AssertionError("no stacked expert param found")
    dist.reset_mesh()


@pytest.mark.dist
def test_moe_eager_matches_sharded():
    """Same seed MoE model: eager single-device loss == ep-sharded first-step
    loss (routing and einsum dispatch are placement-independent)."""
    from paddle_tpu.models import LlamaMoEConfig, LlamaForCausalLM

    def first_loss(use_mesh):
        dist.reset_mesh()
        if use_mesh:
            dist.init_mesh(ep=2, dp=4)
        paddle.seed(3)
        cfg = LlamaMoEConfig.tiny(num_hidden_layers=2, hidden_size=64,
                                  intermediate_size=128, num_attention_heads=4,
                                  num_key_value_heads=4, vocab_size=128,
                                  num_experts=4, top_k=2)
        model = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 128, (4, 16)).astype("int32"))
        if use_mesh:
            o = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
            step = dist.ShardedTrainStep(model, lambda m, x, y: m(x, labels=y), o)
            return float(step(ids, ids))
        return float(model(ids, labels=ids))

    eager = first_loss(False)
    sharded = first_loss(True)
    np.testing.assert_allclose(sharded, eager, rtol=2e-5)
    dist.reset_mesh()


@pytest.mark.dist
def test_global_scatter_gather_roundtrip():
    dist.reset_mesh()
    dist.init_mesh(ep=4, dp=2)
    # [src_rank=4, n_expert=4, capacity=2, d=8]
    x_np = np.arange(4 * 4 * 2 * 8, dtype="float32").reshape(4, 4, 2, 8)
    x = paddle.to_tensor(x_np)
    counts = paddle.to_tensor(np.full((4,), 2, dtype="int64"))
    y = dist.global_scatter(x, counts, counts)
    # out[r, s*(E/ep)+j] == x[s, r*(E/ep)+j]; here E/ep == 1
    for r in range(4):
        for s in range(4):
            np.testing.assert_allclose(y.numpy()[r, s], x_np[s, r])
    z = dist.global_gather(y, counts, counts)
    np.testing.assert_allclose(z.numpy(), x_np)
    # scatter actually permutes data across ep ranks (a2a, not identity)
    assert not np.allclose(y.numpy(), x_np)
    # ragged counts mask overflow slots: count=1 zeroes capacity slot 1
    ragged = paddle.to_tensor(np.full((4,), 1, dtype="int64"))
    y2 = dist.global_scatter(x, ragged, ragged)
    assert np.allclose(y2.numpy()[:, :, 1, :], 0.0)
    assert not np.allclose(y2.numpy()[:, :, 0, :], 0.0)
    dist.reset_mesh()


@pytest.mark.dist
def test_gradient_merge_strategy():
    """strategy.gradient_merge: update applies every k steps on the summed
    (averaged) grads — parity with one big-batch step
    (reference meta_optimizers/gradient_merge_optimizer.py)."""
    dist.reset_mesh()
    dist.init_mesh(dp=8)
    from paddle_tpu.distributed import fleet

    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    fleet.init(is_collective=True, strategy=strat)
    paddle.seed(3)
    net = nn.Linear(8, 8)
    w0 = net.weight.numpy().copy()
    o = fleet.distributed_optimizer(
        opt.SGD(learning_rate=0.1, parameters=net.parameters()))
    x1 = paddle.to_tensor(np.random.RandomState(0).rand(4, 8).astype("float32"))
    x2 = paddle.to_tensor(np.random.RandomState(1).rand(4, 8).astype("float32"))
    for x in (x1, x2):
        loss = (net(x) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
    w_merged = net.weight.numpy().copy()

    # reference: single step on the averaged gradient of both microbatches
    paddle.seed(3)
    net2 = nn.Linear(8, 8)
    o2 = opt.SGD(learning_rate=0.1, parameters=net2.parameters())
    ((net2(x1) ** 2).mean() + (net2(x2) ** 2).mean()).backward()
    for p in net2.parameters():
        if p.grad is not None:
            p.grad.data = p.grad.data / 2
    o2.step()
    np.testing.assert_allclose(w_merged, net2.weight.numpy(), rtol=1e-5)
    dist.reset_mesh()


@pytest.mark.dist
def test_lamb_strategy_swaps_rule():
    dist.reset_mesh()
    dist.init_mesh(dp=8)
    from paddle_tpu.distributed import fleet

    strat = fleet.DistributedStrategy()
    strat.lamb = True
    fleet.init(is_collective=True, strategy=strat)
    net = nn.Linear(4, 4)
    o = fleet.distributed_optimizer(
        opt.AdamW(learning_rate=0.01, parameters=net.parameters()))
    assert type(o._inner_opt).__name__ == "Lamb"
    dist.reset_mesh()


@pytest.mark.dist
def test_gradient_merge_drop_bad_batch():
    """clear_grad WITHOUT step = drop the batch: window restarts clean."""
    dist.reset_mesh()
    dist.init_mesh(dp=8)
    from paddle_tpu.distributed import fleet

    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": False}
    fleet.init(is_collective=True, strategy=strat)
    paddle.seed(4)
    net = nn.Linear(4, 4)
    o = fleet.distributed_optimizer(
        opt.SGD(learning_rate=0.1, parameters=net.parameters()))
    x = paddle.to_tensor(np.ones((2, 4), "float32"))

    # poisoned batch: backward, then drop the window explicitly
    (net(x) * 100.0).mean().backward()
    o.discard_merge_window()
    assert net.parameters()[0].grad is None or \
        float(np.abs(net.parameters()[0].grad.numpy()).max()) == 0.0
    # clear_grad mid-window stays idempotent (double clears preserve grads)
    (net(x)).mean().backward()
    o.step()
    o.clear_grad()
    o.clear_grad()
    assert net.parameters()[0].grad is not None

    # a full clean window of 2 microbatches then applies only their grads
    w0 = net.weight.numpy().copy()
    for _ in range(2):
        (net(x)).mean().backward()
        o.step()
        o.clear_grad()
    assert not np.allclose(net.weight.numpy(), w0)
    dist.reset_mesh()


def _moe_run(layer, x):
    # fresh non-leaf input each run so x.grad exercises the dispatch
    # backward (d_xt of _idx_dispatch), not just parameter grads
    xin = x * 1.0
    xin.stop_gradient = False
    out = layer(xin)
    loss = (out * out).mean()
    loss.backward()
    grads = {n: p.grad.numpy().copy() for n, p in layer.named_parameters()}
    grads["__x__"] = xin.grad.numpy().copy()
    for p in layer.parameters():
        p.clear_grad()
    return out.numpy(), grads


def _moe_dispatch_vs_oracle(capacity_factor, mode):
    """Run one MoE layer under `mode` and under the GShard einsum oracle at
    the same capacity; assert identical outputs and grads."""
    from paddle_tpu.framework import flags
    from paddle_tpu.nn.layer.moe import MoELayer

    dist.reset_mesh()
    paddle.seed(5)
    layer = MoELayer(d_model=32, num_experts=4, intermediate_size=64,
                     top_k=2, capacity_factor=capacity_factor)
    x = paddle.randn([2, 24, 32])
    try:
        flags.set_flags({"FLAGS_moe_dispatch": "einsum"})
        ref_out, ref_g = _moe_run(layer, x)
        flags.set_flags({"FLAGS_moe_dispatch": mode})
        got_out, got_g = _moe_run(layer, x)
    finally:
        flags.set_flags({"FLAGS_moe_dispatch": "index"})
    np.testing.assert_allclose(got_out, ref_out, rtol=1e-5, atol=1e-6)
    for n in ref_g:
        np.testing.assert_allclose(got_g[n], ref_g[n], rtol=1e-4,
                                   atol=1e-6, err_msg=n)


def test_moe_sort_dispatch_matches_einsum_oracle():
    """argsort capacity routing must reproduce the GShard one-hot einsum
    dispatch exactly — same drops (slot-major priority), same combine
    weights — forward AND backward. Tight cap forces drops."""
    _moe_dispatch_vs_oracle(1.1, "sort")


def test_moe_index_dispatch_matches_einsum_oracle():
    """The default cumsum-position routing: same slot-major drop semantics
    as the oracle, fwd AND bwd, under a drop-forcing capacity."""
    _moe_dispatch_vs_oracle(1.1, "index")


def test_moe_gmm_dropless_matches_undropped_oracle():
    """The grouped-matmul dropless path must equal the einsum oracle when
    the oracle's capacity is large enough that nothing drops (cf = e/k
    guarantees cap >= n)."""
    _moe_dispatch_vs_oracle(2.0, "gmm")
