"""paddle.distribution + paddle.text + LARS optimizer tests."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest
from scipy import stats as sps

import paddle_tpu as paddle
from paddle_tpu import distribution as D
from paddle_tpu import text


def _np(t):
    return np.asarray(t.data)


# -- distributions ------------------------------------------------------------

def test_normal_log_prob_entropy_kl():
    n = D.Normal(1.0, 2.0)
    x = np.asarray([0.5, 1.0, 3.0], "float32")
    np.testing.assert_allclose(_np(n.log_prob(x)), sps.norm(1, 2).logpdf(x),
                               rtol=1e-5)
    np.testing.assert_allclose(float(_np(n.entropy())), sps.norm(1, 2).entropy(),
                               rtol=1e-5)
    m = D.Normal(0.0, 1.0)
    kl = float(_np(D.kl_divergence(n, m)))
    # closed form: log(1/2) + (4 + 1)/2 - 0.5
    np.testing.assert_allclose(kl, np.log(0.5) + 2.5 - 0.5, rtol=1e-5)
    s = n.sample([2000])
    assert abs(float(_np(s).mean()) - 1.0) < 0.2


def test_normal_log_prob_is_differentiable():
    loc = paddle.to_tensor(np.asarray(0.5, "float32"), stop_gradient=False)
    scale = paddle.to_tensor(np.asarray(1.5, "float32"), stop_gradient=False)
    n = D.Normal(loc, scale)
    lp = n.log_prob(paddle.to_tensor(np.asarray([1.0], "float32")))
    lp.sum().backward()
    assert loc.grad is not None and scale.grad is not None


def test_uniform_and_categorical():
    u = D.Uniform(0.0, 4.0)
    np.testing.assert_allclose(float(_np(u.entropy())), np.log(4.0), rtol=1e-6)
    lp = _np(u.log_prob(np.asarray([1.0, 5.0], "float32")))
    np.testing.assert_allclose(lp[0], -np.log(4.0), rtol=1e-6)
    assert np.isinf(lp[1]) and lp[1] < 0

    logits = np.log(np.asarray([0.1, 0.2, 0.7], "float32"))
    c = D.Categorical(logits)
    np.testing.assert_allclose(_np(c.probs(np.asarray([2]))), [0.7], rtol=1e-5)
    ent = float(_np(c.entropy()))
    np.testing.assert_allclose(ent, sps.entropy([0.1, 0.2, 0.7]), rtol=1e-5)
    c2 = D.Categorical(np.log(np.asarray([1 / 3, 1 / 3, 1 / 3], "float32")))
    kl = float(_np(D.kl_divergence(c, c2)))
    np.testing.assert_allclose(
        kl, sps.entropy([0.1, 0.2, 0.7], [1 / 3, 1 / 3, 1 / 3]), rtol=1e-5)
    s = _np(c.sample([500]))
    assert s.shape == (500,) and (s == 2).mean() > 0.5


def test_beta_dirichlet_multinomial():
    b = D.Beta(2.0, 3.0)
    x = np.asarray([0.3, 0.6], "float32")
    np.testing.assert_allclose(_np(b.log_prob(x)), sps.beta(2, 3).logpdf(x),
                               rtol=1e-4)
    np.testing.assert_allclose(float(_np(b.entropy())), sps.beta(2, 3).entropy(),
                               rtol=1e-4)
    np.testing.assert_allclose(float(_np(b.mean)), 0.4, rtol=1e-6)

    d = D.Dirichlet(np.asarray([1.0, 2.0, 3.0], "float32"))
    v = np.asarray([0.2, 0.3, 0.5], "float32")
    np.testing.assert_allclose(float(_np(d.log_prob(v))),
                               sps.dirichlet([1, 2, 3]).logpdf(v), rtol=1e-4)
    s = _np(d.sample([100]))
    assert s.shape == (100, 3)
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)

    m = D.Multinomial(10, np.asarray([0.2, 0.3, 0.5], "float32"))
    counts = _np(m.sample([50]))
    assert counts.shape == (50, 3)
    np.testing.assert_allclose(counts.sum(-1), 10.0)
    lp = float(_np(m.log_prob(np.asarray([2.0, 3.0, 5.0], "float32"))))
    np.testing.assert_allclose(lp, sps.multinomial(10, [0.2, 0.3, 0.5])
                               .logpmf([2, 3, 5]), rtol=1e-4)


def test_kl_beta_dirichlet_uniform():
    kl = float(_np(D.kl_divergence(D.Beta(2.0, 3.0), D.Beta(3.0, 2.0))))
    # numeric reference via quadrature
    xs = np.linspace(1e-5, 1 - 1e-5, 20001)
    p = sps.beta(2, 3).pdf(xs)
    ref = np.trapezoid(p * (sps.beta(2, 3).logpdf(xs) - sps.beta(3, 2).logpdf(xs)), xs)
    np.testing.assert_allclose(kl, ref, rtol=1e-3)
    klu = float(_np(D.kl_divergence(D.Uniform(0.0, 1.0), D.Uniform(-1.0, 2.0))))
    np.testing.assert_allclose(klu, np.log(3.0), rtol=1e-6)
    d1 = D.Dirichlet(np.asarray([1.0, 2.0], "float32"))
    d2 = D.Dirichlet(np.asarray([2.0, 1.0], "float32"))
    assert float(_np(D.kl_divergence(d1, d2))) > 0


# -- text datasets ------------------------------------------------------------

def test_uci_housing(tmp_path):
    rng = np.random.default_rng(0)
    data = rng.random((50, 14)).astype("float32")
    path = os.path.join(str(tmp_path), "housing.data")
    np.savetxt(path, data, fmt="%.6f")
    train = text.UCIHousing(data_file=path, mode="train")
    test = text.UCIHousing(data_file=path, mode="test")
    assert len(train) == 40 and len(test) == 10
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_imdb_dataset(tmp_path):
    path = os.path.join(str(tmp_path), "aclImdb.tar.gz")
    docs = {
        "aclImdb/train/pos/0.txt": b"a great great movie truly great",
        "aclImdb/train/neg/0.txt": b"a terrible movie truly terrible",
        "aclImdb/test/pos/0.txt": b"great movie",
    }
    with tarfile.open(path, "w:gz") as tf:
        for name, content in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(content)
            tf.addfile(info, io.BytesIO(content))
    ds = text.Imdb(data_file=path, mode="train", cutoff=2)
    assert len(ds) == 2
    ids, label = ds[0]
    assert ids.dtype == np.int64 and label in (0, 1)
    # 'great'(3x), 'a'(2), 'movie'(2), 'terrible'(2), 'truly'(2) pass cutoff=2
    assert ds.word_idx["great"] == 0


def test_imikolov_dataset(tmp_path):
    path = os.path.join(str(tmp_path), "simple-examples.tgz")
    train = b"the cat sat\nthe dog sat\n"
    valid = b"the cat ran\n"
    with tarfile.open(path, "w:gz") as tf:
        for name, content in (("./simple-examples/data/ptb.train.txt", train),
                              ("./simple-examples/data/ptb.valid.txt", valid)):
            info = tarfile.TarInfo(name)
            info.size = len(content)
            tf.addfile(info, io.BytesIO(content))
    ds = text.Imikolov(data_file=path, data_type="NGRAM", window_size=2,
                       mode="train", min_word_freq=1)
    assert len(ds) > 0 and ds[0].shape == (3,)
    seq = text.Imikolov(data_file=path, data_type="SEQ", mode="test",
                        min_word_freq=1)
    inp, tgt = seq[0]
    assert len(inp) == len(tgt)


def test_movielens_dataset(tmp_path):
    path = os.path.join(str(tmp_path), "ml-1m.zip")
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("ml-1m/users.dat", "1::M::25::10::12345\n2::F::35::5::54321\n")
        zf.writestr("ml-1m/movies.dat",
                    "10::Toy Story (1995)::Animation|Comedy\n"
                    "20::Heat (1995)::Action\n")
        zf.writestr("ml-1m/ratings.dat",
                    "1::10::5::1\n1::20::3::2\n2::10::4::3\n2::20::2::4\n")
    ds = text.Movielens(data_file=path, mode="train", test_ratio=0.0)
    assert len(ds) == 4
    uid, gender, age, job, mid, title_ids, cats, rating = ds[0]
    assert cats.shape == (3,)  # Animation, Comedy, Action
    assert rating in (5.0, 3.0, 4.0, 2.0)


def test_wmt16_dataset(tmp_path):
    path = os.path.join(str(tmp_path), "wmt16.tar.gz")
    train = b"hello world\thallo welt\ngood day\tguten tag\n"
    with tarfile.open(path, "w:gz") as tf:
        info = tarfile.TarInfo("wmt16/train")
        info.size = len(train)
        tf.addfile(info, io.BytesIO(train))
    ds = text.WMT16(data_file=path, mode="train")
    assert len(ds) == 2
    src, trg_in, trg_out = ds[0]
    assert trg_in[0] == ds.trg_dict["<s>"]
    assert trg_out[-1] == ds.trg_dict["<e>"]


def test_viterbi_decode_matches_brute_force():
    rng = np.random.default_rng(0)
    B, T, N = 2, 5, 4
    emis = rng.standard_normal((B, T, N)).astype("float32")
    trans = rng.standard_normal((N, N)).astype("float32")
    lengths = np.asarray([5, 3], "int64")
    scores, path = text.viterbi_decode(
        paddle.to_tensor(emis), paddle.to_tensor(trans),
        paddle.to_tensor(lengths), include_bos_eos_tag=False)
    scores, path = _np(scores), _np(path)

    import itertools
    for b in range(B):
        L = int(lengths[b])
        best, best_seq = -1e30, None
        for seq in itertools.product(range(N), repeat=L):
            s = emis[b, 0, seq[0]]
            for t in range(1, L):
                s += trans[seq[t - 1], seq[t]] + emis[b, t, seq[t]]
            if s > best:
                best, best_seq = s, seq
        np.testing.assert_allclose(scores[b], best, rtol=1e-5)
        assert tuple(path[b, :L]) == best_seq


def test_lars_momentum_trains():
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    net = nn.Linear(8, 1)
    opt = paddle.optimizer.LarsMomentum(learning_rate=0.5, momentum=0.9,
                                        lars_coeff=0.05,
                                        parameters=net.parameters())
    x = paddle.randn([32, 8])
    w = paddle.randn([8, 1])
    y = x.matmul(w)
    losses = []
    for _ in range(30):
        loss = F.mse_loss(net(x), y)
        losses.append(float(loss))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert losses[-1] < losses[0] * 0.5


def test_lars_rule_matches_numpy():
    import jax.numpy as jnp

    p = np.asarray([3.0, 4.0], "float32")          # ||p|| = 5
    g = np.asarray([0.6, 0.8], "float32")          # ||g|| = 1
    lr, mu, coeff, wd = 0.1, 0.9, 0.001, 0.0005
    state = {"velocity": jnp.zeros(2)}
    new_p, ns = paddle.optimizer.LarsMomentum._rule(
        jnp.asarray(p), jnp.asarray(g), state, jnp.asarray(lr, jnp.float32),
        jnp.asarray(1), {"momentum": mu, "lars_coeff": coeff, "wd": wd, "eps": 0.0})
    local_lr = lr * coeff * 5.0 / (1.0 + wd * 5.0)
    v = local_lr * (g + wd * p)
    np.testing.assert_allclose(np.asarray(new_p), p - v, rtol=1e-6)


def test_categorical_log_prob_differentiable():
    logits = paddle.to_tensor(np.zeros(3, "float32"), stop_gradient=False)
    c = D.Categorical(logits)
    lp = c.log_prob(np.asarray([2]))
    (-lp.sum()).backward()
    assert logits.grad is not None
    g = _np(logits.grad)
    # d(-logp[2])/dlogits = softmax - onehot(2)
    np.testing.assert_allclose(g, [1 / 3, 1 / 3, 1 / 3 - 1.0], rtol=1e-5)


def test_bernoulli_log_prob_differentiable():
    p = paddle.to_tensor(np.asarray([0.6], "float32"), stop_gradient=False)
    b = D.Bernoulli(p)
    lp = b.log_prob(np.asarray([1.0], "float32"))
    lp.sum().backward()
    np.testing.assert_allclose(_np(p.grad), [1 / 0.6], rtol=1e-4)


def test_hapi_grad_accumulation_averages():
    import paddle_tpu.nn as nn

    paddle.seed(0)
    x = np.random.default_rng(0).standard_normal((16, 4)).astype("float32")
    y = np.zeros((16,), "int64")

    def run(accum):
        paddle.seed(42)
        net = nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
                      nn.CrossEntropyLoss())
        ds = paddle.io.TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
        model.fit(ds, epochs=1, batch_size=4, shuffle=False, verbose=0,
                  accumulate_grad_batches=accum)
        return _np(net.weight)

    w1 = run(1)
    w4 = run(4)  # one step over averaged grads ~= similar scale, not 4x
    # averaged-accumulation step must differ from per-batch stepping but stay
    # bounded: the update magnitude should be comparable (not 4x larger)
    assert np.abs(w4).max() < np.abs(w1).max() * 2 + 1.0
