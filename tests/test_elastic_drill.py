"""Elastic failure drill (reference fleet/elastic/manager.py:130): kill a
worker mid-training, manager/controller emits RESTART, gang relaunches at the
surviving world size, training resumes from the sharded checkpoint."""
import json
import os
import sys
import textwrap

import numpy as np
import pytest


_WORKER = textwrap.dedent("""
    import json, os, sys, time
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")  # env var is pinned by site cfg
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.elastic import elastic_worker_env

    rank, world, restart_id, store, manager = elastic_worker_env()
    work = sys.argv[1]
    TOTAL = 8

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
    o = opt.SGD(learning_rate=0.05, parameters=net.parameters())
    start = 0
    latest = os.path.join(work, "LATEST")
    if os.path.exists(latest):
        with open(latest) as f:
            meta = json.load(f)
        start = meta["step"] + 1
        sd = net.state_dict()
        dist.load_state_dict(sd, meta["dir"])

    x = paddle.to_tensor(np.random.RandomState(1).rand(4, 8).astype("float32"))
    y = paddle.to_tensor((np.random.RandomState(1).rand(4, 8) * 0.1).astype("float32"))
    for step in range(start, TOTAL):
        loss = F.mse_loss(net(x), y)
        loss.backward(); o.step(); o.clear_grad()
        if rank == 0:
            d = os.path.join(work, f"ckpt_{step}")
            dist.save_state_dict(net.state_dict(), d, process_rank=0)
            tmp = latest + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step, "dir": d}, f)
            os.replace(tmp, latest)
            # trace LAST (after the marker flip): a kill landing between
            # trace and marker would replay this step on resume and log a
            # duplicate step number (flaky under load)
            with open(os.path.join(work, "trace.log"), "a") as f:
                f.write(json.dumps({"step": step, "world": world,
                                    "restart": restart_id,
                                    "loss": float(loss)}) + "\\n")
        if rank == 1 and restart_id == 0 and step == 3:
            os.kill(os.getpid(), 9)  # simulated node failure
        time.sleep(0.05)
    with open(os.path.join(work, f"done.{rank}.r{restart_id}"), "w") as f:
        f.write("done")
""")


@pytest.mark.dist
def test_kill_restart_resume(tmp_path):
    from paddle_tpu.distributed.fleet.elastic import (ElasticController,
                                                      ElasticStatus)

    script = tmp_path / "elastic_worker.py"
    script.write_text(_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ctl = ElasticController(
        [sys.executable, str(script), str(tmp_path)], np=4, min_np=2,
        log_dir=str(tmp_path / "logs"),
        extra_env={"JAX_PLATFORMS": "cpu",
                   "PYTHONPATH": repo + os.pathsep +
                   os.environ.get("PYTHONPATH", "")})
    try:
        status = ctl.run(max_restarts=2, timeout=300)
        if status != ElasticStatus.COMPLETED:
            import subprocess

            logs = subprocess.run(
                ["find", str(tmp_path / "logs"), "-type", "f"],
                capture_output=True, text=True).stdout
            pytest.fail(f"status={status} events={ctl.events} logs:\n{logs}")
    finally:
        ctl.close()

    # one restart happened, at world size 3
    restarts = [e for e in ctl.events if e["status"] == "restart"]
    assert len(restarts) == 1 and restarts[0]["world"] == 3
    # survivors finished at world 3
    assert (tmp_path / "done.0.r1").exists()
    assert (tmp_path / "done.2.r1").exists()

    # training resumed from the checkpoint: the step sequence continues past
    # the kill point instead of starting over, and the loss keeps decreasing
    trace = [json.loads(l) for l in
             (tmp_path / "trace.log").read_text().splitlines()]
    steps = [t["step"] for t in trace]
    assert steps == sorted(steps) and len(steps) == len(set(steps)), steps
    assert steps[-1] == 7
    w3 = [t for t in trace if t["world"] == 3]
    # resumed from a checkpoint, not from scratch: rank 0 checkpoints every
    # step but may lag rank 1's kill at step 3 (it does extra I/O per
    # step), so the resume point is >= 1 — not necessarily >= 3
    assert w3 and w3[0]["step"] >= 1, trace
    losses = [t["loss"] for t in trace]
    assert losses[-1] < losses[0]
