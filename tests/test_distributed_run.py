"""Controller-generation launcher (distributed.run): master rendezvous,
collective env wiring, PS pod split, gang failure surfacing.

Reference roles: python/paddle/distributed/run/controllers/master.py
(sync_peers), collective.py (trainer env), ps.py (server/trainer pods).
"""
import json
import os
import subprocess
import sys
import threading

import pytest

from paddle_tpu.distributed.run import parse_args
from paddle_tpu.distributed.run.controllers import (
    CollectiveController, Controller, PSController)
from paddle_tpu.distributed.run.master import Master, free_port, node_payload


def test_master_sync_peers_arrival_order():
    port = free_port()
    main = Master(f"127.0.0.1:{port}")
    assert main.role == Master.MAIN
    results = {}

    def participant(i):
        m = Master(f"127.0.0.1:{port}")
        assert m.role == Master.PARTICIPANT
        peers, rank = m.sync_peers("/t/rdv", f"peer{i}", 3)
        results[i] = (peers, rank)

    threads = [threading.Thread(target=participant, args=(i,))
               for i in (1, 2)]
    for t in threads:
        t.start()
    peers, rank = main.sync_peers("/t/rdv", "peer0", 3)
    for t in threads:
        t.join(timeout=30)
    assert rank == 0  # MAIN is pinned to rank 0
    assert peers[0] == "peer0"
    assert sorted(peers) == ["peer0", "peer1", "peer2"]
    for i, (ppeers, prank) in results.items():
        assert ppeers == peers and ppeers[prank] == f"peer{i}"
    main.stop()


def test_master_sync_peers_explicit_ranks():
    port = free_port()
    main = Master(f"127.0.0.1:{port}")
    out = {}

    def participant():
        m = Master(f"127.0.0.1:{port}")
        out["p"] = m.sync_peers("/t/expl", "b", 2, rank=0)

    t = threading.Thread(target=participant)
    t.start()
    peers, rank = main.sync_peers("/t/expl", "a", 2, rank=1)
    t.join(timeout=30)
    assert peers == ["b", "a"] and rank == 1
    assert out["p"][0] == ["b", "a"] and out["p"][1] == 0
    main.stop()


def test_collective_env_single_node():
    args = parse_args(["--nproc_per_node", "2", "train.py"])
    c = Controller.factory(args)
    assert isinstance(c, CollectiveController)
    peers = [node_payload(2)]
    env0 = c.worker_envs(peers, 0, 0)
    env1 = c.worker_envs(peers, 0, 1)
    assert env0["PADDLE_TRAINER_ID"] == "0"
    assert env1["PADDLE_TRAINER_ID"] == "1"
    assert env0["PADDLE_TRAINERS_NUM"] == "2"
    assert "PADDLE_MASTER" not in env0  # single node: no coordinator


def test_collective_env_multi_node():
    args = parse_args(["--nnodes", "2", "--nproc_per_node", "1",
                       "--master", "127.0.0.1:12345", "train.py"])
    c = CollectiveController(args)
    p0 = json.dumps({"ip": "10.0.0.1", "nproc": 1, "coord_port": 7000})
    p1 = json.dumps({"ip": "10.0.0.2", "nproc": 1, "coord_port": 7001})
    env = c.worker_envs([p0, p1], 1, 0)
    assert env["PADDLE_TRAINER_ID"] == "1"
    assert env["PADDLE_TRAINERS_NUM"] == "2"
    # coordinator is rank-0 node's advertised endpoint
    assert env["PADDLE_MASTER"] == "10.0.0.1:7000"


def test_ps_env_split():
    args = parse_args(["--mode", "ps", "--servers", "2", "--trainers", "2",
                       "train.py"])
    c = Controller.factory(args)
    assert isinstance(c, PSController)
    assert c.n_local_procs() == 4
    envs = [c.worker_envs([], 0, r) for r in range(4)]
    assert [e["TRAINING_ROLE"] for e in envs] == \
        ["PSERVER", "PSERVER", "TRAINER", "TRAINER"]
    assert envs[0]["PADDLE_PS_IS_MASTER"] == "1"
    assert envs[1]["PADDLE_PS_IS_MASTER"] == "0"
    assert envs[2]["PADDLE_TRAINER_ID"] == "0"
    assert envs[3]["PADDLE_TRAINER_ID"] == "1"
    # every role shares one store endpoint
    assert len({e["PADDLE_PS_ENDPOINT"] for e in envs}) == 1


def test_ps_env_multi_node_shares_one_store():
    args = parse_args(["--mode", "ps", "--servers", "1", "--trainers", "1",
                       "--nnodes", "2", "--master", "127.0.0.1:12346",
                       "train.py"])
    c = PSController(args)
    p0 = json.dumps({"ip": "10.0.0.1", "nproc": 2, "coord_port": 7000,
                     "ps_port": 7100})
    p1 = json.dumps({"ip": "10.0.0.2", "nproc": 2, "coord_port": 7001,
                     "ps_port": 7101})
    envs = [c.worker_envs([p0, p1], nr, lr)
            for nr in (0, 1) for lr in (0, 1)]
    # one global store: rank-0 node's advertised ps endpoint everywhere
    assert {e["PADDLE_PS_ENDPOINT"] for e in envs} == {"10.0.0.1:7100"}
    assert [e["TRAINING_ROLE"] for e in envs] == \
        ["PSERVER", "TRAINER", "PSERVER", "TRAINER"]
    assert envs[0]["PADDLE_SERVER_ID"] == "0"
    assert envs[2]["PADDLE_SERVER_ID"] == "1"
    assert envs[0]["PADDLE_PS_IS_MASTER"] == "1"
    assert envs[2]["PADDLE_PS_IS_MASTER"] == "0"
    assert envs[1]["PADDLE_TRAINER_ID"] == "0"
    assert envs[3]["PADDLE_TRAINER_ID"] == "1"
    assert envs[0]["PADDLE_SERVERS_NUM"] == "2"  # global count


def test_elastic_multi_node_rejected():
    args = parse_args(["--nnodes", "2", "--master", "127.0.0.1:12347",
                       "--elastic", "train.py"])
    c = CollectiveController(args)
    c._rendezvous = lambda: ([node_payload(1), node_payload(1)], 0)
    with pytest.raises(NotImplementedError, match="single-node"):
        c.run()


def test_run_end_to_end_gang(tmp_path):
    """`-m paddle_tpu.distributed.run --nproc_per_node 2` runs a script
    that asserts its wired env; non-zero exit propagates with a log tail."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "rank = os.environ['PADDLE_TRAINER_ID']\n"
        "assert os.environ['PADDLE_TRAINERS_NUM'] == '2'\n"
        "print('worker', rank, 'ok')\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.run",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs"),
         str(script)],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr
    logs = sorted((tmp_path / "logs").glob("workerlog.*"))
    assert len(logs) == 2
    assert "ok" in logs[0].read_text()

    bad = tmp_path / "bad.py"
    bad.write_text("import sys; print('about to fail'); sys.exit(3)\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.run",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "logs2"),
         str(bad)],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 3
    assert "about to fail" in r.stderr  # failed container's tail surfaced
